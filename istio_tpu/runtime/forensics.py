"""Tail-latency forensics — the "why was THAT request slow" plane.

PR 12 made the <1ms p99 a MEASURED per-request number at the wire;
this module makes a p99 *violation* attributable without a rerun.
Three legs, all bounded and lock-light:

  * FLIGHT RECORDER (FlightRecorder / RECORDER): requests whose
    end-to-end latency exceeds a threshold (default: the live SLO
    target, monitor.CHECK_P99_TARGET_MS; adaptive live-p99 mode
    opt-in) capture a complete per-stage timeline — queue wait,
    tensorize, h2d, device step, fold, grant decision, respond, plus
    per-handler host-action waits and the native front's wire-decode
    wall — into a bounded ring with the active trace id. The tape is
    THREAD-LOCAL: the batch worker opens it (batch_begin), the
    existing monitor.observe_stage calls feed it through a registered
    tap, and the executor's resolve() adds its deadline-bounded host
    waits, so the serving path pays one thread-local read per stage
    observation and nothing else. Served at /debug/slow.

  * MESH EVENT TIMELINE (EventTimeline / EVENTS): a timestamped ring
    of control-plane events — config publish generations, canary
    verdicts, bank rebuild/reuse, prewarm start/end per shape,
    breaker state transitions, quota flushes, grant revocations,
    provider refreshes, chaos arms, drains/quiesce — recorded by the
    planes that own them. Served at /debug/events; every slow-request
    exemplar is annotated with the events that overlapped its
    lifetime (plus a short pre-window: the breaker that opened 50ms
    before a request explains it), so "why slow" is one HTTP GET.

  * ON-DEMAND DEVICE PROFILING (capture_profile / thread_stacks):
    /debug/profile?seconds=N drives a jax.profiler trace capture into
    a configurable directory (ServerArgs.profile_dir / mixs
    --profile-dir), serialized by a lock and fail-soft where the
    profiler is unavailable; /debug/threads dumps every thread's
    python stack for diagnosing wedged pumps/lanes without gdb.

Overflow on either ring is bounded AND typed:
mixer_forensics_dropped_total{ring=} in runtime/monitor.py,
zero-shaped before the first drop per the promtext doctrine. The
recorder's clean-traffic overhead is pinned by bench.py's
forensics_overhead_pct (≤2% gate in the smoke) — the fast path is a
threshold compare per batch, not per-request work.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any

from istio_tpu.runtime import monitor

__all__ = ["FlightRecorder", "EventTimeline", "RECORDER", "EVENTS",
           "record_event", "capture_profile", "thread_stacks",
           "ProfileBusy"]

# events recorded up to this many seconds BEFORE a slow request's
# enqueue still annotate its exemplar: the control-plane cause often
# immediately precedes the victim (a breaker opens, THEN requests
# route slow) — a strict-overlap window would hide exactly the event
# an on-call needs
EVENT_PRE_WINDOW_S = 1.0


class EventTimeline:
    """Bounded ring of timestamped control-plane events.

    record() is safe from any thread and any lock context (the ring
    lock is a leaf; breaker transitions call it under the breaker
    lock). `coalesce_s` folds bursts of one kind into a single entry
    with an `n` count — quota flushes fire per window and must not
    evict the publish/prewarm history the ring exists to keep."""

    def __init__(self, capacity: int = 512):
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(
            maxlen=max(int(capacity), 8))

    def configure(self, capacity: int | None = None) -> None:
        if capacity is None:
            return
        capacity = max(int(capacity), 8)
        with self._lock:
            if capacity != self._buf.maxlen:
                self._buf = collections.deque(self._buf,
                                              maxlen=capacity)

    @staticmethod
    def _mergeable(a: dict, b: dict) -> bool:
        """Two detail payloads may coalesce only when their IDENTITY
        fields (everything non-numeric: provider names, ok flags,
        shapes) are equal — a provider_refresh failure must never be
        masked by a neighboring success, and two distinct providers
        never fold into one entry. Numeric fields (counts) accumulate
        instead."""
        if a.keys() != b.keys():
            return False
        for k, v in a.items():
            w = b[k]
            if isinstance(v, bool) or isinstance(w, bool) \
                    or not isinstance(v, (int, float)) \
                    or not isinstance(w, (int, float)):
                if v != w:
                    return False
        return True

    def record(self, kind: str, coalesce_s: float = 0.0,
               **detail: Any) -> None:
        ev = {"wall": time.time(), "t": time.perf_counter(),
              "kind": kind, "n": 1, "detail": detail}
        monitor.FORENSICS_EVENTS.inc()
        with self._lock:
            if coalesce_s and self._buf:
                last = self._buf[-1]
                if last["kind"] == kind and \
                        ev["t"] - last["t"] < coalesce_s and \
                        self._mergeable(last["detail"], detail):
                    last["n"] += 1
                    last["t"] = ev["t"]
                    last["wall"] = ev["wall"]
                    for k, v in detail.items():
                        if not isinstance(v, bool) and \
                                isinstance(v, (int, float)) and \
                                not isinstance(last["detail"][k],
                                               bool):
                            last["detail"][k] = \
                                last["detail"][k] + v
                    return
            if len(self._buf) == self._buf.maxlen:
                monitor.note_forensics_drop("events")
            self._buf.append(ev)

    def snapshot(self, kind: str | None = None,
                 limit: int = 128) -> list[dict]:
        """Most-recent-last copy; `kind` filters, `limit` keeps the
        newest (after the filter — an old publish event must stay
        findable behind a burst of newer flushes)."""
        with self._lock:
            out = list(self._buf)
        if kind:
            out = [e for e in out if e["kind"] == kind]
        return out[-limit:] if limit else out

    def overlapping(self, t0: float, t1: float,
                    pre_s: float = EVENT_PRE_WINDOW_S,
                    limit: int = 16) -> list[dict]:
        """Events whose perf_counter stamp lands in
        [t0 - pre_s, t1] — the annotation set for a request that
        lived [t0, t1]. Newest-last, bounded."""
        lo = t0 - pre_s
        with self._lock:
            out = [e for e in self._buf if lo <= e["t"] <= t1]
        return out[-limit:] if limit else out

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def reset(self) -> None:
        with self._lock:
            self._buf.clear()


class FlightRecorder:
    """Per-request flight recorder over the serving path's own stage
    observations (see module docstring for the tape contract)."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(capacity), 4))
        self._local = threading.local()
        self._enabled = True
        # 0 → the live SLO target (monitor.CHECK_P99_TARGET_MS)
        self._threshold_ms = 0.0
        self._adaptive = False
        self._thr_cache_s = monitor.CHECK_P99_TARGET_MS / 1e3
        self._thr_refreshed = 0.0

    # -- config (RuntimeServer arms this; last writer wins, like the
    #    process-global monitor counters) ------------------------------

    def configure(self, enabled: bool | None = None,
                  threshold_ms: float | None = None,
                  adaptive: bool | None = None,
                  capacity: int | None = None) -> None:
        if enabled is not None:
            self._enabled = bool(enabled)
        if threshold_ms is not None:
            self._threshold_ms = max(float(threshold_ms), 0.0)
        if adaptive is not None:
            self._adaptive = bool(adaptive)
        self._thr_refreshed = 0.0
        if capacity is not None:
            capacity = max(int(capacity), 4)
            with self._lock:
                if capacity != self._ring.maxlen:
                    self._ring = collections.deque(self._ring,
                                                   maxlen=capacity)

    def reset(self) -> None:
        """Drop retained exemplars (smoke/test phase boundaries); the
        process-lifetime counters in monitor.py keep accumulating —
        readers delta against their own baseline."""
        with self._lock:
            self._ring.clear()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def threshold_s(self) -> float:
        """The live capture threshold in seconds. Adaptive mode tracks
        the sliding-window p99 (never below the configured/SLO base),
        refreshed at most every 250ms — the window sort is scrape-rate
        work, not per-batch work."""
        base = (self._threshold_ms or monitor.CHECK_P99_TARGET_MS) \
            / 1e3
        if not self._adaptive:
            return base
        now = time.perf_counter()
        if now - self._thr_refreshed > 0.25:
            self._thr_refreshed = now
            try:
                p99 = monitor.CHECK_WINDOW.quantile(0.99)
            except Exception:
                p99 = 0.0
            self._thr_cache_s = max(base, p99)
        return self._thr_cache_s

    # -- the hot-path tape (thread-local, zero alloc when disabled) ----

    def batch_begin(self) -> None:
        """Open this thread's stage tape for the batch about to run.
        Absorbs any pre-marks the front staged (the native pump's
        wire-decode wall). Disabled → clears the tape so a stale one
        never attributes a previous batch's stages."""
        if not self._enabled:
            self._local.tape = None
            return
        tape = getattr(self._local, "pre", None) or []
        self._local.pre = None
        self._local.tape = tape

    def stage_mark(self, stage: str, seconds: float) -> None:
        """One stage observation on this thread's open tape (the
        monitor.observe_stage tap target). No-op off-batch."""
        tape = getattr(self._local, "tape", None)
        if tape is not None:
            tape.append((stage, seconds))

    def host_wait(self, handler: str, seconds: float) -> None:
        """One executor-lane claim wait (AdapterExecutor.resolve) —
        the stage a wedged adapter shows up as."""
        tape = getattr(self._local, "tape", None)
        if tape is not None:
            tape.append(("host:" + handler, seconds))

    def note_wire_decode(self, seconds: float) -> None:
        """Front-side pre-mark: the wire→bag decode wall the next
        batch_begin on this thread folds into its tape (the native
        pump decodes, then dispatches, on one thread)."""
        if not self._enabled:
            return
        pre = getattr(self._local, "pre", None)
        if pre is None:
            pre = []
            self._local.pre = pre
        elif len(pre) >= 4:
            # bounded: if every chunk keeps expiring pre-dispatch no
            # batch_begin ever consumes these — never grow without
            # bound on a deadline-storm thread
            del pre[0]
        pre.append(("wire_decode", seconds))

    def clear_premarks(self) -> None:
        """Drop this thread's unconsumed pre-marks. The front calls
        it after a dispatch that ended in a typed rejection (no
        batch_begin ran) — a stale decode wall must never inflate the
        NEXT unrelated batch's wire_decode stage."""
        self._local.pre = None

    # -- capture -------------------------------------------------------

    def note_batch(self, e2e_s: float, rows: int,
                   trace: dict | None) -> None:
        """Batcher-path completion: called once per batch with the
        SLOWEST request's e2e and its submit-time trace. Consumes the
        tape; captures one exemplar when over threshold (one per
        batch — batch-mates share the stage timeline)."""
        tape = getattr(self._local, "tape", None)
        self._local.tape = None
        if tape is None or e2e_s < self.threshold_s():
            return
        self._capture(e2e_s, rows, tape, trace, "batcher")

    def note_direct(self, e2e_s: float, rows: int) -> None:
        """Pre-batched-path completion (check_many / BatchCheck /
        native pump chunks): every row shares the batch e2e; the
        current thread span (the pump's rpc.check root) is the
        trace."""
        tape = getattr(self._local, "tape", None)
        self._local.tape = None
        if tape is None or e2e_s < self.threshold_s():
            return
        trace = None
        try:
            from istio_tpu.utils import tracing
            tr = tracing.get_tracer()
            if tr.reporter is not None:
                trace = tr._current()
        except Exception:
            trace = None
        self._capture(e2e_s, rows, tape, trace, "prebatched")

    def _capture(self, e2e_s: float, rows: int, tape: list,
                 trace: dict | None, source: str) -> None:
        """Build + ring one exemplar. Runs only for over-threshold
        requests — bounded dict work off the common path."""
        now = time.perf_counter()
        stages: dict[str, float] = {}
        for stage, s in tape:
            stages[stage] = stages.get(stage, 0.0) + s
        # host-action claims AND the grant fold happen INSIDE the
        # dispatcher's respond window, so the respond stage wall
        # contains both — net them out (the report plane's
        # adapter_dispatch doctrine: a wedged adapter is blamed as
        # host:<handler> and a slow grant fold as grant, never
        # smeared into respond; stage sums stay <= e2e)
        inner_s = sum(v for k, v in stages.items()
                      if k.startswith("host:") or k == "grant")
        if inner_s and "respond" in stages:
            stages["respond"] = max(stages["respond"] - inner_s, 0.0)
        top = max(stages, key=stages.get) if stages else None
        entry = {
            "wall": time.time(),
            "e2e_ms": round(e2e_s * 1e3, 3),
            "threshold_ms": round(self.threshold_s() * 1e3, 3),
            "rows": int(rows),
            "source": source,
            "stages_ms": {k: round(v * 1e3, 3)
                          for k, v in sorted(stages.items())},
            "top_stage": top,
            "trace_id": trace.get("traceId")
            if isinstance(trace, dict) else None,
            "events": [
                {"wall": e["wall"], "kind": e["kind"], "n": e["n"],
                 "detail": e["detail"]}
                for e in EVENTS.overlapping(now - e2e_s, now)],
        }
        if entry["trace_id"]:
            entry["traces_link"] = \
                f"/debug/traces?trace={entry['trace_id']}"
        monitor.FORENSICS_SLOW.inc()
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                monitor.note_forensics_drop("slow")
            self._ring.append(entry)

    # -- read side -----------------------------------------------------

    def snapshot(self, top_k: int = 10) -> dict:
        """/debug/slow payload: config + counters + the top-K slowest
        exemplars still retained (sorted slowest-first; the ring is
        recency-bounded so a startup outlier ages out)."""
        with self._lock:
            ring = list(self._ring)
        ring.sort(key=lambda e: e["e2e_ms"], reverse=True)
        return {
            "enabled": self._enabled,
            "threshold_ms": round(self.threshold_s() * 1e3, 3),
            "threshold_configured_ms": self._threshold_ms,
            "adaptive": self._adaptive,
            "capacity": self._ring.maxlen,
            "retained": len(ring),
            "counters": monitor.forensics_counters(),
            "slowest": ring[:max(int(top_k), 1)],
        }


# process-wide singletons (the monitor-counter doctrine: one home,
# armed by the owning RuntimeServer, readable by every surface)
RECORDER = FlightRecorder()
EVENTS = EventTimeline()

# feed the existing stage observations into the thread-local tape —
# the serving path keeps its one observe_stage call per stage
monitor.set_stage_tap(RECORDER.stage_mark)


def record_event(kind: str, coalesce_s: float = 0.0,
                 **detail: Any) -> None:
    """The one tap the control planes call. Never raises — forensics
    observes the mesh, it is not allowed to take it down."""
    try:
        EVENTS.record(kind, coalesce_s=coalesce_s, **detail)
    except Exception:
        pass


# -- on-demand device profiling ---------------------------------------

class ProfileBusy(RuntimeError):
    """A capture is already running (the profiler is process-global —
    two concurrent traces would corrupt each other's artifact)."""


_PROFILE_LOCK = threading.Lock()


def capture_profile(directory: str | None, seconds: float) -> dict:
    """Drive one jax.profiler trace capture of `seconds` wall into
    `directory` (None → a fresh mixs-profile-* tempdir, created only
    once the lock is held and the profiler imports — a polling probe
    on a busy or profiler-less rig must not litter /tmp) and return
    the artifact listing. Raises ProfileBusy when a capture is in
    flight; any profiler unavailability returns a fail-soft payload
    ({"available": False, "error": ...}) — a rig without the profiler
    must still serve the endpoint."""
    seconds = min(max(float(seconds), 0.1), 60.0)
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise ProfileBusy("a profile capture is already running")
    try:
        try:
            import jax
            if directory is None:
                import tempfile
                directory = tempfile.mkdtemp(prefix="mixs-profile-")
            os.makedirs(directory, exist_ok=True)
            t0 = time.perf_counter()
            jax.profiler.start_trace(directory)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            wall = time.perf_counter() - t0
        except Exception as exc:
            return {"available": False, "dir": directory,
                    "error": f"{type(exc).__name__}: {exc}"}
        files = []
        total = 0
        for root, _dirs, names in os.walk(directory):
            for name in names:
                p = os.path.join(root, name)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    continue
                files.append({"path": os.path.relpath(p, directory),
                              "bytes": size})
                total += size
        files.sort(key=lambda f: f["path"])
        record_event("profile_capture", seconds=seconds,
                     files=len(files))
        return {"available": True, "dir": directory,
                "seconds": seconds, "wall_s": round(wall, 3),
                "files": files[:64], "n_files": len(files),
                "bytes_total": total}
    finally:
        _PROFILE_LOCK.release()


def thread_stacks() -> dict:
    """Every live thread's python stack (sys._current_frames) keyed
    by thread name — the /debug/threads payload. A wedged pump or
    executor lane names its blocking frame here without gdb."""
    import sys
    import traceback

    frames = sys._current_frames()
    names = {t.ident: (t.name, t.daemon)
             for t in threading.enumerate()}
    threads = []
    for ident, frame in frames.items():
        name, daemon = names.get(ident, (f"unknown-{ident}", None))
        stack = [f"{f.filename}:{f.lineno} {f.name}"
                 + (f" — {f.line.strip()}" if f.line else "")
                 for f in traceback.extract_stack(frame)]
        threads.append({"name": name, "ident": ident,
                        "daemon": daemon, "stack": stack})
    threads.sort(key=lambda t: t["name"])
    return {"n_threads": len(threads), "threads": threads}
