"""Runtime server assembly (reference: mixer/pkg/server/server.go:92
newServer — store → runtime controller → dispatcher → API, plus
monitoring). The gRPC surface lives in istio_tpu/api; this class is the
in-process core those servers wrap (and what tests drive directly, the
reference's in-process e2e pattern mixer/test/e2e).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

from istio_tpu.adapters.sdk import QuotaArgs, QuotaResult
from istio_tpu.attribute.bag import Bag
from istio_tpu.attribute.global_dict import GLOBAL_MANIFEST
from istio_tpu.attribute.types import ValueType
from istio_tpu.runtime.batcher import CheckBatcher
from istio_tpu.runtime.controller import Controller
from istio_tpu.runtime.dispatcher import (CheckResponse,
                                          DEFAULT_IDENTITY_ATTR)
from istio_tpu.runtime.store import Store


@dataclasses.dataclass
class ServerArgs:
    """mixer/pkg/server/args.go:32 analog."""
    identity_attr: str = DEFAULT_IDENTITY_ATTR
    default_manifest: Mapping[str, ValueType] | None = None
    batch_window_s: float = 0.0003
    max_batch: int = 1024
    # in-flight device batches (overlaps host↔device sync across
    # batches; see runtime/batcher.py)
    pipeline: int = 4
    # serving batch shapes (None → batcher.default_buckets(max_batch));
    # each is one jit trace, pre-warmed before config swaps
    buckets: tuple[int, ...] | None = None
    max_str_len: int | None = None
    preprocess: bool = True
    # serve checks through the fused device engine (runtime/fused.py);
    # False falls back to the generic host-adapter dispatch path
    fused: bool = True


class RuntimeServer:
    def __init__(self, store: Store, args: ServerArgs | None = None):
        self.args = args or ServerArgs()
        manifest = self.args.default_manifest
        if manifest is None:
            manifest = GLOBAL_MANIFEST
        from istio_tpu.runtime.batcher import default_buckets
        buckets = tuple(sorted(self.args.buckets)) if self.args.buckets \
            else default_buckets(self.args.max_batch)
        self.controller = Controller(
            store, default_manifest=manifest,
            identity_attr=self.args.identity_attr,
            max_str_len=self.args.max_str_len,
            fused=self.args.fused,
            prewarm_buckets=buckets)
        self.batcher = CheckBatcher(self._run_check_batch,
                                    window_s=self.args.batch_window_s,
                                    max_batch=self.args.max_batch,
                                    pipeline=self.args.pipeline,
                                    buckets=buckets)

    # -- API surface (grpcServer.go Check/Report semantics) --
    # Preprocessing (the APA phase) happens exactly ONCE per request, in
    # the caller-facing entry points; everything downstream of the
    # batcher operates on already-preprocessed bags.

    def preprocess(self, bag: Bag) -> Bag:
        d = self.controller.dispatcher
        # the APA resolve costs a device step per request — skip it
        # outright unless an ATTRIBUTE_GENERATOR action is configured
        if not self.args.preprocess or not d.has_apa:
            return bag
        return d.preprocess(bag)

    def _run_check_batch(self,
                         bags: Sequence[Bag]) -> Sequence[CheckResponse]:
        return self.controller.dispatcher.check(bags)

    def check(self, bag: Bag) -> CheckResponse:
        """One request; coalesced into a device batch."""
        return self.batcher.check(self.preprocess(bag))

    def check_preprocessed(self, bag: Bag) -> CheckResponse:
        """Batcher entry for callers that already ran preprocess()
        (the gRPC server, which reuses the bag for the quota loop)."""
        return self.batcher.check(bag)

    def submit_check_preprocessed(self, bag: Bag):
        """Non-blocking batcher entry → concurrent.futures.Future.
        The async gRPC front awaits it so an in-flight check holds no
        thread (the sync front burns one blocked thread per RPC for
        the whole batch round-trip)."""
        return self.batcher.submit(bag)

    def check_many(self, bags: Sequence[Bag]) -> list[CheckResponse]:
        """Pre-batched entry (load tests / the C++ shim's batches)."""
        return list(self._run_check_batch(
            [self.preprocess(b) for b in bags]))

    def report(self, bags: Sequence[Bag]) -> None:
        d = self.controller.dispatcher
        d.report([self.preprocess(b) for b in bags])

    def quota(self, bag: Bag, quota_name: str,
              args: QuotaArgs | None = None,
              preprocessed: bool = False) -> QuotaResult:
        d = self.controller.dispatcher
        if not preprocessed:
            bag = self.preprocess(bag)
        return d.quota(bag, quota_name, args or QuotaArgs())

    def close(self) -> None:
        self.batcher.close()
        self.controller.close()
