"""Runtime server assembly (reference: mixer/pkg/server/server.go:92
newServer — store → runtime controller → dispatcher → API, plus
monitoring). The gRPC surface lives in istio_tpu/api; this class is the
in-process core those servers wrap (and what tests drive directly, the
reference's in-process e2e pattern mixer/test/e2e).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import numpy as np

from istio_tpu.adapters.sdk import QuotaArgs, QuotaResult
from istio_tpu.attribute.bag import Bag
from istio_tpu.attribute.global_dict import GLOBAL_MANIFEST
from istio_tpu.attribute.types import ValueType
from istio_tpu.runtime.batcher import CheckBatcher
from istio_tpu.runtime.controller import Controller
from istio_tpu.runtime.dispatcher import (CheckResponse,
                                          DEFAULT_IDENTITY_ATTR)
from istio_tpu.runtime.store import Store


@dataclasses.dataclass
class ServerArgs:
    """mixer/pkg/server/args.go:32 analog."""
    identity_attr: str = DEFAULT_IDENTITY_ATTR
    default_manifest: Mapping[str, ValueType] | None = None
    batch_window_s: float = 0.0003
    max_batch: int = 1024
    # in-flight device batches (overlaps host↔device sync across
    # batches; see runtime/batcher.py)
    pipeline: int = 4
    # occupancy threshold for the batcher's adaptive window: batches
    # keep accumulating while >= hold_at trips are in flight. The
    # default (None → 1) serializes trips — right whenever trips
    # contend for one transport/core; a rig whose device genuinely
    # overlaps trips should set hold_at=pipeline to restore overlap
    # (runtime/batcher.py CheckBatcher)
    hold_at: int | None = None
    # coalesce report records across Report RPCs into shared device
    # trips (see RuntimeServer.report); False dispatches each call's
    # records as their own batch
    report_batching: bool = True
    # record coalescer admission bound: submits past it shed typed
    # RESOURCE_EXHAUSTED (the ack-after-enqueue contract's overflow
    # leg — the native front acks a Report once its records are
    # ADMITTED, so admission must be bounded or memory isn't).
    # None → 16×max_batch; 0 → unbounded.
    report_queue_cap: int | None = None
    # allocate quota IN the check trip (FusedPlan.packed_check_instep)
    # instead of a separate pool-flush trip serialized behind it —
    # gated: only the native front's pump consumes it, and only for
    # single-pool, single-rule-per-name snapshots
    # (RuntimeServer.instep_quota_target); everything else keeps the
    # classic defer path
    quota_in_step: bool = False
    # serving batch shapes (None → batcher.default_buckets(max_batch));
    # each is one jit trace, pre-warmed before config swaps
    buckets: tuple[int, ...] | None = None
    # -- sharded serving plane (istio_tpu/sharding) --------------------
    # >0: partition the snapshot's rules by namespace into this many
    # model-parallel banks (each its own compiled RuleSetProgram +
    # FusedPlan) and serve checks through the shard-routed path —
    # verdict-identical to the monolithic compile, which is then never
    # device-warmed (its XLA program is what 100k+ rule snapshots
    # cannot afford). 0 = monolithic serving (the default).
    shards: int = 0
    # replica-parallel serving lanes behind the one front: each lane
    # is its own CheckBatcher + dispatcher set (sticky-by-namespace
    # routing, so one namespace's traffic coalesces into one lane's
    # batches). With shards=0 each replica owns its own FusedPlan over
    # the full snapshot; with shards>0 the banks are shared and lane
    # selection follows the shard assignment. 1 = single lane.
    replicas: int = 1
    # False skips the background FIRST-build prewarm (bench rigs and
    # tests that call plan.prewarm explicitly — the duplicate compile
    # contends for the core); swap-time prewarm stays synchronous
    initial_prewarm: bool = True
    # -- delta compilation & bank cache (compiler/cache.py) ------------
    # True (default): config republishes under sharding diff the
    # incoming store against the live plan by content hash and rebuild
    # ONLY the banks whose namespaces (or the replicated global set)
    # changed — untouched banks carry across generations with their
    # prewarmed shapes, breaker state and rulestats bindings. False is
    # the kill switch: every publish rebuilds every bank (and is what
    # the bench's capacity_republish_full_s measures).
    delta_compile: bool = True
    # namespaces the delta planner may RELOCATE per republish to chase
    # LPT balance — each move recompiles two banks, so this is an
    # explicit republish-latency vs balance trade (0 = perfect plan
    # stability, the default; see sharding/planner.plan_shards)
    shard_rebalance_budget: int = 0
    # JAX persistent compilation cache directory: restarts and rolling
    # deploys skip the XLA compile for every program whose HLO is
    # unchanged (our compiled programs take index tensors as traced
    # ARGUMENTS, so constant-only config edits keep the HLO
    # bit-identical). None → the MIXS_JAX_COMPILE_CACHE_DIR env var →
    # jax's own defaulting (mixs exposes --jax-compile-cache-dir).
    jax_compile_cache_dir: str | None = None
    max_str_len: int | None = None
    preprocess: bool = True
    # serve checks through the fused device engine (runtime/fused.py);
    # False falls back to the generic host-adapter dispatch path
    fused: bool = True
    # multi-chip serving: (dp, mp) factorization of jax.devices() — the
    # snapshot engine jits under shard_engine_check (batch over dp,
    # rules over mp, one psum on the verdict fold; parallel/mesh.py).
    # None = single device. Requires fused=True and every serving
    # bucket divisible by dp.
    mesh_shape: tuple[int, int] | None = None
    # -- overload resilience (runtime/resilience.py + batcher
    #    admission control; mixs exposes these as CLI flags) ----------
    # default Check() deadline for fronts whose wire carries none (the
    # native front; the gRPC fronts prefer the client's RPC deadline).
    # 0 = no default deadline.
    default_check_deadline_ms: float = 0.0
    # check batcher queue cap: submits past it shed RESOURCE_EXHAUSTED.
    # None → 8×max_batch; 0 → unbounded (the pre-resilience behavior).
    check_queue_cap: int | None = None
    # brownout mode: when the live p99 gauge breaches the SLO target
    # and the queue is half full, shed the NEWEST requests first
    brownout: bool = False
    # what Check() answers when BOTH the device path and the CPU
    # oracle fallback are down: "open" → OK (Mixer-client fail-open),
    # "closed" → UNAVAILABLE
    check_fail_policy: str = "closed"
    # consecutive failed device batches that trip the circuit breaker,
    # and how long it stays open before a half-open probe
    breaker_failures: int = 3
    breaker_reset_s: float = 5.0
    # retry a failed device step once (jittered backoff) before it
    # counts as a breaker failure
    device_retry: bool = True
    # -- adapter-executor plane (runtime/executor.py) ------------------
    # route host adapter work (fused-path overlay CHECK actions, quota
    # adapter calls, provider refresh) through the bounded per-handler
    # executor; False = the pre-executor inline loop (the behavioral
    # oracle — shadow replay and the generic path always use it)
    host_executor: bool = True
    # worker threads per handler lane (the bulkhead's concurrency
    # share) and pending-action cap per lane (overflow sheds typed)
    executor_workers: int = 2
    executor_queue_cap: int = 256
    # what an unresolvable host action (deadline overrun, bulkhead
    # shed, open breaker) contributes to the response: "open" → OK
    # with a 1s/1-use TTL, "closed" → UNAVAILABLE (mixs exposes
    # --host-fail-policy)
    host_fail_policy: str = "closed"
    # extra per-action wall bound even when the request carries no
    # deadline (ms; 0 = bound by the request deadline only)
    host_action_timeout_ms: float = 0.0
    # per-handler circuit breaker: consecutive failed/overrun actions
    # that trip it, and the open window before a half-open probe
    host_breaker_failures: int = 3
    host_breaker_reset_s: float = 5.0
    # -- latency plane (measured wire-to-verdict; runtime/grants.py,
    #    batcher continuous lane, dispatcher staged h2d) --------------
    # begin the str_bytes h2d right after the C++ wire decode (async
    # device_put of the tier-narrowed plane from the zero-copy staging
    # buffers) so the dominant transfer overlaps the host-side
    # namespace extraction. None = auto: on for real accelerator
    # backends, off on cpu (where device_put may alias host memory
    # and overlapping buys nothing).
    overlap_h2d: bool | None = None
    # continuous batching on the latency tier: the check batcher
    # dispatches a batch the moment an in-flight slot under
    # `continuous_depth` frees — a request never waits for a batch to
    # fill or a window to expire. False keeps the occupancy-fill
    # policy (throughput-optimal on serialized transports).
    continuous_batching: bool = False
    continuous_depth: int = 2
    # server-issued check-cache grants: valid_duration/valid_use_count
    # derived from config-generation age (runtime/grants.GrantPolicy)
    # so repeat traffic serves from the CLIENT cache and a config
    # delta revokes outstanding grants within the TTL floor. Opt-in:
    # the emitted TTL becomes time-dependent, which byte-exact parity
    # surfaces (shard/mesh/canary TTL comparisons) must opt into
    # knowingly.
    check_grants: bool = False
    grant_ttl_floor_s: float = 1.0
    grant_ttl_cap_s: float = 5.0
    grant_ttl_ramp_per_s: float = 0.5
    # -- tail-latency forensics (runtime/forensics.py) -----------------
    # per-request flight recorder: requests whose e2e latency exceeds
    # the threshold capture a complete stage timeline (+ overlapping
    # control-plane events) into the bounded ring /debug/slow serves.
    # The fast path is one threshold compare per batch — bench pins
    # the clean-traffic overhead at ≤2% (forensics_overhead_pct).
    flight_recorder: bool = True
    # capture threshold in ms; 0 = the live SLO target
    # (monitor.CHECK_P99_TARGET_MS — "slow" means "violates the p99
    # budget" by default)
    slow_threshold_ms: float = 0.0
    # adaptive mode: the threshold tracks max(base, live window p99),
    # refreshed at scrape rate — opt-in (an overloaded server would
    # otherwise stop capturing exactly when everything is slow, which
    # is sometimes what you want: only the OUTLIERS above the current
    # regime are exemplars)
    slow_adaptive: bool = False
    # bounded ring capacities (overflow is typed:
    # mixer_forensics_dropped_total{ring=})
    slow_ring_capacity: int = 256
    event_ring_capacity: int = 512
    # jax.profiler trace capture directory for /debug/profile
    # (mixs --profile-dir; None → MIXS_PROFILE_DIR env → a tempdir
    # created per capture)
    profile_dir: str | None = None
    # -- rule-level telemetry (runtime/rulestats.py) -------------------
    # fold per-rule hit/deny/err counts into on-device accumulators
    # inside the fused check step (requires fused=True to do anything)
    rule_telemetry: bool = True
    # accumulator drain cadence: the background thread pulls deltas
    # device→host every this many seconds and feeds the aggregator /
    # counter families / adapter exporters. 0 disables the thread
    # (drains then happen only on demand: /debug/rulestats, tests).
    rulestats_drain_s: float = 0.5
    # -- config canary (istio_tpu/canary/) -----------------------------
    # shadow-replay recorded live Check() traffic through every
    # rebuilt snapshot before the atomic publish: "off" disables the
    # recorder + replay entirely; "warn" replays and records the diff
    # report but always publishes; "gate" VETOES a publish whose
    # divergence rate exceeds canary_max_divergence (the old
    # dispatcher keeps serving; CanaryRejected surfaces via
    # /debug/canary and Controller.last_canary_rejection)
    canary: str = "off"
    # recorder sampling ring: capacity bounds memory, sample_every=k
    # keeps every k-th request (uniform stride across batches)
    canary_capacity: int = 2048
    canary_sample_every: int = 1
    # newest recorded rows replayed per candidate evaluation
    canary_replay_limit: int = 1024
    # non-waived divergent rows / replayed rows beyond which `gate`
    # vetoes (strictly greater-than; 0.0 = any divergence vetoes)
    canary_max_divergence: float = 0.0
    # qualified rule names ("ns/name") whose divergences are reported
    # but never gate — the "this rule is SUPPOSED to change" hatch
    canary_waivers: tuple = ()

    # -- mesh audit plane (runtime/audit.py) ---------------------------
    # background invariant auditor: report/check/quota conservation,
    # grant coherence, plane agreement, shard routing — plus the
    # fault-explainability scorer. Strictly off the hot path (reads
    # existing counters/ledgers on its own thread); violations emit
    # audit_violation events, bump mixer_audit_* and flip the
    # mixer_audit_healthy gauge. /debug/audit + /debug/slo serve it.
    audit: bool = True
    # evaluation cadence; the quota counter-plane recount samples
    # every audit_quota_every-th evaluation (its pull is the one
    # audit read that can touch the device transport)
    audit_interval_s: float = 0.5
    audit_quota_every: int = 8
    # fault-explainability matching window: an injection unmatched to
    # a forensics exemplar/event past this long counts unexplained
    audit_explain_window_s: float = 10.0

    # -- secure serving plane (istio_tpu/secure/) ----------------------
    # off | permissive | strict (secure/mtls.py). The API fronts read
    # this plus a ServingCerts holder the operator/mixs wires; strict
    # without certs is a construction-time error in each front. The
    # runtime core itself stays transport-agnostic — the knob lives
    # here so mixs/operators configure one surface (mixs --mtls).
    mtls: str = "off"
    # workload identity the serving fronts present
    # (spiffe://<domain>/ns/<ns>/sa/<sa>); empty → the mixs default
    mtls_identity: str = ""
    # serving-cert TTL and rotation point (fraction of TTL remaining
    # at which the maintenance lane renews; node-agent half-life
    # policy) for the WorkloadIdentity the fronts serve from
    mtls_cert_ttl_minutes: int = 60
    mtls_rotation_fraction: float = 0.5


class RuntimeServer:
    def __init__(self, store: Store, args: ServerArgs | None = None):
        self.args = args or ServerArgs()
        # flipped FIRST in shutdown(): every background warm this
        # server starts (bank prewarm, in-step prewarm) polls it
        # between shapes so no thread compiles into teardown
        self._stopping = False
        # persistent XLA compilation cache (compiler/cache.py): wire
        # it BEFORE the first compile so the controller's initial
        # publish already reads/writes cached artifacts
        from istio_tpu.compiler import cache as compile_cache
        cache_dir = compile_cache.resolve_cache_dir(
            self.args.jax_compile_cache_dir)
        if cache_dir:
            compile_cache.configure_persistent_cache(cache_dir)
            compile_cache.install_event_counters()
        self._compile_cache_dir = cache_dir
        # tail-latency forensics (runtime/forensics.py): arm the
        # process-wide flight recorder + event ring BEFORE the
        # controller's initial publish so the first generation's
        # events (publish, prewarm) land on the timeline
        from istio_tpu.runtime import forensics
        forensics.RECORDER.configure(
            enabled=self.args.flight_recorder,
            threshold_ms=self.args.slow_threshold_ms,
            adaptive=self.args.slow_adaptive,
            capacity=self.args.slow_ring_capacity)
        forensics.EVENTS.configure(
            capacity=self.args.event_ring_capacity)
        manifest = self.args.default_manifest
        if manifest is None:
            manifest = GLOBAL_MANIFEST
        from istio_tpu.runtime.batcher import default_buckets
        buckets = tuple(sorted(self.args.buckets)) if self.args.buckets \
            else default_buckets(self.args.max_batch)
        mesh = None
        if self.args.mesh_shape is not None:
            if not self.args.fused:
                raise ValueError("mesh serving requires fused=True")
            from istio_tpu.parallel.mesh import MeshSpec
            dp, mp = self.args.mesh_shape
            bad = [b for b in buckets if b % dp]
            if bad:
                raise ValueError(
                    f"serving buckets {bad} not divisible by dp={dp}")
            mesh = MeshSpec(dp=dp, mp=mp).build()
        # rule-level telemetry aggregator (runtime/rulestats.py):
        # created BEFORE the controller so the initial publish can
        # attach it; the drain thread below pulls the device
        # accumulators on the snapshot interval
        from istio_tpu.runtime.rulestats import (RuleStatsAggregator,
                                                 RuleStatsDrainer)
        self.rulestats = RuleStatsAggregator()
        # config canary (istio_tpu/canary): built before the
        # controller so the very first dispatcher already carries the
        # recorder tap — the gate itself only engages from the second
        # rebuild on (there is nothing recorded before traffic flows)
        self.canary = None
        if self.args.canary != "off":
            from istio_tpu.canary import CanaryConfig, ConfigCanary
            self.canary = ConfigCanary(CanaryConfig(
                mode=self.args.canary,
                max_divergence_rate=self.args.canary_max_divergence,
                waivers=tuple(self.args.canary_waivers),
                capacity=self.args.canary_capacity,
                sample_every=self.args.canary_sample_every,
                replay_limit=self.args.canary_replay_limit))
        # sharded serving plane (istio_tpu/sharding): when shards or
        # extra replicas are requested the check path serves through
        # namespace-sharded banks / replica lanes and the parent
        # monolithic plan stays un-warmed (metadata + oracle only)
        if self.args.shards < 0 or self.args.replicas < 1:
            raise ValueError(
                f"shards must be >= 0 and replicas >= 1, got "
                f"shards={self.args.shards} "
                f"replicas={self.args.replicas}")
        self._sharded_serving = (self.args.shards > 0
                                 or self.args.replicas > 1)
        if self._sharded_serving and not self.args.fused:
            raise ValueError("sharded/replica serving requires "
                             "fused=True")
        if self._sharded_serving and self.args.mesh_shape is not None:
            raise ValueError("sharded serving and mesh_shape are "
                             "mutually exclusive (banks own their "
                             "device leases)")
        self._sharded: dict | None = None
        # delta-compilation rebuild ledger — zero-shaped before the
        # first sharded publish (the promtext doctrine applied to
        # /debug/shards): per-generation and cumulative reused-vs-
        # recompiled bank counts, the last rebuild wall, and the last
        # rebuild ERROR with the generation it struck (satellite fix:
        # a swallowed bank-build failure must be loudly visible, not
        # one log line deep in the publish path)
        self._rebuild_status: dict = {
            "rebuilds": 0,
            "banks_reused": 0,
            "banks_recompiled": 0,
            "banks_reused_total": 0,
            "banks_recompiled_total": 0,
            "last_wall_s": 0.0,
            "revision": 0,
            "errors": 0,
            "last_error": None,
            "last_error_revision": None,
        }
        # adapter-executor plane (runtime/executor.py): built BEFORE
        # the controller so the initial publish's dispatcher already
        # runs host actions bulkheaded; lanes + breakers persist
        # across config swaps (handler identity outlives snapshots)
        self.executor = None
        if self.args.host_executor:
            from istio_tpu.runtime.executor import (AdapterExecutor,
                                                    ExecutorConfig)
            self.executor = AdapterExecutor(ExecutorConfig(
                workers=self.args.executor_workers,
                queue_cap=self.args.executor_queue_cap,
                fail_policy=self.args.host_fail_policy,
                action_timeout_s=self.args.host_action_timeout_ms
                / 1e3,
                breaker_failures=self.args.host_breaker_failures,
                breaker_reset_s=self.args.host_breaker_reset_s))
        # check-cache grant policy (runtime/grants.py): built before
        # the controller so the initial publish's dispatcher already
        # clamps TTLs; revocation fires from _on_config_publish with
        # the delta's changed-namespace set when sharding knows it
        self.grants = None
        if self.args.check_grants:
            from istio_tpu.runtime.grants import GrantPolicy
            self.grants = GrantPolicy(
                ttl_floor_s=self.args.grant_ttl_floor_s,
                ttl_cap_s=self.args.grant_ttl_cap_s,
                ttl_ramp_per_s=self.args.grant_ttl_ramp_per_s)
        # overlapped h2d: auto-resolve None → on for real accelerator
        # backends only (on cpu jax may alias the staging buffer
        # zero-copy and the "transfer" overlaps nothing)
        overlap = self.args.overlap_h2d
        if overlap is None:
            try:
                import jax
                overlap = jax.default_backend() not in ("cpu",)
            except Exception:
                overlap = False
        self._overlap_h2d = bool(overlap)
        self.controller = Controller(
            store, default_manifest=manifest,
            identity_attr=self.args.identity_attr,
            max_str_len=self.args.max_str_len,
            fused=self.args.fused,
            prewarm_buckets=buckets,
            mesh=mesh,
            rule_telemetry=self.args.rule_telemetry,
            canary=self.canary,
            on_publish=self._on_config_publish,
            initial_prewarm=self.args.initial_prewarm,
            prewarm_hook=self._prewarm_instep_for,
            warm_parent_plans=not self._sharded_serving,
            executor=self.executor,
            grants=self.grants,
            overlap_h2d=self._overlap_h2d)
        self._rulestats_drainer = RuleStatsDrainer(
            self.rulestats, self.args.rulestats_drain_s) \
            if (self.args.rule_telemetry and self.args.fused
                and self.args.rulestats_drain_s > 0) else None
        # resilience layer in front of the device step: retry, circuit
        # breaker with CPU-oracle fallback, fail-open/closed policy
        # (runtime/resilience.py). Every serving entry routes its
        # batches through _run_check_batch and therefore through this.
        from istio_tpu.runtime.resilience import (ResilienceConfig,
                                                  ResilientChecker)
        if self.args.check_fail_policy not in ("open", "closed"):
            raise ValueError(
                f"check_fail_policy must be 'open' or 'closed', got "
                f"{self.args.check_fail_policy!r}")
        self.resilience = ResilientChecker(
            device=self._run_check_batch_device,
            oracle=self._run_check_batch_oracle,
            config=ResilienceConfig(
                fail_policy=self.args.check_fail_policy,
                breaker_failures=self.args.breaker_failures,
                breaker_reset_s=self.args.breaker_reset_s,
                retry=self.args.device_retry))
        cap = self.args.check_queue_cap
        max_queue = 8 * self.args.max_batch if cap is None else cap
        if self._sharded_serving:
            # N CheckBatcher lanes behind the one front attribute
            # every wire front / introspect surface reads; each lane's
            # admission control (cap, deadline, brownout) is the same
            # CheckBatcher machinery, per lane
            from istio_tpu.sharding import ReplicaRouter
            self._replica_router = ReplicaRouter(
                self.args.replicas, self.args.identity_attr,
                dict(window_s=self.args.batch_window_s,
                     max_batch=self.args.max_batch,
                     pipeline=self.args.pipeline,
                     buckets=buckets,
                     hold_at=self.args.hold_at,
                     max_queue=max_queue,
                     brownout=self.args.brownout,
                     continuous=self.args.continuous_batching,
                     continuous_depth=self.args.continuous_depth))
            self.batcher = self._replica_router
            # the controller's initial publish fired before the router
            # existed — build the first generation's banks now
            self._rebuild_sharded(self.controller.dispatcher)
        else:
            self._replica_router = None
            self.batcher = CheckBatcher(
                self._run_check_batch,
                window_s=self.args.batch_window_s,
                max_batch=self.args.max_batch,
                pipeline=self.args.pipeline,
                buckets=buckets,
                hold_at=self.args.hold_at,
                max_queue=max_queue,
                brownout=self.args.brownout,
                continuous=self.args.continuous_batching,
                continuous_depth=self.args.continuous_depth)
        # the REPORT coalescer: records from concurrent Report RPCs
        # share packed device trips (see report()). Separate instance
        # so report trips are separately counted and the two queues
        # can't starve each other's windows.
        from istio_tpu.runtime import monitor as _monitor
        rcap = self.args.report_queue_cap
        self._report_batcher = CheckBatcher(
            self._run_report_batch,
            window_s=self.args.batch_window_s,
            max_batch=self.args.max_batch,
            pipeline=self.args.pipeline,
            buckets=buckets,
            hold_at=self.args.hold_at,
            size_hist=_monitor.REPORT_BATCH_SIZE,
            # the fused report resolve pads per chunk itself — don't
            # allocate padding here just to trim it
            pad_batches=False,
            # report records must not feed the CHECK latency
            # decomposition / live p99 window — they feed the report
            # pipeline's own coalesce_wait stage instead
            observe_latency=False,
            stage_observer=lambda w: _monitor.observe_report_stage(
                "coalesce_wait", w),
            # bounded admission: the ack-after-enqueue contract needs
            # a typed RESOURCE_EXHAUSTED at overflow, never unbounded
            # memory behind an already-acked wire
            max_queue=16 * self.args.max_batch if rcap is None
            else rcap) \
            if self.args.report_batching else None
        # initial publish ran before this hook's dependencies existed;
        # warm the in-step quota program in the background like the
        # controller's own initial prewarm (swaps re-warm in-line via
        # _on_config_publish). close() flips the stop flag so a still-
        # running background warm exits between shapes instead of
        # compiling into interpreter teardown.
        self._instep_prewarm_stop = False
        try:
            self.prewarm_instep(background=True)
        except Exception:
            import logging
            logging.getLogger("istio_tpu.runtime.server").exception(
                "initial in-step quota prewarm failed")
        # mesh audit plane: background invariant auditor + fault
        # explainability scorer (runtime/audit.py). Created LAST so
        # every surface it reads (controller, batchers, grants,
        # routers) already exists; reads snapshots only — nothing on
        # the hot path learns it is being audited.
        self.audit = None
        if self.args.audit:
            from istio_tpu.runtime.audit import (AuditPlane,
                                                 install_chaos_observer)
            install_chaos_observer()
            self.audit = AuditPlane(
                self,
                interval_s=self.args.audit_interval_s,
                explain_window_s=self.args.audit_explain_window_s,
                quota_every=self.args.audit_quota_every)
            self.audit.start()

    # -- API surface (grpcServer.go Check/Report semantics) --
    # Preprocessing (the APA phase) happens exactly ONCE per request, in
    # the caller-facing entry points; everything downstream of the
    # batcher operates on already-preprocessed bags.

    def _on_config_publish(self, dispatcher) -> None:
        """Controller publish hook: rebind the rulestats aggregator to
        the fresh snapshot (draining the outgoing plan first so a
        config swap never drops in-flight counts). Must never raise —
        telemetry is an observer of the publish, not a participant."""
        # grant revocation ordering: the monolithic serving surface
        # revokes INSIDE the controller, immediately before the
        # dispatcher ref swap (a response from the new generation must
        # never carry an old-generation grant); the sharded serving
        # surface revokes inside _rebuild_sharded before set_routers,
        # delta-scoped when the bank diff attributes the change.
        # staging-ring reuse bound: the zero-copy decoder's buffer
        # lifecycle contract requires staging_depth > the number of
        # batches concurrently in flight — raise the ring depth to
        # cover the configured pipeline (growing is always safe: the
        # ring allocates slots lazily and never shrinks live ones)
        self._bound_staging_depth(dispatcher)
        try:
            self.rulestats.attach(dispatcher)
        except Exception:
            import logging
            logging.getLogger("istio_tpu.runtime.server").exception(
                "rulestats attach failed")
        # maintenance lane: (re)register the published handlers'
        # provider-refresh jobs (list_adapter's TTL loop) with the
        # executor's scheduler — refresh runs pinned off the timed
        # request window, and a failing provider keeps serving the
        # last good list while the counters say so
        if self.executor is not None:
            try:
                self.executor.register_refreshables(
                    dispatcher.handlers)
            except Exception:
                import logging
                logging.getLogger(
                    "istio_tpu.runtime.server").exception(
                    "refreshable registration failed")
        # sharded serving plane: rebuild the shard banks / replica
        # lanes for the freshly published snapshot and swap every lane
        # atomically (set_routers) — old banks keep serving while the
        # new generation compiles, so a config swap never drops or
        # stalls a queued request. Failure policy mirrors the canary's
        # fail-open: a bank build error keeps the previous generation
        # serving and surfaces loudly (log + /debug/shards revision
        # mismatch) instead of killing the publish.
        if getattr(self, "_replica_router", None) is not None:
            try:
                self._rebuild_sharded(dispatcher)
            except Exception as exc:
                # conservative revoke: a failed rebuild left grant
                # state un-attributed — shortening budgets is always
                # safe, a stale long grant is not
                if self.grants is not None:
                    self.grants.on_publish(None)
                # surfaced, not just logged: /debug/shards renders the
                # ledger so an on-call sees WHICH generation failed to
                # build banks and that the previous one keeps serving
                st = self._rebuild_status
                st["errors"] += 1
                st["last_error"] = f"{type(exc).__name__}: {exc}"
                st["last_error_revision"] = \
                    dispatcher.snapshot.revision
                import logging
                logging.getLogger(
                    "istio_tpu.runtime.server").exception(
                    "sharded serving rebuild failed for generation "
                    "%d; previous generation keeps serving",
                    dispatcher.snapshot.revision)
        # in-step quota prewarm backstop (ADVICE r5: fused.
        # prewarm_instep was defined but never called, so the first
        # quota-carrying batch paid its XLA trace in-band). The main
        # warm runs PRE-SWAP via the controller's prewarm_hook
        # (_prewarm_instep_for); this post-publish pass uses the
        # precise instep_quota_target eligibility and catches a pool
        # whose counts shape changed with the new config — already-
        # compiled shapes just re-execute cheap dummy trips. The
        # initial publish fires before self.controller exists and is
        # covered by prewarm_instep() at the end of __init__.
        try:
            if getattr(self, "controller", None) is not None:
                self.prewarm_instep()
        except Exception:
            import logging
            logging.getLogger("istio_tpu.runtime.server").exception(
                "in-step quota prewarm failed")

    def _bound_staging_depth(self, dispatcher) -> None:
        """Keep the wire decoder's staging ring deeper than the
        number of batches that can be in flight against it (+2
        slack: the decode in progress and the batch a pump still
        holds). Under sharded serving every replica LANE shares the
        same bank — and therefore the same tensorizer — so the bound
        scales with replicas, not just the per-lane pipeline. Slots
        allocate lazily, so a deep bound costs nothing until used."""
        try:
            plan = getattr(dispatcher, "fused", None)
            native = getattr(plan, "native", None)
            if native is not None:
                lanes = max(self.args.replicas, 1)
                native.staging_depth = max(
                    native.staging_depth,
                    self.args.pipeline * lanes + 2)
        except Exception:
            pass   # decoder hardening must never break a publish

    def _rebuild_sharded(self, dispatcher) -> None:
        """Build the sharded serving generation for a published
        dispatcher and fan it across every surface coherently:
        plan (delta-stable against the live plan) → DIFF by bank
        content hash → compile only the banks whose namespaces (or
        the replicated global set) changed, carrying every untouched
        bank — prewarmed shapes, breaker state, rulestats bindings —
        across the generation (off-path; the previous generation
        keeps serving), prewarm the NEW banks' serving shapes, swap
        all replica lanes with one atomic set_routers, rebind the
        rulestats aggregator to the bank dispatchers (name-keyed
        counts merge globally), and record the plan decision + the
        reused-vs-recompiled ledger for /debug/shards.
        The canary recorder taps the bank dispatchers the same way it
        taps a monolithic one — bank-local rule indices resolve
        through the bank's own qualified_rule_names, which are the
        global names."""
        import time as _time

        from istio_tpu.sharding import (ReplicaRouter, ShardRouter,
                                        bank_content_key,
                                        compile_shard_bank,
                                        snapshot_static_digest)
        from istio_tpu.sharding.banks import (ShardingUnsupported,
                                              full_bank)
        from istio_tpu.sharding.planner import (costs_from_ruleset,
                                                plan_shards,
                                                trivial_plan)

        router: ReplicaRouter = self._replica_router
        snap = dispatcher.snapshot
        recorder = self.canary.recorder if self.canary is not None \
            else None
        buckets = self.controller.prewarm_buckets
        t0 = _time.perf_counter()
        n_lanes = router.n_replicas
        reason = ""
        bank_keys: list[str] = []
        reused_ids: list[int] = []
        if self.args.shards > 0:
            try:
                preds = snap.ruleset.rules[:snap.n_config_rules]
                # costs come from the decomposition compile_ruleset
                # just retained — never a second 100k-rule parse+DNF
                # pass on the rebuild thread
                costs = costs_from_ruleset(
                    snap.ruleset, snap.finder)[:snap.n_config_rules]
                # the content-addressed bank cache: the previous
                # generation's banks keyed by their ruleset-
                # decomposition hash. Delta planning keeps unchanged
                # namespaces on their current shards, so an unchanged
                # shard's key matches and its compiled bank carries
                # over; pop-on-use so two identical shards (possible
                # when both hold only replicated globals) never share
                # one bank object.
                prev = self._sharded if self.args.delta_compile \
                    else None
                prev_plan = None
                cache: dict[str, Any] = {}
                if prev is not None and prev.get("mode") == "sharded":
                    prev_plan = prev["plan"]
                    for b, key in zip(prev["banks"],
                                      prev.get("bank_keys", ())):
                        cache.setdefault(key, b)
                plan = plan_shards(
                    preds, snap.finder, self.args.shards, costs=costs,
                    revision=snap.revision, prev=prev_plan,
                    rebalance_budget=self.args.shard_rebalance_budget)
                static = snapshot_static_digest(
                    snap, identity_attr=self.args.identity_attr,
                    buckets=buckets,
                    rule_telemetry=self.args.rule_telemetry)
                banks = []
                for k in range(plan.n_shards):
                    key = bank_content_key(snap, plan, k, static)
                    bank_keys.append(key)
                    carried = cache.pop(key, None)
                    if carried is not None:
                        # carry the compiled artifact by SHALLOW COPY:
                        # the new generation's bank shares the
                        # dispatcher/snapshot/checker (the expensive,
                        # content-matched parts) but owns its
                        # local_to_global — the outgoing generation's
                        # routers keep the ORIGINAL object, so
                        # in-flight folds never see the incoming
                        # generation's rule numbering and a rebuild
                        # that fails on a later bank leaves serving
                        # state untouched
                        banks.append(dataclasses.replace(
                            carried, shard_id=k,
                            local_to_global=np.asarray(
                                plan.shard_rules[k], np.int64),
                            predicted_cost=float(plan.shard_cost[k])
                            if plan.shard_cost else 0.0))
                        reused_ids.append(k)
                    else:
                        b = compile_shard_bank(
                            snap, dispatcher.handlers, plan, k,
                            identity_attr=self.args.identity_attr,
                            buckets=buckets,
                            rule_telemetry=self.args.rule_telemetry,
                            recorder=recorder,
                            executor=self.executor,
                            grants=self.grants,
                            overlap_h2d=self._overlap_h2d)
                        b.content_key = key
                        banks.append(b)
                # grant revocation scoped to the DELTA: only the
                # recompiled banks' namespaces drop to the TTL floor
                # (reused banks' configs are content-identical — their
                # outstanding client grants stay valid); a scratch
                # rebuild (nothing reused) revokes globally. This runs
                # BEFORE the router swap below — new-generation
                # responses never carry old-generation grants.
                if self.grants is not None:
                    changed = {k for k in range(plan.n_shards)
                               if k not in reused_ids}
                    if reused_ids:
                        # union the OLD plan's namespaces for the
                        # changed shards: a namespace whose rules
                        # were entirely DELETED is absent from the
                        # new ns_to_shard but its cached verdicts
                        # still need revoking (shard ids are stable
                        # under delta planning, so the old map's
                        # shard numbering matches)
                        ns_maps = [plan.ns_to_shard]
                        if prev_plan is not None:
                            ns_maps.append(prev_plan.ns_to_shard)
                        self.grants.on_publish(
                            {ns for m in ns_maps
                             for ns, s in m.items() if s in changed})
                    else:
                        self.grants.on_publish(None)
                bank_map = {b.shard_id: b for b in banks}
                routers = [ShardRouter(bank_map, plan,
                                       self.args.identity_attr,
                                       replica=i)
                           for i in range(n_lanes)]
            except ShardingUnsupported as exc:
                # un-shardable snapshot (rbac pseudo-rules): fall back
                # to replica-only lanes over the monolithic plan —
                # the server keeps serving, /debug/shards says why
                reason = str(exc)
                plan = trivial_plan(n_lanes)
                banks = [full_bank(
                    snap, dispatcher.handlers, i,
                    identity_attr=self.args.identity_attr,
                    buckets=buckets,
                    rule_telemetry=self.args.rule_telemetry,
                    recorder=recorder,
                    dispatcher=dispatcher if i == 0 else None,
                    executor=self.executor,
                    grants=self.grants,
                    overlap_h2d=self._overlap_h2d)
                    for i in range(n_lanes)]
                # un-attributable rebuild: revoke every namespace
                # (the delta-scoped refinement only exists on the
                # sharded success path)
                if self.grants is not None:
                    self.grants.on_publish(None)
                routers = [
                    ShardRouter({s: banks[i]
                                 for s in range(plan.n_shards)},
                                plan, self.args.identity_attr,
                                replica=i)
                    for i in range(n_lanes)]
        else:
            # replica-only: each lane owns its own FusedPlan over the
            # full snapshot (lane 0 rides the published dispatcher)
            plan = trivial_plan(n_lanes)
            banks = [full_bank(
                snap, dispatcher.handlers, i,
                identity_attr=self.args.identity_attr,
                buckets=buckets,
                rule_telemetry=self.args.rule_telemetry,
                recorder=recorder,
                dispatcher=dispatcher if i == 0 else None,
                executor=self.executor,
                grants=self.grants,
                overlap_h2d=self._overlap_h2d)
                for i in range(n_lanes)]
            # replica-only publishes carry no delta attribution:
            # conservative global revoke, same as monolithic
            if self.grants is not None:
                self.grants.on_publish(None)
            routers = [
                ShardRouter({s: banks[i] for s in range(plan.n_shards)},
                            plan, self.args.identity_attr, replica=i)
                for i in range(n_lanes)]
        # each bank is its own device lease, so it carries its OWN
        # resilience wrap: retry → per-bank circuit breaker → the
        # bank's CPU-oracle fallback (Dispatcher.check_host_oracle
        # over the bank's rules) — a flapping bank degrades to
        # correct-but-slower answers without touching its siblings,
        # the same contract the monolithic ResilientChecker gives the
        # un-sharded path. The CHECKER is per generation (its device/
        # oracle callables belong to THIS generation's banks — an
        # in-flight batch on the old routers must finish on the old
        # banks, never be handed the new cold ones mid-window); only
        # the BREAKER persists across swaps, keyed by shard id: the
        # device behind a shard is the same physical lease, and a
        # fresh breaker per publish would re-pay breaker_failures
        # failed in-band batches on a device that is still down.
        from istio_tpu.runtime.resilience import (ResilienceConfig,
                                                  ResilientChecker)
        breakers = getattr(self, "_bank_breakers", {})
        reused_set = set(reused_ids)
        for b in banks:
            if b.shard_id in reused_set and b.checker is not None:
                # carried bank: its checker's device/oracle callables
                # ARE this bank's dispatcher — checker, breaker state
                # and all, it rides along untouched
                breakers[b.shard_id] = b.checker.breaker
                continue
            b.checker = ResilientChecker(
                device=b.dispatcher.check,
                oracle=b.dispatcher.check_host_oracle,
                config=ResilienceConfig(
                    fail_policy=self.args.check_fail_policy,
                    breaker_failures=self.args.breaker_failures,
                    breaker_reset_s=self.args.breaker_reset_s,
                    retry=self.args.device_retry),
                name=f"bank:{b.shard_id}")
            prev_brk = breakers.get(b.shard_id)
            if prev_brk is not None:
                b.checker.breaker = prev_brk
            else:
                breakers[b.shard_id] = b.checker.breaker
        self._bank_breakers = breakers
        # warm each NEW bank's serving shapes BEFORE the lane swap —
        # the previous generation serves meanwhile, so no request pays
        # a bank's first XLA trace in-band (the monolithic swap-warm
        # doctrine, per bank); on swaps the warm yields to live
        # serving between shapes exactly like the monolithic one.
        # Carried banks keep their already-compiled shape set — NOT
        # re-warmed, that is the delta-compilation win.
        from istio_tpu.runtime.controller import _serving_backoff
        first_build = self._sharded is None
        distinct = {id(b.dispatcher.fused): b for b in banks
                    if b.dispatcher.fused is not None
                    and b.shard_id not in reused_set}
        for b in distinct.values():
            b.dispatcher.fused.prewarm(
                buckets,
                should_stop=lambda: self._stopping,
                backoff=None if first_build else _serving_backoff)
        for b in banks:   # staging-ring depth >= pipeline bound
            self._bound_staging_depth(b.dispatcher)
        router.set_routers(routers, plan)
        # telemetry fan: bank plans' per-rule accumulators merge into
        # the one aggregator by qualified rule name (lane 0 in
        # replica-only mode IS the attached parent dispatcher — the
        # aggregator dedups by plan identity)
        try:
            self.rulestats.attach_lanes(
                [b.dispatcher for b in banks])
        except Exception:
            import logging
            logging.getLogger("istio_tpu.runtime.server").exception(
                "rulestats lane attach failed")
        wall = _time.perf_counter() - t0
        n_recompiled = len(banks) - len(reused_ids)
        self._sharded = {
            "plan": plan,
            "banks": banks,
            "bank_keys": bank_keys,
            "revision": snap.revision,
            "mode": "sharded" if self.args.shards > 0 and not reason
                    else "replica-only",
            "fallback_reason": reason,
            "build_wall_s": wall,
            "built_wall": _time.time(),
            "delta": {
                "reused": sorted(reused_ids),
                "recompiled": sorted(
                    b.shard_id for b in banks
                    if b.shard_id not in reused_set),
                "plan_stability": dict(plan.stability),
            },
        }
        st = self._rebuild_status
        st["rebuilds"] += 1
        st["banks_reused"] = len(reused_ids)
        st["banks_recompiled"] = n_recompiled
        st["banks_reused_total"] += len(reused_ids)
        st["banks_recompiled_total"] += n_recompiled
        st["last_wall_s"] = round(wall, 4)
        st["revision"] = snap.revision
        # mesh event timeline: which banks this generation carried vs
        # recompiled — the event a shard's cold-bank tail rides next to
        from istio_tpu.runtime import forensics
        forensics.record_event("bank_rebuild",
                               generation=snap.revision,
                               reused=len(reused_ids),
                               recompiled=n_recompiled,
                               wall_ms=round(wall * 1e3, 1))

    def _prewarm_instep_for(self, plan) -> None:
        """Controller prewarm_hook: compile the CANDIDATE plan's
        merged check+quota program BEFORE the dispatcher swap (old
        plan keeps serving), so no quota batch in the swap window
        traces in-band. Uses the live pool's counter shape — pools
        persist across swaps (quota state continuity); if the new
        config changes the shape, the post-publish backstop
        (_on_config_publish → prewarm_instep) compiles the real one."""
        if not self.args.quota_in_step or plan is None \
                or not plan.quota_actions:
            return
        pools = getattr(self.controller, "device_quotas", None) \
            if getattr(self, "controller", None) is not None else None
        if not pools or len(set(map(id, pools.values()))) != 1:
            return
        pool = next(iter(pools.values()))
        plan.prewarm_instep(
            self.controller.prewarm_buckets, pool.counts,
            should_stop=lambda: getattr(
                self, "_instep_prewarm_stop", False))

    def prewarm_instep(self, background: bool = False) -> None:
        """Compile the merged check+quota-alloc program for every
        serving bucket (and byte tier) BEFORE traffic selects it —
        only when the in-step quota path is actually configured and
        the live snapshot is in-step eligible. No-op otherwise."""
        if not self.args.quota_in_step:
            return
        d = self.controller.dispatcher
        plan = d.fused
        target = self.instep_quota_target()
        if plan is None or target is None:
            return
        pool, _ = target
        buckets = self.controller.prewarm_buckets

        def warm() -> None:
            try:
                plan.prewarm_instep(
                    buckets, pool.counts,
                    should_stop=lambda: self._instep_prewarm_stop)
            except Exception:
                import logging
                logging.getLogger(
                    "istio_tpu.runtime.server").exception(
                    "in-step quota prewarm failed")

        if background:
            import threading
            t = threading.Thread(target=warm, daemon=True,
                                 name="prewarm-instep")
            self._instep_prewarm_thread = t
            t.start()
        else:
            warm()

    def preprocess(self, bag: Bag) -> Bag:
        d = self.controller.dispatcher
        # the APA resolve costs a device step per request — skip it
        # outright unless an ATTRIBUTE_GENERATOR action is configured
        if not self.args.preprocess or not d.has_apa:
            return bag
        return d.preprocess(bag)

    def _run_check_batch(self, bags: Sequence[Bag],
                         deadline: float | None = None
                         ) -> Sequence[CheckResponse]:
        # pre-batched entries (check_many / BatchCheck) under sharded
        # serving route through the shard path too — a mixed-namespace
        # batch fans across banks inside the router; lane attribution
        # rides replica 0 (the submitting caller chose no lane)
        rr = self._replica_router
        if rr is not None and rr.routers:
            return rr.routers[0].check(bags, deadline=deadline)
        return self.resilience.run_batch(bags, deadline=deadline)

    def _run_check_batch_device(self, bags: Sequence[Bag],
                                deadline: float | None = None
                                ) -> Sequence[CheckResponse]:
        """The device serving path (ResilientChecker's primary).
        Resolved per call: a config swap publishes a new dispatcher and
        the breaker/fallback must follow it."""
        return self.controller.dispatcher.check(bags, deadline=deadline)

    def _run_check_batch_oracle(self, bags: Sequence[Bag]
                                ) -> Sequence[CheckResponse]:
        """The CPU oracle fallback (ResilientChecker's degraded path —
        no device step anywhere)."""
        return self.controller.dispatcher.check_host_oracle(bags)

    def _run_report_batch(self, bags: Sequence[Bag]) -> Sequence[None]:
        """Report batcher hook: dispatch the coalesced record batch
        (unpadded — the fused resolve pads per chunk); results are
        completion-only (Report returns empty)."""
        self.controller.dispatcher.report(bags)
        return [None] * len(bags)

    def check(self, bag: Bag,
              deadline: float | None = None) -> CheckResponse:
        """One request; coalesced into a device batch. `deadline`:
        absolute time.perf_counter() instant (see CheckBatcher.submit);
        expired/shed requests raise the typed CheckRejected errors from
        runtime/resilience.py."""
        return self.batcher.check(self.preprocess(bag),
                                  deadline=deadline)

    def check_preprocessed(self, bag: Bag,
                           deadline: float | None = None
                           ) -> CheckResponse:
        """Batcher entry for callers that already ran preprocess()
        (the gRPC server, which reuses the bag for the quota loop)."""
        return self.batcher.check(bag, deadline=deadline)

    def submit_check_preprocessed(self, bag: Bag, trace=None,
                                  deadline: float | None = None):
        """Non-blocking batcher entry → concurrent.futures.Future.
        The async gRPC front awaits it so an in-flight check holds no
        thread (the sync front burns one blocked thread per RPC for
        the whole batch round-trip). `trace`: the RPC's root span dict
        (the batch span parents under it — API-layer root spans).
        `deadline`: absolute perf_counter instant; expired requests
        resolve DEADLINE_EXCEEDED before tensorize."""
        return self.batcher.submit(bag, trace=trace, deadline=deadline)

    def check_many(self, bags: Sequence[Bag]) -> list[CheckResponse]:
        """Pre-batched entry (load tests / the C++ shim's batches).
        Observes the full stage decomposition: the preprocess+handoff
        time counts as this batch's queue-wait (no batcher queue in
        front of a pre-formed batch), and every request's wall time
        feeds the e2e histogram + live-percentile tracker."""
        import time as _time

        from istio_tpu.runtime import forensics
        from istio_tpu.runtime import monitor as _monitor

        t0 = _time.perf_counter()
        forensics.RECORDER.batch_begin()
        pre = [self.preprocess(b) for b in bags]
        _monitor.observe_stage("queue_wait", _time.perf_counter() - t0)
        out = list(self._run_check_batch(pre))
        e2e = _time.perf_counter() - t0
        for _ in bags:
            _monitor.observe_check_e2e(e2e)
        forensics.RECORDER.note_direct(e2e, len(bags))
        return out

    def check_batch_preprocessed(self,
                                 bags: Sequence[Bag]
                                 ) -> list[CheckResponse]:
        """Pre-batched entry for callers that already ran preprocess()
        and padded to a bucket shape (the BatchCheck gRPC front)."""
        import time as _time

        from istio_tpu.runtime import forensics
        from istio_tpu.runtime import monitor as _monitor
        from istio_tpu.runtime.batcher import trim_pads

        t0 = _time.perf_counter()
        # flight recorder: the native pump / BatchCheck front's batch
        # tape — stage marks land on THIS thread (the dispatcher runs
        # inline below), and the front's wire-decode pre-mark is
        # absorbed here
        forensics.RECORDER.batch_begin()
        out = list(self._run_check_batch(bags))
        e2e = _time.perf_counter() - t0
        real = trim_pads(bags)
        for _ in real:                 # padding rows carry no caller
            _monitor.observe_check_e2e(e2e)
        forensics.RECORDER.note_direct(e2e, len(real))
        return out

    def submit_report(self, bags: Sequence[Bag]) -> list:
        """Non-blocking report entry → concurrent Futures, one per
        record (empty when no batcher is configured — records already
        dispatched inline). Records coalesce ACROSS RPCs into shared
        device trips via the report batcher, so N concurrent 64-record
        Report RPCs form one bucket-sized packed pull instead of N
        separate trips — on a trip-serialized transport
        records/s = trips/s × batch size. The aio front awaits the
        futures so an in-flight Report holds no thread; the native
        front acks after ENQUEUE (inspecting only already-rejected
        futures) so its pump never waits out a device trip.

        Record conservation: every record is counted ACCEPTED here and
        counted exported or typed-rejected exactly once when its
        future resolves (monitor.report_record_done) — the batcher's
        lifecycle guarantees (watchdog, drain-on-close, typed
        admission sheds) mean no future is ever abandoned, so
        accepted == exported + rejected holds at quiescence."""
        from istio_tpu.runtime import monitor as _monitor

        bags = [self.preprocess(b) for b in bags]
        rb = self._report_batcher
        if rb is None:
            # inline dispatch (report_batching=False): same
            # conservation accounting, no coalescer
            _monitor.report_accepted(len(bags))
            try:
                self.controller.dispatcher.report(bags)
            except Exception as exc:
                _monitor.report_rejected(
                    len(bags), "error",
                    f"{type(exc).__name__}: {exc}")
                raise
            _monitor.report_exported(len(bags))
            return []
        from concurrent.futures import Future

        from istio_tpu.runtime.resilience import (CheckRejected,
                                                  UnavailableError)
        futs = []
        for b in bags:
            _monitor.report_accepted(1)
            try:
                fut = rb.submit(b)
            except Exception as exc:
                # a CLOSED coalescer (post-shutdown submit) raises —
                # convert to a typed-rejected future so the record
                # stays on the conservation ledger (an accepted count
                # with no resolving future would leak in_flight
                # forever) and fronts answer UNAVAILABLE, not a stack
                # trace
                fut = Future()
                fut.set_exception(
                    exc if isinstance(exc, CheckRejected) else
                    UnavailableError(
                        f"report coalescer closed: "
                        f"{type(exc).__name__}: {exc}"))
            fut.add_done_callback(_monitor.report_record_done)
            futs.append(fut)
        return futs

    def report(self, bags: Sequence[Bag]) -> None:
        """Blocking report: returns after EVERY record's batch
        completed (grpcServer.go Report returns post-dispatch); the
        first batch error re-raises only after all futures resolved —
        abandoning later batches would leave records executing past
        the call and their exceptions unretrieved."""
        from concurrent.futures import wait as _wait

        futs = self.submit_report(bags)
        if not futs:
            return
        _wait(futs)
        first = next((e for e in (f.exception() for f in futs)
                      if e is not None), None)
        if first is not None:
            raise first

    def quota(self, bag: Bag, quota_name: str,
              args: QuotaArgs | None = None,
              preprocessed: bool = False,
              deadline: float | None = None) -> QuotaResult:
        """`deadline`: absolute perf_counter instant bounding the host
        quota adapter call (the executor plane); callers without one
        inherit the server default — a wedged shared-quota backend
        must never hold a front thread unbounded."""
        d = self.controller.dispatcher
        if not preprocessed:
            bag = self.preprocess(bag)
        if deadline is None and self.args.default_check_deadline_ms:
            import time as _time
            deadline = _time.perf_counter() + \
                self.args.default_check_deadline_ms / 1e3
        return d.quota(bag, quota_name, args or QuotaArgs(),
                       deadline=deadline)

    def quota_fused(self, bag: Bag, quota_name: str, args: QuotaArgs,
                    check_result):
        """Served quota via the device pools (runtime/device_quota.py):
        reuses the CHECK step's activity bits instead of re-resolving.
        Returns a QuotaFuture, a final QuotaResult (no device work
        needed), or None → the caller must take the dispatcher.quota
        fallback (generic path / non-memquota quota handler)."""
        from istio_tpu.adapters.sdk import QuotaResult
        from istio_tpu.expr.oracle import EvalError
        from istio_tpu.models.policy_engine import INTERNAL

        if check_result.active_quota_rules is None:
            return None
        # rule indices are positional within the snapshot that served
        # the check — use THAT dispatcher, not the current one (a config
        # swap mid-request would renumber rules under us)
        d = check_result.quota_context
        if d is None:
            # no quota actions existed at check time: grant freely
            # (dispatcher.quota tail — the reference returns empty)
            return QuotaResult(granted_amount=args.quota_amount)
        plan = d.fused
        if plan is None:
            return None
        active = set(check_result.active_quota_rules)
        snap = d.snapshot
        for ridx, handler_q, inst_q, names in plan.quota_actions:
            if ridx not in active or quota_name not in names:
                continue
            pool = self.controller.device_quotas.get(handler_q)
            # limits are keyed by the handler config's quota names,
            # which match QUALIFIED instance names (memquota looks up
            # instance["name"] — see tests/test_runtime.py convention)
            if pool is None or not pool.knows(inst_q):
                return None   # non-memquota quota handler → host path
            try:
                instance = snap.instances[inst_q].build(bag)
            except EvalError as exc:   # dispatcher.quota parity
                return QuotaResult(granted_amount=0,
                                   status_code=INTERNAL,
                                   status_message=str(exc))
            except Exception as exc:   # safeDispatch parity
                return QuotaResult(granted_amount=0,
                                   status_code=INTERNAL,
                                   status_message=f"instance build: "
                                                  f"{exc}")
            return pool.alloc(inst_q, instance, args)
        # no matching active quota rule: grant freely
        return QuotaResult(granted_amount=args.quota_amount)

    # -- in-step quota (gated: ServerArgs.quota_in_step) ---------------

    def instep_quota_target(self) -> tuple | None:
        """(pool, {name → (ridx, inst_q)}) when the CURRENT snapshot is
        in-step eligible: exactly one device pool, and every quota name
        resolving to exactly one quota action on that pool's handler
        whose rule predicate is device-evaluated (host-fallback rules'
        activity is invisible to the device gate). None → callers use
        the classic defer/pool-flush path."""
        if not self.args.quota_in_step:
            return None
        if self._replica_router is not None:
            # the in-step merge compiles ONE check+quota program per
            # pool; a rule set split across banks has no single
            # program to merge into — sharded serving keeps the
            # classic defer path (quota STATE still routes correctly:
            # pools are controller-owned and shared across banks)
            return None
        d = self.controller.dispatcher
        cached = getattr(self, "_instep_cache", None)
        if cached is not None and cached[0] is d.snapshot:
            return cached[1]
        target = self._build_instep_target(d)
        self._instep_cache = (d.snapshot, target)
        return target

    def _build_instep_target(self, d) -> tuple | None:
        plan = d.fused
        pools = self.controller.device_quotas
        if plan is None or not plan.quota_actions or not pools:
            return None
        if len(set(map(id, pools.values()))) != 1:
            return None
        pool = next(iter(pools.values()))
        rs = d.snapshot.ruleset
        # the device alloc gates on the DEVICE status (a denied check
        # must not consume, grpcServer.go:188); host overlay actions
        # or host-fallback predicates could flip the final status
        # after the trip — such snapshots keep the classic path
        n_cfg = len(d.snapshot.rules)
        if plan.host_actions or \
                any(r < n_cfg for r in rs.host_fallback):
            return None
        by_name: dict[str, Any] = {}
        for ridx, handler_q, inst_q, names in plan.quota_actions:
            for name in names:
                by_name.setdefault(name, []).append(
                    (ridx, handler_q, inst_q))
        out: dict[str, tuple] = {}
        for name, cands in by_name.items():
            if len(cands) != 1:
                continue
            ridx, handler_q, inst_q = cands[0]
            if pools.get(handler_q) is not pool \
                    or not pool.knows(inst_q) \
                    or ridx in rs.host_fallback:
                continue
            out[name] = (ridx, inst_q)
        return (pool, out) if out else None

    def check_batch_quota_instep(self, bags: Sequence[Bag],
                                 qrows: Sequence[tuple],
                                 target: tuple):
        """One padded batch with its quota rows allocated IN the check
        trip. `qrows`: [(slot, requested name, QuotaArgs)]; `target`
        from instep_quota_target() (same snapshot). Returns
        (responses, {slot → QuotaResult}). Rows whose instance build
        fails resolve INTERNAL without the trip (quota_fused parity).
        """
        import time as _time

        from istio_tpu.runtime import monitor as _monitor
        from istio_tpu.runtime.batcher import trim_pads

        # quota-carrying batches must feed the e2e histogram + live
        # p99 window like every other serving entry — their stage
        # observations (tensorize below, h2d/device_step in the
        # dispatcher's instep branch) need matching e2e mass. Observed
        # only on SUCCESS: the batcher likewise skips errored batches,
        # so a transient device fault never flips the live p99 / SLO
        # gauges on error-path latency no request was answered with.
        from istio_tpu.runtime import forensics

        t0 = _time.perf_counter()
        forensics.RECORDER.batch_begin()
        out = self._check_batch_quota_instep_inner(bags, qrows, target)
        e2e = _time.perf_counter() - t0
        real = trim_pads(bags)
        for _ in real:
            _monitor.observe_check_e2e(e2e)
        forensics.RECORDER.note_direct(e2e, len(real))
        return out

    def _check_batch_quota_instep_inner(self, bags: Sequence[Bag],
                                        qrows: Sequence[tuple],
                                        target: tuple):
        from istio_tpu.expr.oracle import EvalError
        from istio_tpu.models.policy_engine import INTERNAL

        d = self.controller.dispatcher
        snap = d.snapshot
        pool, by_name = target
        early: dict[int, QuotaResult] = {}
        rows: list[tuple] = []
        rule_idx = np.full(len(bags), -1, np.int32)
        for slot, name, args in qrows:
            ridx, inst_q = by_name[name]
            try:
                instance = snap.instances[inst_q].build(bags[slot])
            except EvalError as exc:
                early[slot] = QuotaResult(granted_amount=0,
                                          status_code=INTERNAL,
                                          status_message=str(exc))
                continue
            except Exception as exc:
                early[slot] = QuotaResult(
                    granted_amount=0, status_code=INTERNAL,
                    status_message=f"instance build: {exc}")
                continue
            rule_idx[slot] = ridx
            rows.append((slot, inst_q, instance, args))
        # tensorize OUTSIDE the counter token: the token covers ONLY
        # stage→dispatch (the successor counters swap in as a device
        # future and the next trip chains on it), so concurrent
        # pumps' host work AND their trips overlap on the transport
        # (measured: a token held across the pull made in-step SLOWER
        # than two serialized trips)
        import time as _time

        from istio_tpu.runtime import monitor as _monitor

        t_tz = _time.perf_counter()
        pre = d._tensorize_for_device(bags)
        _monitor.observe_stage("tensorize",
                               _time.perf_counter() - t_tz)
        sess = pool.inline_begin(len(bags), rows,
                                 pool._clock()) if rows else None
        if sess is None:
            if rows:   # pool closed under a config swap: fall back
                for slot, _, _, args in rows:
                    early[slot] = QuotaResult(
                        granted_amount=0, status_code=14,
                        status_message="quota pool closed by config "
                                       "swap")
            return d.check(bags, pre_tensorized=pre), early
        results: dict[int, QuotaResult] = {}

        def on_pull(granted, gate) -> None:
            # fires right after the device pull, inside d.check —
            # commits (in dispatch order) before the per-row response
            # python runs
            results.update(sess.commit(np.asarray(granted),
                                       np.asarray(gate)))

        try:
            q = {"buckets": sess.buckets, "amounts": sess.amounts,
                 "be": sess.be, "mx": sess.mx, "active": sess.active,
                 "ticks": sess.ticks, "lasts": sess.lasts,
                 "rolling": sess.rolling, "rule_idx": rule_idx}
            responses = d.check(
                bags, instep=(q, sess.prev_counts, sess.dispatched,
                              on_pull),
                pre_tensorized=pre)
        except BaseException:
            sess.abort()   # no-op when on_pull already committed
            raise
        results.update(sess.early)
        results.update(early)
        return responses, results

    def shutdown(self, deadline: float | None = 5.0) -> None:
        """Ordered graceful shutdown — the lifecycle plane's runtime
        leg (COMPONENTS.md "Lifecycle & shutdown"; ordering: admission
        → pump → device → flush → join):

          1. stop admission — new checks/reports resolve a typed
             UNAVAILABLE immediately (never a hang, never a drop);
          2. drain the batchers — queued and in-flight batches run to
             completion, bounded by `deadline` seconds (leftovers past
             it still resolve: CheckBatcher.close flushes, the typed
             rejection path covers the rest);
          3. stop the batchers and flush the telemetry plane (final
             rulestats drain; the canary recorder ring is sampling
             state rebuilt from live traffic — dropped by design);
          4. close the controller — reaps prewarm threads, closes
             handlers, and closes the device quota pools (each pool's
             worker flushes pending allocations before exiting).

        Idempotent; close() is shutdown() with the default grace."""
        if getattr(self, "_shutdown_done", False):
            return
        self._shutdown_done = True
        from istio_tpu.runtime import forensics
        forensics.record_event("shutdown",
                               deadline_s=deadline)
        # flip every background-warm stop flag FIRST (flag-only, no
        # joins): bank prewarms poll _stopping between shapes, and
        # begin_close() stops the controller admitting new rebuilds
        # (a debounce Timer firing now becomes a no-op) and flips the
        # warm threads' flags so they wind down while the fronts drain
        self._stopping = True
        ctrl = getattr(self, "controller", None)
        if ctrl is not None:
            ctrl.begin_close()
        # stop the audit thread first: a mid-teardown evaluation would
        # read surfaces (batchers, pools) as they are being closed
        if getattr(self, "audit", None) is not None:
            self.audit.stop()
        # a still-running initial in-step prewarm must not race
        # interpreter/pool teardown (its dummy trips touch jax state):
        # flip the stop flag (polled between shapes), then reap.
        # Untimed join — the thread exits after at most the in-flight
        # compile; expiring mid-compile would abort teardown anyway.
        self._instep_prewarm_stop = True
        t = getattr(self, "_instep_prewarm_thread", None)
        if t is not None and t.is_alive():
            t.join()
        self.batcher.quiesce()
        if self._report_batcher is not None:
            self._report_batcher.quiesce()
        self.batcher.drain(deadline)
        if self._report_batcher is not None:
            self._report_batcher.drain(deadline)
        self.batcher.close()
        if self._report_batcher is not None:
            self._report_batcher.close()
            # record conservation at quiescence (the ingestion plane's
            # invariant): every record this process ever accepted must
            # by now be exported or typed-rejected — close() resolves
            # every leftover future. Non-zero in_flight here is a
            # silently-dropped record: log it loudly (counters are
            # process-global, so another still-serving RuntimeServer
            # in this process can legitimately hold records — only a
            # negative/positive residue with no other server is a bug;
            # the smoke gate asserts the exact form per scenario).
            from istio_tpu.runtime import monitor as _monitor
            cons = _monitor.report_conservation()
            if not cons["exact"]:
                import logging
                logging.getLogger("istio_tpu.runtime.server").warning(
                    "report record conservation residue at shutdown: "
                    "%s", cons)
        if self._rulestats_drainer is not None:
            self._rulestats_drainer.close()
            try:   # flush whatever the last interval left on device
                self.rulestats.drain()
            except Exception:
                pass
        # executor AFTER the batchers (no more batches can submit host
        # actions) and BEFORE the controller (handlers close last):
        # in-flight adapter calls get a bounded grace, wedged workers
        # are leaked as daemons — never waited on forever. The
        # conservation ledger must read exact at quiescence.
        if self.executor is not None:
            self.executor.close()
            from istio_tpu.runtime import monitor as _monitor
            hc = _monitor.host_action_counters()
            if not hc["exact"]:
                import logging
                logging.getLogger("istio_tpu.runtime.server").warning(
                    "host action conservation residue at shutdown: "
                    "submitted=%d resolved=%d", hc["submitted"],
                    hc["resolved"])
        self.controller.close()

    def close(self) -> None:
        self.shutdown()
