"""Mesh audit plane: live invariant auditing + fault explainability.

Every serving plane carries its own counters, but the invariants that
only emerge under composition — exact report conservation, quota
accounting across device pools and the host oracle, grant/generation
coherence, discovery↔mixer plane agreement — were each verified only
inside their own smoke script, never continuously at runtime. The
AuditPlane here is a background thread, strictly OFF the hot path:
it reads existing counter families and ledgers (monitor.*, the
forensics rings, GrantPolicy.watermark, DeviceQuotaPool.audit_view,
ReplicaRouter.routing_stats) and evaluates six typed mesh-wide
invariants as AuditCheck objects with status ∈ {ok, degraded,
violated}, evidence deltas and the config generation checked at:

  report_conservation    accepted == exported + typed_rejected (the
                         report-plane ledger, audited between scrapes
                         instead of only at shutdown)
  check_accounting       decoded == answered + typed-rejected residue
                         per front (serving + resilience families)
  quota_conservation     device pools' counter cells within bounds +
                         a sampled host memquota-oracle recount
  grant_coherence        no post-revocation grant carries a
                         pre-publish generation (revoke-before-swap,
                         watched live via a generation watermark)
  plane_agreement        analysis/planes equivalence over the
                         CURRENTLY SERVED snapshot pair, memoized by
                         content digest (plus the discovery scope
                         program when a DiscoveryService is attached)
  routing_conservation   routed == folded + misrouted (the replica
                         router's routing_stats fold)

CONSERVATION IS EXACT ONLY AT QUIESCENCE: while requests are in
flight the ledgers legitimately disagree by the in-flight volume, so
a non-zero residue is `degraded` (transient) and only an IMPOSSIBLE
state — negative in-flight, or a residue that sits frozen across
consecutive evaluations beyond what typed rejections account for —
is `violated`.

Violations emit forensics EVENTS (`audit_violation` with the
invariant name + evidence note), bump the zero-shaped `mixer_audit_*`
families and flip the /readyz-adjacent `mixer_audit_healthy` gauge.

The FAULT-EXPLAINABILITY SCORER: every ChaosHooks injection commits
an expected-signature record here (CHAOS.on_inject → the module
InjectionLedger) — wedge → host:<handler> breaker event / exemplar
stage wait; device fault → fallback counter delta or device breaker
event; oracle fault → batch-failure delta; adapter fault → host
error-outcome delta. The auditor matches records against the
forensics rings + counter deltas within a bounded window and
publishes `mixer_fault_explainability_rate` = matched /
(matched + expired-unmatched) — the "every injected fault must be
explainable" soak-gate metric. Vacuously 1.0 with no injections.

SEAMS is a test-only corruption shim: the audit smoke skews one
reading at the auditor's READ side (never the real counters, never
the serving path) to prove the detector fires end to end.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

from istio_tpu.runtime import forensics, monitor
from istio_tpu.utils.log import scope

log = scope("runtime.audit")

OK = "ok"
DEGRADED = "degraded"
VIOLATED = "violated"

INVARIANTS = monitor.AUDIT_INVARIANTS


@dataclass
class AuditCheck:
    """One invariant's verdict at one evaluation."""
    name: str
    status: str = OK
    evidence: dict = field(default_factory=dict)
    generation: int = -1
    wall: float = 0.0
    note: str = ""

    def as_dict(self) -> dict:
        return {"name": self.name, "status": self.status,
                "evidence": self.evidence, "generation": self.generation,
                "wall": self.wall, "note": self.note}


class AuditSeams:
    """Test-only corruption seams, applied at the auditor's READ side.

    The smoke gate needs to prove a corrupted counter flips
    audit_healthy and surfaces evidence over real HTTP — skewing the
    auditor's reading exercises the whole detection path without
    poisoning the process-global families other suites share."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.report_accepted_skew = 0
        self.check_decoded_skew = 0
        self.grant_issue_skew = 0
        self.routing_misrouted_skew = 0
        self.quota_negative_cells_skew = 0
        # extra (name, pilot, mixer) pairs appended to the served
        # snapshot's plane-agreement pair set
        self.plane_pairs_extra: list = []


SEAMS = AuditSeams()


class InjectionLedger:
    """Expected-signature records for ChaosHooks injections.

    note() runs at the injection-commit points (CHAOS.on_inject) —
    it must stay cheap and never raise: one lock round, counter-
    baseline reads, coalescing per (kind, handler) within a short
    window so a hard outage (10^9 armed failures) is one record with
    n=count, not a ring flood."""

    def __init__(self, capacity: int = 256,
                 coalesce_s: float = 1.0) -> None:
        self._lock = threading.Lock()
        self._capacity = capacity
        self._coalesce_s = coalesce_s
        self._records: list[dict] = []
        self._matched_n = 0
        self._expired_n = 0

    def reset(self) -> None:
        with self._lock:
            self._records = []
            self._matched_n = 0
            self._expired_n = 0

    def note(self, kind: str, **detail) -> None:
        try:
            base = self._baseline(kind)
            now = time.perf_counter()
            key = (kind, detail.get("handler", ""))
            with self._lock:
                for rec in reversed(self._records):
                    if (rec["key"] == key and not rec["matched"]
                            and now - rec["t"] <= self._coalesce_s):
                        rec["n"] += 1
                        break
                else:
                    self._records.append({
                        "key": key, "kind": kind,
                        "detail": dict(detail),
                        "t": now, "wall": time.time(), "n": 1,
                        "base": base, "matched": False,
                        "matched_by": "", "expired": False,
                    })
                    if len(self._records) > self._capacity:
                        dropped = self._records.pop(0)
                        if not dropped["matched"] \
                                and not dropped["expired"]:
                            self._expired_n += dropped["n"]
            monitor.FAULT_INJECTIONS.labels(kind=kind).inc()
        except Exception:   # the chaos seam must never observe a raise
            pass

    def _baseline(self, kind: str) -> dict:
        if kind in ("device", "oracle"):
            rc = monitor.resilience_counters()
            return {"fallback_total": rc["fallback_total"],
                    "batch_failures_total": rc["batch_failures_total"]}
        if kind == "discovery":
            # note() fires INSIDE publish, before the generation bump —
            # the baseline is the generation the delayed push started
            # from; evidence is the generation advancing past it (the
            # stalled push completed)
            return {"generation":
                    int(monitor.DISCOVERY_GENERATION.value())}
        hc = monitor.host_action_counters()
        out = hc.get("outcomes", {})
        return {"error": out.get("error", 0),
                "overrun": out.get("overrun", 0),
                "breaker_open": out.get("breaker_open", 0),
                "expired": out.get("expired", 0),
                "retries": hc.get("retries", 0)}

    # -- matching (runs on the audit thread) ---------------------------

    def evaluate(self, window_s: float) -> dict:
        """Match pending records against forensics evidence; expire
        unmatched records older than the window; publish the rate."""
        now = time.perf_counter()
        events = forensics.EVENTS.snapshot(limit=256)
        try:
            exemplars = forensics.RECORDER.snapshot(
                top_k=64)["slowest"]
        except Exception:
            exemplars = []
        rc = monitor.resilience_counters()
        _hc_full = monitor.host_action_counters()
        hc = dict(_hc_full.get("outcomes", {}))
        hc["retries"] = _hc_full.get("retries", 0)
        gen = int(monitor.DISCOVERY_GENERATION.value())
        with self._lock:
            for rec in self._records:
                if rec["matched"] or rec["expired"]:
                    continue
                matched_by = self._signature(rec, events, exemplars,
                                             rc, hc, gen)
                if matched_by:
                    rec["matched"] = True
                    rec["matched_by"] = matched_by
                    self._matched_n += rec["n"]
                    monitor.FAULT_MATCHED.labels(
                        kind=rec["kind"]).inc(rec["n"])
                elif now - rec["t"] > window_s:
                    rec["expired"] = True
                    self._expired_n += rec["n"]
            matched, expired = self._matched_n, self._expired_n
            pending = sum(r["n"] for r in self._records
                          if not r["matched"] and not r["expired"])
            recent = [{k: r[k] for k in ("kind", "detail", "wall", "n",
                                         "matched", "matched_by",
                                         "expired")}
                      for r in self._records[-32:]]
        denom = matched + expired
        rate = matched / denom if denom else 1.0
        monitor.FAULT_EXPLAINABILITY.set(rate)
        return {"rate": round(rate, 4), "matched": matched,
                "unexplained": expired, "pending": pending,
                "records": recent}

    @staticmethod
    def _signature(rec: dict, events: list, exemplars: list,
                   rc: dict, hc: dict, gen: int = 0) -> str:
        """The expected-signature match for one injection record —
        returns the evidence name, or '' while unexplained."""
        kind = rec["kind"]
        t0 = rec["t"] - 0.05           # clock slack: same process
        base = rec["base"]

        def event(kinds, name=None):
            for e in events:
                if e["kind"] in kinds and e["t"] >= t0:
                    if name is None or \
                            e.get("detail", {}).get("name") == name:
                        return e
            return None

        if kind in ("wedge", "adapter"):
            handler = rec["detail"].get("handler", "")
            lane = f"host:{handler}"
            ev = event(("breaker",), name=lane)
            if ev is not None:
                return f"event:breaker {lane}"
            for ex in exemplars:
                if ex.get("wall", 0.0) >= rec["wall"] - 0.05 and \
                        lane in ex.get("stages_ms", {}):
                    return f"exemplar:{lane}"
            if kind == "adapter" and \
                    hc.get("error", 0) > base.get("error", 0):
                return "counter:host_action error"
            if kind == "wedge":
                for oc in ("overrun", "breaker_open", "expired"):
                    if hc.get(oc, 0) > base.get(oc, 0):
                        return f"counter:host_action {oc}"
            return ""
        if kind == "device":
            if rc["fallback_total"] > base.get("fallback_total", 0):
                return "counter:fallback_total"
            ev = event(("breaker",), name="device")
            if ev is not None:
                return "event:breaker device"
            return ""
        if kind == "oracle":
            if rc["batch_failures_total"] > \
                    base.get("batch_failures_total", 0):
                return "counter:batch_failures_total"
            return ""
        if kind == "quota":
            # an injected backend failure rides the executor's mq lane
            # and lands as a typed host-action error outcome; a single
            # transient failure may instead be absorbed by the lane's
            # one jittered retry (outcome ok, retries bumped), and
            # under a storm the lane breaker may absorb the tail
            if hc.get("error", 0) > base.get("error", 0):
                return "counter:host_action error"
            if hc.get("retries", 0) > base.get("retries", 0):
                return "counter:host_action retries"
            handler = rec["detail"].get("handler", "")
            ev = event(("breaker",), name=f"host:{handler}")
            if ev is not None:
                return f"event:breaker host:{handler}"
            for oc in ("overrun", "breaker_open", "expired"):
                if hc.get(oc, 0) > base.get(oc, 0):
                    return f"counter:host_action {oc}"
            return ""
        if kind == "discovery":
            # the delayed publish completed: generation advanced past
            # the mid-publish baseline
            if gen > base.get("generation", 0):
                return "counter:discovery_generation"
            return ""
        return ""


INJECTIONS = InjectionLedger()


def install_chaos_observer() -> None:
    """Point the process-wide chaos seam at the ledger (idempotent).
    Lives outside ChaosHooks.reset() on purpose: the chaos suites
    reset the seam per scenario and the scorer must survive it."""
    from istio_tpu.runtime.resilience import CHAOS
    CHAOS.on_inject = INJECTIONS.note


class AuditPlane:
    """The background auditor. One instance per RuntimeServer,
    started at the end of __init__ and stopped first in shutdown().
    Every read is a snapshot/ledger accessor that takes at most a
    brief bookkeeping lock — the auditor never times, never blocks
    and never writes the serving path."""

    def __init__(self, runtime: Any = None, *,
                 interval_s: float = 0.5,
                 explain_window_s: float = 10.0,
                 quota_every: int = 8,
                 stuck_after: int = 3,
                 stuck_floor_s: float | None = None,
                 max_pairs: int = 128) -> None:
        self.runtime = runtime
        self.interval_s = max(float(interval_s), 0.05)
        self.explain_window_s = float(explain_window_s)
        self.quota_every = max(int(quota_every), 1)
        self.stuck_after = max(int(stuck_after), 2)
        if stuck_floor_s is None:
            # a frozen residue younger than the slowest LEGITIMATE
            # request is transient by definition: cover the serving
            # deadline (a wedged adapter answers typed at deadline,
            # freezing the tuple for that long) plus slack
            deadline_ms = getattr(getattr(runtime, "args", None),
                                  "default_check_deadline_ms",
                                  None) or 0.0
            stuck_floor_s = max(self.stuck_after * self.interval_s,
                                deadline_ms / 1e3 + 0.5, 2.0)
        self.stuck_floor_s = float(stuck_floor_s)
        self.max_pairs = int(max_pairs)
        self._discovery: Any = None
        self._lock = threading.RLock()
        self._checks: dict[str, AuditCheck] = {}
        self._explain: dict = {"rate": 1.0, "matched": 0,
                               "unexplained": 0, "pending": 0,
                               "records": []}
        self._stuck: dict[str, tuple] = {}   # name → (reading, n, t0)
        self._grant_base: tuple | None = None    # (policy gen, revision)
        self._plane_digest: str | None = None
        self._plane_cached: AuditCheck | None = None
        self._quota_cached: AuditCheck | None = None
        self._evaluations = 0
        self._last_wall = 0.0
        self._stop_ev = threading.Event()
        self._thread: threading.Thread | None = None
        install_chaos_observer()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_ev.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mesh-audit")
        self._thread.start()

    def stop(self, deadline_s: float = 2.0) -> None:
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=deadline_s)
        self._thread = None

    close = stop

    def attach_discovery(self, svc: Any) -> None:
        """Fold a DiscoveryService's scope program into the
        plane_agreement check (its pairs re-derive the served routes'
        source constraints against the carried compiled program)."""
        self._discovery = svc
        self._plane_digest = None   # force re-evaluation

    def _run(self) -> None:
        while not self._stop_ev.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:     # the auditor must never die
                log.exception("audit evaluation failed")

    # -- evaluation ----------------------------------------------------

    def evaluate(self) -> dict:
        """One full pass over every invariant + the explainability
        scorer; callable on demand (the introspect handler refreshes
        before serving). Thread-safe; returns the snapshot dict."""
        with self._lock:
            wall = time.time()
            gen = self._generation()
            checks = [
                self._report_conservation(),
                self._check_accounting(),
                self._quota_conservation(),
                self._grant_coherence(),
                self._plane_agreement(),
                self._routing_conservation(),
            ]
            for chk in checks:
                chk.generation = gen
                chk.wall = wall
                monitor.AUDIT_CHECKS.labels(
                    invariant=chk.name, status=chk.status).inc()
                prev = self._checks.get(chk.name)
                if chk.status == VIOLATED and (
                        prev is None or prev.status != VIOLATED):
                    monitor.AUDIT_VIOLATIONS.labels(
                        invariant=chk.name).inc()
                    forensics.record_event(
                        "audit_violation", invariant=chk.name,
                        note=chk.note or chk.status)
                    log.warning("audit violation: %s — %s %s",
                                chk.name, chk.note, chk.evidence)
            self._checks = {c.name: c for c in checks}
            healthy = all(c.status != VIOLATED for c in checks)
            monitor.AUDIT_HEALTHY.set(1.0 if healthy else 0.0)
            monitor.AUDIT_EVALUATIONS.inc()
            self._explain = INJECTIONS.evaluate(self.explain_window_s)
            self._evaluations += 1
            self._last_wall = wall
            return self.snapshot()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "interval_s": self.interval_s,
                "evaluations": self._evaluations,
                "wall": self._last_wall,
                "healthy": all(c.status != VIOLATED
                               for c in self._checks.values()),
                "checks": [self._checks[n].as_dict()
                           for n in INVARIANTS if n in self._checks],
                "explainability": dict(self._explain),
                "counters": monitor.audit_counters(),
            }

    # -- helpers -------------------------------------------------------

    def _generation(self) -> int:
        try:
            return int(
                self.runtime.controller.dispatcher.snapshot.revision)
        except Exception:
            return -1

    def _stuck_state(self, name: str, reading: tuple) -> tuple:
        """(consecutive evaluations, seconds) this invariant's raw
        reading has been frozen. A non-zero in-flight residue that
        never moves is lost rows, not traffic — but only once it has
        been frozen BOTH for stuck_after evaluations AND longer than
        stuck_floor_s: a single wedged request legitimately holds the
        tuple frozen for its full deadline, and back-to-back manual
        evaluations must not promote a transient to violated."""
        now = time.perf_counter()
        prev, n, t0 = self._stuck.get(name, (None, 0, now))
        if reading == prev:
            n += 1
        else:
            n, t0 = 1, now
        self._stuck[name] = (reading, n, t0)
        return n, now - t0

    # -- invariants ----------------------------------------------------

    def _report_conservation(self) -> AuditCheck:
        cons = monitor.report_conservation()
        accepted = cons["accepted"] + SEAMS.report_accepted_skew
        in_flight = accepted - cons["exported"] - cons["rejected_total"]
        ev = {"accepted": accepted, "exported": cons["exported"],
              "rejected": cons["rejected"],
              "rejected_total": cons["rejected_total"],
              "in_flight": in_flight}
        chk = AuditCheck("report_conservation", evidence=ev)
        if in_flight < 0:
            chk.status = VIOLATED
            chk.note = ("more records exported+rejected than the wire "
                        "ever accepted")
        elif in_flight == 0:
            self._stuck.pop(chk.name, None)
        else:
            reading = (accepted, cons["exported"],
                       cons["rejected_total"])
            n, frozen_s = self._stuck_state(chk.name, reading)
            ev["stuck_evaluations"] = n
            ev["frozen_s"] = round(frozen_s, 3)
            if n >= self.stuck_after and \
                    frozen_s >= self.stuck_floor_s:
                chk.status = VIOLATED
                chk.note = (f"{in_flight} records in flight, frozen "
                            f"{frozen_s:.1f}s across {n} evaluations "
                            f"— silently dropped, not in transit")
            else:
                chk.status = DEGRADED
                chk.note = "records in flight (transient)"
        return chk

    def _check_accounting(self) -> AuditCheck:
        sc = monitor.serving_counters()
        rc = monitor.resilience_counters()
        decoded = sc["requests_decoded"] + SEAMS.check_decoded_skew
        sent = sc["responses_sent"]
        in_flight = decoded - sent
        typed = (rc["shed_total"] + rc["expired_total"]
                 + rc["cancelled_shed_total"])
        ev = {"decoded": decoded, "answered": sent,
              "in_flight": in_flight, "shed_total": rc["shed_total"],
              "expired_total": rc["expired_total"],
              "fallback_total": rc["fallback_total"],
              "cancelled_shed_total": rc["cancelled_shed_total"],
              "breaker_state": rc["breaker_state"]}
        chk = AuditCheck("check_accounting", evidence=ev)
        if in_flight < 0:
            chk.status = VIOLATED
            chk.note = "more responses sent than requests decoded"
        elif in_flight == 0:
            self._stuck.pop(chk.name, None)
        else:
            n, frozen_s = self._stuck_state(
                chk.name, (decoded, sent, typed))
            ev["stuck_evaluations"] = n
            ev["frozen_s"] = round(frozen_s, 3)
            if n < self.stuck_after or frozen_s < self.stuck_floor_s:
                chk.status = DEGRADED
                chk.note = "requests in flight (transient)"
            elif in_flight <= typed:
                # a rejected wire RPC decodes without per-row
                # responses; the typed shed/expired counters account
                # for every such row
                chk.note = (f"steady residue {in_flight} covered by "
                            f"typed rejections ({typed})")
            else:
                chk.status = VIOLATED
                chk.note = (f"{in_flight} decoded requests frozen "
                            f"{frozen_s:.1f}s unanswered, only "
                            f"{typed} typed rejections to account "
                            f"for them")
        return chk

    def _quota_conservation(self) -> AuditCheck:
        # the device half pulls counter planes — sampled every Nth
        # evaluation so the auditor's device traffic stays negligible
        # next to serving trips
        if self._quota_cached is not None and \
                self._evaluations % self.quota_every != 0:
            cached = self._quota_cached
            chk = AuditCheck(cached.name, cached.status,
                             dict(cached.evidence), note=cached.note)
            chk.evidence["sampled"] = False
            return chk
        chk = AuditCheck("quota_conservation")
        pools: dict[int, Any] = {}
        handlers: dict[str, Any] = {}
        try:
            dispatcher = self.runtime.controller.dispatcher
            for qname, pool in getattr(self.runtime.controller,
                                       "device_quotas", {}).items():
                pools.setdefault(id(pool), (qname, pool))
            for qname, h in getattr(dispatcher, "handlers",
                                    {}).items():
                backend = getattr(h, "_backend", None)
                if backend is not None and hasattr(backend, "cells"):
                    handlers[qname] = backend
        except Exception:
            pass
        device_ev, problems = {}, []
        for _pid, (qname, pool) in list(pools.items())[:4]:
            try:
                view = pool.audit_view()
            except Exception as exc:
                problems.append(f"{qname}: audit_view failed {exc}")
                continue
            view["negative_cells"] += SEAMS.quota_negative_cells_skew
            device_ev[qname] = view
            if view["negative_cells"] > 0:
                problems.append(f"{qname}: {view['negative_cells']} "
                                f"negative counter cells")
            if view["over_cap_cells"] > 0:
                problems.append(f"{qname}: {view['over_cap_cells']} "
                                f"cells above the window max "
                                f"{view['max_limit']}")
            if view["nonzero_beyond_keymap"] > 0:
                problems.append(f"{qname}: counts outside the "
                                f"allocated keymap")
        host_ev = {}
        from istio_tpu.adapters.memquota import _TICKS_PER_WINDOW
        for qname, backend in list(handlers.items())[:4]:
            cells_checked = 0
            with backend.lock:
                for key, cell in list(backend.cells.items())[:256]:
                    cells_checked += 1
                    count = getattr(cell, "count", None)
                    if count is not None:      # exact cell
                        if not 0 <= count <= cell.max:
                            problems.append(
                                f"{qname}/{key}: exact count {count} "
                                f"outside [0, {cell.max}]")
                        continue
                    ticks = getattr(cell, "ticks", None)
                    if not ticks:
                        continue
                    if any(v < 0 for v in ticks.values()):
                        problems.append(
                            f"{qname}/{key}: negative tick amount")
                    newest = max(ticks)
                    recent = sum(v for t, v in ticks.items()
                                 if t > newest - _TICKS_PER_WINDOW)
                    if recent > cell.max:
                        problems.append(
                            f"{qname}/{key}: in-window usage "
                            f"{recent} > max {cell.max}")
            host_ev[qname] = {"cells_checked": cells_checked}
        chk.evidence = {"device_pools": device_ev,
                        "host_backends": host_ev, "sampled": True}
        if problems:
            chk.status = VIOLATED
            chk.note = "; ".join(problems[:4])
            chk.evidence["problems"] = problems[:16]
        self._quota_cached = chk
        return chk

    def _grant_coherence(self) -> AuditCheck:
        chk = AuditCheck("grant_coherence")
        policy = getattr(self.runtime, "grants", None)
        if policy is None:
            chk.evidence = {"enabled": False}
            return chk
        wm = policy.watermark()
        issued_at = wm["issued_at_generation"] + SEAMS.grant_issue_skew
        revision = self._generation()
        if self._grant_base is None:
            self._grant_base = (wm["generation"], revision)
        base_gen, base_rev = self._grant_base
        d_gen = wm["generation"] - base_gen
        d_rev = revision - base_rev
        chk.evidence = {"enabled": True,
                        "policy_generation": wm["generation"],
                        "issued_at_generation": issued_at,
                        "revocations": wm["revocations"],
                        "grants_issued": wm["grants_issued"],
                        "publishes_since_audit_start": d_rev,
                        "revocations_since_audit_start": d_gen}
        if issued_at > wm["generation"]:
            chk.status = VIOLATED
            chk.note = (f"a grant was issued at generation "
                        f"{issued_at}, beyond the policy watermark "
                        f"{wm['generation']}")
        elif 0 <= d_rev and d_gen < d_rev:
            # revoke-before-swap broken: a snapshot published without
            # the grant policy revoking first, so outstanding client
            # caches carry pre-publish TTLs
            chk.status = VIOLATED
            chk.note = (f"{d_rev} publishes but only {d_gen} "
                        f"revocations since audit start — a publish "
                        f"did not revoke before its swap")
        return chk

    def _plane_agreement(self) -> AuditCheck:
        from istio_tpu.compiler.cache import stable_digest

        pairs: list = []
        finder = None
        try:
            snap = self.runtime.controller.dispatcher.snapshot
            finder = snap.finder
            for i in range(min(snap.n_config_rules, self.max_pairs)):
                compiled = snap.ruleset.rules[i]
                config_text = (snap.rules[i].match or "").strip() \
                    or "true"
                pairs.append((compiled.name, config_text,
                              compiled.ast if compiled.ast is not None
                              else (compiled.match.strip() or "true")))
        except Exception:
            pass
        pairs.extend(SEAMS.plane_pairs_extra)
        disc_pairs: list = []
        svc = self._discovery
        if svc is not None:
            try:
                disc_pairs = svc._snapshot.scope_audit_pairs(
                    limit=self.max_pairs)
            except Exception:
                disc_pairs = []
        digest = stable_digest([
            [(n, str(a), str(b)) for n, a, b in pairs],
            [(n, str(a), str(b)) for n, a, b in disc_pairs]])
        if digest == self._plane_digest \
                and self._plane_cached is not None:
            cached = self._plane_cached
            chk = AuditCheck(cached.name, cached.status,
                             dict(cached.evidence), note=cached.note)
            chk.evidence["memoized"] = True
            return chk
        chk = AuditCheck("plane_agreement")
        findings = []
        try:
            from istio_tpu.analysis.planes import check_plane_pairs
            if pairs and finder is not None:
                findings += check_plane_pairs(pairs, finder)
            if disc_pairs:
                from istio_tpu.pilot.route_nfa import ROUTE_FINDER
                findings += check_plane_pairs(disc_pairs, ROUTE_FINDER)
        except Exception as exc:
            chk.status = DEGRADED
            chk.note = f"plane check failed: {exc}"
            chk.evidence = {"n_pairs": len(pairs) + len(disc_pairs)}
            return chk
        from istio_tpu.analysis.findings import Severity
        errors = [f for f in findings if f.severity == Severity.ERROR]
        warns = [f for f in findings if f.severity == Severity.WARNING]
        chk.evidence = {
            "n_pairs": len(pairs), "n_discovery_pairs": len(disc_pairs),
            "digest": digest[:16], "memoized": False,
            "findings": [{"code": f.code, "message": f.message}
                         for f in (errors + warns)[:8]],
        }
        if errors:
            chk.status = VIOLATED
            chk.note = (f"{len(errors)} witness-confirmed divergences "
                        f"between the served planes")
        elif warns:
            chk.status = DEGRADED
            chk.note = f"{len(warns)} pairs unproven"
        self._plane_digest = digest
        self._plane_cached = chk
        return chk

    def _routing_conservation(self) -> AuditCheck:
        chk = AuditCheck("routing_conservation")
        router = getattr(self.runtime, "_replica_router", None)
        if router is None:
            chk.evidence = {"enabled": False}
            return chk
        stats = router.routing_stats()
        misrouted = stats["misrouted"] + SEAMS.routing_misrouted_skew
        chk.evidence = {"enabled": True,
                        "rows_total": stats["rows_total"],
                        "rows_per_shard": stats["rows_per_shard"],
                        "misrouted": misrouted}
        if misrouted > 0:
            # the shard router counts a misroute then RAISES — any
            # non-zero count means rows reached a bank that does not
            # own their namespace
            chk.status = VIOLATED
            chk.note = f"{misrouted} rows misrouted across shards"
        return chk
