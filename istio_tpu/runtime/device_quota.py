"""Device-backed quota pools for the serving path.

Reference flow (mixer/pkg/api/grpcServer.go:188-230): after a
successful precondition Check, the server walks the request's quotas
map and dispatches each to the one matching quota action. The host
path re-resolves rules per quota call — a full device round-trip per
request on this build. This module replaces that with:

  host dedup-replay cache  (memquota.go:259 buildWithDedup semantics)
        │ miss
  exact dims→bucket keymap (the host assigns each distinct instance
        │                   key its own counter row — no hash collisions
        │                   conflating cells)
  batched device scatter-add alloc (models/quota_alloc.py, one XLA
                                    step per batch window)

Rule matching reuses the CHECK step's activity bits: the fused plan
exposes which quota-bearing rules matched each request
(CheckResponse.active_quota_rules), so the quota loop never re-resolves.

Windowing (r4): ROLLING windows with host-adapter parity — counters
are per-(bucket, tick-slot) planes; each flush rolls the touched
buckets (reclaiming slots whose ticks left the window) before
allocating, exactly like adapters/memquota._Window (the reference's
rollingWindow.go quantized to _TICKS_PER_WINDOW slots per window).
Exact counters (duration 0) live in slot 0 of the same plane and match
the host `_Exact` cell; the parity tests pin both, plus dedup replay
and best-effort semantics, against MemQuotaHandler under an injected
clock.

State is per-replica and best-effort, like the reference. Pools are
REUSED across config generations when the (handler signature, quota
name) is unchanged — handlerTable.go's signature diffing applied to
counter state.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from istio_tpu.adapters.memquota import _TICKS_PER_WINDOW
from istio_tpu.adapters.memquota import _key as dims_key
from istio_tpu.adapters.sdk import QuotaArgs, QuotaResult
from istio_tpu.models.policy_engine import RESOURCE_EXHAUSTED
from istio_tpu.models.quota_alloc import make_rolling_alloc_step
from istio_tpu.utils.log import scope

log = scope("runtime.device_quota")

DEFAULT_BUCKETS = 131_072    # BASELINE config 4: 100k-key counter eval


class DeviceQuotaPool:
    """Counters for every quota name of ONE memquota handler config.

    Bucket space is shared: each distinct (name, dimensions) instance
    key gets the next free row, so 100k live keys need ~100k rows
    regardless of how many quota names the handler defines."""

    def __init__(self, quotas: Mapping[str, Mapping[str, Any]],
                 n_buckets: int = DEFAULT_BUCKETS,
                 min_dedup_s: float = 1.0,
                 # a WIDE window: every flush is a device round-trip
                 # that contends with check batches for the transport
                 # (profiled: 0.5ms windows fragmented the device path
                 # into dozens of tiny trips and halved served
                 # throughput); +10ms on a quota grant is noise next
                 # to the trip itself
                 batch_window_s: float = 0.010,
                 max_batch: int = 512,
                 clock: Callable[[], float] = time.monotonic,
                 jit: bool = True):
        self.limits = {str(n): {"max": int(q.get("max_amount", 0)),
                                "duration": float(
                                    q.get("valid_duration_s", 0.0))}
                       for n, q in quotas.items()}
        self.n_buckets = n_buckets
        self.min_dedup_s = min_dedup_s
        self._clock = clock
        self._lock = threading.Lock()
        self._bucket_of: dict[str, int] = {}
        self._dedup: dict[str, tuple[int, float]] = {}
        # rolling-window bookkeeping (host side): tick length per
        # bucket (0 = exact cell), the last tick each bucket rolled to
        # (absolute), and a per-bucket tick base so device ticks stay
        # small rebased int32s while HOST tick boundaries (floor of
        # absolute now / tick_len) match adapters/memquota._Window
        # exactly
        self.k_ticks = _TICKS_PER_WINDOW
        self._tick_len: np.ndarray = np.zeros(n_buckets, np.float64)
        self._last_tick: np.ndarray = np.zeros(n_buckets, np.int64)
        self._tick_base: np.ndarray = np.zeros(n_buckets, np.int64)
        self.counts = jnp.zeros((n_buckets, self.k_ticks), jnp.int32)
        # scan is the sequential parity oracle; the SERVING path only
        # ever selects fast/unit/seg (all parallel — VERDICT r4 item
        # 4: a hot key + amount=5 used to stall the transport for
        # ~177ms in the O(B) scan)
        (self._alloc_scan, self._alloc_fast, self._alloc_unit,
         self._alloc_seg) = \
            make_rolling_alloc_step(n_buckets, self.k_ticks, jit=jit)
        # pending batched allocations: [(bucket, amount, best_effort,
        # max, future)]
        self._pending: list = []
        self._window_s = batch_window_s
        self._max_batch = max_batch
        self._small_batch = min(64, max_batch)
        self._wake = threading.Condition(self._lock)
        self._closed = False
        # counter-buffer ownership token: `counts` is mutated by the
        # worker's flush AND by in-step sessions (quota alloc riding
        # the check trip, see inline_begin). Sessions hold it only
        # from stage to DISPATCH (the successor buffer is swapped in
        # as a device future — trips chain on-device, so two pumps'
        # trips overlap on the transport while the data dependency
        # resolves in XLA). Lock order: ALWAYS _counts_lock then
        # self._lock (inline_begin and the worker's _flush both) —
        # taking self._lock first would deadlock against them.
        self._counts_lock = threading.Lock()
        # in-step commit ordering: bookkeeping (dedup-cache writes,
        # pending-dedup replays) must apply in DISPATCH order even
        # though pulls race — sessions take numbered turns
        self._seq_next = 0
        self._commit_cv = threading.Condition(threading.Lock())
        self._commit_turn = 0
        # dedup ids consumed by a dispatched-but-uncommitted session:
        # a same-id row staged meanwhile must NOT re-consume — it
        # resolves from the cache at its own (later) commit turn
        self._dedup_pending: dict[str, int] = {}
        # dedup ids whose consuming session committed GATE-OFF (rule
        # inactive → granted freely, nothing consumed, nothing in
        # _dedup — consumed outcomes only): id → expiry. A pending
        # replay that finds its id here replays grant-freely instead
        # of failing "quota trip failed" (ADVICE r5 parity gap).
        self._dedup_free: dict[str, float] = {}
        # last known-good counter handle (restore target when a
        # dispatched trip's pull fails)
        self._counts_good = self.counts
        # compile every program the serving path can hit (both pad
        # shapes × the serving alloc variants: fast/unit/seg)
        # BEFORE the worker starts — a first-quota-batch compile
        # mid-serve stalls every pending quota future behind it for
        # seconds behind a device tunnel (observed r4: 60s quota waits
        # from variable-shape compiles). Running here, pre-thread,
        # also keeps `counts` single-owner: only __init__ and the
        # worker ever touch it.
        self._prewarm()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="device-quota")
        self._thread.start()

    # -- public ---------------------------------------------------------

    def knows(self, name: str) -> bool:
        return name in self.limits

    def alloc(self, name: str, instance: Mapping[str, Any],
              args: QuotaArgs) -> "QuotaFuture":
        """Non-blocking; returns a future resolving to QuotaResult."""
        fut = QuotaFuture()
        lim = self.limits.get(name)
        if lim is None:
            fut.set(QuotaResult(granted_amount=0,
                                status_code=RESOURCE_EXHAUSTED,
                                status_message=f"unknown quota {name}"))
            return fut
        now = self._clock()
        with self._lock:
            self._gc_dedup(now)
            if args.dedup_id:
                hit = self._dedup.get(args.dedup_id)
                if hit is not None and hit[1] > now:
                    status = 0 if hit[0] > 0 or args.quota_amount == 0 \
                        else RESOURCE_EXHAUSTED
                    fut.set(QuotaResult(granted_amount=hit[0],
                                        valid_duration_s=lim["duration"],
                                        status_code=status))
                    return fut
                free_exp = self._dedup_free.get(args.dedup_id)
                if free_exp is not None and free_exp > now:
                    # first transmission committed GATE-OFF (granted
                    # freely, nothing consumed): dedup-id semantics
                    # replay that outcome on EVERY path — consuming
                    # fresh here would double-book the retransmission
                    fut.set(QuotaResult(
                        granted_amount=args.quota_amount))
                    return fut
            if self._closed:   # post-swap drain raced the caller
                fut.set(QuotaResult(
                    granted_amount=0, status_code=14,  # UNAVAILABLE
                    status_message="quota pool closed by config swap"))
                return fut
            bucket = self._bucket_for(dims_key(instance), lim, now)
            if bucket < 0:   # keyspace exhausted: fail closed
                fut.set(QuotaResult(
                    granted_amount=0, status_code=RESOURCE_EXHAUSTED,
                    status_message="quota keyspace exhausted"))
                return fut
            self._pending.append((bucket, int(args.quota_amount),
                                  bool(args.best_effort), lim["max"],
                                  lim["duration"], args.dedup_id, fut))
            # wake on the empty→non-empty edge (the worker idles in a
            # 100ms poll otherwise — a silent +100ms on every
            # low-rate quota RPC) and when a full batch is ready
            if len(self._pending) == 1 \
                    or len(self._pending) >= self._max_batch:
                self._wake.notify()
        return fut

    def inline_begin(self, n: int, rows: list, now: float
                     ) -> "InlineQuotaSession | None":
        """Stage in-step quota rows for ONE check trip (the quota
        alloc rides the packed check program instead of its own
        serialized device trip — FusedPlan.packed_check_instep).

        `rows`: [(slot, name, instance, args)], slot < n indexing the
        check batch row (at most one quota per row — callers defer
        multi-quota requests to the classic pool path). Returns a
        session HOLDING the pool's counter token until commit/abort,
        or None when the pool is closed (callers fall back). Rows
        resolved without the trip — dedup replays, unknown quota
        names, keyspace exhaustion — land in session.early and their
        array rows stay inactive; in-batch duplicate dedup ids replay
        the first row's outcome at commit (the _flush first_of rule).
        """
        self._counts_lock.acquire()
        sess = InlineQuotaSession(self, n)
        try:
            with self._lock:
                if self._closed:
                    self._counts_lock.release()
                    return None
                sess.seq = self._seq_next
                self._seq_next += 1
                sess.prev_counts = self.counts
                self._gc_dedup(now)
                first_of: dict[str, int] = {}
                for slot, name, instance, args in rows:
                    lim = self.limits.get(name)
                    if lim is None:
                        sess.early[slot] = QuotaResult(
                            granted_amount=0,
                            status_code=RESOURCE_EXHAUSTED,
                            status_message=f"unknown quota {name}")
                        continue
                    did = args.dedup_id
                    if did:
                        hit = self._dedup.get(did)
                        if hit is not None and hit[1] > now:
                            status = 0 if hit[0] > 0 or \
                                args.quota_amount == 0 \
                                else RESOURCE_EXHAUSTED
                            sess.early[slot] = QuotaResult(
                                granted_amount=hit[0],
                                valid_duration_s=lim["duration"],
                                status_code=status)
                            continue
                        free_exp = self._dedup_free.get(did)
                        if free_exp is not None and free_exp > now:
                            # gate-off outcome replay (see alloc)
                            sess.early[slot] = QuotaResult(
                                granted_amount=int(
                                    args.quota_amount))
                            continue
                        if did in first_of:
                            sess.replay_of[slot] = (first_of[did],
                                                    lim["duration"])
                            continue
                        if did in self._dedup_pending:
                            # consumed by a dispatched-but-uncommitted
                            # session: resolve from the cache at OUR
                            # (later) commit turn — never re-consume
                            sess.pending_replay[slot] = \
                                (did, lim["duration"],
                                 int(args.quota_amount))
                            continue
                    bucket = self._bucket_for(dims_key(instance),
                                              lim, now)
                    if bucket < 0:
                        sess.early[slot] = QuotaResult(
                            granted_amount=0,
                            status_code=RESOURCE_EXHAUSTED,
                            status_message="quota keyspace exhausted")
                        continue
                    if did:
                        first_of[did] = slot
                        self._dedup_pending[did] = sess.seq
                    sess.stage(slot, bucket, args, lim, did, now)
            sess.now = now
            return sess
        except BaseException:
            self._counts_lock.release()
            if sess.seq >= 0:   # consume the turn or later sessions wedge
                sess._take_turn()
                sess._end_turn()
            raise

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._wake.notify()
        self._thread.join(timeout=5)
        # the worker flushes pending work before exiting; anything
        # still queued (worker died) must not hang callers
        with self._lock:
            leftovers, self._pending = self._pending, []
        for *_x, fut in leftovers:
            fut.set(QuotaResult(granted_amount=0, status_code=14,
                                status_message="quota pool closed"))

    def audit_view(self) -> dict:
        """Sampled counter-plane reading for the mesh audit plane
        (runtime/audit.py quota_conservation). Copies the CURRENT
        counter handle reference and host bookkeeping under the locks
        (briefly, in the documented _counts_lock→_lock order), then
        pulls OUTSIDE both: counter arrays are functional — every trip
        swaps the pool onto a NEW handle rather than mutating this one
        — so a blocked pull only ever delays the auditor, never the
        serving path. Returns raw facts; the auditor judges. Cell
        invariants that hold regardless of tick staleness: every cell
        is >= 0, every cell is <= the pool's largest window max (each
        alloc caps in-window usage at max, so no single slot can ever
        accrue more), and cells beyond the allocated bucket range are
        exactly 0. The exact used<=max recount runs against the HOST
        memquota oracle (adapters/memquota._Window.used), which owns
        window gc — raw device row sums may legitimately include
        not-yet-reclaimed slots from expired ticks."""
        with self._counts_lock:
            with self._lock:
                handle = self.counts
                n_used = len(self._bucket_of)
        arr = np.asarray(handle)
        max_limit = max((l["max"] for l in self.limits.values()),
                        default=0)
        used = arr[:n_used] if n_used else arr[:0]
        beyond = arr[n_used:]
        return {
            "n_buckets": self.n_buckets,
            "n_used": n_used,
            "max_limit": int(max_limit),
            "negative_cells": int((arr < 0).sum()),
            "max_cell": int(used.max()) if used.size else 0,
            "over_cap_cells": int((used > max_limit).sum())
            if used.size else 0,
            "nonzero_beyond_keymap": int((beyond != 0).sum()),
        }

    # -- internals ------------------------------------------------------

    def _prewarm(self) -> None:
        # every program the SERVING path can hit; the scan oracle is
        # deliberately absent (never serving-selected, so its compile
        # would be pure startup cost)
        for pn in {self._small_batch, self._max_batch}:
            zeros_i = jnp.zeros(pn, jnp.int32)
            zeros_b = jnp.zeros(pn, bool)
            for fn in (self._alloc_seg, self._alloc_fast,
                       self._alloc_unit):
                # all-inactive batch: grants nothing, counters unchanged
                _, self.counts = fn(self.counts, zeros_i, zeros_i,
                                    zeros_b, zeros_i, zeros_b,
                                    zeros_i, zeros_i, zeros_b)
        jax.block_until_ready(self.counts)

    def _bucket_for(self, key: str, lim: Mapping[str, Any],
                    now: float) -> int:
        b = self._bucket_of.get(key)
        if b is None:
            if len(self._bucket_of) >= self.n_buckets:
                return -1
            b = len(self._bucket_of)
            self._bucket_of[key] = b
            dur = lim["duration"]
            if dur > 0:
                tl = dur / self.k_ticks    # _Window.tick_len parity
                tick0 = int(now / tl)
                self._tick_len[b] = tl
                self._tick_base[b] = tick0
                self._last_tick[b] = tick0
        return b

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._wake.wait(timeout=0.1)
                if self._closed and not self._pending:
                    return
                # batch-window timing is a TRANSPORT concern — always
                # wall clock. The injectable self._clock is quota
                # SEMANTICS (window ticks, dedup expiry); driving the
                # collect loop with it meant a frozen test clock never
                # expired the window and futures hung once arrivals
                # stopped short of a full batch
                deadline = time.monotonic() + self._window_s
                while (len(self._pending) < self._max_batch
                       and not self._closed):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._wake.wait(timeout=remaining)
                batch = self._pending[:self._max_batch]
                del self._pending[:len(batch)]
            if batch:
                try:
                    self._flush(batch)
                except Exception as exc:   # pragma: no cover
                    log.exception("quota flush failed")
                    for *_x, fut in batch:
                        fut.set(QuotaResult(
                            granted_amount=0, status_code=13,
                            status_message=f"quota alloc failed: {exc}"))

    def _flush(self, batch: list) -> None:
        now = self._clock()
        # mesh event timeline (runtime/forensics.py): a flush trip is
        # a control-plane event a concurrent request's tail can ride
        # behind; coalesced so a quota-heavy window is one ring entry
        from istio_tpu.runtime import forensics
        forensics.record_event("quota_flush", coalesce_s=0.25,
                               items=len(batch))
        # dedup WITHIN the window too: a sidecar retransmission can land
        # in the same batch as its original, before _flush has written
        # the dedup cache — memquota's mutex serializes those, replaying
        # the first outcome without consuming (buildWithDedup :259)
        first_of: dict[str, int] = {}
        replay_items: list[tuple[Any, int]] = []   # (item, kept index)
        cache_replays: list = []   # (item, cached granted)
        free_replays: list = []    # gate-off outcome: grant freely
        deferred: list = []   # dedup id held by an uncommitted session
        kept: list = []
        with self._lock:
            for item in batch:
                dedup_id = item[5]
                if dedup_id:
                    # re-check the cache under the lock: a
                    # retransmission that raced the ORIGINAL's flush
                    # (alloc() checked before the cache was written)
                    # must replay, not re-consume
                    hit = self._dedup.get(dedup_id)
                    if hit is not None and hit[1] > now:
                        cache_replays.append((item, hit[0]))
                        continue
                    free_exp = self._dedup_free.get(dedup_id)
                    if free_exp is not None and free_exp > now:
                        # gate-off outcome: replay grant-freely (the
                        # deferred-past-a-gate-off-commit case lands
                        # here on its re-flush)
                        free_replays.append(item)
                        continue
                    if dedup_id in self._dedup_pending:
                        # consumed by a dispatched-but-uncommitted
                        # in-step session: memquota's mutex would
                        # serialize and REPLAY — defer this item past
                        # the session's commit (re-queued below; the
                        # next flush resolves it from the cache, or
                        # consumes fresh if the session aborted)
                        deferred.append(item)
                        continue
                    if dedup_id in first_of:
                        replay_items.append((item, first_of[dedup_id]))
                        continue
                    first_of[dedup_id] = len(kept)
                kept.append(item)
        for (_, amount, _, _, duration, _, fut), g in cache_replays:
            status = 0 if g > 0 or amount == 0 else RESOURCE_EXHAUSTED
            fut.set(QuotaResult(granted_amount=g,
                                valid_duration_s=duration,
                                status_code=status))
        for (_, amount, *_rest, fut) in free_replays:
            fut.set(QuotaResult(granted_amount=amount))
        batch = kept
        if not batch:
            self._requeue_deferred(deferred)
            return
        n = len(batch)
        # pad to one of TWO fixed shapes: every distinct shape is its
        # own XLA compile (multi-second behind a device tunnel), and a
        # mid-serve compile stalls every quota future behind it past
        # client deadlines (observed r4: variable pow-2 pads produced a
        # fresh compile per arrival-burst size and 60s quota waits)
        pn = self._small_batch if n <= self._small_batch \
            else self._max_batch
        buckets = np.zeros(pn, np.int32)
        amounts = np.zeros(pn, np.int32)
        be = np.zeros(pn, bool)
        mx = np.zeros(pn, np.int32)
        active = np.zeros(pn, bool)
        ticks = np.zeros(pn, np.int32)
        lasts = np.zeros(pn, np.int32)
        rolling = np.zeros(pn, bool)
        # The tick/last staging and the roll application MUST happen
        # under _lock INSIDE the _counts_lock critical section, ordered
        # exactly like InlineQuotaSession.stage (ADVICE r5): _last_tick
        # is shared with in-step sessions, and a flush that read it
        # outside the locks could stage a stale `last` (the device
        # kernel then re-rolls slots holding fresh consumption — an
        # over-grant) or regress it after a session's optimistic
        # advance (under-grant). _counts_lock serializes this trip
        # against session dispatch; _lock orders the host bookkeeping.
        # The update is OPTIMISTIC like stage()'s: the dispatched
        # program rolls every active row's bucket unconditionally, so
        # host _last_tick and the device slots agree for whatever trip
        # chains next, on either path.
        with self._counts_lock:
            with self._lock:
                for i, (b_, a_, e_, m_, *_rest) in enumerate(batch):
                    buckets[i], amounts[i], be[i], mx[i] = \
                        b_, a_, e_, m_
                    active[i] = True
                    tl = self._tick_len[b_]
                    if tl > 0:
                        # absolute tick boundary = host adapter's
                        # _Window (floor(now / tick_len)); device gets
                        # REBASED int32s
                        abs_tick = int(now / tl)
                        base = int(self._tick_base[b_])
                        ticks[i] = abs_tick - base
                        lasts[i] = int(self._last_tick[b_]) - base
                        rolling[i] = True
                        self._last_tick[b_] = abs_tick
            # sequential-within-batch semantics only matter when a
            # bucket repeats — rare at 100k-key scale. Contended
            # batches where every amount is 1 (the dominant rate-limit
            # shape) take the parallel rank kernel; other contended
            # batches the segmented prefix-sum kernel (deterministic
            # ao-before-be amount-ascending intra-window order —
            # quota_alloc.step_seg). The O(B) scan is a test/bench
            # parity oracle only: NO serving-reachable input selects
            # it.
            if len(np.unique(buckets[:n])) < n:
                all_unit = bool((amounts[:n] == 1).all())   # hotpath: sync-ok (host numpy)
                alloc = self._alloc_unit if all_unit \
                    else self._alloc_seg
            else:
                alloc = self._alloc_fast
            granted, self.counts = alloc(
                self.counts, jnp.asarray(buckets),
                jnp.asarray(amounts), jnp.asarray(be),
                jnp.asarray(mx), jnp.asarray(active),
                jnp.asarray(ticks), jnp.asarray(lasts),
                jnp.asarray(rolling))
            # the worker's designated pull — hotpath: sync-ok
            granted = np.asarray(granted)   # hotpath: sync-ok
        with self._lock:
            for i, (_, amount, _, _, duration, dedup_id, fut) \
                    in enumerate(batch):
                g = int(granted[i])
                if dedup_id:
                    expiry = now + max(duration, self.min_dedup_s)
                    self._dedup[dedup_id] = (g, expiry)
                status = 0 if g > 0 or amount == 0 \
                    else RESOURCE_EXHAUSTED
                fut.set(QuotaResult(granted_amount=g,
                                    valid_duration_s=duration,
                                    status_code=status))
        for (_, amount, _, _, duration, _, fut), k in replay_items:
            g = int(granted[k])
            status = 0 if g > 0 or amount == 0 else RESOURCE_EXHAUSTED
            fut.set(QuotaResult(granted_amount=g,
                                valid_duration_s=duration,
                                status_code=status))
        self._requeue_deferred(deferred)

    def _requeue_deferred(self, deferred: list) -> None:
        """Items whose dedup id was held by a dispatched-but-
        uncommitted in-step session: re-queue for the next flush (the
        session commits in its dispatch-order turn — typically within
        one device trip — after which the cache replays the outcome,
        or a fresh consume runs if the session aborted). A closing
        pool resolves them immediately instead of spinning."""
        if not deferred:
            return
        with self._lock:
            if not self._closed:
                self._pending.extend(deferred)
                self._wake.notify()
                return
        for *_x, fut in deferred:
            fut.set(QuotaResult(granted_amount=0, status_code=14,
                                status_message="quota pool closed"))

    def _gc_dedup(self, now: float) -> None:
        if len(self._dedup) > 10_000:
            for k in [k for k, (_, exp) in self._dedup.items()
                      if exp <= now]:
                del self._dedup[k]
        if len(self._dedup_free) > 10_000:
            for k in [k for k, exp in self._dedup_free.items()
                      if exp <= now]:
                del self._dedup_free[k]


class QuotaFuture:
    """Tiny thread-safe future. The sync gRPC front blocks in
    result(); the aio front registers a callback via add_done_callback
    and awaits — holding an executor thread per in-flight quota would
    serialize the event loop behind ~5 threads × a device RTT each
    (observed: served throughput collapsed 6× when it did)."""

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._value: QuotaResult | None = None
        self._cbs: list = []
        self._lock = threading.Lock()

    def set(self, value: QuotaResult) -> None:
        with self._lock:
            self._value = value
            self._ev.set()
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            try:
                cb(value)
            except Exception:   # callbacks must not kill the worker
                log.exception("quota future callback failed")

    def add_done_callback(self, cb) -> None:
        """cb(QuotaResult) — fires immediately if already resolved,
        else from the pool worker thread on set()."""
        with self._lock:
            if not self._ev.is_set():
                self._cbs.append(cb)
                return
            value = self._value
        cb(value)

    def result(self, timeout: float | None = 30.0) -> QuotaResult:
        if not self._ev.wait(timeout):
            raise TimeoutError("quota allocation timed out")
        assert self._value is not None
        return self._value

    def done(self) -> bool:
        return self._ev.is_set()


class InlineQuotaSession:
    """One check trip's staged in-step quota work (pipelined).

    Lifecycle: inline_begin (stage, token held) → dispatched(new)
    (pool.counts swaps to the trip's DEVICE FUTURE and the token
    releases — the next trip chains on-device, so trips overlap on
    the transport) → commit(granted, gate) in dispatch order (the
    commit turn serializes dedup-cache writes and pending replays).
    Tick bookkeeping is optimistic at stage time: the dispatched
    program rolls every staged row's bucket unconditionally (only the
    ALLOC is gated via zeroed amounts), so host _last_tick and device
    slots agree for chained trips. A trip that fails AFTER dispatch
    restores the last known-good counter handle; its optimistic tick
    advances then under-grant (never over-grant) for at most one
    window — the documented device-failure tradeoff.

    Result parity (memquota/dispatcher semantics): gate-off rows grant
    the requested amount freely WITHOUT consuming (dispatcher.quota's
    no-matching-rule tail); dedup ids cache only consumed outcomes."""

    def __init__(self, pool: DeviceQuotaPool, n: int) -> None:
        self.pool = pool
        self.n = n
        self.now = 0.0
        self.seq = -1
        self.prev_counts: Any = None
        self.new_counts: Any = None
        self.early: dict[int, QuotaResult] = {}
        self.replay_of: dict[int, tuple[int, float]] = {}
        # slot → (dedup id, duration, requested amount): same-id rows
        # racing a dispatched-but-uncommitted session
        self.pending_replay: dict[int, tuple[str, float, int]] = {}
        self._staged: dict[int, tuple] = {}   # slot → (amount, dur, did)
        self.buckets = np.zeros(n, np.int32)
        self.amounts = np.zeros(n, np.int32)
        self.be = np.zeros(n, bool)
        self.mx = np.zeros(n, np.int32)
        self.active = np.zeros(n, bool)
        self.ticks = np.zeros(n, np.int32)
        self.lasts = np.zeros(n, np.int32)
        self.rolling = np.zeros(n, bool)
        self._token_held = True
        self._done = False

    def stage(self, slot: int, bucket: int, args: QuotaArgs,
              lim: Mapping[str, Any], dedup_id: str,
              now: float) -> None:
        """Called under pool._lock (inline_begin)."""
        p = self.pool
        self.buckets[slot] = bucket
        self.amounts[slot] = int(args.quota_amount)
        self.be[slot] = bool(args.best_effort)
        self.mx[slot] = lim["max"]
        self.active[slot] = True
        tl = p._tick_len[bucket]
        if tl > 0:
            abs_tick = int(now / tl)
            base = int(p._tick_base[bucket])
            self.ticks[slot] = abs_tick - base
            self.lasts[slot] = int(p._last_tick[bucket]) - base
            self.rolling[slot] = True
            # OPTIMISTIC: the dispatched program rolls this bucket to
            # abs_tick regardless of the alloc gate — chained trips
            # must stage against the post-roll state
            p._last_tick[bucket] = abs_tick
        self._staged[slot] = (int(args.quota_amount), lim["duration"],
                              dedup_id)

    def dispatched(self, new_counts) -> None:
        """The program is in flight: swap the pool onto its output
        future and release the token — the next trip chains on it."""
        self.new_counts = new_counts
        self.pool.counts = new_counts
        self._token_held = False
        self.pool._counts_lock.release()

    def _take_turn(self) -> None:
        cv = self.pool._commit_cv
        with cv:
            while self.pool._commit_turn != self.seq:
                cv.wait(timeout=1.0)

    def _end_turn(self) -> None:
        cv = self.pool._commit_cv
        with cv:
            self.pool._commit_turn = self.seq + 1
            cv.notify_all()

    def commit(self, granted: np.ndarray, gate: np.ndarray
               ) -> dict[int, QuotaResult]:
        """granted/gate: the pulled per-row outputs. Returns
        {slot → QuotaResult} for staged/replay/pending rows (merge
        with .early for the full picture)."""
        p = self.pool
        out: dict[int, QuotaResult] = {}
        self._take_turn()
        try:
            with p._lock:
                p._counts_good = self.new_counts
                for slot, (amount, duration, did) in \
                        self._staged.items():
                    if did:
                        p._dedup_pending.pop(did, None)
                    if not gate[slot]:
                        # no active quota rule for this request: grant
                        # the requested amount freely, consuming
                        # nothing (dispatcher.quota tail). The outcome
                        # is recorded in _dedup_free (NOT the consumed-
                        # outcome cache) so a same-id row that raced
                        # this session into pending_replay resolves
                        # grant-freely too, like a serialized memquota
                        # would
                        if did:
                            p._dedup_free[did] = self.now + max(
                                duration, p.min_dedup_s)
                        out[slot] = QuotaResult(granted_amount=amount)
                        continue
                    g = int(granted[slot])
                    if did:
                        expiry = self.now + max(duration,
                                                p.min_dedup_s)
                        p._dedup[did] = (g, expiry)
                    status = 0 if g > 0 or amount == 0 \
                        else RESOURCE_EXHAUSTED
                    out[slot] = QuotaResult(granted_amount=g,
                                            valid_duration_s=duration,
                                            status_code=status)
                for slot, (did, duration, amount) in \
                        self.pending_replay.items():
                    hit = p._dedup.get(did)
                    free_exp = p._dedup_free.get(did)
                    if hit is not None and hit[1] > self.now:
                        status = 0 if hit[0] > 0 or amount == 0 \
                            else RESOURCE_EXHAUSTED
                        out[slot] = QuotaResult(
                            granted_amount=hit[0],
                            valid_duration_s=duration,
                            status_code=status)
                    elif free_exp is not None and free_exp > self.now:
                        # consuming session committed GATE-OFF: the
                        # serialized outcome is grant-freely (this
                        # row's own requested amount, nothing
                        # consumed) — never "quota trip failed"
                        out[slot] = QuotaResult(granted_amount=amount)
                    else:
                        # the consuming session aborted (device
                        # failure): no outcome to replay
                        out[slot] = QuotaResult(
                            granted_amount=0, status_code=14,
                            status_message="quota trip failed")
            for slot, (first, duration) in self.replay_of.items():
                prior = out.get(first, self.early.get(first))
                if prior is None:   # first row resolved early w/o entry
                    prior = QuotaResult(granted_amount=0,
                                        status_code=RESOURCE_EXHAUSTED)
                out[slot] = prior
            return out
        finally:
            self._done = True
            self._end_turn()

    def abort(self) -> None:
        """Trip failed. Pre-dispatch: release the token, nothing
        changed. Post-dispatch: take the commit turn, drop pending
        markers, and restore the last known-good counter handle unless
        a later trip already chained past this one."""
        if self._done:
            return
        self._done = True
        p = self.pool
        if self._token_held:
            self._token_held = False
            p._counts_lock.release()
            # the turn MUST still be consumed or every later session
            # wedges behind this seq
            self._take_turn()
            self._end_turn()
            return
        self._take_turn()
        try:
            with p._lock:
                for _slot, (_a, _d, did) in self._staged.items():
                    if did:
                        p._dedup_pending.pop(did, None)
            with p._counts_lock:
                if p.counts is self.new_counts:
                    p.counts = p._counts_good
        finally:
            self._end_turn()


class DeviceQuotaTable:
    """Pool lifecycle with signature reuse across config generations
    (handlerTable.go pattern): an unchanged (handler signature) keeps
    its pool — and therefore its counters, keymap and dedup cache —
    across snapshot swaps."""

    def __init__(self, n_buckets: int = DEFAULT_BUCKETS,
                 jit: bool = True):
        self.n_buckets = n_buckets
        self.jit = jit
        self._by_sig: dict[str, DeviceQuotaPool] = {}

    def rebuild(self, snapshot) -> tuple[dict[str, DeviceQuotaPool],
                                         list[DeviceQuotaPool]]:
        """→ (handler qname → pool, orphaned pools to close)."""
        out: dict[str, DeviceQuotaPool] = {}
        new_sigs: dict[str, DeviceQuotaPool] = {}
        for qname, hc in snapshot.handlers.items():
            if hc.adapter != "memquota":
                continue
            quotas = {str(q.get("name", "")): q
                      for q in hc.params.get("quotas", ())}
            if not quotas:
                continue
            sig = hc.signature
            pool = self._by_sig.get(sig) or new_sigs.get(sig)
            if pool is None:
                pool = DeviceQuotaPool(
                    quotas, n_buckets=self.n_buckets,
                    min_dedup_s=float(hc.params.get(
                        "min_deduplication_duration_s", 1.0)),
                    jit=self.jit)
            new_sigs[sig] = pool
            out[qname] = pool
        orphans = [p for sig, p in self._by_sig.items()
                   if sig not in new_sigs]
        self._by_sig = new_sigs
        return out, orphans

    def close(self) -> None:
        for p in self._by_sig.values():
            p.close()
        self._by_sig = {}
