"""Built-handler lifecycle with signature-based reuse.

Reference: mixer/pkg/runtime/handlerTable.go — across config
generations, a handler whose (adapter, params) signature is unchanged
is REUSED (adapters hold sockets/caches); new signatures are built,
vanished ones closed after the old snapshot drains.
"""
from __future__ import annotations

import logging
import threading
from typing import Mapping

from istio_tpu.adapters.registry import adapter_registry, load_inventory
from istio_tpu.adapters.sdk import AdapterError, Env, Handler
from istio_tpu.runtime.config import HandlerConfig, Snapshot

log = logging.getLogger("istio_tpu.runtime.handlers")


class HandlerTable:
    def __init__(self) -> None:
        load_inventory()
        self._lock = threading.Lock()
        self._by_sig: dict[str, Handler] = {}

    def rebuild(self, snapshot: Snapshot
                ) -> tuple[dict[str, Handler], list[Handler]]:
        """Build/reuse handlers for a snapshot. Returns (handler-name →
        Handler, orphans). Orphans are NOT closed here — the caller
        closes them after the old dispatcher drains (the reference's
        cleanupResolver ordering, resolver.go:240-247): requests in
        flight on the previous snapshot may still be using them."""
        out: dict[str, Handler] = {}
        new_sigs: dict[str, Handler] = {}
        with self._lock:
            for qname, hc in snapshot.handlers.items():
                sig = hc.signature
                handler = self._by_sig.get(sig) or new_sigs.get(sig)
                if handler is None:
                    try:
                        handler = self._build(hc, snapshot)
                    except Exception as exc:
                        snapshot.errors.append(
                            f"handler {qname}: build failed: {exc}")
                        continue
                new_sigs[sig] = handler
                out[qname] = handler
            orphans = [h for sig, h in self._by_sig.items()
                       if sig not in new_sigs]
            self._by_sig = new_sigs
        return out, orphans

    @staticmethod
    def close_handlers(handlers: list[Handler]) -> None:
        for h in handlers:
            try:
                h.close()
            except Exception:
                log.exception("handler close failed")

    def _build(self, hc: HandlerConfig, snapshot: Snapshot) -> Handler:
        info = adapter_registry.get(hc.adapter)
        params = dict(hc.params)
        if hc.adapter == "rbac":
            # the reference's rbac adapter runs its own CRD controller
            # (rbac.go:113); here role/binding kinds ride the main store
            params.setdefault("roles", snapshot.roles)
            params.setdefault("bindings", snapshot.bindings)
        builder = info.builder(params, Env(hc.adapter))
        # inferred instance types for this handler's templates
        types: dict[str, Mapping] = {}
        for rule_idx in range(len(snapshot.rules)):
            for action in snapshot.rules[rule_idx].actions:
                if action.handler != f"{hc.name}.{hc.namespace}" \
                        and action.handler != hc.name:
                    continue
                for inst in action.instances:
                    ib = snapshot.instances.get(inst)
                    if ib is not None:
                        types[inst] = ib.inferred
        builder.set_types(types)
        errs = builder.validate()
        if errs:
            raise AdapterError("; ".join(errs))
        return builder.build()

    def close(self) -> None:
        with self._lock:
            handlers = list(self._by_sig.values())
            self._by_sig = {}
        for h in handlers:
            try:
                h.close()
            except Exception:
                log.exception("handler close failed")
