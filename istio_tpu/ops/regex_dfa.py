"""Regex → byte-level DFA compiler for device-side `matches()`.

The reference evaluates RE2 regexes on the host per call
(mixer/pkg/il/runtime/externs.go:118 `matches`). On TPU we compile each
pattern ONCE (host side, config time) into a dense uint8-alphabet DFA
transition table; evaluation is then a fixed-length `lax.scan` of gathers
(or a Pallas one-hot matmul) over the padded subject bytes — thousands of
subjects × patterns per device step.

Supported syntax (the subset real mesh configs use): literals, `.`,
character classes `[a-z]`/`[^...]` with escapes, groups `(...)`,
alternation `|`, repetition `* + ? {m} {m,} {m,n}`, anchors `^`/`$` at the
pattern edges, escapes `\\d \\D \\w \\W \\s \\S` and escaped
metacharacters. Unsupported constructs (backreferences, lookaround,
non-greedy — irrelevant for acceptance — inner anchors, unicode classes)
raise UnsupportedRegex; callers fall back to the host oracle.

Semantics target: Go regexp.MatchString — UNANCHORED search. Patterns are
compiled as `.*(pattern)` and acceptance is monitored at every prefix
length, so `search` semantics come out of a single end-state check per
step.
"""
from __future__ import annotations

import dataclasses

import numpy as np

# the bit-lane codec lives with its on-device inverse (unpack_bits);
# re-exported here because the packed one-hot step banks below are its
# heaviest producer
from istio_tpu.ops.bytes_ops import pack_bits

ALPHABET = 256


class UnsupportedRegex(ValueError):
    pass


# ---------------------------------------------------------------------------
# Pattern AST
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Node:
    kind: str                      # lit/class/any/cat/alt/star/plus/opt/rep/empty
    chars: frozenset[int] | None = None
    children: tuple["_Node", ...] = ()
    lo: int = 0
    hi: int = 0


_CLASS_ESCAPES = {
    "d": frozenset(range(0x30, 0x3A)),
    "w": frozenset(list(range(0x30, 0x3A)) + list(range(0x41, 0x5B)) +
                   list(range(0x61, 0x7B)) + [0x5F]),
    "s": frozenset([0x20, 0x09, 0x0A, 0x0D, 0x0B, 0x0C]),
}
_META = set(".*+?()[]{}|^$\\")


def _negate(s: frozenset[int]) -> frozenset[int]:
    return frozenset(range(ALPHABET)) - s


class _RegexParser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self) -> tuple[_Node, bool, bool]:
        """Returns (ast, anchored_start, anchored_end)."""
        anchored_start = False
        anchored_end = False
        if self.peek() == "^":
            self.next()
            anchored_start = True
        node = self.alternation()
        # trailing $ is consumed inside alternation handling; detect flag
        if self.i < len(self.p):
            raise UnsupportedRegex(f"trailing junk in pattern: {self.p[self.i:]!r}")
        if node.kind == "cat" and node.children and \
                node.children[-1].kind == "end_anchor":
            node = _Node("cat", children=node.children[:-1])
            anchored_end = True
        elif node.kind == "end_anchor":
            node = _Node("empty")
            anchored_end = True
        return node, anchored_start, anchored_end

    def alternation(self) -> _Node:
        branches = [self.concat()]
        while self.peek() == "|":
            self.next()
            branches.append(self.concat())
        if len(branches) == 1:
            return branches[0]
        if any(b.kind == "end_anchor" or
               (b.kind == "cat" and any(c.kind == "end_anchor"
                                        for c in b.children))
               for b in branches):
            raise UnsupportedRegex("anchor inside alternation")
        return _Node("alt", children=tuple(branches))

    def concat(self) -> _Node:
        parts: list[_Node] = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                break
            parts.append(self.repeat())
        if not parts:
            return _Node("empty")
        for p in parts[:-1]:
            if p.kind == "end_anchor":
                raise UnsupportedRegex("$ not at pattern end")
        if len(parts) == 1:
            return parts[0]
        return _Node("cat", children=tuple(parts))

    def repeat(self) -> _Node:
        atom = self.atom()
        while True:
            c = self.peek()
            if c == "*":
                self.next()
                atom = _Node("star", children=(atom,))
            elif c == "+":
                self.next()
                atom = _Node("plus", children=(atom,))
            elif c == "?":
                self.next()
                atom = _Node("opt", children=(atom,))
            elif c == "{":
                atom = self.bounded(atom)
            else:
                if self.peek() == "?":  # non-greedy suffix like *? — greedy
                    self.next()         # equivalence holds for acceptance
                    continue
                return atom

    def bounded(self, atom: _Node) -> _Node:
        self.next()  # consume {
        spec = ""
        while self.peek() is not None and self.peek() != "}":
            spec += self.next()
        if self.peek() != "}":
            raise UnsupportedRegex("unterminated {}")
        self.next()
        parts = spec.split(",")
        try:
            if len(parts) == 1:
                lo = hi = int(parts[0])
            elif parts[1] == "":
                lo, hi = int(parts[0]), -1
            else:
                lo, hi = int(parts[0]), int(parts[1])
        except ValueError:
            raise UnsupportedRegex(f"bad repetition {{{spec}}}")
        if hi != -1 and (hi < lo or hi > 64):
            raise UnsupportedRegex(f"repetition bound too large {{{spec}}}")
        return _Node("rep", children=(atom,), lo=lo, hi=hi)

    def atom(self) -> _Node:
        c = self.next()
        if c == "(":
            if self.peek() == "?":
                self.next()
                if self.peek() == ":":
                    self.next()          # (?: non-capturing — fine
                else:
                    raise UnsupportedRegex("(?...) construct")
            node = self.alternation()
            if self.peek() != ")":
                raise UnsupportedRegex("unbalanced paren")
            self.next()
            return node
        if c == "[":
            return self.char_class()
        if c == ".":
            return _Node("any")
        if c == "$":
            return _Node("end_anchor")
        if c == "^":
            raise UnsupportedRegex("^ not at pattern start")
        if c == "\\":
            return self.escape()
        if c in "*+?{":
            raise UnsupportedRegex(f"dangling {c!r}")
        if ord(c) > 255:
            raise UnsupportedRegex("non-byte character")
        return _Node("lit", chars=frozenset([ord(c)]))

    def escape(self) -> _Node:
        if self.peek() is None:
            raise UnsupportedRegex("trailing backslash")
        c = self.next()
        if c in _CLASS_ESCAPES:
            return _Node("class", chars=_CLASS_ESCAPES[c])
        if c.upper() in _CLASS_ESCAPES and c.isupper():
            return _Node("class", chars=_negate(_CLASS_ESCAPES[c.lower()]))
        if c == "n":
            return _Node("lit", chars=frozenset([10]))
        if c == "t":
            return _Node("lit", chars=frozenset([9]))
        if c == "r":
            return _Node("lit", chars=frozenset([13]))
        if c in _META or not c.isalnum():
            return _Node("lit", chars=frozenset([ord(c)]))
        if c.upper() == "B":
            raise UnsupportedRegex("word boundary")
        raise UnsupportedRegex(f"escape \\{c}")

    def char_class(self) -> _Node:
        negated = False
        if self.peek() == "^":
            self.next()
            negated = True
        chars: set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise UnsupportedRegex("unterminated character class")
            if c == "]" and not first:
                self.next()
                break
            first = False
            c = self.next()
            if c == "\\":
                nxt = self.next()
                if nxt in _CLASS_ESCAPES:
                    chars |= _CLASS_ESCAPES[nxt]
                    continue
                if nxt.upper() in _CLASS_ESCAPES and nxt.isupper():
                    chars |= _negate(_CLASS_ESCAPES[nxt.lower()])
                    continue
                lo_ch = {"n": 10, "t": 9, "r": 13}.get(nxt, ord(nxt))
            else:
                lo_ch = ord(c)
            if self.peek() == "-" and self.i + 1 < len(self.p) and \
                    self.p[self.i + 1] != "]":
                self.next()
                hi_c = self.next()
                if hi_c == "\\":
                    hi_c = self.next()
                chars |= set(range(lo_ch, ord(hi_c) + 1))
            else:
                chars.add(lo_ch)
        if any(ch > 255 for ch in chars):
            raise UnsupportedRegex("non-byte character in class")
        return _Node("class",
                     chars=_negate(frozenset(chars)) if negated
                     else frozenset(chars))


# ---------------------------------------------------------------------------
# Thompson NFA
# ---------------------------------------------------------------------------

class _NFA:
    def __init__(self) -> None:
        self.eps: list[list[int]] = []
        self.trans: list[list[tuple[frozenset[int], int]]] = []

    def new_state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def add_eps(self, a: int, b: int) -> None:
        self.eps[a].append(b)

    def add_trans(self, a: int, chars: frozenset[int], b: int) -> None:
        self.trans[a].append((chars, b))


_ANY = frozenset(range(ALPHABET))


def _build(nfa: _NFA, node: _Node) -> tuple[int, int]:
    """Thompson construction: returns (start, accept)."""
    s, t = nfa.new_state(), nfa.new_state()
    k = node.kind
    if k == "empty":
        nfa.add_eps(s, t)
    elif k in ("lit", "class"):
        nfa.add_trans(s, node.chars, t)
    elif k == "any":
        nfa.add_trans(s, _ANY, t)
    elif k == "cat":
        prev = s
        for child in node.children:
            cs, ct = _build(nfa, child)
            nfa.add_eps(prev, cs)
            prev = ct
        nfa.add_eps(prev, t)
    elif k == "alt":
        for child in node.children:
            cs, ct = _build(nfa, child)
            nfa.add_eps(s, cs)
            nfa.add_eps(ct, t)
    elif k == "star":
        cs, ct = _build(nfa, node.children[0])
        nfa.add_eps(s, cs)
        nfa.add_eps(s, t)
        nfa.add_eps(ct, cs)
        nfa.add_eps(ct, t)
    elif k == "plus":
        cs, ct = _build(nfa, node.children[0])
        nfa.add_eps(s, cs)
        nfa.add_eps(ct, cs)
        nfa.add_eps(ct, t)
    elif k == "opt":
        cs, ct = _build(nfa, node.children[0])
        nfa.add_eps(s, cs)
        nfa.add_eps(ct, t)
        nfa.add_eps(s, t)
    elif k == "rep":
        prev = s
        for _ in range(node.lo):
            cs, ct = _build(nfa, node.children[0])
            nfa.add_eps(prev, cs)
            prev = ct
        if node.hi == -1:  # {m,}
            cs, ct = _build(nfa, node.children[0])
            nfa.add_eps(prev, cs)
            nfa.add_eps(ct, cs)
            nfa.add_eps(ct, t)
            nfa.add_eps(prev, t)
        else:
            for _ in range(node.hi - node.lo):
                cs, ct = _build(nfa, node.children[0])
                nfa.add_eps(prev, cs)
                nfa.add_eps(prev, t)
                prev = ct
            nfa.add_eps(prev, t)
    elif k == "end_anchor":
        raise UnsupportedRegex("$ in unsupported position")
    else:  # pragma: no cover
        raise UnsupportedRegex(f"internal: node {k}")
    return s, t


# ---------------------------------------------------------------------------
# Subset construction → dense DFA
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DFA:
    """Dense byte DFA. transitions[state, byte] → state;
    accept[state] → bool. State 0 is the start state.

    For unanchored (search) semantics, acceptance is sticky: accepting
    states only transition to accepting states, so checking acceptance
    after consuming all `len` bytes is equivalent to checking at every
    prefix. This keeps the device step to a single scan with one final
    accept gather."""
    transitions: np.ndarray  # int32 [n_states, 256]
    accept: np.ndarray       # bool  [n_states]
    pattern: str

    @property
    def n_states(self) -> int:
        return int(self.transitions.shape[0])


_MAX_DFA_STATES = 2048


def compile_regex(pattern: str) -> DFA:
    """Compile to a dense search-semantics DFA (Go regexp.MatchString
    equivalence for the supported subset)."""
    ast, anchored_start, anchored_end = _RegexParser(pattern).parse()

    # search semantics: allow any prefix unless ^-anchored
    if not anchored_start:
        ast = _Node("cat", children=(_Node("star", children=(_Node("any"),)),
                                     ast))
    # unless $-anchored, allow any suffix — combined with sticky accept
    if not anchored_end:
        ast = _Node("cat", children=(ast,
                                     _Node("star", children=(_Node("any"),))))

    nfa = _NFA()
    start, accept = _build(nfa, ast)

    def eps_closure(states: frozenset[int]) -> frozenset[int]:
        stack = list(states)
        seen = set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_set = eps_closure(frozenset([start]))
    dfa_ids: dict[frozenset[int], int] = {start_set: 0}
    worklist = [start_set]
    rows: list[np.ndarray] = []
    accepts: list[bool] = []

    while worklist:
        cur = worklist.pop()
        cur_id = dfa_ids[cur]
        while len(rows) <= cur_id:
            rows.append(np.zeros(ALPHABET, dtype=np.int32))
            accepts.append(False)
        accepts[cur_id] = accept in cur
        is_accepting = accept in cur

        # group target NFA states by byte
        by_byte: list[set[int]] = [set() for _ in range(ALPHABET)]
        for s in cur:
            for chars, t in nfa.trans[s]:
                for ch in chars:
                    by_byte[ch].add(t)
        row = np.zeros(ALPHABET, dtype=np.int32)
        closure_cache: dict[frozenset[int], int] = {}
        for ch in range(ALPHABET):
            tgt = frozenset(by_byte[ch])
            key = tgt
            if key in closure_cache:
                row[ch] = closure_cache[key]
                continue
            nxt = eps_closure(tgt) if tgt else frozenset()
            # sticky accept for search semantics
            if is_accepting and not anchored_end:
                pass  # suffix .* already keeps acceptance
            tid = dfa_ids.get(nxt)
            if tid is None:
                tid = len(dfa_ids)
                if tid >= _MAX_DFA_STATES:
                    raise UnsupportedRegex(
                        f"DFA for {pattern!r} exceeds {_MAX_DFA_STATES} states")
                dfa_ids[nxt] = tid
                worklist.append(nxt)
            row[ch] = tid
            closure_cache[key] = tid
        rows[cur_id] = row

    while len(rows) < len(dfa_ids):
        rows.append(np.zeros(ALPHABET, dtype=np.int32))
        accepts.append(False)
    # fill states discovered but not yet expanded (empty set sink)
    for st, sid in dfa_ids.items():
        if sid < len(accepts):
            accepts[sid] = accept in st

    return DFA(transitions=np.stack(rows), accept=np.array(accepts, bool),
               pattern=pattern)


def dfa_matches_host(dfa: DFA, subject: bytes) -> bool:
    """Host-side DFA run (oracle for the device kernel)."""
    state = 0
    for b in subject:
        state = int(dfa.transitions[state, b])
    return bool(dfa.accept[state])


def pack_dfas(dfas: list[DFA]) -> tuple[np.ndarray, np.ndarray]:
    """Stack several DFAs into one padded transition bank for the
    vectorized device step: returns (trans [n, S_max, 256] int32,
    accept [n, S_max] bool)."""
    smax = max(d.n_states for d in dfas)
    trans = np.zeros((len(dfas), smax, ALPHABET), dtype=np.int32)
    accept = np.zeros((len(dfas), smax), dtype=bool)
    for i, d in enumerate(dfas):
        trans[i, :d.n_states] = d.transitions
        accept[i, :d.n_states] = d.accept
    return trans, accept


def pack_dfas_classes(dfas: list[DFA]) -> dict:
    """CHEAP phase of the one-hot packing: renumber all automata into
    one global state space and compute the bank-wide byte EQUIVALENCE
    CLASSES (bytes with identical transition columns across every
    state). O(S·256) numpy work — callers size-gate on
    n_states/n_classes BEFORE paying for the step matrix
    (pack_dfas_onehot)."""
    n = len(dfas)
    offs = np.cumsum([0] + [d.n_states for d in dfas])
    s_tot = int(offs[-1])
    gt = np.zeros((s_tot, ALPHABET), np.int32)
    accept = np.zeros((s_tot, n), np.float32)
    for i, d in enumerate(dfas):
        gt[offs[i]:offs[i + 1]] = d.transitions + offs[i]
        accept[offs[i]:offs[i + 1], i] = d.accept
    _, class_of = np.unique(gt, axis=1, return_inverse=True)
    class_of = class_of.reshape(-1)
    n_cls = int(class_of.max()) + 1
    rep = np.zeros(n_cls, np.int64)   # a representative byte per class
    for byte in range(ALPHABET - 1, -1, -1):
        rep[class_of[byte]] = byte
    return {"gt": gt, "class_of": class_of, "rep": rep,
            "starts": offs[:-1].astype(np.int32), "accept": accept,
            "n_states": s_tot, "n_classes": n_cls}


def pack_dfas_onehot(dfas: list[DFA],
                     classes: dict | None = None) -> dict:
    """Pack several DFAs for the MXU (one-hot matmul) device kernel
    (bytes_ops.dfa_match_many_onehot).

    Returns {"step_bits": [S·C, ceil(S/32)] BIT-PACKED one-hot
    transition matrix (row s·C+c → one-hot of next state; pack_bits
    lanes, unpacked to bf16 on device once per kernel invocation —
    bytes_ops.unpack_bits), "cls": [256, C] one-hot byte→class matrix,
    "starts": [N] int32 global start states, "accept": [S, N] pattern
    acceptance matrix}. The step matrix is O(S²·C) one-hot entries —
    bit lanes keep the resident bank at 1/32 of the f32 formulation's
    bytes; size-gate via pack_dfas_classes first."""
    k = classes if classes is not None else pack_dfas_classes(dfas)
    s_tot, n_cls = k["n_states"], k["n_classes"]
    gt, class_of, rep = k["gt"], k["class_of"], k["rep"]
    step = np.zeros((s_tot * n_cls, s_tot), bool)
    rows = (np.arange(s_tot)[:, None] * n_cls
            + np.arange(n_cls)[None, :]).reshape(-1)
    cols = gt[:, rep].reshape(-1)          # [S, C] next states
    step[rows, cols] = True
    cls = np.zeros((ALPHABET, n_cls), np.float32)
    cls[np.arange(ALPHABET), class_of] = 1.0
    return {"step_bits": pack_bits(step), "cls": cls,
            "starts": k["starts"], "accept": k["accept"],
            "n_states": s_tot, "n_classes": n_cls}


def pack_dfas_onehot_blocked(dfas: list[DFA],
                             classes: dict | None = None) -> dict:
    """BLOCK-DIAGONAL one-hot packing: per-pattern step matrices padded
    to the widest automaton, for bytes_ops.dfa_match_many_onehot_blocked
    (a batched matmul over the pattern axis).

    The dense pack_dfas_onehot matrix is O((Σsᵢ)²·C) — quadratic in the
    BANK, so a 23-glob bank blows the size gate and used to fall back
    to the latency-bound gather scan. Blocks are O(N·s_max²·C): states
    never cross patterns, so the dense matrix was block-diagonal
    anyway — this stores only the blocks.

    Returns {"step_bits": [N, s_max·C, ceil(s_max/32)] bit-packed
    blocks (pack_bits lanes, device-unpacked once per invocation),
    "cls": [256, C], "accept": [N, s_max] (acceptance of pattern i's
    own states), "n_states_max", "n_classes", "n_pats"}; pattern i
    starts in its local state 0 (compile_regex numbers the start
    state 0)."""
    k = classes if classes is not None else pack_dfas_classes(dfas)
    n = len(dfas)
    n_cls = int(k["n_classes"])
    class_of, rep = k["class_of"], k["rep"]
    s_max = max(d.n_states for d in dfas)
    step = np.zeros((n, s_max * n_cls, s_max), bool)
    accept = np.zeros((n, s_max), np.float32)
    for i, d in enumerate(dfas):
        s_i = d.n_states
        rows = (np.arange(s_i)[:, None] * n_cls
                + np.arange(n_cls)[None, :]).reshape(-1)
        cols = d.transitions[:, rep].reshape(-1)
        step[i, rows, cols] = True
        accept[i, :s_i] = d.accept
        # padding states self-loop dead (all-zero rows: a one-hot that
        # reaches them vanishes — they are unreachable from state 0)
    cls = np.zeros((ALPHABET, n_cls), np.float32)
    cls[np.arange(ALPHABET), class_of] = 1.0
    return {"step_bits": pack_bits(step), "cls": cls, "accept": accept,
            "n_states_max": s_max, "n_classes": n_cls, "n_pats": n}


def pack_dfas_tiered(dfas: "list[DFA]") -> dict:
    """One home for the engine-wide DFA bank strategy (used by both
    tensor_expr.compile_dfa_group and the policy engine's list banks):
    dense one-hot MXU matmul (small banks), BLOCK-DIAGONAL one-hot
    (banks of many small automata — O(N·s_max²·C) per step where dense
    is quadratic in the whole bank), flat-gather scan (pathological
    single automata too big for either). The MXU formulations win at
    EVERY batch size — the per-step [B, N] gather is latency-bound on
    TPU — so flat tables are built ONLY when both one-hot tiers are
    infeasible (they would otherwise be dead device weight).

    → {"packed", "packed_blk", "trans", "accept", "classes"} with
    exactly one of packed / packed_blk / (trans, accept) non-None.
    """
    classes = pack_dfas_classes(dfas)
    s_max = max(d.n_states for d in dfas)
    dense_ok = (classes["n_states"] ** 2 * classes["n_classes"]
                <= 4_000_000)
    blocked_ok = (len(dfas) * s_max ** 2 * classes["n_classes"]
                  <= 8_000_000)
    packed = pack_dfas_onehot(dfas, classes) if dense_ok else None
    packed_blk = None if dense_ok or not blocked_ok else \
        pack_dfas_onehot_blocked(dfas, classes)
    trans = accept = None
    if packed is None and packed_blk is None:
        trans, accept = pack_dfas(dfas)
    return {"packed": packed, "packed_blk": packed_blk,
            "trans": trans, "accept": accept, "classes": classes}
