"""Device-side byte-string predicates over padded uint8 tensors.

Strings that participate in glob/regex/prefix/suffix predicates are
materialized as fixed-width ``uint8[B, L]`` rows plus ``int32[B]`` lengths
(SURVEY.md §7 "hard parts #1"). Everything here is jit-compatible and
shape-static; XLA fuses the comparisons into neighbouring ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pad_bytes(values: list[bytes], max_len: int) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: pack python byte strings into [N, L] uint8 + [N] int32."""
    out = np.zeros((len(values), max_len), dtype=np.uint8)
    lens = np.zeros(len(values), dtype=np.int32)
    for i, v in enumerate(values):
        trunc = v[:max_len]
        out[i, :len(trunc)] = np.frombuffer(trunc, dtype=np.uint8)
        lens[i] = len(trunc)
    return out, lens


def prefix_match(data: jnp.ndarray, lens: jnp.ndarray,
                 prefix: bytes) -> jnp.ndarray:
    """startsWith(const): [B, L] × pattern → bool [B]."""
    p = np.frombuffer(prefix, dtype=np.uint8)
    k = len(p)
    if k == 0:
        return jnp.ones(data.shape[0], dtype=bool)
    if k > data.shape[1]:
        return jnp.zeros(data.shape[0], dtype=bool)
    eq = jnp.all(data[:, :k] == jnp.asarray(p), axis=-1)
    return eq & (lens >= k)


def suffix_match(data: jnp.ndarray, lens: jnp.ndarray,
                 suffix: bytes) -> jnp.ndarray:
    """endsWith(const): compare a window ending at each row's length."""
    p = np.frombuffer(suffix, dtype=np.uint8)
    k = len(p)
    b, l = data.shape
    if k == 0:
        return jnp.ones(b, dtype=bool)
    if k > l:
        return jnp.zeros(b, dtype=bool)
    # gather indices len-k .. len-1 per row (clipped; masked by lens >= k)
    offs = jnp.arange(k, dtype=jnp.int32)[None, :] + (lens[:, None] - k)
    offs = jnp.clip(offs, 0, l - 1)
    window = jnp.take_along_axis(data, offs, axis=1)
    return jnp.all(window == jnp.asarray(p), axis=-1) & (lens >= k)


def exact_match(data: jnp.ndarray, lens: jnp.ndarray,
                pattern: bytes) -> jnp.ndarray:
    p = np.frombuffer(pattern, dtype=np.uint8)
    k = len(p)
    if k > data.shape[1]:
        return jnp.zeros(data.shape[0], dtype=bool)
    padded = np.zeros(data.shape[1], dtype=np.uint8)
    padded[:k] = p
    return jnp.all(data == jnp.asarray(padded), axis=-1) & (lens == k)


def glob_match(data: jnp.ndarray, lens: jnp.ndarray,
               pattern: str) -> jnp.ndarray:
    """The `match()` extern with a constant pattern
    (externs.go:108-116): trailing '*' = prefix, leading '*' = suffix,
    else exact."""
    pb = pattern.encode()
    if pb.endswith(b"*"):
        return prefix_match(data, lens, pb[:-1])
    if pb.startswith(b"*"):
        return suffix_match(data, lens, pb[1:])
    return exact_match(data, lens, pb)


def dyn_prefix_match(s_data, s_lens, p_data, p_lens) -> jnp.ndarray:
    """startsWith with a RUNTIME prefix: both sides are byte planes.
    [B, L] × [B, L] → bool [B]."""
    l = s_data.shape[1]
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    eq = (s_data == p_data) | (pos >= p_lens[:, None])
    return jnp.all(eq, axis=1) & (s_lens >= p_lens)


def dyn_suffix_match(s_data, s_lens, p_data, p_lens,
                     p_shift: int = 0) -> jnp.ndarray:
    """endsWith with a RUNTIME suffix: compare s's last (p_len - shift)
    bytes against p[shift:] (shift=1 serves `*x` globs)."""
    l = s_data.shape[1]
    k = p_lens - p_shift                       # effective suffix length
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    offs = jnp.clip(pos + (s_lens - k)[:, None], 0, l - 1)
    window = jnp.take_along_axis(s_data, offs, axis=1)
    if p_shift:
        p_cmp = jnp.roll(p_data, -p_shift, axis=1)
    else:
        p_cmp = p_data
    eq = (window == p_cmp) | (pos >= k[:, None])
    return jnp.all(eq, axis=1) & (s_lens >= k) & (k >= 0)


def dyn_exact_match(s_data, s_lens, p_data, p_lens) -> jnp.ndarray:
    eq = jnp.all(s_data == p_data, axis=1)
    return eq & (s_lens == p_lens)


def dyn_glob_match(s_data, s_lens, p_data, p_lens) -> jnp.ndarray:
    """match() with a RUNTIME pattern (externs.go:108-116 semantics):
    trailing '*' = prefix of p[:-1], leading '*' = suffix of p[1:],
    else exact. The '*' probes read the pattern's first/last bytes
    per row; all three candidate verdicts are computed and selected."""
    l = s_data.shape[1]
    star = np.uint8(ord("*"))
    last = jnp.take_along_axis(
        p_data, jnp.clip(p_lens - 1, 0, l - 1)[:, None], axis=1)[:, 0]
    trailing = (p_lens > 0) & (last == star)
    leading = (p_lens > 0) & (p_data[:, 0] == star)
    prefix = dyn_prefix_match(s_data, s_lens, p_data,
                              jnp.maximum(p_lens - 1, 0))
    suffix = dyn_suffix_match(s_data, s_lens, p_data, p_lens,
                              p_shift=1)
    exact = dyn_exact_match(s_data, s_lens, p_data, p_lens)
    return jnp.where(trailing, prefix,
                     jnp.where(leading, suffix, exact))


def lex_cmp(a_data: jnp.ndarray, a_lens: jnp.ndarray,
            b_data: jnp.ndarray, b_lens: jnp.ndarray) -> jnp.ndarray:
    """Row-wise lexicographic comparison of two padded byte planes →
    int32 [B] in {-1, 0, 1} (sign of a ⋛ b).

    Padding is zero, so when one row is a strict prefix of the other
    the first differing position reads 0 vs the longer row's next byte
    — the correct "shorter sorts first" verdict — except when the
    longer row's byte IS 0 (embedded NUL), which the length tiebreak
    below also resolves. Numeric order keys are fixed 8-byte rows, so
    for them every path is exact. Ordered comparisons (expr LSS/LEQ/
    GTR/GEQ, reference func.go) lower here over the SAME planes the
    string predicates use; truncation handling lives in the caller
    (tensor_expr._compile_cmp)."""
    diff = a_data != b_data                       # [B, L]
    has = jnp.any(diff, axis=1)
    first = jnp.argmax(diff, axis=1)
    av = jnp.take_along_axis(a_data, first[:, None], axis=1)[:, 0]
    bv = jnp.take_along_axis(b_data, first[:, None], axis=1)[:, 0]
    byte_cmp = jnp.sign(av.astype(jnp.int32) - bv.astype(jnp.int32))
    len_cmp = jnp.sign(a_lens - b_lens).astype(jnp.int32)
    return jnp.where(has, byte_cmp, len_cmp)


def pack_bits(a: np.ndarray) -> np.ndarray:
    """Host-side bit packing of a bool/0-1 array along its LAST axis →
    uint32 lanes, little-endian bit order within each 32-bit word,
    width ceil(n/32). THE storage format for every bit-packed bank /
    mask weight (one-hot DFA step matrices in regex_dfa; attr/instance
    literal masks in the engine + packer): a one-hot transition bank
    stored as f32 was 32× the HBM-resident bytes of its information
    content. `unpack_bits` below is the on-device inverse."""
    a = np.ascontiguousarray(np.asarray(a) != 0)
    n = a.shape[-1]
    w = max((n + 31) // 32, 0)
    padded = np.zeros(a.shape[:-1] + (w * 32,), bool)
    padded[..., :n] = a
    packed8 = np.ascontiguousarray(
        np.packbits(padded, axis=-1, bitorder="little"))
    return packed8.view(np.uint32)


def unpack_bits(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """On-device inverse of pack_bits: uint32 bit lanes
    [..., W] → bool [..., n] (little-endian within each word). The
    unpack is elementwise VPU work that runs ONCE per kernel
    invocation; the packed lanes are what lives in HBM (and what the
    compiled program carries), so a bank's resident weight is 1/32 of
    its f32 one-hot formulation."""
    bits = (packed[..., None] >> jnp.arange(32, dtype=jnp.uint32)) \
        & jnp.uint32(1)
    flat = bits.reshape(packed.shape[:-1] + (-1,))
    return flat[..., :n] != 0


def dfa_match(data: jnp.ndarray, lens: jnp.ndarray,
              transitions: jnp.ndarray, accept: jnp.ndarray) -> jnp.ndarray:
    """Run one dense DFA over every row: state := T[state, byte] for the
    first `len` bytes, then read the accept bit.

    data [B, L] uint8, transitions [S, 256] int32, accept [S] bool.
    Implemented as a lax.scan over the L byte positions (time-major
    transpose) — each step is one [B] gather from the flattened table.
    """
    b, l = data.shape
    flat = transitions.reshape(-1)  # [S*256]
    bytes_tm = data.T  # [L, B]
    # data-dependent trip count: strings are typically far shorter than
    # the slot width, and every position ≥ max(lens) is a frozen no-op
    # — a while_loop stops at the batch's longest string instead of
    # paying the full L scan-step latencies
    maxlen = jnp.minimum(jnp.max(lens), l)

    def cond(carry):
        i, _ = carry
        return i < maxlen

    def body(carry):
        i, state = carry
        byte = jax.lax.dynamic_index_in_dim(bytes_tm, i, 0,
                                            keepdims=False)
        nxt = flat[state * 256 + byte.astype(jnp.int32)]
        state = jnp.where(i < lens, nxt, state)
        return i + 1, state

    _, final = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.zeros(b, dtype=jnp.int32)))
    return accept[final]


def dfa_match_many(data: jnp.ndarray, lens: jnp.ndarray,
                   trans_bank: jnp.ndarray,
                   accept_bank: jnp.ndarray) -> jnp.ndarray:
    """Vectorized multi-pattern DFA: run N packed DFAs (pack_dfas) over the
    same subject rows in ONE scan.

    data [B, L], trans_bank [N, S, 256], accept_bank [N, S] →  bool [B, N].

    All N automata are renumbered into ONE global state space (state of
    pattern i lives at i·S + s), so each scan step is a single 1-D
    gather of [B, N] next-states from a flat [(N·S)·256] table — the
    same efficient gather shape as the single-DFA case. (A vmap over
    per-pattern dfa_match compiled to a batched gather XLA:TPU executes
    ~16× slower — 58 ms vs 3.6 ms for 11 patterns × 256 bytes.)
    """
    n, s, a = trans_bank.shape
    offsets = jnp.arange(n, dtype=jnp.int32) * s           # [N]
    flat = (trans_bank.astype(jnp.int32)
            + offsets[:, None, None]).reshape(-1)          # [(N·S)·A]
    accept_flat = accept_bank.reshape(-1)                  # [N·S]
    b, l = data.shape

    bytes_tm = data.T                                      # [L, B]
    maxlen = jnp.minimum(jnp.max(lens), l)

    def cond(carry):
        i, _ = carry
        return i < maxlen

    def body(carry):
        i, state = carry
        byte = jax.lax.dynamic_index_in_dim(bytes_tm, i, 0,
                                            keepdims=False)
        nxt = flat[state * a + byte[:, None].astype(jnp.int32)]
        state = jnp.where((i < lens)[:, None], nxt, state)
        return i + 1, state

    init = jnp.broadcast_to(offsets[None, :], (b, n))
    _, final = jax.lax.while_loop(cond, body, (jnp.int32(0), init))
    return accept_flat[final]


def dfa_match_many_onehot(data: jnp.ndarray, lens: jnp.ndarray,
                          packed: dict) -> jnp.ndarray:
    """Multi-pattern DFA on the MXU: states ride as ONE-HOT vectors and
    each byte step is a matmul, not a gather (`packed` from
    regex_dfa.pack_dfas_onehot).

    Per step: class one-hot [B, C] from a byte compare + cls matmul,
    outer-product with the state one-hot u [B, S] → [B, S·C], then
    × step-matrix [S·C, S] → next one-hot. All values are exact 0/1 so
    bf16 accumulation is lossless. XLA:TPU executes the raw per-step
    [B, N] table gather at ~0.5 GB/s effective (58 ms for 11 patterns ×
    256 bytes); this formulation runs the same automata in ~2 ms.

    → bool [B, N] acceptance per pattern.
    """
    b, l = data.shape
    s_tot, n_cls = packed["n_states"], packed["n_classes"]
    # bit-packed bank → bf16 once per invocation (unpack-on-device)
    step_m = unpack_bits(jnp.asarray(packed["step_bits"]),
                         s_tot).astype(jnp.bfloat16)
    cls_m = jnp.asarray(packed["cls"], jnp.bfloat16)
    accept = jnp.asarray(packed["accept"], jnp.bfloat16)
    starts = packed["starts"]

    u0 = np.zeros((1, s_tot), np.float32)
    u0[0, starts] = 1.0   # one-hot start of every pattern, summed —
    # patterns never share states, so the N automata advance
    # independently inside one vector
    u0 = jnp.broadcast_to(jnp.asarray(u0, jnp.bfloat16), (b, s_tot))

    bytes_tm = data.T
    maxlen = jnp.minimum(jnp.max(lens), l)

    def cond(carry):
        i, _ = carry
        return i < maxlen

    def body(carry):
        i, u = carry
        byte = jax.lax.dynamic_index_in_dim(bytes_tm, i, 0,
                                            keepdims=False)
        onehot256 = (byte[:, None] ==
                     jnp.arange(256, dtype=byte.dtype)[None, :]
                     ).astype(jnp.bfloat16)
        c1 = onehot256 @ cls_m                     # [B, C]
        v = (u[:, :, None] * c1[:, None, :]).reshape(b, s_tot * n_cls)
        nxt = v @ step_m                           # [B, S]
        u = jnp.where((i < lens)[:, None], nxt, u)
        return i + 1, u

    _, final = jax.lax.while_loop(cond, body, (jnp.int32(0), u0))
    return (final @ accept) > 0.5


def dfa_match_many_onehot_blocked(data: jnp.ndarray, lens: jnp.ndarray,
                                  packed: dict) -> jnp.ndarray:
    """Block-diagonal MXU DFA bank (regex_dfa.pack_dfas_onehot_blocked):
    per-pattern one-hot states [B, N, s_max] advanced by a batched
    matmul over the pattern axis. Per-step flops are O(B·N·s_max²·C) —
    linear in the bank where the dense formulation is quadratic — so
    banks of many small automata (glob groups) ride the MXU instead of
    the latency-bound gather scan.

    → bool [B, N] acceptance per pattern."""
    b, l = data.shape
    s_max, n_cls = packed["n_states_max"], packed["n_classes"]
    n = packed["n_pats"]
    # bit-packed blocks → bf16 once per invocation [N, s·C, s]
    step_m = unpack_bits(jnp.asarray(packed["step_bits"]),
                         s_max).astype(jnp.bfloat16)
    cls_m = jnp.asarray(packed["cls"], jnp.bfloat16)     # [256, C]
    accept = jnp.asarray(packed["accept"], jnp.bfloat16)  # [N, s]

    u0 = np.zeros((1, n, s_max), np.float32)
    u0[0, :, 0] = 1.0          # every pattern starts in local state 0
    u0 = jnp.broadcast_to(jnp.asarray(u0, jnp.bfloat16), (b, n, s_max))

    bytes_tm = data.T
    maxlen = jnp.minimum(jnp.max(lens), l)

    def cond(carry):
        i, _ = carry
        return i < maxlen

    def body(carry):
        i, u = carry
        byte = jax.lax.dynamic_index_in_dim(bytes_tm, i, 0,
                                            keepdims=False)
        onehot256 = (byte[:, None] ==
                     jnp.arange(256, dtype=byte.dtype)[None, :]
                     ).astype(jnp.bfloat16)
        c1 = onehot256 @ cls_m                        # [B, C]
        v = (u[:, :, :, None] * c1[:, None, None, :]
             ).reshape(b, n, s_max * n_cls)
        nxt = jnp.einsum("bnk,nks->bns", v, step_m,
                         preferred_element_type=jnp.bfloat16)
        u = jnp.where((i < lens)[:, None, None], nxt, u)
        return i + 1, u

    _, final = jax.lax.while_loop(cond, body, (jnp.int32(0), u0))
    return jnp.einsum("bns,ns->bn", final, accept,
                      preferred_element_type=jnp.float32) > 0.5
