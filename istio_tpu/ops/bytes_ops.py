"""Device-side byte-string predicates over padded uint8 tensors.

Strings that participate in glob/regex/prefix/suffix predicates are
materialized as fixed-width ``uint8[B, L]`` rows plus ``int32[B]`` lengths
(SURVEY.md §7 "hard parts #1"). Everything here is jit-compatible and
shape-static; XLA fuses the comparisons into neighbouring ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pad_bytes(values: list[bytes], max_len: int) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: pack python byte strings into [N, L] uint8 + [N] int32."""
    out = np.zeros((len(values), max_len), dtype=np.uint8)
    lens = np.zeros(len(values), dtype=np.int32)
    for i, v in enumerate(values):
        trunc = v[:max_len]
        out[i, :len(trunc)] = np.frombuffer(trunc, dtype=np.uint8)
        lens[i] = len(trunc)
    return out, lens


def prefix_match(data: jnp.ndarray, lens: jnp.ndarray,
                 prefix: bytes) -> jnp.ndarray:
    """startsWith(const): [B, L] × pattern → bool [B]."""
    p = np.frombuffer(prefix, dtype=np.uint8)
    k = len(p)
    if k == 0:
        return jnp.ones(data.shape[0], dtype=bool)
    if k > data.shape[1]:
        return jnp.zeros(data.shape[0], dtype=bool)
    eq = jnp.all(data[:, :k] == jnp.asarray(p), axis=-1)
    return eq & (lens >= k)


def suffix_match(data: jnp.ndarray, lens: jnp.ndarray,
                 suffix: bytes) -> jnp.ndarray:
    """endsWith(const): compare a window ending at each row's length."""
    p = np.frombuffer(suffix, dtype=np.uint8)
    k = len(p)
    b, l = data.shape
    if k == 0:
        return jnp.ones(b, dtype=bool)
    if k > l:
        return jnp.zeros(b, dtype=bool)
    # gather indices len-k .. len-1 per row (clipped; masked by lens >= k)
    offs = jnp.arange(k, dtype=jnp.int32)[None, :] + (lens[:, None] - k)
    offs = jnp.clip(offs, 0, l - 1)
    window = jnp.take_along_axis(data, offs, axis=1)
    return jnp.all(window == jnp.asarray(p), axis=-1) & (lens >= k)


def exact_match(data: jnp.ndarray, lens: jnp.ndarray,
                pattern: bytes) -> jnp.ndarray:
    p = np.frombuffer(pattern, dtype=np.uint8)
    k = len(p)
    if k > data.shape[1]:
        return jnp.zeros(data.shape[0], dtype=bool)
    padded = np.zeros(data.shape[1], dtype=np.uint8)
    padded[:k] = p
    return jnp.all(data == jnp.asarray(padded), axis=-1) & (lens == k)


def glob_match(data: jnp.ndarray, lens: jnp.ndarray,
               pattern: str) -> jnp.ndarray:
    """The `match()` extern with a constant pattern
    (externs.go:108-116): trailing '*' = prefix, leading '*' = suffix,
    else exact."""
    pb = pattern.encode()
    if pb.endswith(b"*"):
        return prefix_match(data, lens, pb[:-1])
    if pb.startswith(b"*"):
        return suffix_match(data, lens, pb[1:])
    return exact_match(data, lens, pb)


def dfa_match(data: jnp.ndarray, lens: jnp.ndarray,
              transitions: jnp.ndarray, accept: jnp.ndarray) -> jnp.ndarray:
    """Run one dense DFA over every row: state := T[state, byte] for the
    first `len` bytes, then read the accept bit.

    data [B, L] uint8, transitions [S, 256] int32, accept [S] bool.
    Implemented as a lax.scan over the L byte positions (time-major
    transpose) — each step is one [B] gather from the flattened table.
    """
    b, l = data.shape
    flat = transitions.reshape(-1)  # [S*256]

    def step(state, inp):
        byte, pos = inp
        nxt = flat[state * 256 + byte.astype(jnp.int32)]
        state = jnp.where(pos < lens, nxt, state)
        return state, None

    init = jnp.zeros(b, dtype=jnp.int32)
    bytes_tm = data.T  # [L, B]
    positions = jnp.arange(l, dtype=jnp.int32)[:, None]  # [L, 1] broadcasts
    final, _ = jax.lax.scan(step, init, (bytes_tm, positions))
    return accept[final]


def dfa_match_many(data: jnp.ndarray, lens: jnp.ndarray,
                   trans_bank: jnp.ndarray,
                   accept_bank: jnp.ndarray) -> jnp.ndarray:
    """Vectorized multi-pattern DFA: run N packed DFAs (pack_dfas) over the
    same subject rows in ONE scan.

    data [B, L], trans_bank [N, S, 256], accept_bank [N, S] →  bool [B, N].
    Each scan step gathers [B, N] next-states; this is the batched-NFA
    shape the north star asks for (rules × requests per device step).
    """
    def one(tr, ac):
        return dfa_match(data, lens, tr, ac)

    return jax.vmap(one, in_axes=(0, 0), out_axes=1)(trans_bank, accept_bank)
