"""Recovery gates — evaluated from EXISTING surfaces only.

Nothing here adds instrumentation: the gates read the audit plane
(srv.audit.evaluate), the monitor counter families (deltas against a
pre-soak baseline, because the families are process-lifetime
cumulative and survive a RuntimeServer restart), the grant watermark,
and the fleet's client-side ledgers. A soak passes when, after the
storm clears:

  gate_conservation      report plane exact (accepted == exported +
                         rejected, in_flight 0) over the soak window
  gate_audit_ok          all six invariants ok, mixer_audit_healthy 1
  gate_explainability    mixer_fault_explainability_rate == 1.0 with
                         nothing pending — every injected fault
                         explained from forensics evidence alone
  gate_fault_kinds       >= min_kinds distinct injected kinds matched
  gate_no_stale_grants   grant watermark coherent (nothing issued
                         beyond the live generation) + the audited
                         grant_coherence invariant ok
  gate_plane_agreement   discovery <-> mixer agreement held live
  gate_client_accounting the per-sidecar outcome ledgers sum to the
                         server-side mixer_* front accounting
  gate_recovered         audit reached no-violated + fully-explained
                         under live traffic within the bound
                         (soak_recovery_s); strict all-ok is
                         re-asserted post-quiesce by gate_audit_ok
  gate_quiet_after       zero NEW violations after the recovery point
"""
from __future__ import annotations

import time

from istio_tpu.runtime import monitor


def snapshot_baselines() -> dict:
    """Pre-soak counter baselines (process-lifetime families)."""
    return {
        "report": monitor.report_conservation(),
        "serving": monitor.serving_counters(),
        "audit": monitor.audit_counters(),
    }


def wait_quiesce(base: dict | None = None, timeout_s: float = 20.0,
                 poll_s: float = 0.02) -> bool:
    """Drain wait: report plane in_flight → 0, deltaed against the
    soak baseline (the families are process-global — a sibling test's
    residue must not wedge this wait)."""
    since = (base or {}).get("report")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if not monitor.report_conservation(since=since)["in_flight"]:
            return True
        time.sleep(poll_s)
    return not monitor.report_conservation(since=since)["in_flight"]


def wait_recovery(audit, timeout_s: float = 30.0,
                  poll_s: float = 0.2) -> dict:
    """Poll the auditor until no invariant is violated AND the
    explainability ledger has nothing pending (rate 1.0).

    This runs with the fleet still sending: a typed-covered residue
    (e.g. deadline-expired wire RPCs that never get per-row
    responses) legitimately reads `degraded (transient)` for as long
    as traffic keeps the counter tuple moving — the auditor only
    promotes it to steady-state ok once the reading freezes, which
    cannot happen under live load. So the live recovery bar is
    "nothing violated + every injection explained"; the strict
    every-check-ok bar is asserted post-quiesce by evaluate_gates().
    soak_recovery_s is measured from entry (the caller invokes this
    at storm end)."""
    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < timeout_s:
        last = audit.evaluate()
        ex = last["explainability"]
        none_violated = all(c["status"] != "violated"
                            for c in last["checks"])
        if none_violated and last["healthy"] and ex["rate"] == 1.0 \
                and not ex["pending"]:
            return {"recovered": True,
                    "soak_recovery_s":
                        round(time.monotonic() - t0, 3),
                    "snapshot": last}
        time.sleep(poll_s)
    return {"recovered": False,
            "soak_recovery_s": round(time.monotonic() - t0, 3),
            "snapshot": last}


def _matched_kinds(ex: dict) -> set:
    return {r["kind"] for r in ex.get("records", ()) if r["matched"]}


def evaluate_gates(srv, fleet_totals: dict, base: dict, *,
                   recovery: dict, min_kinds: int = 3,
                   restarted: bool = False,
                   settle_evals: int = 3,
                   settle_sleep_s: float = 0.25) -> dict:
    """One verdict per gate + the soak_* metrics. Call AFTER the fleet
    stopped and wait_quiesce() passed; `recovery` is wait_recovery()'s
    result; `restarted` relaxes the client-accounting identity to the
    inequality (transport-level failures during the bounce never
    reached the server)."""
    gates: dict[str, bool] = {}
    detail: dict = {}

    cons = monitor.report_conservation(since=base["report"])
    gates["conservation"] = bool(cons["exact"]
                                 and not cons["in_flight"])
    detail["report_conservation"] = cons

    # strict every-check-ok, asserted at quiescence. A typed-covered
    # residue promotes from `degraded` to steady-state ok only once
    # its reading has been frozen past the auditor's stuck floor
    # (>= 2s after the last counter movement), so give the promotion
    # a bounded window instead of judging the first post-drain read.
    snap = None
    if srv.audit is not None:
        floor_s = getattr(srv.audit, "stuck_floor_s", 2.0)
        deadline = time.monotonic() + floor_s + 4.0
        while True:
            snap = srv.audit.evaluate()
            bad = [c for c in snap["checks"] if c["status"] != "ok"]
            if not bad or time.monotonic() > deadline:
                break
            time.sleep(0.3)
    if snap is None:
        gates["audit_ok"] = False
        ex = {"rate": 0.0, "pending": 1, "records": []}
    else:
        bad = [c for c in snap["checks"] if c["status"] != "ok"]
        gates["audit_ok"] = bool(snap["healthy"] and not bad)
        if bad or not snap["healthy"]:
            detail["audit_ok"] = {
                "healthy": snap["healthy"],
                "violated": [{"name": c["name"],
                              "status": c["status"],
                              "evidence": c.get("evidence")}
                             for c in bad]}
        ex = snap["explainability"]
    gates["explainability"] = bool(ex["rate"] == 1.0
                                   and not ex["pending"])
    kinds = _matched_kinds(ex)
    gates["fault_kinds"] = len(kinds) >= min_kinds
    detail["fault_kinds"] = sorted(kinds)
    detail["explainability"] = {"rate": ex["rate"],
                                "matched": ex.get("matched", 0),
                                "unexplained": ex.get("unexplained",
                                                      0)}

    # zero stale-generation serves: the watermark must never show
    # grants issued beyond the live generation, and the audited
    # grant_coherence invariant must read ok
    wm = srv.grants.watermark() if getattr(srv, "grants", None) \
        else None
    coherent = True
    if wm is not None:
        coherent = wm.get("issued_at_generation",
                          wm["generation"]) <= wm["generation"]
    if snap is not None:
        gc = next((c for c in snap["checks"]
                   if c["name"] == "grant_coherence"), None)
        coherent = coherent and (gc is None or gc["status"] == "ok")
    gates["no_stale_grants"] = bool(coherent)
    detail["grant_watermark"] = wm

    if snap is not None:
        pa = next((c for c in snap["checks"]
                   if c["name"] == "plane_agreement"), None)
        gates["plane_agreement"] = pa is None or \
            pa["status"] == "ok"
    else:
        gates["plane_agreement"] = False

    # client ledger <-> server front accounting
    sc = monitor.serving_counters()
    decoded = sc["requests_decoded"] \
        - base["serving"]["requests_decoded"]
    responded = sc["responses_sent"] \
        - base["serving"]["responses_sent"]
    oc = fleet_totals["outcomes"]
    wire = fleet_totals["wire_checks"]
    # cache-answered checks land in ok/denied but never crossed the
    # wire: only the wire-answered subset can match responses_sent
    answered = oc["ok"] + oc["denied"] \
        - fleet_totals.get("cache_hits", 0)
    rejected = oc["shed"] + oc["expired"] + oc["unavailable"] \
        + oc["error"]
    if restarted:
        # transport failures during the bounce never reached a front:
        # decoded is bounded by what the clients sent, and everything
        # decoded beyond the completed answers is a typed rejection
        ok_acct = (answered <= decoded <= wire
                   and responded >= answered
                   and decoded - responded <= rejected)
    else:
        ok_acct = (decoded == wire and responded == answered
                   and decoded - responded == rejected)
    gates["client_accounting"] = bool(ok_acct)
    detail["accounting"] = {
        "decoded_delta": decoded, "responded_delta": responded,
        "client_wire": wire, "client_answered": answered,
        "client_rejected": rejected,
        "client_outcomes": dict(oc),
        "restarted": restarted,
    }

    # routing conservation as the CLIENT saw it: no applied discovery
    # generation ever stopped serving a sidecar's own service
    gates["no_client_misroutes"] = oc.get("misrouted", 0) == 0

    gates["recovered"] = bool(recovery.get("recovered"))

    # violations after recovery: the counters must stay frozen over a
    # few more evaluations
    v0 = monitor.audit_counters()["violations"]
    for _ in range(max(int(settle_evals), 1)):
        time.sleep(settle_sleep_s)
        if srv.audit is not None:
            srv.audit.evaluate()
    v1 = monitor.audit_counters()["violations"]
    after = sum(v1[k] - v0.get(k, 0) for k in v1)
    gates["quiet_after_recovery"] = after == 0
    detail["violations_after_recovery"] = after

    return {
        "gates": gates,
        "all_ok": all(gates.values()),
        "detail": detail,
        "metrics": {
            "soak_recovery_s": recovery.get("soak_recovery_s"),
            "soak_explainability_rate": ex["rate"],
            "soak_violations_after_recovery": after,
            "soak_fault_kinds": sorted(kinds),
        },
    }
