"""Seeded storm choreographer: a deterministic schedule of
control-side events replayed against the live mesh.

make_schedule(seed, ...) derives every event time/parameter from one
np.random.default_rng(seed) stream — the replay contract: the printed
seed line reproduces the exact injection schedule, byte for byte
(schedule_signature() is what the tier-1 determinism test compares).

The choreographer executes the schedule in typed phases
(warmup → storm → recovery) against a duck-typed harness:

    harness.churn(ns_index, tick)   discovery-plane one-namespace churn
    harness.mixer_churn(tick)       mixer config bump → swap + grant
                                    revocation
    harness.poke_quota()            one host-path quota call (makes an
                                    armed quota-backend failure land
                                    deterministically)
    harness.canary_poison() /       install / remove a deny-everything
    harness.canary_heal()           rule (gate-mode canary vetoes it;
                                    heal restores publishability)
    harness.restart()               the mid-soak quiesce→restart cycle
                                    (ordered shutdown, fresh server)
    harness.wedged_handler          qualified handler name to wedge
    harness.quota_name              quota instance the stall targets

Chaos arms (wedge, latency, device/oracle faults, quota failures,
discovery push delay) go straight through the process-wide CHAOS seam
— every injected FAILURE registers in the InjectionLedger at its
commit point, which is what the explainability gate scores.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Sequence

import numpy as np

log = logging.getLogger("istio_tpu.soak.storm")

PHASES = ("warmup", "storm", "recovery")


@dataclasses.dataclass(frozen=True)
class StormEvent:
    t: float            # seconds from storm-phase start
    kind: str
    params: tuple = ()  # sorted (key, value) pairs — hashable/stable

    def sig(self) -> tuple:
        return (round(self.t, 4), self.kind, self.params)


def _ev(t: float, kind: str, **params) -> StormEvent:
    return StormEvent(round(float(t), 4), kind,
                      tuple(sorted((k, str(v))
                                   for k, v in params.items())))


def make_schedule(seed: int, storm_s: float, *,
                  n_namespaces: int = 4,
                  restart: bool = True,
                  canary: bool = False) -> list[StormEvent]:
    """The full storm, seeded. Windows are placed so every fault kind
    lands inside the storm phase with room to clear before recovery:

      * adapter wedge + unwedge (the bulkhead/overrun lever)
      * adapter latency set + clear
      * device-fault burst tripping the breaker into oracle fallback,
        with the quota-backend failure armed INSIDE the outage window
        (served quota rides the host memquota lane only while the
        device pools are bypassed — the realistic coupling) plus one
        deterministic host-path poke so the injection always commits
      * discovery push delay armed around a churn (the delayed publish
        is synchronous with the store event — deterministic evidence)
      * namespace churn ticks (delta publishes) through the storm
      * mixer config bumps (config swaps → grant revocation storm)
      * optionally a canary poison/heal pair and the mid-soak restart
    """
    rng = np.random.default_rng(seed)
    span = max(float(storm_s), 2.0)
    ev: list[StormEvent] = []

    # adapter wedge window, early in the storm
    t0 = float(rng.uniform(0.05, 0.15)) * span
    hold = float(rng.uniform(0.25, 0.5))
    ev.append(_ev(t0, "wedge"))
    ev.append(_ev(t0 + hold, "unwedge"))

    # adapter latency window
    t1 = float(rng.uniform(0.2, 0.3)) * span
    ev.append(_ev(t1, "adapter_latency",
                  s=round(float(rng.uniform(0.01, 0.03)), 4)))
    ev.append(_ev(t1 + float(rng.uniform(0.4, 0.8)),
                  "adapter_latency_clear"))

    # device outage window; quota-backend failures armed inside it
    t2 = float(rng.uniform(0.35, 0.5)) * span
    ev.append(_ev(t2, "device_faults", n=int(rng.integers(4, 9))))
    ev.append(_ev(t2 + 0.1, "quota_faults",
                  n=int(rng.integers(2, 5))))
    ev.append(_ev(t2 + 0.15, "poke_quota"))

    # discovery push delay armed around its own churn
    t3 = float(rng.uniform(0.55, 0.65)) * span
    ev.append(_ev(t3, "discovery_delay",
                  s=round(float(rng.uniform(0.05, 0.12)), 4),
                  ns=int(rng.integers(n_namespaces))))

    # churn ticks through the whole storm
    for k in range(4 + int(rng.integers(4))):
        ev.append(_ev(float(rng.uniform(0.05, 0.9)) * span, "churn",
                      ns=int(rng.integers(n_namespaces)), tick=k))

    # mixer config bumps: swaps under load → grant revocations
    for k in range(2 + int(rng.integers(3))):
        ev.append(_ev(float(rng.uniform(0.1, 0.85)) * span,
                      "mixer_churn", tick=k))

    if canary:
        t4 = float(rng.uniform(0.15, 0.25)) * span
        ev.append(_ev(t4, "canary_poison"))
        ev.append(_ev(t4 + 0.5, "canary_heal"))

    if restart:
        # fixed mid-storm placement: the restart must land with chaos
        # windows on both sides, not wander to an edge
        ev.append(_ev(0.7 * span, "restart"))

    ev.sort(key=lambda e: (e.t, e.kind))
    return ev


def schedule_signature(schedule: Sequence[StormEvent]) -> tuple:
    return tuple(e.sig() for e in schedule)


def clear_chaos() -> None:
    """Targeted storm-end cleanup: release every armed seam WITHOUT
    CHAOS.reset() (reset would also drop the seed stamp and injected-
    counter provenance mid-run)."""
    from istio_tpu.runtime.resilience import CHAOS
    for h in list(CHAOS._adapter_wedged):
        CHAOS.unwedge_adapter(h)
    CHAOS.adapter_latency_s.clear()
    CHAOS.adapter_failures.clear()
    CHAOS.quota_latency_s.clear()
    CHAOS.quota_failures.clear()
    CHAOS.device_failures = 0
    CHAOS.device_latency_s = 0.0
    CHAOS.oracle_failures = 0
    CHAOS.discovery_push_delay_s = 0.0


class StormChoreographer:
    """Executes a schedule against the harness on its own thread; the
    caller drives the phase boundaries (run() blocks through all
    three). The executed-event log is for operators — determinism is
    asserted on the SCHEDULE, which is pure f(seed)."""

    def __init__(self, harness, schedule: Sequence[StormEvent],
                 *, warmup_s: float = 1.0, storm_s: float = 6.0):
        self.harness = harness
        self.schedule = list(schedule)
        self.warmup_s = float(warmup_s)
        self.storm_s = float(storm_s)
        self.log: list[dict] = []
        self.phase = "idle"

    def _note(self, ev: StormEvent) -> None:
        self.log.append({"phase": self.phase, "t": ev.t,
                         "kind": ev.kind, "params": dict(ev.params)})

    def _execute(self, ev: StormEvent) -> None:
        from istio_tpu.runtime.resilience import CHAOS
        h = self.harness
        p = dict(ev.params)
        kind = ev.kind
        try:
            if kind == "wedge":
                CHAOS.wedge_adapter(h.wedged_handler)
            elif kind == "unwedge":
                CHAOS.unwedge_adapter(h.wedged_handler)
            elif kind == "adapter_latency":
                CHAOS.adapter_latency_s[h.wedged_handler] = \
                    float(p["s"])
            elif kind == "adapter_latency_clear":
                CHAOS.adapter_latency_s.clear()
            elif kind == "device_faults":
                CHAOS.device_failures = int(p["n"])
            elif kind == "quota_faults":
                CHAOS.quota_failures[h.quota_name] = int(p["n"])
            elif kind == "poke_quota":
                h.poke_quota()
            elif kind == "discovery_delay":
                CHAOS.discovery_push_delay_s = float(p["s"])
                try:
                    # the armed delay needs a publish to stall: drive
                    # one churn synchronously while armed
                    h.churn(int(p["ns"]), tick=997)
                finally:
                    CHAOS.discovery_push_delay_s = 0.0
            elif kind == "churn":
                h.churn(int(p["ns"]), tick=int(p["tick"]))
            elif kind == "mixer_churn":
                h.mixer_churn(int(p["tick"]))
            elif kind == "canary_poison":
                h.canary_poison()
            elif kind == "canary_heal":
                h.canary_heal()
            elif kind == "restart":
                h.restart()
            else:
                log.warning("unknown storm event kind %r", kind)
        except Exception:
            log.exception("storm event %s failed", kind)
        self._note(ev)

    def run(self) -> list[dict]:
        self.phase = "warmup"
        time.sleep(self.warmup_s)
        self.phase = "storm"
        t0 = time.monotonic()
        for ev in self.schedule:
            delay = ev.t - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            self._execute(ev)
        # hold the storm open to its nominal span (events may cluster
        # early), then clear every armed seam
        tail = self.storm_s - (time.monotonic() - t0)
        if tail > 0:
            time.sleep(tail)
        self.phase = "recovery"
        clear_chaos()
        return self.log
