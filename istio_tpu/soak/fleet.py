"""Fleet-lifecycle simulator: N sidecars living the full client life.

Each simulated sidecar owns a real MixerClient (check-cache optional),
paces itself closed-loop, and classifies EVERY check it issues into a
typed outcome — the client half of the conservation story. The server
half is monitor.serving_counters(): every check that crossed the wire
was decoded exactly once, every completed answer (ok or denied) was
counted as a response, and every typed rejection is the difference.
With the server up (no restart window) the identity is exact:

    wire_checks   == requests_decoded Δ
    ok + denied   == responses_sent Δ
    shed + expired + unavailable + error == decoded Δ - responses Δ

Across a mid-soak restart, transport-level failures (connection
refused while the front is down) never reach the server, so the gate
degrades to the honest inequality (gates.evaluate_gates).

The discovery leg mirrors a sidecar's xDS loop: park on watch(),
apply the new generation by pulling its own RDS config, and count a
version that no longer serves the sidecar's own service as
`misrouted` — the client-side reading of routing conservation.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Mapping, Sequence

import grpc
import numpy as np

OUTCOMES = ("ok", "denied", "shed", "expired", "unavailable",
            "misrouted", "error")

_GRPC_OUTCOME = {
    grpc.StatusCode.DEADLINE_EXCEEDED: "expired",
    grpc.StatusCode.RESOURCE_EXHAUSTED: "shed",
    grpc.StatusCode.UNAVAILABLE: "unavailable",
}

PERMISSION_DENIED = 7


class SidecarLedger:
    """Typed outcome ledger for one simulated sidecar. Every check the
    sidecar issued lands in exactly one outcome bucket; wire_checks
    counts the subset that actually crossed the wire (cache hits
    answered locally)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.outcomes = {o: 0 for o in OUTCOMES}
        self.checks = 0
        self.cache_hits = 0
        self.reports_ok = 0
        self.reports_failed = 0
        self.quota_granted = 0
        self.quota_denied = 0
        self.versions_applied = 0
        self.watch_errors = 0
        # secure-plane leg (WorkloadIdentity lifecycle): every CSR the
        # sidecar issued lands in exactly one bucket, same discipline
        # as the check outcomes
        self.identity_issues = 0
        self.identity_rotations = 0
        self.identity_failures = 0

    def count(self, outcome: str) -> None:
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1

    @property
    def wire_checks(self) -> int:
        return self.checks - self.cache_hits

    def totals(self) -> dict:
        with self._lock:
            return {
                "outcomes": dict(self.outcomes),
                "checks": self.checks,
                "cache_hits": self.cache_hits,
                "wire_checks": self.wire_checks,
                "reports_ok": self.reports_ok,
                "reports_failed": self.reports_failed,
                "quota_granted": self.quota_granted,
                "quota_denied": self.quota_denied,
                "versions_applied": self.versions_applied,
                "watch_errors": self.watch_errors,
                "identity_issues": self.identity_issues,
                "identity_rotations": self.identity_rotations,
                "identity_failures": self.identity_failures,
            }


def _merge_totals(parts: Sequence[dict]) -> dict:
    out: dict = {"outcomes": {o: 0 for o in OUTCOMES}}
    for p in parts:
        for o, v in p["outcomes"].items():
            out["outcomes"][o] = out["outcomes"].get(o, 0) + v
        for k, v in p.items():
            if k == "outcomes":
                continue
            out[k] = out.get(k, 0) + v
    return out


def _node_identity(node: str) -> tuple[str, str]:
    """(own host, namespace) from a workloads.make_discovery_world
    node id `sidecar~ip~svc{i}-{r}.{ns}~domain`."""
    inst = node.split("~")[2]
    svc_inst, ns = inst.split(".", 1)
    svc = svc_inst.rsplit("-", 1)[0]
    return f"{svc}.{ns}.svc.cluster.local", ns


class FleetSimulator:
    """N sidecar threads against one target provider.

    `target`: () -> "host:port", re-read every iteration — a mid-soak
    restart just changes what it returns and the sidecars reconnect
    (the old channel's failures land as typed `unavailable` outcomes,
    exactly what a real sidecar sees through a control-plane bounce).

    `discovery`/`nodes`/`ns_ports`: optional xDS leg — one watcher
    thread per sidecar parks on DiscoveryService.watch and validates
    each applied generation still serves the sidecar's own service.

    `ca_client`: optional secure-plane leg — each sidecar owns a
    WorkloadIdentity (spiffe://.../ns/<ns>/sa/sidecar-<i>), obtains
    its bundle from the CA before the first check and rotates every
    `identity_rotate_every` checks (deterministic cadence — a soak
    wants reproducible rotation pressure, not wall-clock TTLs).
    Issue/rotate outcomes land in the typed ledger. When
    `tls_server_name` is also set the sidecar's MixerClient fronts
    mTLS from the live bundle and reconnects after every rotation so
    each fresh cert actually handshakes.
    """

    def __init__(self, target: Callable[[], str],
                 requests: Sequence[Mapping], *,
                 n_sidecars: int = 4, seed: int = 0,
                 pace_s: float = 0.002,
                 quota_every: int = 0,
                 quota_name: str = "rq.istio-system",
                 report_every: int = 0,
                 enable_check_cache: bool = True,
                 discovery=None, nodes: Sequence[str] = (),
                 ns_ports: Mapping[str, int] | None = None,
                 ca_client=None, identity_ns: str = "default",
                 identity_ttl_minutes: int = 60,
                 identity_rotate_every: int = 0,
                 tls_server_name: str | None = None):
        if not requests:
            raise ValueError("fleet needs a non-empty request set")
        self._target = target
        self._requests = list(requests)
        self.n_sidecars = int(n_sidecars)
        self._seed = int(seed)
        self._pace_s = float(pace_s)
        self._quota_every = int(quota_every)
        self._quota_name = quota_name
        self._report_every = int(report_every)
        self._cache = bool(enable_check_cache)
        self._discovery = discovery
        self._nodes = list(nodes)
        self._ns_ports = dict(ns_ports or {})
        self._ca_client = ca_client
        self._identity_ns = identity_ns
        self._identity_ttl_minutes = int(identity_ttl_minutes)
        self._identity_rotate_every = int(identity_rotate_every)
        self._tls_server_name = tls_server_name
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.ledgers = [SidecarLedger() for _ in range(self.n_sidecars)]

    # -- sidecar lifecycle --------------------------------------------

    def _identity_for(self, idx: int):
        if self._ca_client is None:
            return None
        from istio_tpu.secure.identity import WorkloadIdentity
        from istio_tpu.security import spiffe_id
        return WorkloadIdentity(
            self._ca_client,
            spiffe_id(self._identity_ns, f"sidecar-{idx}"),
            ttl_minutes=self._identity_ttl_minutes)

    def _client_for(self, led, cur, cur_target: str | None, wi=None):
        """Reconnect when the target moved (mid-soak restart): fold
        the dying client's cache accounting into the ledger first —
        cache-answered checks never crossed the wire and wire_checks
        must say so."""
        from istio_tpu.api.client import MixerClient
        t = self._target()
        if cur is not None and t == cur_target:
            return cur, cur_target
        if cur is not None:
            led.cache_hits += cur.cache_stats["hits"]
            try:
                cur.close()
            except Exception:
                pass
        kw = {}
        if wi is not None and self._tls_server_name:
            key_pem, cert_pem, root_pem = wi.ensure()
            kw = dict(root_cert_pem=root_pem, key_pem=key_pem,
                      cert_pem=cert_pem,
                      server_name=self._tls_server_name)
        return MixerClient(t, enable_check_cache=self._cache, **kw), t

    def _sidecar(self, idx: int) -> None:
        led = self.ledgers[idx]
        rng = np.random.default_rng(self._seed * 1009 + idx)
        order = rng.permutation(len(self._requests))
        client = None
        cur_target: str | None = None
        pos = 0
        wi = self._identity_for(idx)
        if wi is not None:
            try:
                wi.ensure()
                led.identity_issues += 1
            except Exception:
                led.identity_failures += 1
        try:
            while not self._stop.is_set():
                if wi is not None and wi.bundle() is None:
                    # no identity yet (CA was down at start): retry the
                    # obtain before spending checks — a strict front
                    # would refuse the handshake anyway
                    try:
                        wi.ensure()
                        led.identity_issues += 1
                    except Exception:
                        led.identity_failures += 1
                        time.sleep(0.05)
                        continue
                try:
                    client, cur_target = self._client_for(
                        led, client, cur_target, wi)
                except Exception:
                    led.count("unavailable")
                    time.sleep(0.05)
                    continue
                rq = self._requests[int(order[pos % len(order)])]
                pos += 1
                led.checks += 1
                quotas = None
                if self._quota_every and \
                        pos % self._quota_every == 0:
                    quotas = {self._quota_name: 1}
                try:
                    resp = client.check(rq, quotas=quotas)
                except grpc.RpcError as exc:
                    outcome = _GRPC_OUTCOME.get(exc.code(), "error")
                    led.count(outcome)
                    if outcome == "unavailable":
                        # the front is down (restart window): back off
                        # like a real sidecar instead of hammering the
                        # dead port at full pace
                        time.sleep(0.02)
                except Exception:
                    led.count("error")
                else:
                    code = resp.precondition.status.code
                    led.count("ok" if code == 0 else
                              "denied" if code == PERMISSION_DENIED
                              else "error")
                    if quotas and code == 0:
                        qr = resp.quotas.get(self._quota_name)
                        if qr is not None and qr.granted_amount > 0:
                            led.quota_granted += 1
                        else:
                            led.quota_denied += 1
                if self._report_every and \
                        pos % self._report_every == 0:
                    try:
                        client.report([rq])
                        led.reports_ok += 1
                    except Exception:
                        led.reports_failed += 1
                if wi is not None and self._identity_rotate_every \
                        and pos % self._identity_rotate_every == 0:
                    try:
                        wi.rotate()
                        led.identity_rotations += 1
                    except Exception:
                        led.identity_failures += 1
                    else:
                        if self._tls_server_name and client is not None:
                            # handshake the fresh cert: drop the old
                            # channel (cache accounting folds first)
                            led.cache_hits += \
                                client.cache_stats["hits"]
                            try:
                                client.close()
                            except Exception:
                                pass
                            client, cur_target = None, None
                if self._pace_s:
                    time.sleep(self._pace_s)
        finally:
            if client is not None:
                led.cache_hits += client.cache_stats["hits"]
                try:
                    client.close()
                except Exception:
                    pass

    # -- discovery watcher leg ----------------------------------------

    def _watcher(self, idx: int) -> None:
        led = self.ledgers[idx]
        node = self._nodes[idx % len(self._nodes)]
        host, ns = _node_identity(node)
        port = self._ns_ports.get(ns)
        have = 0
        while not self._stop.is_set():
            try:
                out = self._discovery.watch(node, have, timeout_s=0.25)
            except Exception:
                led.watch_errors += 1
                time.sleep(0.05)
                continue
            if not out.get("changed"):
                continue
            have = max(have, int(out.get("shard_version", 0)),
                       int(out.get("version", 0)))
            led.versions_applied += 1
            if port is None:
                continue
            # apply the generation: the sidecar's own RDS config must
            # still route its service — a version that lost it is a
            # misroute as the CLIENT experiences it
            try:
                raw = self._discovery.list_routes(str(port), "svc-mesh",
                                                  node)
            except Exception:
                led.count("misrouted")
                continue
            if host.encode() not in raw:
                led.count("misrouted")

    # -- control ------------------------------------------------------

    def start(self) -> "FleetSimulator":
        for i in range(self.n_sidecars):
            t = threading.Thread(target=self._sidecar, args=(i,),
                                 daemon=True, name=f"soak-sidecar-{i}")
            t.start()
            self._threads.append(t)
            if self._discovery is not None and self._nodes:
                w = threading.Thread(target=self._watcher, args=(i,),
                                     daemon=True,
                                     name=f"soak-watch-{i}")
                w.start()
                self._threads.append(w)
        return self

    def stop(self, grace_s: float = 10.0) -> dict:
        self._stop.set()
        deadline = time.monotonic() + grace_s
        for t in self._threads:
            t.join(max(deadline - time.monotonic(), 0.1))
        return self.totals()

    def totals(self) -> dict:
        return _merge_totals([led.totals() for led in self.ledgers])
