"""The soak rig: build the whole mesh in-process, run the phases,
gate the recovery. scripts/soak_smoke.py (tier-1 scale) and bench.py's
`soak_*` section (sustained scale) are both thin wrappers over
run_soak() — one code path, two durations, same gates.

The harness owns every mutable endpoint so the mid-soak restart is
just "replace what I own": the fleet reads ports through closures and
reconnects on its own, exactly like sidecars through a control-plane
bounce. The restart rides the ordered-shutdown doctrine
(scripts/lifecycle_smoke.py): fronts stop first, the runtime drains
and reaps its threads, then a fresh server + fronts come up over the
SAME stores — counters are process-global, so conservation is checked
straight across the quiesce.
"""
from __future__ import annotations

import dataclasses
import logging
import time

log = logging.getLogger("istio_tpu.soak.harness")

WEDGED = "cilist.istio-system"
QUOTA_NAME = "rq.istio-system"
DEADLINE_MS = 600.0


@dataclasses.dataclass
class SoakConfig:
    seed: int = 0
    n_rules: int = 32
    n_services: int = 12
    n_namespaces: int = 4
    replicas: int = 2
    n_sidecars_grpc: int = 3
    n_sidecars_native: int = 1
    warmup_s: float = 1.0
    storm_s: float = 6.0
    recovery_timeout_s: float = 30.0
    pace_s: float = 0.002
    quota_every: int = 5
    report_every: int = 7
    restart: bool = True
    canary: bool = False
    min_fault_kinds: int = 3
    buckets: tuple = (8, 16)


def overlay_request(i: int, n_services: int) -> dict:
    """Request matching make_store(host_overlay_every=5) rule i (the
    executor_smoke convention — i % 5 == 2, k == 0 → cilist): the
    traffic that makes a wedged cilist lane observable."""
    return {
        "destination.service":
            f"svc{i % n_services}.ns{i % 23}.svc.cluster.local",
        "source.namespace": "ns2",
        "request.method": "GET",
        "request.path": f"/api/v{i % 3}/items",
    }


class SoakHarness:
    """Owns the mesh: mixer store + RuntimeServer + both fronts +
    introspect, and the discovery world + in-process
    DiscoveryService. Implements the choreographer's event surface."""

    wedged_handler = WEDGED
    quota_name = QUOTA_NAME

    def __init__(self, cfg: SoakConfig):
        from istio_tpu.pilot.discovery import DiscoveryService
        from istio_tpu.testing import workloads

        self.cfg = cfg
        self.store = workloads.make_store(cfg.n_rules,
                                          host_overlay_every=5,
                                          seed=cfg.seed)
        (self.registry, self.dstore, self.nodes,
         self.meta) = workloads.make_discovery_world(
            n_services=cfg.n_services,
            n_namespaces=cfg.n_namespaces,
            replicas=cfg.replicas, seed=cfg.seed)
        self.disc = DiscoveryService(self.registry, self.dstore)
        self.ns_ports = {f"ns{k}": p
                         for k, p in self.meta["ns_ports"].items()}
        self._churnable = sorted(self.meta["rules_by_ns"])
        self.srv = None
        self.g = self.native = self.intro = None
        self.gport = self.nport = self.http_port = 0
        self.restarts = 0
        self.restart_wall_s = 0.0
        self._build_server()

    def _args(self):
        from istio_tpu.runtime import ServerArgs
        from istio_tpu.testing import workloads
        cfg = self.cfg
        return ServerArgs(
            batch_window_s=0.0005, max_batch=16,
            buckets=cfg.buckets,
            default_check_deadline_ms=DEADLINE_MS,
            host_breaker_failures=2, host_breaker_reset_s=0.4,
            breaker_reset_s=1.5,
            audit_interval_s=0.2,
            # the explainability window must cover the WHOLE soak —
            # storm + recovery + settle — or early injections age out
            # of the matched-kinds reading before the final evaluate
            audit_explain_window_s=max(120.0, cfg.storm_s * 4 + 60.0),
            check_grants=True,
            canary="gate" if cfg.canary else "off",
            default_manifest=workloads.MESH_MANIFEST)

    def _build_server(self) -> None:
        from istio_tpu.api.grpc_server import MixerGrpcServer
        from istio_tpu.api.native_server import NativeMixerServer
        from istio_tpu.introspect import IntrospectServer

        from istio_tpu.runtime import RuntimeServer

        self.srv = RuntimeServer(self.store, self._args())
        if self.srv.audit is not None:
            self.srv.audit.attach_discovery(self.disc)
        plan = self.srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm(self.cfg.buckets)
        self.g = MixerGrpcServer(runtime=self.srv)
        self.native = NativeMixerServer(self.srv, min_fill=8,
                                        window_us=500)
        self.intro = IntrospectServer(runtime=self.srv)
        self.gport = self.g.start()
        self.nport = self.native.start()
        self.http_port = self.intro.start()

    # -- choreographer event surface ----------------------------------

    def churn(self, ns: int, tick: int) -> None:
        from istio_tpu.testing import workloads
        k = self._churnable[ns % len(self._churnable)]
        workloads.churn_discovery_rule(self.dstore, self.meta, k, tick)

    def mixer_churn(self, tick: int) -> None:
        """Mixer config bump: re-setting a rule's spec fires the store
        event → debounced rebuild → atomic swap → pre-swap grant
        revocation (the revocation-storm lever, no verdict change)."""
        key = ("rule", "istio-system", "report-all")
        spec = self.store.get(key)
        if spec is not None:
            self.store.set(key, dict(spec))

    def poke_quota(self) -> None:
        """One host-path quota call (dispatcher.quota → executor mq
        lane → MemQuotaHandler): lands the armed quota-backend failure
        deterministically instead of waiting for the fleet to catch
        the device-outage window."""
        from istio_tpu.adapters.sdk import QuotaArgs
        from istio_tpu.attribute.bag import bag_from_mapping
        try:
            self.srv.quota(
                bag_from_mapping({
                    "source.user": "soak-poke",
                    "destination.service":
                        "svc0.ns0.svc.cluster.local"}),
                QUOTA_NAME, QuotaArgs(quota_amount=1))
        except Exception:
            pass    # an injected failure surfacing typed is the point

    def canary_poison(self) -> None:
        self.store.set(("rule", "istio-system", "soak-veto"), {
            "match": "",
            "actions": [{"handler": "denyall.istio-system",
                         "instances": ["nothing.istio-system"]}]})

    def canary_heal(self) -> None:
        self.store.delete(("rule", "istio-system", "soak-veto"))

    def restart(self) -> None:
        """Mid-soak quiesce→restart under live fleet traffic, riding
        the ordered-shutdown doctrine: fronts stop (clients see typed
        UNAVAILABLE, never hangs), the runtime drains and reaps, a
        fresh server + fronts replace them; the fleet reconnects via
        the port closures."""
        t0 = time.monotonic()
        try:
            self.native.stop()
            self.g.stop()
            self.srv.shutdown(deadline=5.0)
            self.intro.close()
        except Exception:
            log.exception("soak restart: teardown leg failed")
        self._build_server()
        self.restarts += 1
        self.restart_wall_s = round(time.monotonic() - t0, 3)

    def close(self) -> None:
        for step in (lambda: self.native.stop(),
                     lambda: self.g.stop(),
                     lambda: self.intro.close(),
                     lambda: self.srv.close()):
            try:
                step()
            except Exception:
                pass


def run_soak(cfg: SoakConfig) -> dict:
    """Build the mesh, run warmup → storm → recovery, stop the fleet,
    evaluate the gates. Chaos/ledger state is reset on entry; the
    caller owns the final reset (smoke/bench `finally` blocks)."""
    from istio_tpu.runtime import monitor
    from istio_tpu.runtime.audit import INJECTIONS, SEAMS
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.soak import fleet as fleet_mod
    from istio_tpu.soak import gates as gates_mod
    from istio_tpu.soak import storm as storm_mod
    from istio_tpu.testing import workloads

    CHAOS.reset()
    INJECTIONS.reset()
    SEAMS.reset()
    CHAOS.seed = cfg.seed

    harness = SoakHarness(cfg)
    schedule = storm_mod.make_schedule(
        cfg.seed, cfg.storm_s, n_namespaces=cfg.n_namespaces,
        restart=cfg.restart, canary=cfg.canary)
    n_services = max(cfg.n_rules // 2, 1)
    ci_rules = [i for i in range(2, cfg.n_rules, 5)
                if (i // 5) % 3 == 0]
    requests = list(workloads.make_request_dicts(24, seed=cfg.seed))
    requests += [overlay_request(i, n_services) for i in ci_rules]

    fleets = []
    try:
        base = gates_mod.snapshot_baselines()
        stage_base = monitor.stage_baseline()
        fg = fleet_mod.FleetSimulator(
            lambda: f"127.0.0.1:{harness.gport}", requests,
            n_sidecars=cfg.n_sidecars_grpc, seed=cfg.seed,
            pace_s=cfg.pace_s, quota_every=cfg.quota_every,
            quota_name=QUOTA_NAME, report_every=cfg.report_every,
            enable_check_cache=True, discovery=harness.disc,
            nodes=harness.nodes, ns_ports=harness.ns_ports)
        fn = fleet_mod.FleetSimulator(
            lambda: f"127.0.0.1:{harness.nport}", requests,
            n_sidecars=cfg.n_sidecars_native, seed=cfg.seed + 1,
            pace_s=cfg.pace_s, enable_check_cache=False)
        fleets = [fg.start(), fn.start()]

        storm = storm_mod.StormChoreographer(
            harness, schedule, warmup_s=cfg.warmup_s,
            storm_s=cfg.storm_s)
        t_run0 = time.monotonic()
        storm_log = storm.run()
        recovery = gates_mod.wait_recovery(
            harness.srv.audit, timeout_s=cfg.recovery_timeout_s)

        fleet_totals = fleet_mod._merge_totals(
            [f.stop() for f in fleets])
        fleets = []
        run_wall_s = time.monotonic() - t_run0
        quiesced = gates_mod.wait_quiesce(base)
        verdict = gates_mod.evaluate_gates(
            harness.srv, fleet_totals, base, recovery=recovery,
            min_kinds=cfg.min_fault_kinds, restarted=cfg.restart)
        verdict["gates"]["quiesced"] = quiesced
        verdict["all_ok"] = all(verdict["gates"].values())
        lat = monitor.latency_snapshot(since=stage_base)
        return {
            "seed": cfg.seed,
            "schedule": storm_mod.schedule_signature(schedule),
            "storm_log": storm_log,
            "gates": verdict["gates"],
            "all_ok": verdict["all_ok"],
            "detail": verdict["detail"],
            "metrics": verdict["metrics"],
            "fleet": fleet_totals,
            "throughput_rps": round(
                fleet_totals["checks"] / run_wall_s, 1)
            if run_wall_s > 0 else 0.0,
            "latency": lat,
            "restarts": harness.restarts,
            "restart_wall_s": harness.restart_wall_s,
        }
    finally:
        for f in fleets:
            try:
                f.stop(grace_s=5.0)
            except Exception:
                pass
        harness.close()
