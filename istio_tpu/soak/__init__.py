"""Whole-mesh chaos soak (ROADMAP item 5's open leg).

Three pieces, composed by scripts/soak_smoke.py and bench.py's
`soak_*` section:

  * fleet.FleetSimulator — N simulated sidecars running the full
    client lifecycle concurrently (discovery watch + config-version
    apply, Check/Report/quota traffic through the REAL fronts with
    client check-caches, closed-loop pacing) with a per-sidecar typed
    outcome ledger, so conservation is checkable from the client side;
  * storm.StormChoreographer — a seeded, deterministic schedule of
    control-side events (churn publishes, canary vetoes, adapter
    wedges, device faults, quota-backend stalls, discovery push
    delays, grant revocation storms, a mid-soak restart) replayed
    against the live server in typed phases warmup → storm → recovery,
    every injection registered in the audit plane's InjectionLedger;
  * gates — the recovery gates, evaluated from existing surfaces only:
    exact report conservation, audit all-ok within a bound
    (soak_recovery_s), explainability rate 1.0, zero stale-generation
    serves, plane agreement, and the client-ledger ↔ mixer_* counter
    accounting identity.
"""
from istio_tpu.soak.fleet import (FleetSimulator, SidecarLedger,
                                  OUTCOMES)
from istio_tpu.soak.storm import (StormChoreographer, StormEvent,
                                  make_schedule, clear_chaos,
                                  schedule_signature, PHASES)
from istio_tpu.soak.gates import (snapshot_baselines, wait_quiesce,
                                  wait_recovery, evaluate_gates)
from istio_tpu.soak.harness import SoakConfig, SoakHarness, run_soak

__all__ = [
    "FleetSimulator", "SidecarLedger", "OUTCOMES",
    "StormChoreographer", "StormEvent", "make_schedule",
    "clear_chaos", "schedule_signature", "PHASES",
    "snapshot_baselines", "wait_quiesce", "wait_recovery",
    "evaluate_gates", "SoakConfig", "SoakHarness", "run_soak",
]
