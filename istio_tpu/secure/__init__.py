"""Secure serving plane — SPIFFE workload identity, CA-driven cert
rotation, mTLS fronts feeding the device-compiled RBAC plane.

Layout:

  backend.py  — the `PkiBackend` seam: one PEM-bytes API, two
                implementations (`cryptography` when importable, the
                `openssl` CLI otherwise) so the PKI plane runs — and
                tier-1 exercises it — on crypto-less rigs too.
  identity.py — `WorkloadIdentity`: obtain / cache / rotate short-TTL
                workload certs against the CA gRPC service, rotation
                driven off the executor maintenance lane, issuance /
                rotation / expiry as forensics events + zero-shaped
                mixer_identity_* counters.
  mtls.py     — mTLS modes (off|permissive|strict), hot-reloadable
                serving credentials for the gRPC fronts
                (dynamic_ssl_server_credentials fetcher) and the
                stdlib-ssl HTTP fronts (per-accept context swap), and
                peer SPIFFE identity extraction at request admission.
  tlslane.py  — stdlib-ssl terminating TLS lane in front of the
                native h2 pump (the C++ front keeps its exact wire
                accounting; TLS terminates in the lane).
"""
from istio_tpu.secure.backend import (CertInfo, PkiBackend, PkiError,
                                      available_backends,
                                      default_backend,
                                      set_default_backend)
from istio_tpu.secure.identity import WorkloadIdentity
from istio_tpu.secure.mtls import (MTLS_MODES, ServingCerts,
                                   client_channel_credentials,
                                   peer_identity_from_auth_context)
from istio_tpu.secure.tlslane import TlsTerminatingLane

__all__ = [
    "CertInfo", "PkiBackend", "PkiError", "available_backends",
    "default_backend", "set_default_backend", "WorkloadIdentity",
    "MTLS_MODES", "ServingCerts", "client_channel_credentials",
    "peer_identity_from_auth_context", "TlsTerminatingLane",
]
