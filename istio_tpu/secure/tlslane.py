"""TLS-terminating lane for the native h2 front.

The native front's C++ pump (native/httpd.cpp) owns exact wire
accounting — frames decoded, bytes in/out, batch fills — that the
parity gates compare against the device plane. Compiling OpenSSL into
it would fork that accounting per rig; instead the lane terminates
TLS in front of the pump and relays the PLAINTEXT h2 byte stream to
the loopback native port. The C++ counters see byte-for-byte the same
stream as a plaintext deployment, so every existing parity/ceiling
gate survives mtls unchanged.

Trade-off (the builder's call the issue left open): the lane gives
the native front transport security + CONNECTION-level client-cert
authentication (strict mode refuses the handshake without a verified
client cert). Per-request identity→attribute-bag injection lands on
the gRPC fronts — the take-blob protocol between the pump and Python
carries no connection identity, and that is the surface the
acceptance gate (mtls_smoke RBAC parity) exercises.

Rotation: sockets wrap per-accept against the ServingCerts holder's
CURRENT context — established relays ride out a rotate() untouched.
"""
from __future__ import annotations

import logging
import socket
import ssl
import threading

from istio_tpu.secure.mtls import MTLS_STRICT, ServingCerts

log = logging.getLogger("istio_tpu.secure")

_CHUNK = 65536


class TlsTerminatingLane:
    """Accepts TLS on its own port, relays plaintext to `backend_port`
    (the native pump's loopback listener)."""

    def __init__(self, certs: ServingCerts, backend_port: int,
                 mode: str = MTLS_STRICT, host: str = "127.0.0.1",
                 port: int = 0):
        self.certs = certs
        self.backend_port = int(backend_port)
        self.mode = mode
        self._host = host
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conns: set = set()
        self._lock = threading.Lock()
        self.stats = {"connections": 0, "handshake_failures": 0,
                      "relays_open": 0}

    # -- lifecycle -----------------------------------------------------

    def start(self) -> int:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="tls-lane")
        self._accept_thread.start()
        log.info("TLS lane on port %d -> native :%d (%s)",
                 self.port, self.backend_port, self.mode)
        return self.port

    def stop(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    # -- accept + relay ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                raw, _addr = self._lsock.accept()
            except OSError:
                return            # listener closed
            threading.Thread(target=self._serve_conn, args=(raw,),
                             daemon=True, name="tls-lane-conn").start()

    def _serve_conn(self, raw: socket.socket) -> None:
        # handshake per-accept against the CURRENT generation: this is
        # where rotation lands, and where strict mode enforces the
        # client cert (connection-level authn for the native front)
        try:
            tls = self.certs.wrap_server_socket(
                raw, require_client_cert=self.mode == MTLS_STRICT)
        except (ssl.SSLError, OSError) as exc:
            with self._lock:
                self.stats["handshake_failures"] += 1
            log.debug("TLS lane handshake failed: %s", exc)
            try:
                raw.close()
            except OSError:
                pass
            return
        try:
            back = socket.create_connection(
                (self._host, self.backend_port), timeout=10)
        except OSError:
            try:
                tls.close()
            except OSError:
                pass
            return
        with self._lock:
            self.stats["connections"] += 1
            self.stats["relays_open"] += 1
            self._conns.update((tls, back))
        a = threading.Thread(target=self._pump, args=(tls, back),
                             daemon=True)
        b = threading.Thread(target=self._pump, args=(back, tls),
                             daemon=True)
        a.start()
        b.start()
        a.join()
        b.join()
        with self._lock:
            self.stats["relays_open"] -= 1
            self._conns.discard(tls)
            self._conns.discard(back)
        for s in (tls, back):
            try:
                s.close()
            except OSError:
                pass

    @staticmethod
    def _pump(src, dst) -> None:
        try:
            while True:
                data = src.recv(_CHUNK)
                if not data:
                    break
                dst.sendall(data)
        except (OSError, ssl.SSLError):
            pass
        # half-close toward the reader so h2 GOAWAY sequences finish
        try:
            dst.shutdown(socket.SHUT_WR)
        except (OSError, ssl.SSLError):
            pass
