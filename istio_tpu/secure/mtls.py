"""mTLS plumbing for the serving fronts.

Modes (ServerArgs.mtls):
  off        — plaintext fronts, identity attributes never populated.
  permissive — TLS serving, cert-less peers still served (Istio's
               permissive PeerAuthentication): transport encryption
               without peer identity. grpcio's server API is binary —
               "don't request client certs" or "require AND verify" —
               so permissive cannot ALSO collect identities from
               willing peers; `connection.mtls` stays honest (unset).
  strict     — TLS serving with the client cert REQUIRED and verified
               against the mesh root at the handshake (a cert-less
               peer cannot connect, exactly Istio's strict posture).
               On top of that, admission rejects any VERIFIED peer
               whose cert carries no spiffe:// URI SAN with a typed
               UNAUTHENTICATED (google.rpc code 16) — the identity
               boundary stays a typed wire status the meshlint
               typed-rejections pass can audit, never a silent
               anonymous pass-through.

Hot rotation: `ServingCerts` is the one swappable holder. The gRPC
fronts serve through `grpc.dynamic_ssl_server_credentials`, whose
fetcher re-reads the holder per handshake — in-flight RPCs and open
connections ride out a rotate() untouched (the zero-drop contract,
gated by scripts/mtls_smoke.py). The stdlib-ssl HTTP fronts wrap
per-accept against the holder's current SSLContext.
"""
from __future__ import annotations

import ssl
import tempfile
import threading
from typing import Mapping

from istio_tpu.secure.backend import default_backend

MTLS_OFF = "off"
MTLS_PERMISSIVE = "permissive"
MTLS_STRICT = "strict"
MTLS_MODES = (MTLS_OFF, MTLS_PERMISSIVE, MTLS_STRICT)


def validate_mode(mode: str) -> str:
    if mode not in MTLS_MODES:
        raise ValueError(f"mtls must be one of {MTLS_MODES}, "
                         f"got {mode!r}")
    return mode


class ServingCerts:
    """Hot-swappable serving credential bundle (key, cert chain, and
    the client-verification root). `rotate()` bumps the generation;
    every serving surface re-reads lazily — no front restarts."""

    def __init__(self, key_pem: bytes, cert_pem: bytes,
                 root_pem: bytes):
        self._lock = threading.Lock()
        self._key = bytes(key_pem)
        self._cert = bytes(cert_pem)
        self._root = bytes(root_pem)
        self.generation = 1
        # per-consumer served-generation marks (grpc fetchers), and a
        # memoized SSLContext per (generation, verify-mode)
        self._ctx_cache: dict = {}

    def rotate(self, key_pem: bytes, cert_pem: bytes,
               root_pem: bytes | None = None) -> int:
        with self._lock:
            self._key = bytes(key_pem)
            self._cert = bytes(cert_pem)
            if root_pem is not None:
                self._root = bytes(root_pem)
            self.generation += 1
            self._ctx_cache.clear()
            return self.generation

    def bundle(self) -> tuple[bytes, bytes, bytes, int]:
        with self._lock:
            return self._key, self._cert, self._root, self.generation

    @property
    def root_pem(self) -> bytes:
        with self._lock:
            return self._root

    # -- gRPC serving credentials (sync + aio fronts) ------------------

    def grpc_server_credentials(self, require_client_auth: bool = False):
        """Dynamic server credentials: grpcio calls the fetcher on
        every handshake; it returns a fresh certificate configuration
        only when the generation moved (None = keep serving the
        current one). `require_client_auth` (strict mode): the
        handshake demands a client cert and verifies it against the
        root — grpcio offers no request-but-don't-require middle
        ground (see module docstring)."""
        import grpc
        served = {"gen": 0}

        def _config():
            key, cert, root, gen = self.bundle()
            served["gen"] = gen
            return grpc.ssl_server_certificate_configuration(
                [(key, cert)], root_certificates=root)

        initial = _config()

        def _fetch():
            if self.generation == served["gen"]:
                return None
            return _config()

        return grpc.dynamic_ssl_server_credentials(
            initial, _fetch,
            require_client_authentication=bool(require_client_auth))

    # -- stdlib ssl (introspect/discovery HTTP fronts, TLS lane) -------

    def ssl_server_context(self,
                           require_client_cert: bool = False
                           ) -> ssl.SSLContext:
        """Current-generation server SSLContext. Callers wrap
        PER-ACCEPT (not once at bind) so a rotation applies to every
        connection accepted after it."""
        key, cert, root, gen = self.bundle()
        cache_key = (gen, bool(require_client_cert))
        with self._lock:
            ctx = self._ctx_cache.get(cache_key)
        if ctx is not None:
            return ctx
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        # load_cert_chain only takes paths — stage into a private
        # tempdir that dies before this returns
        with tempfile.TemporaryDirectory(prefix="mtls-") as d:
            cert_f, key_f = d + "/cert.pem", d + "/key.pem"
            with open(cert_f, "wb") as fh:
                fh.write(cert)
            with open(key_f, "wb") as fh:
                fh.write(key)
            ctx.load_cert_chain(cert_f, key_f)
        ctx.load_verify_locations(cadata=root.decode("ascii"))
        ctx.verify_mode = ssl.CERT_REQUIRED if require_client_cert \
            else ssl.CERT_OPTIONAL
        # gRPC clients REQUIRE a negotiated ALPN property (h2); plain
        # HTTP scrapers offer http/1.1 or nothing — advertise both so
        # one context serves the TLS lane and the introspect front
        ctx.set_alpn_protocols(["h2", "http/1.1"])
        with self._lock:
            if len(self._ctx_cache) > 8:
                self._ctx_cache.clear()
            self._ctx_cache[cache_key] = ctx
        return ctx

    def wrap_server_socket(self, sock,
                           require_client_cert: bool = False):
        return self.ssl_server_context(require_client_cert).wrap_socket(
            sock, server_side=True)

    def ssl_client_context(self, server_hostname_ok: bool = False
                           ) -> ssl.SSLContext:
        """Client context trusting the root and presenting the
        workload cert (for smoke drivers / the TLS lane's tests)."""
        key, cert, root, _gen = self.bundle()
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(cadata=root.decode("ascii"))
        ctx.check_hostname = False
        with tempfile.TemporaryDirectory(prefix="mtls-") as d:
            cert_f, key_f = d + "/cert.pem", d + "/key.pem"
            with open(cert_f, "wb") as fh:
                fh.write(cert)
            with open(key_f, "wb") as fh:
                fh.write(key)
            ctx.load_cert_chain(cert_f, key_f)
        return ctx


def client_channel_credentials(root_pem: bytes,
                               key_pem: bytes | None = None,
                               cert_pem: bytes | None = None):
    """grpc channel credentials: server verification against the mesh
    root, plus the client identity pair when doing mTLS."""
    import grpc
    return grpc.ssl_channel_credentials(
        root_certificates=bytes(root_pem),
        private_key=bytes(key_pem) if key_pem else None,
        certificate_chain=bytes(cert_pem) if cert_pem else None)


# -- peer identity extraction (request admission) ----------------------

# peer-cert PEM → SPIFFE URI (or None): the TLS layer already VERIFIED
# the cert against the root; parsing its SAN is pure and cacheable.
# Bounded: a mesh has few distinct peer certs per rotation window.
_PEER_CACHE: dict[bytes, "str | None"] = {}
_PEER_CACHE_LOCK = threading.Lock()
_PEER_CACHE_CAP = 1024


def spiffe_identity_from_pem(cert_pem: bytes) -> str | None:
    """First spiffe:// URI SAN of a VERIFIED peer cert; None when the
    cert carries no SPIFFE identity (or does not parse)."""
    pem = bytes(cert_pem)
    with _PEER_CACHE_LOCK:
        if pem in _PEER_CACHE:
            return _PEER_CACHE[pem]
    ident = None
    try:
        for uri in default_backend().cert_info(pem).uris:
            if uri.startswith("spiffe://"):
                ident = uri
                break
    except Exception:
        ident = None
    with _PEER_CACHE_LOCK:
        if len(_PEER_CACHE) >= _PEER_CACHE_CAP:
            _PEER_CACHE.clear()
        _PEER_CACHE[pem] = ident
    return ident


def peer_identity_from_auth_context(auth_ctx: "Mapping | None"
                                    ) -> str | None:
    """grpc `context.auth_context()` → verified peer SPIFFE identity.
    None for plaintext transports and TLS peers without a client
    cert — the caller decides what that means per mtls mode."""
    if not auth_ctx:
        return None
    pems = auth_ctx.get("x509_pem_cert") or ()
    if not pems:
        return None
    return spiffe_identity_from_pem(pems[0])
