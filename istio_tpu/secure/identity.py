"""WorkloadIdentity — the node-agent leg of the secure plane.

One instance owns one workload's SPIFFE identity: it obtains a
short-TTL cert from the CA gRPC service (security/ca_service CSR
flow), caches the bundle, and rotates before expiry. Rotation is
driven off the adapter-executor MAINTENANCE lane
(AdapterExecutor.register_refreshable): `refresh()` is the periodic
hook, so a slow or failing CA occupies the maintenance worker, never
a request lane.

Every lifecycle transition is observable the PR 13 way: forensics
events (identity_issue / identity_rotate / identity_expiry) on the
shared timeline + zero-shaped mixer_identity_* counter families
(runtime/monitor.identity_counters).

Subscribers (`on_rotate`) receive every fresh bundle — the mTLS
fronts' ServingCerts holder and the grant plane's identity fold hang
off this hook, which is what makes "a rotated peer never rides a
stale grant" one ordered step: sign → swap serving certs → revoke
identity grants → count + event.
"""
from __future__ import annotations

import datetime
import logging
import threading
import time
from typing import Callable, Sequence

from istio_tpu.security import pki

log = logging.getLogger("istio_tpu.secure")

Bundle = tuple  # (key_pem, cert_pem, root_pem)


class WorkloadIdentity:
    """Obtain / cache / rotate one workload's certificate bundle.

    `client`: a security.ca_service.CAClient (or any object with its
    `sign_csr`). `rotation_fraction`: rotate when less than this
    fraction of the TTL remains (0.5 = half-life, the reference node
    agent's policy).
    """

    def __init__(self, client, identity: str, *,
                 ttl_minutes: int = 60,
                 rotation_fraction: float = 0.5,
                 credential: bytes = b"",
                 credential_type: str = "onprem",
                 refresh_interval_s: float | None = None,
                 dns_names: Sequence[str] = (),
                 on_rotate: Sequence[Callable[[Bundle], None]] = ()):
        self.client = client
        self.identity = identity
        # serving identities also carry DNS SANs: gRPC clients match
        # the target-name override against hostnames, not URI SANs
        self.dns_names = tuple(dns_names)
        self.ttl_minutes = int(ttl_minutes)
        self.rotation_fraction = float(rotation_fraction)
        self.credential = credential
        self.credential_type = credential_type
        # maintenance-lane cadence: check due-ness well inside the
        # rotation window so a one-tick slip never crosses expiry
        if refresh_interval_s is None:
            refresh_interval_s = max(
                min(60.0, self.ttl_minutes * 60.0 * 0.05), 0.05)
        self.refresh_interval_s = float(refresh_interval_s)
        self._on_rotate: list[Callable[[Bundle], None]] = \
            list(on_rotate)
        self._lock = threading.Lock()
        self._bundle: Bundle | None = None
        self._not_after: datetime.datetime | None = None
        self.generation = 0
        self.rotations = 0
        self.failures = 0
        self.expiries = 0
        self.last_error: str | None = None

    # -- subscriptions -------------------------------------------------

    def subscribe(self, fn: Callable[[Bundle], None]) -> None:
        with self._lock:
            self._on_rotate.append(fn)

    # -- state ---------------------------------------------------------

    def bundle(self) -> Bundle | None:
        with self._lock:
            return self._bundle

    def remaining_ttl_s(self) -> float | None:
        with self._lock:
            na = self._not_after
        if na is None:
            return None
        return (na - datetime.datetime.now(datetime.timezone.utc)
                ).total_seconds()

    def due(self) -> bool:
        rem = self.remaining_ttl_s()
        if rem is None:
            return True
        return rem <= self.ttl_minutes * 60.0 * self.rotation_fraction

    def stats(self) -> dict:
        with self._lock:
            return {
                "identity": self.identity,
                "generation": self.generation,
                "rotations": self.rotations,
                "failures": self.failures,
                "expiries": self.expiries,
                "ttl_minutes": self.ttl_minutes,
                "remaining_ttl_s": None if self._not_after is None
                else (self._not_after - datetime.datetime.now(
                    datetime.timezone.utc)).total_seconds(),
                "last_error": self.last_error,
            }

    # -- lifecycle -----------------------------------------------------

    def ensure(self) -> Bundle:
        """Obtain the initial bundle if absent; return the live one."""
        with self._lock:
            have = self._bundle
        if have is not None:
            return have
        return self._issue("issue")

    def rotate(self) -> Bundle:
        return self._issue("rotate")

    def refresh(self) -> None:
        """Maintenance-lane hook: issue when missing, rotate when due.
        Raises on failure so the lane's refresh counters/forensics see
        it (the lane logs and retries next interval)."""
        from istio_tpu.runtime import forensics, monitor
        rem = self.remaining_ttl_s()
        if rem is not None and rem <= 0:
            # the old cert died before we renewed: loudly typed —
            # fronts serving from this identity are now failing
            # handshakes and the timeline must say why
            with self._lock:
                self.expiries += 1
            monitor.note_identity("expiry", "failed")
            forensics.record_event("identity_expiry", coalesce_s=1.0,
                                   identity=self.identity)
        if self._bundle is None or self.due():
            self._issue("issue" if self._bundle is None else "rotate")

    def _issue(self, event: str) -> Bundle:
        from istio_tpu.runtime import forensics, monitor
        t0 = time.perf_counter()
        try:
            key = pki.generate_key()
            csr = pki.generate_csr(key, self.identity,
                                   dns_names=self.dns_names)
            resp = self.client.sign_csr(csr, self.credential,
                                        self.credential_type,
                                        self.ttl_minutes)
            if not resp.is_approved:
                raise RuntimeError(
                    f"CSR rejected: {resp.status_message}")
            bundle = (pki.key_to_pem(key), bytes(resp.signed_cert),
                      bytes(resp.cert_chain))
            not_after = pki.not_after(bundle[1])
        except Exception as exc:
            with self._lock:
                self.failures += 1
                self.last_error = f"{type(exc).__name__}: {exc}"
            monitor.note_identity(event, "failed")
            forensics.record_event(f"identity_{event}",
                                   coalesce_s=0.0,
                                   identity=self.identity, ok=False,
                                   error=str(exc)[:200])
            raise
        with self._lock:
            self._bundle = bundle
            self._not_after = not_after
            self.generation += 1
            if event == "rotate":
                self.rotations += 1
            self.last_error = None
            subscribers = list(self._on_rotate)
            gen = self.generation
        # subscribers run OUTSIDE the lock (a ServingCerts.rotate or
        # grant revocation must never deadlock against stats readers);
        # one failing subscriber must not starve the rest
        for fn in subscribers:
            try:
                fn(bundle)
            except Exception:
                log.exception("identity on_rotate subscriber failed")
        monitor.note_identity(event, "ok")
        forensics.record_event(
            f"identity_{event}", coalesce_s=0.0,
            identity=self.identity, ok=True, generation=gen,
            wall_ms=round((time.perf_counter() - t0) * 1e3, 3))
        return bundle
