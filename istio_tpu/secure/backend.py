"""The `PkiBackend` seam: one PEM-bytes PKI API, two backends.

The reference CA (security/pkg/pki) assumes a crypto library is always
there; this rig sometimes has no `cryptography` wheel but always has
an `openssl` CLI (1.1.1w here). Everything above this module —
security/pki.py's object helpers, the IstioCA, the CSR gRPC service,
the mTLS fronts — speaks ONLY this seam, in PEM bytes, so the whole
secure plane (and its tier-1 tests) runs identically on either rig.

Both backends emit standard PKCS8 private keys and X.509 PEM: the
outputs interoperate byte-format-for-byte-format (a CSR minted by one
backend signs under the other, and either output feeds the TLS stack).

openssl-CLI notes (1.1.1-era constraints this module absorbs):
  * `x509 -req` only supports whole `-days`, but workload TTLs need
    minute precision (rotation tests, short-TTL grants) — leaf signing
    therefore drives `openssl ca` with a throwaway database and
    explicit `-startdate`/`-enddate` GeneralizedTimes.
  * there is no `-copy_extensions`: the CSR's SANs are parsed out and
    written into the signing extfile, mirroring ca.go's honor-the-CSR
    behavior (and the authorization contract stays in ca_service,
    which authorizes every SAN before this layer ever runs).
"""
from __future__ import annotations

import dataclasses
import datetime
import os
import re
import secrets as _secrets
import shutil
import subprocess
import tempfile
from typing import Sequence

BACKDATE_S = 300          # not_valid_before skew absorbed (ca.go)


class PkiError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class CertInfo:
    """Parsed view of a cert or CSR — everything the plane reads."""
    subject: str = ""
    uris: tuple = ()
    dns: tuple = ()
    not_after: datetime.datetime | None = None
    is_ca: bool = False
    signature_ok: bool = True


class PkiBackend:
    """PEM-bytes-only PKI operations. Subclasses implement; callers
    never see a backend-native key/cert object."""

    name = "abstract"

    # -- keys --
    def generate_key(self, ec_key: bool = True) -> bytes:
        raise NotImplementedError

    def public_key_pem(self, key_pem: bytes) -> bytes:
        raise NotImplementedError

    def cert_public_key_pem(self, cert_pem: bytes) -> bytes:
        raise NotImplementedError

    # -- CSRs --
    def generate_csr(self, key_pem: bytes, uris: Sequence[str] = (),
                     dns: Sequence[str] = (),
                     org: str = "istio_tpu") -> bytes:
        raise NotImplementedError

    def csr_info(self, csr_pem: bytes) -> CertInfo:
        raise NotImplementedError

    # -- certs --
    def cert_info(self, cert_pem: bytes) -> CertInfo:
        raise NotImplementedError

    def self_signed_root(self, org: str,
                         ttl: datetime.timedelta
                         ) -> tuple[bytes, bytes]:
        raise NotImplementedError

    def sign_csr(self, ca_key_pem: bytes, ca_cert_pem: bytes,
                 csr_pem: bytes, ttl: datetime.timedelta) -> bytes:
        raise NotImplementedError

    def verify_chain(self, cert_pem: bytes, root_pem: bytes) -> bool:
        raise NotImplementedError

    # -- derived --
    def key_cert_pair_ok(self, key_pem: bytes,
                         cert_pem: bytes) -> bool:
        try:
            return self.public_key_pem(key_pem) == \
                self.cert_public_key_pem(cert_pem)
        except PkiError:
            return False


# ---------------------------------------------------------------------
# cryptography backend
# ---------------------------------------------------------------------

class CryptographyBackend(PkiBackend):
    """The original istio_tpu/security/pki.py implementation, folded
    behind the seam."""

    name = "cryptography"

    def generate_key(self, ec_key: bool = True) -> bytes:
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import ec, rsa
        if ec_key:
            key = ec.generate_private_key(ec.SECP256R1())
        else:
            key = rsa.generate_private_key(public_exponent=65537,
                                           key_size=2048)
        return key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption())

    def public_key_pem(self, key_pem: bytes) -> bytes:
        from cryptography.hazmat.primitives import serialization
        try:
            key = serialization.load_pem_private_key(key_pem,
                                                     password=None)
        except Exception as exc:
            raise PkiError(f"bad private key: {exc}") from exc
        return key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    def cert_public_key_pem(self, cert_pem: bytes) -> bytes:
        from cryptography import x509
        from cryptography.hazmat.primitives import serialization
        try:
            cert = x509.load_pem_x509_certificate(cert_pem)
        except Exception as exc:
            raise PkiError(f"bad certificate: {exc}") from exc
        return cert.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    def generate_csr(self, key_pem: bytes, uris: Sequence[str] = (),
                     dns: Sequence[str] = (),
                     org: str = "istio_tpu") -> bytes:
        from cryptography import x509
        from cryptography.hazmat.primitives import (hashes,
                                                    serialization)
        from cryptography.x509.oid import NameOID
        key = serialization.load_pem_private_key(key_pem, password=None)
        builder = x509.CertificateSigningRequestBuilder().subject_name(
            x509.Name([x509.NameAttribute(NameOID.ORGANIZATION_NAME,
                                          org)]))
        sans = [x509.UniformResourceIdentifier(u) for u in uris] + \
            [x509.DNSName(d) for d in dns]
        if sans:
            builder = builder.add_extension(
                x509.SubjectAlternativeName(sans), critical=False)
        return builder.sign(key, hashes.SHA256()).public_bytes(
            serialization.Encoding.PEM)

    @staticmethod
    def _sans(obj) -> tuple[tuple, tuple]:
        from cryptography import x509
        try:
            ext = obj.extensions.get_extension_for_class(
                x509.SubjectAlternativeName)
        except x509.ExtensionNotFound:
            return (), ()
        return (tuple(ext.value.get_values_for_type(
                    x509.UniformResourceIdentifier)),
                tuple(ext.value.get_values_for_type(x509.DNSName)))

    def csr_info(self, csr_pem: bytes) -> CertInfo:
        from cryptography import x509
        try:
            csr = x509.load_pem_x509_csr(csr_pem)
        except Exception as exc:
            raise PkiError(f"bad CSR: {exc}") from exc
        uris, dns = self._sans(csr)
        return CertInfo(subject=csr.subject.rfc4514_string(),
                        uris=uris, dns=dns,
                        signature_ok=csr.is_signature_valid)

    def cert_info(self, cert_pem: bytes) -> CertInfo:
        from cryptography import x509
        try:
            cert = x509.load_pem_x509_certificate(cert_pem)
        except Exception as exc:
            raise PkiError(f"bad certificate: {exc}") from exc
        uris, dns = self._sans(cert)
        na = getattr(cert, "not_valid_after_utc", None)
        if na is None:
            na = cert.not_valid_after.replace(
                tzinfo=datetime.timezone.utc)
        try:
            bc = cert.extensions.get_extension_for_class(
                x509.BasicConstraints)
            is_ca = bool(bc.value.ca)
        except x509.ExtensionNotFound:
            is_ca = False
        return CertInfo(subject=cert.subject.rfc4514_string(),
                        uris=uris, dns=dns, not_after=na, is_ca=is_ca)

    def self_signed_root(self, org: str, ttl: datetime.timedelta
                         ) -> tuple[bytes, bytes]:
        from cryptography import x509
        from cryptography.hazmat.primitives import (hashes,
                                                    serialization)
        from cryptography.x509.oid import NameOID
        key_pem = self.generate_key()
        key = serialization.load_pem_private_key(key_pem, password=None)
        now = datetime.datetime.now(datetime.timezone.utc)
        # the root's subject must differ from leaf subjects (all
        # O=<org>): subject==issuer on a leaf reads as self-signed to
        # chain verifiers and TLS handshakes fail
        name = x509.Name([
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
            x509.NameAttribute(NameOID.COMMON_NAME, f"{org} root CA")])
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(
                    now - datetime.timedelta(seconds=BACKDATE_S))
                .not_valid_after(now + ttl)
                .add_extension(x509.BasicConstraints(ca=True,
                                                     path_length=None),
                               critical=True)
                .add_extension(x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True,
                    crl_sign=True, content_commitment=False,
                    key_encipherment=False, data_encipherment=False,
                    key_agreement=False, encipher_only=False,
                    decipher_only=False), critical=True)
                .sign(key, hashes.SHA256()))
        return key_pem, cert.public_bytes(serialization.Encoding.PEM)

    def sign_csr(self, ca_key_pem: bytes, ca_cert_pem: bytes,
                 csr_pem: bytes, ttl: datetime.timedelta) -> bytes:
        from cryptography import x509
        from cryptography.hazmat.primitives import (hashes,
                                                    serialization)
        key = serialization.load_pem_private_key(ca_key_pem,
                                                 password=None)
        ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
        csr = x509.load_pem_x509_csr(csr_pem)
        uris, dns = self._sans(csr)
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (x509.CertificateBuilder()
                   .subject_name(csr.subject)
                   .issuer_name(ca_cert.subject)
                   .public_key(csr.public_key())
                   .serial_number(x509.random_serial_number())
                   .not_valid_before(
                       now - datetime.timedelta(seconds=BACKDATE_S))
                   .not_valid_after(now + ttl)
                   .add_extension(x509.BasicConstraints(
                       ca=False, path_length=None), critical=True)
                   .add_extension(x509.ExtendedKeyUsage(
                       [x509.ExtendedKeyUsageOID.SERVER_AUTH,
                        x509.ExtendedKeyUsageOID.CLIENT_AUTH]),
                       critical=False))
        if uris or dns:
            builder = builder.add_extension(
                x509.SubjectAlternativeName(
                    [x509.UniformResourceIdentifier(u)
                     for u in uris] +
                    [x509.DNSName(d) for d in dns]),
                critical=False)
        cert = builder.sign(key, hashes.SHA256())
        return cert.public_bytes(serialization.Encoding.PEM)

    def verify_chain(self, cert_pem: bytes, root_pem: bytes) -> bool:
        from cryptography import x509
        try:
            cert = x509.load_pem_x509_certificate(cert_pem)
            root = x509.load_pem_x509_certificate(root_pem)
            cert.verify_directly_issued_by(root)
            return True
        except Exception:
            return False


# ---------------------------------------------------------------------
# openssl-CLI backend
# ---------------------------------------------------------------------

_SAN_SPLIT = re.compile(r",\s*")


class OpensslBackend(PkiBackend):
    """PKI via the `openssl` binary (1.1.1-compatible invocations)."""

    name = "openssl"

    def __init__(self, binary: str = "openssl"):
        self._bin = shutil.which(binary) or binary

    def _run(self, args: list[str], stdin: bytes | None = None,
             ok_rc: tuple[int, ...] = (0,),
             cwd: str | None = None) -> subprocess.CompletedProcess:
        env = dict(os.environ)
        env["LC_ALL"] = "C"                 # stable date formatting
        env.setdefault("RANDFILE", os.devnull)
        try:
            proc = subprocess.run([self._bin] + args, input=stdin,
                                  capture_output=True, env=env, cwd=cwd,
                                  timeout=30)
        except (OSError, subprocess.TimeoutExpired) as exc:
            raise PkiError(f"openssl {args[0]} failed to run: "
                           f"{exc}") from exc
        if proc.returncode not in ok_rc:
            err = proc.stderr.decode("utf-8", "replace").strip()
            raise PkiError(f"openssl {args[0]} rc={proc.returncode}: "
                           f"{err[-500:]}")
        return proc

    # -- keys --

    def generate_key(self, ec_key: bool = True) -> bytes:
        if ec_key:
            args = ["genpkey", "-algorithm", "EC",
                    "-pkeyopt", "ec_paramgen_curve:P-256",
                    "-pkeyopt", "ec_param_enc:named_curve"]
        else:
            args = ["genpkey", "-algorithm", "RSA",
                    "-pkeyopt", "rsa_keygen_bits:2048"]
        return self._run(args).stdout

    def public_key_pem(self, key_pem: bytes) -> bytes:
        return self._run(["pkey", "-pubout"], stdin=key_pem).stdout

    def cert_public_key_pem(self, cert_pem: bytes) -> bytes:
        return self._run(["x509", "-pubkey", "-noout"],
                         stdin=cert_pem).stdout

    # -- CSRs --

    @staticmethod
    def _alt_section(uris: Sequence[str],
                     dns: Sequence[str]) -> str:
        lines = ["[alt]"]
        for i, u in enumerate(uris, 1):
            lines.append(f"URI.{i} = {u}")
        for i, d in enumerate(dns, 1):
            lines.append(f"DNS.{i} = {d}")
        return "\n".join(lines) + "\n"

    def generate_csr(self, key_pem: bytes, uris: Sequence[str] = (),
                     dns: Sequence[str] = (),
                     org: str = "istio_tpu") -> bytes:
        with tempfile.TemporaryDirectory(prefix="pki-") as d:
            key_f = os.path.join(d, "key.pem")
            with open(key_f, "wb") as fh:
                fh.write(key_pem)
            cfg = ("[req]\nprompt = no\ndistinguished_name = dn\n"
                   f"[dn]\nO = {org}\n")
            args = ["req", "-new", "-sha256", "-key", key_f]
            if uris or dns:
                cfg += "[ext]\nsubjectAltName = @alt\n" + \
                    self._alt_section(uris, dns)
                args += ["-reqexts", "ext"]
            cfg_f = os.path.join(d, "req.cnf")
            with open(cfg_f, "w") as fh:
                fh.write(cfg)
            args += ["-config", cfg_f]
            return self._run(args).stdout

    @staticmethod
    def _parse_sans(text: str) -> tuple[tuple, tuple]:
        uris: list[str] = []
        dns: list[str] = []
        lines = text.splitlines()
        for i, line in enumerate(lines):
            if "Subject Alternative Name" not in line:
                continue
            if i + 1 < len(lines):
                for part in _SAN_SPLIT.split(lines[i + 1].strip()):
                    if part.startswith("URI:"):
                        uris.append(part[4:])
                    elif part.startswith("DNS:"):
                        dns.append(part[4:])
            break
        return tuple(uris), tuple(dns)

    @staticmethod
    def _parse_subject(text: str) -> str:
        m = re.search(r"Subject:\s*(.*)", text)
        return m.group(1).strip() if m else ""

    def csr_info(self, csr_pem: bytes) -> CertInfo:
        # -verify makes the rc reflect CSR signature validity; rerun
        # without it to still parse a tampered CSR's text
        proc = self._run(["req", "-noout", "-text", "-verify"],
                         stdin=csr_pem, ok_rc=(0, 1))
        sig_ok = proc.returncode == 0
        text = proc.stdout.decode("utf-8", "replace")
        if not sig_ok and "Certificate Request" not in text:
            text = self._run(["req", "-noout", "-text"],
                             stdin=csr_pem).stdout.decode(
                                 "utf-8", "replace")
        uris, dns = self._parse_sans(text)
        return CertInfo(subject=self._parse_subject(text), uris=uris,
                        dns=dns, signature_ok=sig_ok)

    # -- certs --

    def cert_info(self, cert_pem: bytes) -> CertInfo:
        text = self._run(["x509", "-noout", "-text"],
                         stdin=cert_pem).stdout.decode("utf-8",
                                                       "replace")
        uris, dns = self._parse_sans(text)
        na = None
        m = re.search(r"Not After\s*:\s*(.*)", text)
        if m:
            try:
                na = datetime.datetime.strptime(
                    m.group(1).strip(), "%b %d %H:%M:%S %Y %Z"
                ).replace(tzinfo=datetime.timezone.utc)
            except ValueError:
                na = None
        return CertInfo(subject=self._parse_subject(text), uris=uris,
                        dns=dns, not_after=na, is_ca="CA:TRUE" in text)

    def self_signed_root(self, org: str, ttl: datetime.timedelta
                         ) -> tuple[bytes, bytes]:
        key_pem = self.generate_key()
        days = max(int(ttl.total_seconds() // 86400), 1)
        with tempfile.TemporaryDirectory(prefix="pki-") as d:
            key_f = os.path.join(d, "key.pem")
            with open(key_f, "wb") as fh:
                fh.write(key_pem)
            cfg_f = os.path.join(d, "root.cnf")
            with open(cfg_f, "w") as fh:
                fh.write(
                    "[req]\nprompt = no\ndistinguished_name = dn\n"
                    "x509_extensions = v3ca\n"
                    f"[dn]\nO = {org}\nCN = {org} root CA\n"
                    "[v3ca]\n"
                    "basicConstraints = critical,CA:TRUE\n"
                    "keyUsage = critical,digitalSignature,"
                    "keyCertSign,cRLSign\n"
                    "subjectKeyIdentifier = hash\n")
            cert = self._run(["req", "-x509", "-new", "-sha256",
                              "-key", key_f, "-config", cfg_f,
                              "-days", str(days)]).stdout
        return key_pem, cert

    @staticmethod
    def _gtime(dt: datetime.datetime) -> str:
        return dt.astimezone(datetime.timezone.utc).strftime(
            "%Y%m%d%H%M%SZ")

    def sign_csr(self, ca_key_pem: bytes, ca_cert_pem: bytes,
                 csr_pem: bytes, ttl: datetime.timedelta) -> bytes:
        info = self.csr_info(csr_pem)
        now = datetime.datetime.now(datetime.timezone.utc)
        start = self._gtime(now - datetime.timedelta(
            seconds=BACKDATE_S))
        end = self._gtime(now + ttl)
        with tempfile.TemporaryDirectory(prefix="pki-ca-") as d:
            for fname, blob in (("ca-key.pem", ca_key_pem),
                                ("ca-cert.pem", ca_cert_pem),
                                ("in.csr", csr_pem)):
                with open(os.path.join(d, fname), "wb") as fh:
                    fh.write(blob)
            with open(os.path.join(d, "index.txt"), "w"):
                pass
            with open(os.path.join(d, "serial"), "w") as fh:
                fh.write("%016x\n" % _secrets.randbits(63))
            leaf = ("[leaf]\n"
                    "basicConstraints = critical,CA:FALSE\n"
                    "extendedKeyUsage = serverAuth,clientAuth\n"
                    "subjectKeyIdentifier = hash\n")
            if info.uris or info.dns:
                leaf += "subjectAltName = @alt\n" + \
                    self._alt_section(info.uris, info.dns)
            with open(os.path.join(d, "ca.cnf"), "w") as fh:
                fh.write(
                    "[ca]\ndefault_ca = CA_default\n"
                    "[CA_default]\n"
                    f"database = {d}/index.txt\n"
                    f"serial = {d}/serial\n"
                    f"new_certs_dir = {d}\n"
                    f"certificate = {d}/ca-cert.pem\n"
                    f"private_key = {d}/ca-key.pem\n"
                    "default_md = sha256\n"
                    "policy = pol_any\n"
                    "email_in_dn = no\n"
                    "unique_subject = no\n"
                    "x509_extensions = leaf\n"
                    "[pol_any]\n"
                    "countryName = optional\n"
                    "stateOrProvinceName = optional\n"
                    "localityName = optional\n"
                    "organizationName = optional\n"
                    "organizationalUnitName = optional\n"
                    "commonName = optional\n"
                    "emailAddress = optional\n" + leaf)
            self._run(["ca", "-batch", "-config",
                       os.path.join(d, "ca.cnf"),
                       "-in", os.path.join(d, "in.csr"),
                       "-out", os.path.join(d, "leaf.pem"),
                       "-startdate", start, "-enddate", end,
                       "-notext", "-md", "sha256"], cwd=d)
            with open(os.path.join(d, "leaf.pem"), "rb") as fh:
                return fh.read()

    def verify_chain(self, cert_pem: bytes, root_pem: bytes) -> bool:
        with tempfile.TemporaryDirectory(prefix="pki-v-") as d:
            root_f = os.path.join(d, "root.pem")
            cert_f = os.path.join(d, "cert.pem")
            with open(root_f, "wb") as fh:
                fh.write(root_pem)
            with open(cert_f, "wb") as fh:
                fh.write(cert_pem)
            try:
                self._run(["verify", "-CAfile", root_f, cert_f])
                return True
            except PkiError:
                return False


# ---------------------------------------------------------------------
# backend selection
# ---------------------------------------------------------------------

_DEFAULT: PkiBackend | None = None


def available_backends() -> tuple[str, ...]:
    names = []
    try:
        import cryptography  # noqa: F401
        names.append("cryptography")
    except ImportError:
        pass
    if shutil.which("openssl"):
        names.append("openssl")
    return tuple(names)


def default_backend() -> PkiBackend:
    """`cryptography` when importable, else the openssl CLI. Raises
    PkiError (not ImportError) when neither exists so callers can gate
    cleanly."""
    global _DEFAULT
    if _DEFAULT is None:
        avail = available_backends()
        if "cryptography" in avail:
            _DEFAULT = CryptographyBackend()
        elif "openssl" in avail:
            _DEFAULT = OpensslBackend()
        else:
            raise PkiError(
                "no PKI backend: neither the `cryptography` package "
                "nor an `openssl` binary is available")
    return _DEFAULT


def set_default_backend(backend: PkiBackend | None) -> None:
    """Pin (tests) or reset (None) the process-wide backend."""
    global _DEFAULT
    _DEFAULT = backend
