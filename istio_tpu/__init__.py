"""istio_tpu — a TPU-native service-mesh control plane.

A brand-new framework with the capabilities of early Istio (reference:
istio/istio ~v0.4, surveyed in /root/repo/SURVEY.md): attribute-based policy
(Check/Report/Quota) with templates and adapters, an abstract service/routing
model compiled to sidecar configuration, and SPIFFE workload identity.

Unlike the Go reference, the policy hot path is JAX/XLA-first: rule-match
predicates, authz/listentry/quota templates and VirtualService header/URI
matches compile into dense tensor programs (DNF atom/conjunction/rule
matrices + byte-DFA string automata) evaluated as batched jit-compiled TPU
steps.

Layout (maps to SURVEY.md §2):
  utils/      — shared substrate: log, config, metrics, probes, caches
                (reference: pkg/log, pkg/probe, pkg/cache)
  attribute/  — attribute bags, global dictionary, wire codec, tensorization
                (reference: mixer/pkg/attribute)
  expr/       — expression language: parser, type checker, oracle interpreter,
                externs (reference: mixer/pkg/expr + mixer/pkg/il)
  ops/        — TPU kernels: byte-DFA string matching, masked 3-valued logic,
                hashed-set membership, quota counters
  compiler/   — AST → tensor programs; rulesets → DNF matcher matrices
                (replaces mixer/pkg/il/compiler + interpreter)
  runtime/    — resolver/dispatcher/controller + batching front-end
                (reference: mixer/pkg/runtime)
  templates/  — template framework: listentry, authorization, metric, quota...
                (reference: mixer/template)
  adapters/   — denier, list, memquota, rbac, stdio, prometheus, noop
                (reference: mixer/adapter)
  pilot/      — service/config model + route compiler (reference: pilot/)
  security/   — SPIFFE CA, CSR flow, secret controller (reference: security/)
  parallel/   — device mesh + sharding strategy for multi-chip scale-out
  models/     — the flagship fused policy-engine step (PolicyEngine)
"""

__version__ = "0.1.0"
