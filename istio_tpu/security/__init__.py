"""Security — workload identity (reference: security/, SURVEY.md §2.7):
SPIFFE-style identities, a self-signed-bootstrap CA signing workload
CSRs, a CSR gRPC service with pluggable platform-credential
authentication, a secret controller minting per-service-account
key+cert bundles, and a node agent running the rotation loop.
Backed by the `PkiBackend` seam (istio_tpu/secure/backend.py): real
X.509 via the `cryptography` package when importable, via the
`openssl` CLI otherwise — the same plane runs on either rig.
"""
from istio_tpu.security.spiffe import (identity_from_san, spiffe_id,
                                       parse_spiffe)
from istio_tpu.security.pki import (generate_csr, generate_key,
                                    key_cert_pair_ok, load_cert, san_uris)
from istio_tpu.security.ca import CertificateAuthority, IstioCA
from istio_tpu.security.platform import (DialOptions, new_platform_client,
                                         PlatformError)
from istio_tpu.security.workload import (FlexVolumeDriver, SecretConfig,
                                         SecretFileServer,
                                         new_secret_server)

__all__ = ["identity_from_san", "spiffe_id", "parse_spiffe",
           "generate_csr", "generate_key", "key_cert_pair_ok",
           "load_cert", "san_uris", "CertificateAuthority", "IstioCA",
           "DialOptions", "new_platform_client", "PlatformError",
           "FlexVolumeDriver", "SecretConfig", "SecretFileServer",
           "new_secret_server"]
