"""Istio CA (reference: security/pkg/pki/ca/ca.go): the
CertificateAuthority interface (:50 Sign/GetRootCertificate), self-
signed bootstrap (:82 NewSelfSignedIstioCAOptions — root persisted via
a pluggable secret store, the k8s-secret role), CSR signing (:182) with
TTL clamping, and the secret controller minting per-service-account
bundles (controller/secret.go).
"""
from __future__ import annotations

import dataclasses
import datetime
import threading
from typing import Callable, Mapping

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.x509.oid import NameOID

from istio_tpu.security import pki
from istio_tpu.security.spiffe import spiffe_id

DEFAULT_WORKLOAD_TTL = datetime.timedelta(hours=24 * 90)
DEFAULT_ROOT_TTL = datetime.timedelta(days=365 * 10)
CA_SECRET_NAME = "istio-ca-secret"       # ca.go cASecret
WORKLOAD_SECRET_TYPE = "istio.io/key-and-cert"   # controller/secret.go


class CAError(RuntimeError):
    pass


class CertificateAuthority:
    """ca.go:50."""

    def sign(self, csr_pem: bytes, ttl: datetime.timedelta | None = None
             ) -> bytes:
        raise NotImplementedError

    def get_root_certificate(self) -> bytes:
        raise NotImplementedError


@dataclasses.dataclass
class IstioCAOptions:
    cert_ttl: datetime.timedelta = DEFAULT_WORKLOAD_TTL
    max_cert_ttl: datetime.timedelta = DEFAULT_ROOT_TTL
    org: str = "istio_tpu"


class IstioCA(CertificateAuthority):
    def __init__(self, signing_key_pem: bytes, signing_cert_pem: bytes,
                 opts: IstioCAOptions | None = None):
        self.opts = opts or IstioCAOptions()
        self._key = pki.key_from_pem(signing_key_pem)
        self._cert = pki.load_cert(signing_cert_pem)
        self._cert_pem = signing_cert_pem
        self._serial_lock = threading.Lock()

    # -- construction --

    @classmethod
    def new_self_signed(cls, secret_store: "dict | None" = None,
                        org: str = "istio_tpu",
                        root_ttl: datetime.timedelta = DEFAULT_ROOT_TTL,
                        opts: IstioCAOptions | None = None) -> "IstioCA":
        """NewSelfSignedIstioCAOptions (ca.go:82): reuse the persisted
        CA secret when present; otherwise mint a root and persist it."""
        if secret_store is not None and CA_SECRET_NAME in secret_store:
            blob = secret_store[CA_SECRET_NAME]
            return cls(blob["ca-key.pem"], blob["ca-cert.pem"], opts)
        key = pki.generate_key()
        now = datetime.datetime.now(datetime.timezone.utc)
        # the root's subject must differ from leaf subjects (all
        # O=<org>): subject==issuer on a leaf reads as self-signed to
        # chain verifiers and TLS handshakes fail
        name = x509.Name([
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
            x509.NameAttribute(NameOID.COMMON_NAME, f"{org} root CA")])
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(now + root_ttl)
                .add_extension(x509.BasicConstraints(ca=True,
                                                     path_length=None),
                               critical=True)
                .add_extension(x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True,
                    crl_sign=True, content_commitment=False,
                    key_encipherment=False, data_encipherment=False,
                    key_agreement=False, encipher_only=False,
                    decipher_only=False), critical=True)
                .sign(key, hashes.SHA256()))
        key_pem = pki.key_to_pem(key)
        cert_pem = cert.public_bytes(serialization.Encoding.PEM)
        if secret_store is not None:
            secret_store[CA_SECRET_NAME] = {"ca-key.pem": key_pem,
                                            "ca-cert.pem": cert_pem}
        return cls(key_pem, cert_pem, opts)

    # -- CertificateAuthority --

    def sign(self, csr_pem: bytes,
             ttl: datetime.timedelta | None = None) -> bytes:
        """ca.go:182 Sign: honor the CSR's URI SANs, clamp TTL."""
        csr = pki.load_csr(csr_pem)
        if not csr.is_signature_valid:
            raise CAError("CSR signature invalid")
        ttl = ttl or self.opts.cert_ttl
        if ttl > self.opts.max_cert_ttl:
            raise CAError(f"requested TTL {ttl} exceeds max "
                          f"{self.opts.max_cert_ttl}")
        uris = pki.san_uris(csr)
        dns = pki.san_dns(csr)
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (x509.CertificateBuilder()
                   .subject_name(csr.subject)
                   .issuer_name(self._cert.subject)
                   .public_key(csr.public_key())
                   .serial_number(x509.random_serial_number())
                   .not_valid_before(now - datetime.timedelta(minutes=5))
                   .not_valid_after(now + ttl)
                   .add_extension(x509.BasicConstraints(ca=False,
                                                        path_length=None),
                                  critical=True)
                   .add_extension(x509.ExtendedKeyUsage(
                       [x509.ExtendedKeyUsageOID.SERVER_AUTH,
                        x509.ExtendedKeyUsageOID.CLIENT_AUTH]),
                       critical=False))
        if uris or dns:
            builder = builder.add_extension(
                x509.SubjectAlternativeName(
                    [x509.UniformResourceIdentifier(u) for u in uris] +
                    [x509.DNSName(d) for d in dns]),
                critical=False)
        cert = builder.sign(self._key, hashes.SHA256())
        return cert.public_bytes(serialization.Encoding.PEM)

    def get_root_certificate(self) -> bytes:
        return self._cert_pem


class SecretController:
    """controller/secret.go: service-account events → per-SA
    `istio.io/key-and-cert` secrets. The SA source is pluggable (k8s in
    the reference; any registry here); secrets land in a dict-like
    store keyed `istio.<sa>.<ns>`."""

    def __init__(self, ca: CertificateAuthority, secrets: dict,
                 trust_domain: str = "cluster.local",
                 ttl: datetime.timedelta = DEFAULT_WORKLOAD_TTL):
        self.ca = ca
        self.secrets = secrets
        self.trust_domain = trust_domain
        self.ttl = ttl

    @staticmethod
    def secret_name(namespace: str, sa: str) -> str:
        return f"istio.{sa}.{namespace}"

    def on_service_account(self, namespace: str, sa: str,
                           event: str = "add") -> None:
        name = self.secret_name(namespace, sa)
        if event == "delete":
            self.secrets.pop(name, None)
            return
        if name in self.secrets:
            return
        identity = spiffe_id(namespace, sa, self.trust_domain)
        key = pki.generate_key()
        csr = pki.generate_csr(key, identity)
        cert = self.ca.sign(csr, self.ttl)
        self.secrets[name] = {
            "type": WORKLOAD_SECRET_TYPE,
            "key.pem": pki.key_to_pem(key),
            "cert-chain.pem": cert,
            "root-cert.pem": self.ca.get_root_certificate(),
            "identity": identity,
        }
