"""Istio CA (reference: security/pkg/pki/ca/ca.go): the
CertificateAuthority interface (:50 Sign/GetRootCertificate), self-
signed bootstrap (:82 NewSelfSignedIstioCAOptions — root persisted via
a pluggable secret store, the k8s-secret role), CSR signing (:182) with
TTL clamping, and the secret controller minting per-service-account
bundles (controller/secret.go).
"""
from __future__ import annotations

import dataclasses
import datetime
from typing import Callable, Mapping

from istio_tpu.secure.backend import default_backend
from istio_tpu.security import pki
from istio_tpu.security.spiffe import spiffe_id

DEFAULT_WORKLOAD_TTL = datetime.timedelta(hours=24 * 90)
DEFAULT_ROOT_TTL = datetime.timedelta(days=365 * 10)
CA_SECRET_NAME = "istio-ca-secret"       # ca.go cASecret
WORKLOAD_SECRET_TYPE = "istio.io/key-and-cert"   # controller/secret.go


class CAError(RuntimeError):
    pass


class CertificateAuthority:
    """ca.go:50."""

    def sign(self, csr_pem: bytes, ttl: datetime.timedelta | None = None
             ) -> bytes:
        raise NotImplementedError

    def get_root_certificate(self) -> bytes:
        raise NotImplementedError


@dataclasses.dataclass
class IstioCAOptions:
    cert_ttl: datetime.timedelta = DEFAULT_WORKLOAD_TTL
    max_cert_ttl: datetime.timedelta = DEFAULT_ROOT_TTL
    org: str = "istio_tpu"


class IstioCA(CertificateAuthority):
    def __init__(self, signing_key_pem: bytes, signing_cert_pem: bytes,
                 opts: IstioCAOptions | None = None):
        self.opts = opts or IstioCAOptions()
        self._key_pem = bytes(signing_key_pem)
        self._cert_pem = bytes(signing_cert_pem)

    # -- construction --

    @classmethod
    def new_self_signed(cls, secret_store: "dict | None" = None,
                        org: str = "istio_tpu",
                        root_ttl: datetime.timedelta = DEFAULT_ROOT_TTL,
                        opts: IstioCAOptions | None = None) -> "IstioCA":
        """NewSelfSignedIstioCAOptions (ca.go:82): reuse the persisted
        CA secret when present; otherwise mint a root and persist it.
        The root's subject differs from leaf subjects (the backend
        appends "CN=<org> root CA"): subject==issuer on a leaf reads
        as self-signed to chain verifiers and TLS handshakes fail."""
        if secret_store is not None and CA_SECRET_NAME in secret_store:
            blob = secret_store[CA_SECRET_NAME]
            return cls(blob["ca-key.pem"], blob["ca-cert.pem"], opts)
        key_pem, cert_pem = default_backend().self_signed_root(
            org, root_ttl)
        if secret_store is not None:
            secret_store[CA_SECRET_NAME] = {"ca-key.pem": key_pem,
                                            "ca-cert.pem": cert_pem}
        return cls(key_pem, cert_pem, opts)

    # -- CertificateAuthority --

    def sign(self, csr_pem: bytes,
             ttl: datetime.timedelta | None = None) -> bytes:
        """ca.go:182 Sign: honor the CSR's URI SANs, clamp TTL. SAN
        copying, CA:FALSE and the server+client EKU live in the
        backend (both implementations emit the same shape)."""
        csr = pki.load_csr(csr_pem)
        if not csr.is_signature_valid:
            raise CAError("CSR signature invalid")
        ttl = ttl or self.opts.cert_ttl
        if ttl > self.opts.max_cert_ttl:
            raise CAError(f"requested TTL {ttl} exceeds max "
                          f"{self.opts.max_cert_ttl}")
        try:
            return default_backend().sign_csr(
                self._key_pem, self._cert_pem, bytes(csr_pem), ttl)
        except Exception as exc:
            raise CAError(f"signing failed: {exc}") from exc

    def get_root_certificate(self) -> bytes:
        return self._cert_pem


class SecretController:
    """controller/secret.go: service-account events → per-SA
    `istio.io/key-and-cert` secrets. The SA source is pluggable (k8s in
    the reference; any registry here); secrets land in a dict-like
    store keyed `istio.<sa>.<ns>`."""

    def __init__(self, ca: CertificateAuthority, secrets: dict,
                 trust_domain: str = "cluster.local",
                 ttl: datetime.timedelta = DEFAULT_WORKLOAD_TTL):
        self.ca = ca
        self.secrets = secrets
        self.trust_domain = trust_domain
        self.ttl = ttl

    @staticmethod
    def secret_name(namespace: str, sa: str) -> str:
        return f"istio.{sa}.{namespace}"

    def on_service_account(self, namespace: str, sa: str,
                           event: str = "add") -> None:
        name = self.secret_name(namespace, sa)
        if event == "delete":
            self.secrets.pop(name, None)
            return
        if name in self.secrets:
            return
        identity = spiffe_id(namespace, sa, self.trust_domain)
        key = pki.generate_key()
        csr = pki.generate_csr(key, identity)
        cert = self.ca.sign(csr, self.ttl)
        self.secrets[name] = {
            "type": WORKLOAD_SECRET_TYPE,
            "key.pem": pki.key_to_pem(key),
            "cert-chain.pem": cert,
            "root-cert.pem": self.ca.get_root_certificate(),
            "identity": identity,
        }
