"""CA gRPC service + client + node agent.

Reference: security/pkg/server/grpc/server.go (HandleCSR :55 —
authenticate :188 then sign), security/pkg/caclient (retrying CSR
client), security/pkg/platform (credential fetchers: onprem certs,
gcp/aws metadata — the cloud ones are gated here, no metadata servers
in-image), security/cmd/node_agent/na/nodeagent.go (rotation loop).
"""
from __future__ import annotations

import datetime
import logging
import threading
import time
from concurrent import futures
from typing import Callable, Mapping

import grpc

from istio_tpu.security import pki
from istio_tpu.security import ca_service_pb2 as pb
from istio_tpu.security.ca import CertificateAuthority
from istio_tpu.security.spiffe import identity_from_san

log = logging.getLogger("istio_tpu.security")

# credential verifier: (credential_type, credential bytes) → identity
# string or None (reject). The reference authenticates per platform
# (server.go:188); tests inject their own.
Authenticator = Callable[[str, bytes], str | None]

# (authenticated identity, requested SPIFFE ids) → None when allowed,
# else a rejection message (server.go:74 authorizer.authorize role)
Authorizer = Callable[[str, list[str]], str | None]


def insecure_allow_all_authenticator(cred_type: str,
                                     cred: bytes) -> str | None:
    """TEST/BOOTSTRAP ONLY: accepts any caller as 'anonymous'. Under the
    default same-id authorizer an anonymous caller can sign nothing, so
    pairing this with `authorizer=None` (the default) is still safe;
    pairing it with allow_any_identity_authorizer is the fully open
    configuration and must never ship."""
    return "anonymous"


def cert_authenticator(root_cert_pem: bytes) -> Authenticator:
    """onprem platform flow (security/pkg/platform/onprem.go): the
    credential is an existing cert signed by our root; the caller's
    identity is its SPIFFE URI SAN."""
    def auth(cred_type: str, cred: bytes) -> str | None:
        if cred_type != "onprem":
            return None
        try:
            if not pki.verify_chain(cred, root_cert_pem):
                return None
            return identity_from_san(pki.san_uris(pki.load_cert(cred)))
        except Exception:
            return None
    return auth


def token_authenticator(tokens: "Mapping[str, str]",
                        cred_types: tuple[str, ...] = ("gcp", "aws")
                        ) -> Authenticator:
    """Bearer-token platform flows (security/pkg/platform/gcp.go,
    aws.go): the credential is an opaque token the CA operator trusts —
    a GCE service-account JWT or a signed EC2 identity document. The
    reference validates these against the cloud provider; with no
    egress here, the operator provisions the trusted token → identity
    map directly (istio-ca --trusted-tokens-file)."""
    token_map = {str(k): str(v) for k, v in tokens.items()}

    def auth(cred_type: str, cred: bytes) -> str | None:
        if cred_type not in cred_types:
            return None
        return token_map.get(cred.decode("utf-8", "replace"))
    return auth


def composite_authenticator(*auths: Authenticator) -> Authenticator:
    """First authenticator to produce an identity wins (the reference
    CA chains client-cert and platform authenticators the same way)."""
    def auth(cred_type: str, cred: bytes) -> str | None:
        for candidate in auths:
            identity = candidate(cred_type, cred)
            if identity is not None:
                return identity
        return None
    return auth


def same_id_authorizer(caller: str, requested: list[str]) -> str | None:
    """Default: a workload may only request certificates for its own
    SPIFFE identity (the reference's per-caller authorization contract,
    server.go:74)."""
    for rid in requested:
        if rid != caller:
            return f"caller {caller!r} may not request identity {rid!r}"
    return None


def allow_any_identity_authorizer(caller: str,
                                  requested: list[str]) -> str | None:
    """TEST ONLY: no identity restriction."""
    return None


class CAGrpcServer:
    """CSR signing service.

    Security posture (ADVICE r1 high): authentication is explicit (no
    permissive default), the CSR's requested SPIFFE ids are authorized
    against the authenticated identity before signing, and serving is
    TLS by default with a CA-signed certificate (server.go:165-199) —
    `insecure_port=True` is for tests."""

    TLS_DNS = "istio-ca"

    def __init__(self, ca: CertificateAuthority,
                 authenticator: Authenticator,
                 authorizer: Authorizer | None = None,
                 address: str = "127.0.0.1:0",
                 insecure_port: bool = False):
        self.ca = ca
        self.authenticator = authenticator
        self.authorizer = authorizer or same_id_authorizer
        self._server = grpc.server(futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="ca-grpc"))
        handlers = {
            "HandleCSR": grpc.unary_unary_rpc_method_handler(
                self._handle_csr,
                request_deserializer=pb.CsrRequest.FromString,
                response_serializer=pb.CsrResponse.SerializeToString)}
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                "istio.v1.auth.IstioCAService", handlers),))
        if insecure_port:
            self.port = self._server.add_insecure_port(address)
        else:
            key = pki.generate_key()
            csr = pki.generate_csr(
                key, "spiffe://cluster.local/ns/istio-system/sa/istio-ca",
                dns_names=(self.TLS_DNS,))
            cert = ca.sign(csr)
            creds = grpc.ssl_server_credentials(
                [(pki.key_to_pem(key),
                  cert + ca.get_root_certificate())])
            self.port = self._server.add_secure_port(address, creds)

    def start(self) -> int:
        self._server.start()
        log.info("CA grpc server on port %d", self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace).wait()

    def _handle_csr(self, request: "pb.CsrRequest", context
                    ) -> "pb.CsrResponse":
        ident = self.authenticator(request.credential_type,
                                   request.node_agent_credential)
        if ident is None:
            return pb.CsrResponse(is_approved=False,
                                  status_message="authentication failed")
        try:
            csr = pki.load_csr(bytes(request.csr_pem))
            # EVERY SAN the signed cert would carry needs authorization:
            # ca.sign copies DNS SANs too, and an unauthorized
            # DNS=istio-ca would let a workload impersonate this CA's
            # TLS identity to every node agent
            requested = pki.san_uris(csr) + pki.san_dns(csr)
        except Exception as exc:
            return pb.CsrResponse(is_approved=False,
                                  status_message=f"bad CSR: {exc}")
        if not requested:
            return pb.CsrResponse(
                is_approved=False,
                status_message="authorization failed: CSR requests no "
                               "identities")
        denied = self.authorizer(ident, requested)
        if denied is not None:
            log.warning("CSR rejected: %s", denied)
            return pb.CsrResponse(
                is_approved=False,
                status_message=f"authorization failed: {denied}")
        try:
            ttl = datetime.timedelta(
                minutes=request.requested_ttl_minutes) \
                if request.requested_ttl_minutes else None
            cert = self.ca.sign(bytes(request.csr_pem), ttl)
        except Exception as exc:
            return pb.CsrResponse(is_approved=False,
                                  status_message=f"signing failed: {exc}")
        return pb.CsrResponse(
            is_approved=True, signed_cert=cert,
            cert_chain=self.ca.get_root_certificate())


class CAClient:
    """caclient/grpc: CSR submission with bounded retries."""

    def __init__(self, target: str, max_retries: int = 3,
                 retry_interval_s: float = 0.2,
                 root_cert_pem: bytes | None = None):
        if root_cert_pem:
            creds = grpc.ssl_channel_credentials(
                root_certificates=root_cert_pem)
            self._channel = grpc.secure_channel(
                target, creds,
                options=(("grpc.ssl_target_name_override",
                          CAGrpcServer.TLS_DNS),))
        else:
            self._channel = grpc.insecure_channel(target)
        self._call = self._channel.unary_unary(
            "/istio.v1.auth.IstioCAService/HandleCSR",
            request_serializer=pb.CsrRequest.SerializeToString,
            response_deserializer=pb.CsrResponse.FromString)
        self.max_retries = max_retries
        self.retry_interval_s = retry_interval_s

    def sign_csr(self, csr_pem: bytes, credential: bytes = b"",
                 credential_type: str = "onprem",
                 ttl_minutes: int = 0) -> "pb.CsrResponse":
        req = pb.CsrRequest(csr_pem=csr_pem,
                            node_agent_credential=credential,
                            credential_type=credential_type,
                            requested_ttl_minutes=ttl_minutes)
        last: Exception | None = None
        for attempt in range(self.max_retries + 1):
            try:
                return self._call(req)
            except grpc.RpcError as exc:
                last = exc
                time.sleep(self.retry_interval_s * (2 ** attempt))
        raise last   # type: ignore[misc]

    def close(self) -> None:
        self._channel.close()


class NodeAgent:
    """node_agent rotation loop (na/nodeagent.go): obtain a workload
    cert, sleep until ~half its lifetime remains, repeat. Certs land in
    a pluggable sink (filesystem in the reference; callable here)."""

    def __init__(self, client: CAClient, identity: str,
                 on_certs: Callable[[bytes, bytes, bytes], None],
                 ttl_minutes: int = 60,
                 rotation_fraction: float = 0.5,
                 credential: bytes = b"", credential_type: str = "onprem"):
        self.client = client
        self.identity = identity
        self.on_certs = on_certs
        self.ttl_minutes = ttl_minutes
        self.rotation_fraction = rotation_fraction
        self.credential = credential
        self.credential_type = credential_type
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.rotations = 0

    def rotate_once(self) -> bytes:
        key = pki.generate_key()
        csr = pki.generate_csr(key, self.identity)
        resp = self.client.sign_csr(csr, self.credential,
                                    self.credential_type,
                                    self.ttl_minutes)
        if not resp.is_approved:
            raise RuntimeError(f"CSR rejected: {resp.status_message}")
        self.on_certs(pki.key_to_pem(key), bytes(resp.signed_cert),
                      bytes(resp.cert_chain))
        self.rotations += 1
        return bytes(resp.signed_cert)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-agent")
        self._thread.start()

    def _loop(self) -> None:
        backoff = 1.0
        while not self._stop.is_set():
            try:
                cert_pem = self.rotate_once()
                backoff = 1.0
                remaining = pki.not_after(cert_pem) - \
                    datetime.datetime.now(datetime.timezone.utc)
                wait = remaining.total_seconds() * self.rotation_fraction
            except Exception as exc:
                log.warning("rotation failed: %s", exc)
                wait = backoff
                backoff = min(backoff * 2, 300.0)
            self._stop.wait(max(wait, 0.01))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
