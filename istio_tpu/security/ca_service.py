"""CA gRPC service + client + node agent.

Reference: security/pkg/server/grpc/server.go (HandleCSR :55 —
authenticate :188 then sign), security/pkg/caclient (retrying CSR
client), security/pkg/platform (credential fetchers: onprem certs,
gcp/aws metadata — the cloud ones are gated here, no metadata servers
in-image), security/cmd/node_agent/na/nodeagent.go (rotation loop).
"""
from __future__ import annotations

import datetime
import logging
import threading
import time
from concurrent import futures
from typing import Callable, Mapping

import grpc

from istio_tpu.security import pki
from istio_tpu.security import ca_service_pb2 as pb
from istio_tpu.security.ca import CertificateAuthority

log = logging.getLogger("istio_tpu.security")

# credential verifier: (credential_type, credential bytes) → identity
# string or None (reject). The reference authenticates per platform
# (server.go:188); tests inject their own.
Authenticator = Callable[[str, bytes], str | None]


def allow_all_authenticator(cred_type: str, cred: bytes) -> str | None:
    return "anonymous"


class CAGrpcServer:
    def __init__(self, ca: CertificateAuthority,
                 authenticator: Authenticator = allow_all_authenticator,
                 address: str = "127.0.0.1:0"):
        self.ca = ca
        self.authenticator = authenticator
        self._server = grpc.server(futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="ca-grpc"))
        handlers = {
            "HandleCSR": grpc.unary_unary_rpc_method_handler(
                self._handle_csr,
                request_deserializer=pb.CsrRequest.FromString,
                response_serializer=pb.CsrResponse.SerializeToString)}
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                "istio.v1.auth.IstioCAService", handlers),))
        self.port = self._server.add_insecure_port(address)

    def start(self) -> int:
        self._server.start()
        log.info("CA grpc server on port %d", self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace).wait()

    def _handle_csr(self, request: "pb.CsrRequest", context
                    ) -> "pb.CsrResponse":
        ident = self.authenticator(request.credential_type,
                                   request.node_agent_credential)
        if ident is None:
            return pb.CsrResponse(is_approved=False,
                                  status_message="authentication failed")
        try:
            ttl = datetime.timedelta(
                minutes=request.requested_ttl_minutes) \
                if request.requested_ttl_minutes else None
            cert = self.ca.sign(bytes(request.csr_pem), ttl)
        except Exception as exc:
            return pb.CsrResponse(is_approved=False,
                                  status_message=f"signing failed: {exc}")
        return pb.CsrResponse(
            is_approved=True, signed_cert=cert,
            cert_chain=self.ca.get_root_certificate())


class CAClient:
    """caclient/grpc: CSR submission with bounded retries."""

    def __init__(self, target: str, max_retries: int = 3,
                 retry_interval_s: float = 0.2):
        self._channel = grpc.insecure_channel(target)
        self._call = self._channel.unary_unary(
            "/istio.v1.auth.IstioCAService/HandleCSR",
            request_serializer=pb.CsrRequest.SerializeToString,
            response_deserializer=pb.CsrResponse.FromString)
        self.max_retries = max_retries
        self.retry_interval_s = retry_interval_s

    def sign_csr(self, csr_pem: bytes, credential: bytes = b"",
                 credential_type: str = "onprem",
                 ttl_minutes: int = 0) -> "pb.CsrResponse":
        req = pb.CsrRequest(csr_pem=csr_pem,
                            node_agent_credential=credential,
                            credential_type=credential_type,
                            requested_ttl_minutes=ttl_minutes)
        last: Exception | None = None
        for attempt in range(self.max_retries + 1):
            try:
                return self._call(req)
            except grpc.RpcError as exc:
                last = exc
                time.sleep(self.retry_interval_s * (2 ** attempt))
        raise last   # type: ignore[misc]

    def close(self) -> None:
        self._channel.close()


class NodeAgent:
    """node_agent rotation loop (na/nodeagent.go): obtain a workload
    cert, sleep until ~half its lifetime remains, repeat. Certs land in
    a pluggable sink (filesystem in the reference; callable here)."""

    def __init__(self, client: CAClient, identity: str,
                 on_certs: Callable[[bytes, bytes, bytes], None],
                 ttl_minutes: int = 60,
                 rotation_fraction: float = 0.5,
                 credential: bytes = b"", credential_type: str = "onprem"):
        self.client = client
        self.identity = identity
        self.on_certs = on_certs
        self.ttl_minutes = ttl_minutes
        self.rotation_fraction = rotation_fraction
        self.credential = credential
        self.credential_type = credential_type
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.rotations = 0

    def rotate_once(self) -> bytes:
        key = pki.generate_key()
        csr = pki.generate_csr(key, self.identity)
        resp = self.client.sign_csr(csr, self.credential,
                                    self.credential_type,
                                    self.ttl_minutes)
        if not resp.is_approved:
            raise RuntimeError(f"CSR rejected: {resp.status_message}")
        self.on_certs(pki.key_to_pem(key), bytes(resp.signed_cert),
                      bytes(resp.cert_chain))
        self.rotations += 1
        return bytes(resp.signed_cert)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-agent")
        self._thread.start()

    def _loop(self) -> None:
        backoff = 1.0
        while not self._stop.is_set():
            try:
                cert_pem = self.rotate_once()
                backoff = 1.0
                remaining = pki.not_after(cert_pem) - \
                    datetime.datetime.now(datetime.timezone.utc)
                wait = remaining.total_seconds() * self.rotation_fraction
            except Exception as exc:
                log.warning("rotation failed: %s", exc)
                wait = backoff
                backoff = min(backoff * 2, 300.0)
            self._stop.wait(max(wait, 0.01))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
