"""SPIFFE identities (reference: the URI SAN format at
security/pkg/pki/ca/controller/secret.go:229 and
security/pkg/registry/kube/serviceaccount.go:79):

    spiffe://<trust-domain>/ns/<namespace>/sa/<service-account>
"""
from __future__ import annotations

DEFAULT_TRUST_DOMAIN = "cluster.local"
URI_SCHEME = "spiffe"


class SpiffeError(ValueError):
    pass


def spiffe_id(namespace: str, service_account: str,
              trust_domain: str = DEFAULT_TRUST_DOMAIN) -> str:
    return (f"{URI_SCHEME}://{trust_domain}/ns/{namespace}"
            f"/sa/{service_account}")


def parse_spiffe(uri: str) -> tuple[str, str, str]:
    """→ (trust_domain, namespace, service_account)."""
    prefix = f"{URI_SCHEME}://"
    if not uri.startswith(prefix):
        raise SpiffeError(f"not a spiffe uri: {uri}")
    rest = uri[len(prefix):]
    parts = rest.split("/")
    if len(parts) != 5 or parts[1] != "ns" or parts[3] != "sa":
        raise SpiffeError(f"malformed spiffe uri: {uri}")
    return parts[0], parts[2], parts[4]


def identity_from_san(uris: list[str]) -> str | None:
    """First spiffe URI SAN, if any (san.go ExtractIDs role)."""
    for uri in uris:
        if uri.startswith(f"{URI_SCHEME}://"):
            return uri
    return None
