"""Platform credential fetchers for the CA client / node agent.

Reference: security/pkg/platform — `Client` (client.go:33) abstracts
where a node agent's bootstrap credential comes from:
  * onprem (onprem.go): existing cert chain on disk; identity is the
    cert's single SPIFFE SAN; dial with mTLS.
  * gcp (gcp.go): GCE metadata server issues a service-account JWT
    with the CA address as audience; identity is
    spiffe://cluster.local/ns/default/sa/<service account>; dial with
    TLS + per-RPC bearer token.
  * aws (aws.go): EC2 instance-identity document + PKCS7 signature
    from the instance metadata service, verified against the public
    AWS signing certificate before use.
NewClient (client.go:47) selects by platform name.

This image has no cloud metadata endpoints, so each fetcher takes an
injectable `MetadataSource` (the HTTP metadata hop) — the credential
shaping, identity derivation, SAN extraction, and document
verification are all real and tested against fake sources.
"""
from __future__ import annotations

import base64
import dataclasses
import json
from pathlib import Path
from typing import Any, Callable, Mapping, Protocol

from istio_tpu.security import pki


class PlatformError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class DialOptions:
    """Transport material for the CA channel (GetDialOptions): the
    gRPC layer maps root_cert → ssl creds, client key/cert → mTLS,
    bearer_token → per-RPC authorization metadata (gcp.go jwtAccess)."""
    root_cert_pem: bytes = b""
    client_cert_pem: bytes = b""
    client_key_pem: bytes = b""
    bearer_token: str = ""

    @property
    def secure(self) -> bool:
        return bool(self.root_cert_pem)


class MetadataSource(Protocol):
    """The cloud metadata endpoint seam (GCE metadata server / EC2 IMDS)."""

    def available(self) -> bool: ...

    def fetch(self, path: str, audience: str = "") -> str: ...


class PlatformClient(Protocol):
    def is_proper_platform(self) -> bool: ...

    def get_service_identity(self) -> str: ...

    def get_agent_credential(self) -> bytes: ...

    def get_credential_type(self) -> str: ...

    def get_dial_options(self) -> DialOptions: ...


# ---------------------------------------------------------------------------
# onprem (onprem.go)
# ---------------------------------------------------------------------------

class OnPremClient:
    """Credential = the existing cert chain; identity = its single
    SPIFFE SAN (onprem.go:67-85); CA dial is mTLS with that pair."""

    def __init__(self, root_ca_cert_file: str, key_file: str,
                 cert_chain_file: str):
        self.root_ca_cert_file = root_ca_cert_file
        self.key_file = key_file
        self.cert_chain_file = cert_chain_file

    def is_proper_platform(self) -> bool:
        return True

    def _cert_pem(self) -> bytes:
        try:
            return Path(self.cert_chain_file).read_bytes()
        except OSError as exc:
            raise PlatformError(
                f"failed to read cert file: {self.cert_chain_file}") from exc

    def get_service_identity(self) -> str:
        cert = pki.load_cert(self._cert_pem())
        ids = [u for u in pki.san_uris(cert) if u.startswith("spiffe://")]
        if len(ids) != 1:
            raise PlatformError(
                f"cert has {len(ids)} SPIFFE SAN fields, should be 1")
        return ids[0]

    def get_agent_credential(self) -> bytes:
        return self._cert_pem()

    def get_credential_type(self) -> str:
        return "onprem"

    def get_dial_options(self) -> DialOptions:
        try:
            return DialOptions(
                root_cert_pem=Path(self.root_ca_cert_file).read_bytes(),
                client_cert_pem=self._cert_pem(),
                client_key_pem=Path(self.key_file).read_bytes())
        except OSError as exc:
            raise PlatformError(str(exc)) from exc


# ---------------------------------------------------------------------------
# gcp (gcp.go)
# ---------------------------------------------------------------------------

class GcpClient:
    """Credential = a GCE service-account JWT with aud=grpc://<CA>
    (gcp.go:60-66); identity = spiffe for the instance SA."""

    TOKEN_PATH = "instance/service-accounts/default/identity"
    SA_PATH = "instance/service-accounts/default/email"

    def __init__(self, ca_addr: str, metadata: MetadataSource,
                 root_ca_cert_file: str = "",
                 trust_domain: str = "cluster.local"):
        self.ca_addr = ca_addr
        self.metadata = metadata
        self.root_ca_cert_file = root_ca_cert_file
        self.trust_domain = trust_domain

    def is_proper_platform(self) -> bool:
        return self.metadata.available()

    def _token(self) -> str:
        token = self.metadata.fetch(self.TOKEN_PATH,
                                    audience=f"grpc://{self.ca_addr}")
        if not token:
            raise PlatformError("GCE metadata returned an empty token")
        return token

    def get_service_identity(self) -> str:
        sa = self.metadata.fetch(self.SA_PATH)
        if not sa:
            raise PlatformError("GCE metadata returned no service account")
        # temporary format, gcp.go:98-101
        return f"spiffe://{self.trust_domain}/ns/default/sa/{sa}"

    def get_agent_credential(self) -> bytes:
        return self._token().encode()

    def get_credential_type(self) -> str:
        return "gcp"

    def get_dial_options(self) -> DialOptions:
        root = Path(self.root_ca_cert_file).read_bytes() \
            if self.root_ca_cert_file else b""
        return DialOptions(root_cert_pem=root, bearer_token=self._token())


# ---------------------------------------------------------------------------
# aws (aws.go)
# ---------------------------------------------------------------------------

class AwsClient:
    """Credential = the EC2 instance-identity document with its
    signature, verified before use (aws.go:97-130). The PKCS7
    verification against the AWS public certificate is a pluggable
    `verify(document, signature) -> bool` (this image has no pkcs7
    stack; the default checks structural integrity only and is
    documented as such)."""

    DOC_PATH = "instance-identity/document"
    SIG_PATH = "instance-identity/pkcs7"

    def __init__(self, metadata: MetadataSource,
                 root_ca_cert_file: str = "",
                 verify: Callable[[bytes, bytes], bool] | bool | None
                 = None):
        self.metadata = metadata
        self.root_ca_cert_file = root_ca_cert_file
        # aws.go always verifies the PKCS7 signature before trusting the
        # document — absence of a verifier fails CLOSED; skipping
        # verification requires the explicit opt-out verify=False
        self._verify = verify

    def is_proper_platform(self) -> bool:
        return self.metadata.available()

    def get_instance_identity(self) -> dict[str, Any]:
        doc, sig = self._fetch_identity()
        return {"document": json.loads(doc), "signature": sig.decode()}

    def _fetch_identity(self) -> tuple[bytes, bytes]:
        doc = self.metadata.fetch(self.DOC_PATH).encode()
        sig_b64 = self.metadata.fetch(self.SIG_PATH)
        if not doc or not sig_b64:
            raise PlatformError("EC2 metadata returned no identity document")
        try:
            sig = base64.b64decode(sig_b64, validate=True)
        except Exception as exc:
            raise PlatformError(
                f"failed to decode PKCS7 signature: {exc}") from exc
        if callable(self._verify):
            if not self._verify(doc, sig):
                raise PlatformError("instance identity signature rejected")
        elif self._verify is not False:
            # None (and any other non-callable, e.g. a mistaken
            # verify=True) fails closed; ONLY the literal False opts out
            raise PlatformError(
                "no PKCS7 verifier configured; pass verify=False to "
                "explicitly skip signature verification")
        return doc, base64.b64encode(sig)

    def get_service_identity(self) -> str:
        return ""                   # aws.go:92-94: resolved server-side

    def get_agent_credential(self) -> bytes:
        doc, sig = self._fetch_identity()
        return json.dumps({"document": json.loads(doc),
                           "signature": sig.decode()},
                          sort_keys=True).encode()

    def get_credential_type(self) -> str:
        return "aws"

    def get_dial_options(self) -> DialOptions:
        root = Path(self.root_ca_cert_file).read_bytes() \
            if self.root_ca_cert_file else b""
        return DialOptions(root_cert_pem=root)


def new_platform_client(platform: str,
                        config: Mapping[str, Any]) -> PlatformClient:
    """client.go:47 NewClient."""
    if platform == "onprem":
        return OnPremClient(
            root_ca_cert_file=str(config.get("root_ca_cert_file", "")),
            key_file=str(config.get("key_file", "")),
            cert_chain_file=str(config.get("cert_chain_file", "")))
    if platform == "gcp":
        return GcpClient(
            ca_addr=str(config.get("ca_addr", "")),
            metadata=config["metadata"],
            root_ca_cert_file=str(config.get("root_ca_cert_file", "")))
    if platform == "aws":
        return AwsClient(
            metadata=config["metadata"],
            root_ca_cert_file=str(config.get("root_ca_cert_file", "")),
            verify=config.get("verify"))
    raise PlatformError(f"invalid env {platform} specified")
