"""PKI primitives (reference: security/pkg/pki/{crypto.go,san.go},
ca/{generate_cert,generate_csr}.go): key generation, CSRs carrying
SPIFFE URI SANs, PEM load/inspect helpers, and key↔cert consistency
checks.

Everything here delegates to the `PkiBackend` seam
(istio_tpu/secure/backend.py) — `cryptography` when importable, the
`openssl` CLI otherwise — so this module imports and WORKS on rigs
without the cryptography wheel. Keys and certs are PEM bytes under
thin view wrappers; no backend-native object ever escapes."""
from __future__ import annotations

import datetime
from typing import Sequence

from istio_tpu.secure.backend import (CertInfo, PkiError,
                                      default_backend)

__all__ = ["PrivateKey", "CertView", "CsrView", "PkiError",
           "generate_key", "key_to_pem", "key_from_pem",
           "generate_csr", "load_csr", "load_cert", "san_uris",
           "san_dns", "key_cert_pair_ok", "verify_chain", "not_after"]


class PrivateKey:
    """PEM-holding key handle (the old cryptography key object role)."""

    __slots__ = ("pem",)

    def __init__(self, pem: bytes):
        self.pem = bytes(pem)


class _PemView:
    """Parsed cert/CSR: the PEM plus its backend-parsed CertInfo."""

    __slots__ = ("pem", "info")

    def __init__(self, pem: bytes, info: CertInfo):
        self.pem = bytes(pem)
        self.info = info


class CertView(_PemView):
    @property
    def not_valid_after_utc(self) -> datetime.datetime | None:
        return self.info.not_after


class CsrView(_PemView):
    @property
    def is_signature_valid(self) -> bool:
        return self.info.signature_ok


def generate_key(ec_key: bool = True) -> PrivateKey:
    """EC P-256 by default (fast, small); RSA-2048 optional (the
    reference default)."""
    return PrivateKey(default_backend().generate_key(ec_key))


def key_to_pem(key) -> bytes:
    if isinstance(key, PrivateKey):
        return key.pem
    if isinstance(key, (bytes, bytearray)):
        return bytes(key)
    raise PkiError(f"not a key: {type(key).__name__}")


def key_from_pem(pem: bytes) -> PrivateKey:
    return PrivateKey(pem)


def generate_csr(key, identity: str | None, org: str = "istio_tpu",
                 dns_names: Sequence[str] = ()) -> bytes:
    """CSR with the workload identity as a URI SAN (generate_csr.go);
    optional DNS SANs for serving certs (e.g. the CA's own TLS cert,
    server.go:165-199). identity=None builds a SAN-free CSR (the
    vacuous-authorization probe in tests)."""
    uris = (identity,) if identity else ()
    return default_backend().generate_csr(key_to_pem(key), uris,
                                          tuple(dns_names), org)


def load_csr(pem: bytes) -> CsrView:
    return CsrView(pem, default_backend().csr_info(bytes(pem)))


def load_cert(pem: bytes) -> CertView:
    return CertView(pem, default_backend().cert_info(bytes(pem)))


def _info_of(cert_or_csr) -> CertInfo:
    if isinstance(cert_or_csr, _PemView):
        return cert_or_csr.info
    if isinstance(cert_or_csr, CertInfo):
        return cert_or_csr
    pem = bytes(cert_or_csr)
    if b"CERTIFICATE REQUEST" in pem:
        return default_backend().csr_info(pem)
    return default_backend().cert_info(pem)


def san_uris(cert_or_csr) -> list[str]:
    """URI SANs of a cert/CSR (san.go ExtractSANExtension)."""
    return list(_info_of(cert_or_csr).uris)


def san_dns(cert_or_csr) -> list[str]:
    """DNS SANs of a cert/CSR."""
    return list(_info_of(cert_or_csr).dns)


def key_cert_pair_ok(key_pem, cert_pem: bytes) -> bool:
    return default_backend().key_cert_pair_ok(key_to_pem(key_pem),
                                              bytes(cert_pem))


def verify_chain(cert_pem: bytes, root_pem: bytes) -> bool:
    """Leaf-signed-by-root check (crypto.go verify path)."""
    return default_backend().verify_chain(bytes(cert_pem),
                                          bytes(root_pem))


def not_after(cert_pem: bytes) -> datetime.datetime | None:
    return default_backend().cert_info(bytes(cert_pem)).not_after
