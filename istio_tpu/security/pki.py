"""PKI primitives (reference: security/pkg/pki/{crypto.go,san.go},
ca/{generate_cert,generate_csr}.go) via the `cryptography` package:
key generation, CSRs carrying SPIFFE URI SANs, PEM load/inspect
helpers, and key↔cert consistency checks.
"""
from __future__ import annotations

import datetime
from typing import Sequence

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, rsa
from cryptography.x509.oid import NameOID


def generate_key(ec_key: bool = True):
    """EC P-256 by default (fast, small); RSA-2048 optional (the
    reference default)."""
    if ec_key:
        return ec.generate_private_key(ec.SECP256R1())
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def key_to_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


def key_from_pem(pem: bytes):
    return serialization.load_pem_private_key(pem, password=None)


def generate_csr(key, identity: str, org: str = "istio_tpu",
                 dns_names: tuple[str, ...] = ()) -> bytes:
    """CSR with the workload identity as a URI SAN (generate_csr.go);
    optional DNS SANs for serving certs (e.g. the CA's own TLS cert,
    server.go:165-199)."""
    sans = [x509.UniformResourceIdentifier(identity)]
    sans += [x509.DNSName(d) for d in dns_names]
    builder = x509.CertificateSigningRequestBuilder().subject_name(
        x509.Name([x509.NameAttribute(NameOID.ORGANIZATION_NAME, org)])
    ).add_extension(
        x509.SubjectAlternativeName(sans), critical=False)
    return builder.sign(key, hashes.SHA256()).public_bytes(
        serialization.Encoding.PEM)


def load_csr(pem: bytes) -> x509.CertificateSigningRequest:
    return x509.load_pem_x509_csr(pem)


def load_cert(pem: bytes) -> x509.Certificate:
    return x509.load_pem_x509_certificate(pem)


def san_uris(cert_or_csr) -> list[str]:
    """URI SANs of a cert/CSR (san.go ExtractSANExtension)."""
    try:
        ext = cert_or_csr.extensions.get_extension_for_class(
            x509.SubjectAlternativeName)
    except x509.ExtensionNotFound:
        return []
    return list(ext.value.get_values_for_type(
        x509.UniformResourceIdentifier))


def san_dns(cert_or_csr) -> list[str]:
    """DNS SANs of a cert/CSR."""
    try:
        ext = cert_or_csr.extensions.get_extension_for_class(
            x509.SubjectAlternativeName)
    except x509.ExtensionNotFound:
        return []
    return list(ext.value.get_values_for_type(x509.DNSName))


def key_cert_pair_ok(key_pem: bytes, cert_pem: bytes) -> bool:
    key = key_from_pem(key_pem)
    cert = load_cert(cert_pem)
    a = key.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    b = cert.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    return a == b


def verify_chain(cert_pem: bytes, root_pem: bytes) -> bool:
    """Leaf-signed-by-root check (crypto.go verify path)."""
    cert = load_cert(cert_pem)
    root = load_cert(root_pem)
    try:
        cert.verify_directly_issued_by(root)
        return True
    except Exception:
        return False


def not_after(cert_pem: bytes) -> datetime.datetime:
    return load_cert(cert_pem).not_valid_after_utc
