"""Workload secret delivery — SecretServer + flexvolume-style mounts.

Reference: security/pkg/workload — `SecretServer` (secretserver.go)
delivers a workload's identity key/cert over a channel the workload
can reach: SECRET_FILE mode writes the pair to configured paths with
0600/0644 permissions (secretfileserver.go); WORKLOAD_API is
unimplemented in the reference too. The node_agent_k8s flexvolume
driver (security/cmd/node_agent_k8s/flexvolume/driver/driver.go)
bridges kubelet to the node agent: Mount(dir, opts) parses the pod's
uid/name/namespace/serviceAccount from the driver options, provisions
a per-workload directory under the node-agent home, and binds it into
the pod; Unmount tears it down.

Here the tmpfs/bind-mount pair is a `mounter` seam (real mounts need
privileges this build does not assume); the per-workload directory
lifecycle, the driver's JSON response protocol, and the option
validation are faithful.
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
from pathlib import Path
from typing import Any, Callable, Mapping

SECRET_FILE = 0
WORKLOAD_API = 1         # unimplemented, matching the reference

_KEY_MODE = 0o600
_CERT_MODE = 0o644


class WorkloadError(RuntimeError):
    pass


@dataclasses.dataclass
class SecretConfig:
    """workload/config.go Config."""
    mode: int = SECRET_FILE
    service_identity_cert_file: str = ""
    service_identity_private_key_file: str = ""


class SecretServer:
    """secretserver.go SecretServer interface."""

    def set_service_identity_private_key(self, content: bytes) -> None:
        raise NotImplementedError

    def set_service_identity_cert(self, content: bytes) -> None:
        raise NotImplementedError


class SecretFileServer(SecretServer):
    """secretfileserver.go: atomic writes with key 0600 / cert 0644."""

    def __init__(self, config: SecretConfig):
        self.config = config

    @staticmethod
    def _write(path: str, content: bytes, mode: int) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(p.suffix + ".tmp")
        # the temp file carries the final mode from creation — key
        # material must never exist world-readable, even briefly.
        # O_EXCL (after clearing any stale leftover from a crashed run)
        # guarantees the mode applies: O_CREAT alone would silently
        # reuse an existing tmp file's old permissions
        tmp.unlink(missing_ok=True)
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, mode)
        try:
            os.write(fd, content)
        finally:
            os.close(fd)
        os.chmod(tmp, mode)   # mode arg is masked by umask at open
        os.replace(tmp, p)

    def set_service_identity_private_key(self, content: bytes) -> None:
        self._write(self.config.service_identity_private_key_file,
                    content, _KEY_MODE)

    def set_service_identity_cert(self, content: bytes) -> None:
        self._write(self.config.service_identity_cert_file,
                    content, _CERT_MODE)


def new_secret_server(config: SecretConfig) -> SecretServer:
    """secretserver.go NewSecretServer."""
    if config.mode == SECRET_FILE:
        return SecretFileServer(config)
    if config.mode == WORKLOAD_API:
        raise WorkloadError("WORKLOAD API is unimplemented")
    raise WorkloadError(f"mode: {config.mode} is not supported")


# ---------------------------------------------------------------------------
# flexvolume driver (node_agent_k8s/flexvolume/driver/driver.go)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class WorkloadAttrs:
    """WorkloadInfo_WorkloadAttributes: who the mount is for."""
    uid: str
    workload: str
    namespace: str
    service_account: str


def parse_mount_opts(opts: str) -> WorkloadAttrs | None:
    """driver.go checkValidMountOpts: the kubelet passes pod identity
    as JSON driver options."""
    try:
        data = json.loads(opts)
    except (TypeError, ValueError):
        return None
    uid = data.get("kubernetes.io/pod.uid", "")
    name = data.get("kubernetes.io/pod.name", "")
    ns = data.get("kubernetes.io/pod.namespace", "")
    sa = data.get("kubernetes.io/serviceAccount.name", "")
    if not (uid and name and ns):
        return None
    return WorkloadAttrs(uid=uid, workload=name, namespace=ns,
                         service_account=sa)


class FlexVolumeDriver:
    """The driver's verb surface, each returning the kubelet JSON
    response shape (driver.go Resp). `mounter(src, dst)` /
    `unmounter(dst)` inject the privileged tmpfs+bind step; the
    default copies nothing and relies on the shared directory tree
    (sufficient for hermetic runs)."""

    def __init__(self, nodeagent_home: str = "/tmp/nodeagent",
                 mounter: Callable[[str, str], None] | None = None,
                 unmounter: Callable[[str], None] | None = None):
        self.home = Path(nodeagent_home)
        self.mounter = mounter
        self.unmounter = unmounter
        # uid → attrs, the node agent's view of live workloads
        self.workloads: dict[str, WorkloadAttrs] = {}

    @staticmethod
    def _resp(status: str, message: str, **extra: Any) -> dict:
        return {"status": status, "message": message, **extra}

    def init(self) -> dict:
        return self._resp("Success", "Init ok.", attach=False)

    def mount(self, target_dir: str, opts: str) -> dict:
        attrs = parse_mount_opts(opts)
        if attrs is None:
            return self._resp(
                "Failure",
                f"Mount failed with dir {target_dir} with incomplete "
                "inputs")
        workload_dir = self.home / attrs.uid
        try:
            workload_dir.mkdir(parents=True, exist_ok=True)
            if self.mounter is not None:
                self.mounter(str(workload_dir),
                             str(Path(target_dir) / "nodeagent"))
            (workload_dir / "attrs.json").write_text(json.dumps(
                dataclasses.asdict(attrs), sort_keys=True))
        except Exception as exc:
            shutil.rmtree(workload_dir, ignore_errors=True)
            return self._resp(
                "Failure",
                f"Mount failed with dir {target_dir} with error: {exc}")
        self.workloads[attrs.uid] = attrs
        return self._resp("Success", f"Mount ok: {target_dir}")

    def unmount(self, target_dir: str) -> dict:
        # driver.go Unmount: the pod uid is a fixed path component of
        # the kubelet's mount dir
        parts = Path(target_dir).parts
        if len(parts) < 6:
            return self._resp("Failure",
                              f"Unmount failed with dir {target_dir}.")
        uid = parts[5]
        if self.unmounter is not None:
            try:
                self.unmounter(str(Path(target_dir) / "nodeagent"))
                self.unmounter(target_dir)
            except Exception as exc:
                return self._resp(
                    "Failure",
                    f"Unmount failed with dir {target_dir}: {exc}")
        shutil.rmtree(self.home / uid, ignore_errors=True)
        self.workloads.pop(uid, None)
        return self._resp("Success", f"Unmount ok: {target_dir}")

    def secret_server_for(self, uid: str) -> SecretServer:
        """The node agent drops the rotated pair into the workload's
        provisioned directory (node_agent_k8s handler role)."""
        if uid not in self.workloads:
            raise WorkloadError(f"unknown workload uid {uid}")
        base = self.home / uid
        return SecretFileServer(SecretConfig(
            mode=SECRET_FILE,
            service_identity_cert_file=str(base / "cert-chain.pem"),
            service_identity_private_key_file=str(base / "key.pem")))
