"""Attribute bags — the request-scoped key/value data model.

Role of the reference's mixer/pkg/attribute: `Bag` (bag.go:18) is read-only
lookup; `MutableBag` (mutableBag.go:37) is a parent-chained overlay used to
carry preprocessing output; reference tracking (protoBag.go:117-160) records
which attributes a request's evaluation actually touched so sidecars can
cache Check results keyed on them.
"""
from __future__ import annotations

import threading
from typing import Any, Iterable, Mapping


class Bag:
    """Read-only attribute bag interface."""

    # keep subclasses' __slots__ effective (a slotless base silently
    # re-adds per-instance __dict__ to every wire bag)
    __slots__ = ()

    def get(self, name: str) -> tuple[Any, bool]:
        raise NotImplementedError

    def names(self) -> list[str]:
        raise NotImplementedError

    def done(self) -> None:  # release pooled resources; no-op by default
        pass

    def debug_string(self) -> str:
        parts = []
        for n in sorted(self.names()):
            v, _ = self.get(n)
            parts.append(f"{n:30s}: {v!r}")
        return "\n".join(parts)


class DictBag(Bag):
    """Bag over a plain dict — the FakeBag of the test stack
    (reference: mixer/pkg/il/testing/fakebag.go)."""

    def __init__(self, values: Mapping[str, Any] | None = None):
        self._values = dict(values or {})

    def get(self, name: str) -> tuple[Any, bool]:
        if name in self._values:
            return self._values[name], True
        return None, False

    def names(self) -> list[str]:
        return list(self._values)


class MutableBag(Bag):
    """Mutable overlay chained over an optional parent
    (reference: mutableBag.go:37-118)."""

    def __init__(self, parent: Bag | None = None):
        self.parent = parent if parent is not None else DictBag()
        self._values: dict[str, Any] = {}

    def get(self, name: str) -> tuple[Any, bool]:
        if name in self._values:
            return self._values[name], True
        return self.parent.get(name)

    def names(self) -> list[str]:
        seen = dict.fromkeys(self._values)
        for n in self.parent.names():
            seen.setdefault(n)
        return list(seen)

    def set(self, name: str, value: Any) -> None:
        self._values[name] = value

    def delete(self, name: str) -> None:
        self._values.pop(name, None)

    def reset(self) -> None:
        self._values.clear()

    def preserve_merge(self, *bags: Bag) -> None:
        """Merge without clobbering existing values (reference:
        mutableBag.go:180 PreserveMerge — used to fold preprocessing
        output under the request attributes)."""
        for bag in bags:
            for name in bag.names():
                _, exists = self.get(name)
                if not exists:
                    v, ok = bag.get(name)
                    if ok:
                        self._values[name] = v

    def child(self) -> "MutableBag":
        return MutableBag(parent=self)


# Reference-condition markers, mirroring mixerpb ReferencedAttributes
# Condition (ABSENCE / EXACT / REGEX) used in protoBag.go trackReference.
CONDITION_ABSENCE = "ABSENCE"
CONDITION_EXACT = "EXACT"
CONDITION_REGEX = "REGEX"


class TrackingBag(Bag):
    """Wraps a bag and records every attribute (and string-map key)
    resolution, with presence/absence condition.

    This reproduces ProtoBag's referenced-attribute tracking
    (protoBag.go:117 GetReferencedAttributes, :155 trackReference): the
    snapshot powers client-side Check caching, so exact semantics matter —
    a map-key lookup records "name[key]" and a failed lookup records the
    ABSENCE condition.
    """

    def __init__(self, inner: Bag):
        self.inner = inner
        self._refs: dict[tuple[str, str], str] = {}  # (attr, mapkey) -> condition
        self._lock = threading.Lock()

    def get(self, name: str) -> tuple[Any, bool]:
        v, ok = self.inner.get(name)
        with self._lock:
            self._refs[(name, "")] = CONDITION_EXACT if ok else CONDITION_ABSENCE
        return v, ok

    def track_map_key(self, name: str, key: str, found: bool) -> None:
        with self._lock:
            self._refs[(name, key)] = CONDITION_EXACT if found else CONDITION_ABSENCE

    def names(self) -> list[str]:
        return self.inner.names()

    def referenced(self) -> dict[tuple[str, str], str]:
        with self._lock:
            return dict(self._refs)

    def referenced_names(self) -> list[str]:
        """Flat snapshot in the conformance-corpus format: 'attr' and
        'attr[key]' entries, sorted."""
        with self._lock:
            out = []
            for (attr, key), _cond in self._refs.items():
                out.append(f"{attr}[{key}]" if key else attr)
            return sorted(out)

    def clear_referenced(self) -> None:
        with self._lock:
            self._refs.clear()


def bag_from_mapping(values: Mapping[str, Any]) -> DictBag:
    return DictBag(values)


def merged_names(bags: Iterable[Bag]) -> list[str]:
    seen: dict[str, None] = {}
    for b in bags:
        for n in b.names():
            seen.setdefault(n)
    return list(seen)
