"""Dictionary-compressed attribute wire codec.

Role of the reference's CompressedAttributes encode/decode
(mixer/pkg/attribute/mutableBag.go:230 ToProto, :296 GetBagFromProto;
protoBag.go:49 NewProtoBag): attribute names and string values travel as
int32 dictionary indices. Index >= 0 points into the 169-word global
dictionary; index < 0 points into the per-message word list at slot
``-index - 1`` (dictState.go:74-81).

The integer-coded wire form is exactly what the TPU tensorizer wants — a
batch of CompressedAttributes is already most of the way to an int32 device
array (SURVEY.md §2.2 translation note).
"""
from __future__ import annotations

import dataclasses
import datetime
from typing import Any, Mapping

from istio_tpu.attribute.bag import Bag, DictBag
from istio_tpu.attribute.global_dict import GLOBAL_WORD_INDEX, GLOBAL_WORD_LIST


def slot_to_index(slot: int) -> int:
    return -slot - 1


def index_to_slot(index: int) -> int:
    return -index - 1


@dataclasses.dataclass
class CompressedAttributes:
    """Wire-shaped attribute record (mirrors istio.mixer.v1
    CompressedAttributes field-for-field in spirit)."""

    words: list[str] = dataclasses.field(default_factory=list)
    strings: dict[int, int] = dataclasses.field(default_factory=dict)
    int64s: dict[int, int] = dataclasses.field(default_factory=dict)
    doubles: dict[int, float] = dataclasses.field(default_factory=dict)
    bools: dict[int, bool] = dataclasses.field(default_factory=dict)
    timestamps: dict[int, datetime.datetime] = dataclasses.field(default_factory=dict)
    durations: dict[int, datetime.timedelta] = dataclasses.field(default_factory=dict)
    bytes_: dict[int, bytes] = dataclasses.field(default_factory=dict)
    string_maps: dict[int, dict[int, int]] = dataclasses.field(default_factory=dict)


class _DictState:
    """Assigns per-message word slots for words outside the global
    dictionary (reference: dictState.go:17-80)."""

    def __init__(self, global_index: Mapping[str, int]):
        self._global = global_index
        self._message: dict[str, int] = {}

    def assign(self, word: str) -> int:
        idx = self._global.get(word)
        if idx is not None:
            return idx
        idx = self._message.get(word)
        if idx is not None:
            return idx
        idx = slot_to_index(len(self._message))
        self._message[word] = idx
        return idx

    def word_list(self) -> list[str]:
        words = [""] * len(self._message)
        for w, idx in self._message.items():
            words[index_to_slot(idx)] = w
        return words


def encode(bag: Bag, global_index: Mapping[str, int] | None = None) -> CompressedAttributes:
    """Bag → CompressedAttributes (reference: mutableBag.go:230 ToProto)."""
    gi = GLOBAL_WORD_INDEX if global_index is None else global_index
    ds = _DictState(gi)
    out = CompressedAttributes()
    for name in bag.names():
        v, ok = bag.get(name)
        if not ok:
            continue
        k = ds.assign(name)
        if isinstance(v, bool):
            out.bools[k] = v
        elif isinstance(v, int):
            out.int64s[k] = v
        elif isinstance(v, float):
            out.doubles[k] = v
        elif isinstance(v, str):
            out.strings[k] = ds.assign(v)
        elif isinstance(v, bytes):
            out.bytes_[k] = v
        elif isinstance(v, datetime.timedelta):
            out.durations[k] = v
        elif isinstance(v, datetime.datetime):
            out.timestamps[k] = v
        elif isinstance(v, Mapping):
            out.string_maps[k] = {ds.assign(mk): ds.assign(mv) for mk, mv in v.items()}
        else:
            raise TypeError(f"unsupported attribute value type for {name}: {type(v)}")
    out.words = ds.word_list()
    return out


class WordResolutionError(KeyError):
    pass


def _lookup_word(index: int, message_words: list[str],
                 global_words: list[str]) -> str:
    if index >= 0:
        if index < len(global_words):
            return global_words[index]
        raise WordResolutionError(f"global dictionary index {index} out of range")
    slot = index_to_slot(index)
    if slot < len(message_words):
        return message_words[slot]
    raise WordResolutionError(f"message word slot {slot} out of range")


def decode(ca: CompressedAttributes,
           global_words: list[str] | None = None) -> DictBag:
    """CompressedAttributes → eager DictBag (reference:
    mutableBag.go:296 GetBagFromProto + :311 UpdateBagFromProto)."""
    gw = GLOBAL_WORD_LIST if global_words is None else global_words
    values: dict[str, Any] = {}

    def word(i: int) -> str:
        return _lookup_word(i, ca.words, gw)

    for k, vi in ca.strings.items():
        values[word(k)] = word(vi)
    for k, v in ca.int64s.items():
        values[word(k)] = v
    for k, v in ca.doubles.items():
        values[word(k)] = v
    for k, v in ca.bools.items():
        values[word(k)] = v
    for k, v in ca.timestamps.items():
        values[word(k)] = v
    for k, v in ca.durations.items():
        values[word(k)] = v
    for k, v in ca.bytes_.items():
        values[word(k)] = v
    for k, m in ca.string_maps.items():
        values[word(k)] = {word(mk): word(mv) for mk, mv in m.items()}
    return DictBag(values)


def decode_deltas(records: list[CompressedAttributes],
                  global_words: list[str] | None = None) -> list[DictBag]:
    """Decode a Report-style delta-encoded attribute stream: each record
    updates the previous bag (reference: api/grpcServer.go:262-300 with
    UpdateBagFromProto)."""
    out: list[DictBag] = []
    acc: dict[str, Any] = {}
    for rec in records:
        bag = decode(rec, global_words)
        for n in bag.names():
            v, _ = bag.get(n)
            acc[n] = v
        out.append(DictBag(dict(acc)))
    return out
