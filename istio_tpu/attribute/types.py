"""Value types of the policy expression system.

Mirrors the semantics of istio.io/api ValueType as used by the reference
(mixer/pkg/il/types.go, mixer/pkg/expr/expr.go:71-76): eleven wire types.
Runtime Python representations:

  STRING / DNS_NAME / EMAIL_ADDRESS / URI  -> str
  INT64      -> int
  DOUBLE     -> float
  BOOL       -> bool
  TIMESTAMP  -> datetime.datetime (tz-aware, UTC)
  DURATION   -> datetime.timedelta
  IP_ADDRESS -> bytes (4 or 16 bytes, like Go net.IP)
  STRING_MAP -> Mapping[str, str]
"""
from __future__ import annotations

import datetime
import enum
import re


class ValueType(enum.Enum):
    UNSPECIFIED = 0
    STRING = 1
    INT64 = 2
    DOUBLE = 3
    BOOL = 4
    TIMESTAMP = 5
    IP_ADDRESS = 6
    EMAIL_ADDRESS = 7
    URI = 8
    DNS_NAME = 9
    DURATION = 10
    STRING_MAP = 11

    def __str__(self) -> str:
        return self.name


# Types whose runtime representation is a plain Python string.
STRINGY = frozenset({ValueType.STRING, ValueType.EMAIL_ADDRESS,
                     ValueType.URI, ValueType.DNS_NAME})

_GO_DURATION_RE = re.compile(
    r"([0-9]*\.?[0-9]+)(ns|us|µs|μs|ms|s|m|h)")
_GO_UNIT_NS = {
    "ns": 1, "us": 1_000, "µs": 1_000, "μs": 1_000,
    "ms": 1_000_000, "s": 1_000_000_000, "m": 60_000_000_000,
    "h": 3_600_000_000_000,
}


def parse_go_duration(s: str) -> datetime.timedelta:
    """Parse a Go-syntax duration ("300ms", "1h30m", "-2.5s", "0").

    Matches time.ParseDuration semantics, which the reference applies to
    every string literal to decide STRING vs DURATION constants
    (mixer/pkg/expr/expr.go:143-146).
    """
    orig = s
    if not s:
        raise ValueError("empty duration")
    sign = 1
    if s[0] in "+-":
        sign = -1 if s[0] == "-" else 1
        s = s[1:]
    if not s:
        raise ValueError(f"invalid duration {orig!r}")
    if s == "0":
        return datetime.timedelta(0)
    total_ns = 0.0
    pos = 0
    while pos < len(s):
        m = _GO_DURATION_RE.match(s, pos)
        if m is None or m.start() != pos:
            raise ValueError(f"invalid duration {orig!r}")
        total_ns += float(m.group(1)) * _GO_UNIT_NS[m.group(2)]
        pos = m.end()
    return datetime.timedelta(microseconds=sign * total_ns / 1000.0)


def format_go_duration(td: datetime.timedelta) -> str:
    """Format timedelta in Go duration style (for debug output)."""
    ns = int(td.total_seconds() * 1e9)
    if ns == 0:
        return "0s"
    sign = "-" if ns < 0 else ""
    ns = abs(ns)
    parts = []
    for unit, width in (("h", 3_600_000_000_000), ("m", 60_000_000_000)):
        if ns >= width:
            parts.append(f"{ns // width}{unit}")
            ns %= width
    if ns or not parts:
        sec = ns / 1e9
        txt = f"{sec:.9f}".rstrip("0").rstrip(".")
        parts.append(f"{txt}s")
    return sign + "".join(parts)


def parse_rfc3339(s: str) -> datetime.datetime:
    """RFC3339 timestamp parse (the `timestamp()` extern format,
    mixer/pkg/il/runtime/externs.go:95-102)."""
    txt = s.replace("Z", "+00:00")
    dt = datetime.datetime.fromisoformat(txt)
    if dt.tzinfo is None:
        raise ValueError(f"timestamp {s!r} missing timezone")
    return dt


def parse_ip(s: str) -> bytes:
    """Parse dotted-quad / ipv6 text to bytes (the `ip()` extern,
    externs.go:81-86). Returns 4 or 16 bytes."""
    import ipaddress
    return ipaddress.ip_address(s).packed


def ip_equal(a: bytes, b: bytes) -> bool:
    """Compare IPs like Go net.IP.Equal: a 4-byte v4 equals its 16-byte
    v4-in-v6 form (externs.go:88-93)."""
    if len(a) == len(b):
        return a == b
    import ipaddress

    def canon(raw: bytes):
        addr = ipaddress.ip_address(raw)
        # python's IPv6Address never equals an IPv4Address, even for
        # the ::ffff:a.b.c.d mapped form Go's net.IP.Equal accepts —
        # unmap before comparing
        mapped = getattr(addr, "ipv4_mapped", None)
        return mapped if mapped is not None else addr

    try:
        return canon(a) == canon(b)
    except ValueError:
        return False


def type_of_value(v: object) -> ValueType:
    """Infer the ValueType of a runtime Python value."""
    if isinstance(v, bool):
        return ValueType.BOOL
    if isinstance(v, int):
        return ValueType.INT64
    if isinstance(v, float):
        return ValueType.DOUBLE
    if isinstance(v, str):
        return ValueType.STRING
    if isinstance(v, bytes):
        return ValueType.IP_ADDRESS
    if isinstance(v, datetime.timedelta):
        return ValueType.DURATION
    if isinstance(v, datetime.datetime):
        return ValueType.TIMESTAMP
    if isinstance(v, dict):
        return ValueType.STRING_MAP
    return ValueType.UNSPECIFIED
