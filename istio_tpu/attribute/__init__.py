"""Attribute system (reference: mixer/pkg/attribute)."""

from istio_tpu.attribute.bag import (Bag, DictBag, MutableBag, TrackingBag,
                                     CONDITION_ABSENCE, CONDITION_EXACT)
from istio_tpu.attribute.types import ValueType
from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST, GLOBAL_WORD_INDEX
from istio_tpu.attribute.compressed import (CompressedAttributes, encode,
                                            decode, decode_deltas)

__all__ = [
    "Bag", "DictBag", "MutableBag", "TrackingBag",
    "CONDITION_ABSENCE", "CONDITION_EXACT", "ValueType",
    "GLOBAL_WORD_LIST", "GLOBAL_WORD_INDEX",
    "CompressedAttributes", "encode", "decode", "decode_deltas",
]
