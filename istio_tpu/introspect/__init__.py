"""In-process introspection (ControlZ + Mixer self-monitoring port).

Reference anchors: Istio's ControlZ facility (every component exposes
an admin port with process/config/metrics pages) and Mixer's :9093
self-monitoring server (mixer/pkg/server/monitoring.go). This package
is their TPU-build counterpart: one stdlib HTTP server, loopback by
default, no egress, that unifies the repo's three observability
systems — the prometheus_client REGISTRY (runtime/monitor.py), the
homegrown registry (utils/metrics.py, incl. the serving-stage
decomposition + live p99 gauges), and the span stream
(utils/tracing.py) — behind six endpoints:

  /metrics        one merged Prometheus text exposition
  /healthz        liveness (+ optional probe-controller aggregation)
  /readyz         readiness: config snapshot published + device probe
  /debug/config   active snapshot summary (generation, rules, errors)
  /debug/queues   batcher depth/age/in-flight + stage decomposition
  /debug/cache    compile/layout/response cache occupancy
  /debug/traces   ring buffer of recent spans
"""
from istio_tpu.introspect.server import IntrospectServer

__all__ = ["IntrospectServer"]
