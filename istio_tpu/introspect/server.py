"""The introspection HTTP server (ControlZ / Mixer :9093 role).

stdlib http.server only — this image has no egress and the admin
surface must never add a dependency to the serving path. The server
binds loopback by default; every handler is read-only and built to be
safe to hit while the hot path is under load (scrape-rate work only:
no per-request state, quantile sorts happen here, not in serving).
"""
from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

log = logging.getLogger("istio_tpu.introspect")


def _merged_metrics_text() -> str:
    """ONE Prometheus text exposition covering both registries: the
    prometheus_client REGISTRY (runtime/monitor.py — resolve/dispatch
    counters, batch-size histograms, config generation) and the
    homegrown utils/metrics registry (serving-stage decomposition,
    live percentile gauges, native wire counters). The live gauges are
    refreshed first so a scrape always sees percentiles over the
    current window."""
    from prometheus_client import generate_latest

    from istio_tpu.runtime import monitor
    from istio_tpu.utils import metrics as hostmetrics

    monitor.refresh_latency_gauges()
    prom = generate_latest(monitor.REGISTRY).decode("utf-8", "replace")
    home = hostmetrics.default_registry.expose_text()
    if prom and not prom.endswith("\n"):
        prom += "\n"
    return prom + home


class IntrospectServer:
    """Admin server over a RuntimeServer core (+ optional collaborators).

    `runtime`: the RuntimeServer whose controller/batcher/dispatcher
    the debug endpoints read (None → those endpoints degrade to
    minimal payloads instead of failing; /metrics always works).
    `native`: a NativeMixerServer whose counters() mirror into the
    shared registry on every /metrics scrape.
    `probe_controller`: a utils/probe.ProbeController aggregated into
    /healthz (reference: pkg/probe's controller).
    `trace_capacity`: size of the /debug/traces ring; 0 disables ring
    installation (use when the process owns its own reporters).
    """

    def __init__(self, runtime: Any = None, port: int = 0,
                 host: str = "127.0.0.1", native: Any = None,
                 probe_controller: Any = None,
                 trace_capacity: int = 256, discovery: Any = None,
                 tls: Any = None):
        self.runtime = runtime
        # secure.mtls.ServingCerts (or None): TLS-wrap every accepted
        # connection against the holder's CURRENT context — per-accept
        # wrapping is what makes a rotate() apply without a rebind
        self._tls = tls
        self.native = native
        self.probe_controller = probe_controller
        # pilot DiscoveryService whose debug_view() backs
        # /debug/discovery (None → {"enabled": false})
        self.discovery = discovery
        # a runtime with a live audit plane folds the discovery scope
        # program into its plane_agreement invariant — the introspect
        # server is where the two planes first meet in one process
        aud = getattr(runtime, "audit", None)
        if aud is not None and discovery is not None:
            aud.attach_discovery(discovery)
        self._ring = None
        # extra cache-stat providers: name -> zero-arg callable
        self._cache_stats: dict[str, Callable[[], Any]] = {}
        # /debug/analysis memo: (snapshot revision, report dict) — the
        # analyzer runs on first request per config generation, never
        # on the serving path or at swap time
        self._analysis_cache: tuple[int, dict] | None = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:          # noqa: N802 (stdlib API)
                outer._route(self)

            def log_message(self, fmt: str, *args: Any) -> None:
                log.debug("introspect: " + fmt, *args)

        # bind BEFORE touching the global tracer: a bind failure (port
        # in use) raises out of __init__ with no instance to close(),
        # and a ring installed first would leak on the hot path forever
        if tls is not None:
            class TlsHTTPServer(ThreadingHTTPServer):
                def get_request(self):   # per-accept TLS wrap
                    sock, addr = super().get_request()
                    return outer._tls.wrap_server_socket(sock), addr
            self._httpd = TlsHTTPServer((host, port), Handler)
        else:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        if trace_capacity:
            from istio_tpu.utils import tracing
            self._ring = tracing.enable_ring(trace_capacity)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="introspect-http")

    # -- lifecycle --

    def start(self) -> int:
        self._thread.start()
        log.info("introspect server on port %d", self.port)
        return self.port

    def close(self) -> None:
        # shutdown() blocks on an event only serve_forever() sets —
        # calling it when start() never ran (a pre-start failure's
        # cleanup path) would hang the caller forever
        started = self._thread.ident is not None
        if started:
            self._httpd.shutdown()
        self._httpd.server_close()
        if started:
            self._thread.join(timeout=5)
        if self._ring is not None:
            # restore the pre-introspect tracer: a closed admin server
            # must not leave span construction on the hot path (or
            # stack dead rings across create/close cycles)
            from istio_tpu.utils import tracing
            tracing.disable_ring(self._ring)
            self._ring = None

    def add_cache_stats(self, name: str,
                        fn: Callable[[], Any]) -> None:
        """Register an extra /debug/cache section (e.g. an API front's
        response memo)."""
        self._cache_stats[name] = fn

    # -- routing --

    _ROUTES = {
        "/metrics": "_h_metrics",
        "/healthz": "_h_healthz",
        "/readyz": "_h_readyz",
        "/debug/config": "_h_config",
        "/debug/queues": "_h_queues",
        "/debug/cache": "_h_cache",
        "/debug/traces": "_h_traces",
        "/debug/resilience": "_h_resilience",
        "/debug/executor": "_h_executor",
        "/debug/analysis": "_h_analysis",
        "/debug/rulestats": "_h_rulestats",
        "/debug/canary": "_h_canary",
        "/debug/roofline": "_h_roofline",
        "/debug/report": "_h_report",
        "/debug/shards": "_h_shards",
        "/debug/discovery": "_h_discovery",
        "/debug/slow": "_h_slow",
        "/debug/events": "_h_events",
        "/debug/audit": "_h_audit",
        "/debug/slo": "_h_slo",
        "/debug/identity": "_h_identity",
        "/debug/profile": "_h_profile",
        "/debug/threads": "_h_threads",
    }

    @staticmethod
    def _query(req: BaseHTTPRequestHandler) -> dict:
        """?k=v&... of the request path (single values, last wins)."""
        from urllib.parse import parse_qsl
        parts = req.path.split("?", 1)
        if len(parts) < 2:
            return {}
        return dict(parse_qsl(parts[1]))

    def _route(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?", 1)[0]
        name = self._ROUTES.get(path)
        if name is None:
            body = ("not found; endpoints: " +
                    " ".join(sorted(self._ROUTES))).encode()
            self._send(req, 404, "text/plain; charset=utf-8", body)
            return
        try:
            getattr(self, name)(req)
        except Exception as exc:   # an admin page must never take the
            log.exception("introspect handler %s failed", path)
            self._send(req, 500, "text/plain; charset=utf-8",
                       f"{type(exc).__name__}: {exc}".encode())

    @staticmethod
    def _send(req: BaseHTTPRequestHandler, code: int, ctype: str,
              body: bytes) -> None:
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _send_json(self, req: BaseHTTPRequestHandler, payload: Any,
                   code: int = 200) -> None:
        self._send(req, code, "application/json",
                   json.dumps(payload, indent=1, default=str).encode())

    # -- endpoints --

    def _h_metrics(self, req: BaseHTTPRequestHandler) -> None:
        if self.native is not None:
            try:
                self.native.counters()   # mirrors into the registry
            except Exception:
                log.exception("native counter mirror failed")
        self._send(req, 200,
                   "text/plain; version=0.0.4; charset=utf-8",
                   _merged_metrics_text().encode())

    def _probe_status(self) -> tuple[bool, str]:
        if self.probe_controller is None:
            return True, ""
        return self.probe_controller.status()

    def _batcher_health(self) -> tuple[bool, str]:
        """Flusher-thread watchdog (check + report coalescers): a dead
        flusher means new submits fail fast and health must go red —
        the load balancer has to stop sending traffic to a server that
        can no longer answer it."""
        if self.runtime is None:
            return True, ""
        for name, b in (("check", self.runtime.batcher),
                        ("report", self.runtime._report_batcher)):
            if b is None:
                continue
            healthy = getattr(b, "healthy", None)
            if healthy is None:
                continue
            ok, err = healthy()
            if not ok:
                return False, f"{name} batcher: {err}"
        return True, ""

    def _h_healthz(self, req: BaseHTTPRequestHandler) -> None:
        ok, err = self._probe_status()
        if ok:
            ok, err = self._batcher_health()
        payload = {"status": "ok" if ok else "unavailable"}
        if err:
            payload["error"] = err
        if self.runtime is not None:
            payload["config_generation"] = \
                self.runtime.controller.dispatcher.snapshot.revision
        self._send_json(req, payload, 200 if ok else 503)

    def _h_readyz(self, req: BaseHTTPRequestHandler) -> None:
        """Ready = a config snapshot is published, the batcher accepts
        work, and (when probes are wired) every probe is available —
        the gate a load balancer flips traffic on."""
        ok, err = self._probe_status()
        payload: dict[str, Any] = {}
        if self.runtime is not None:
            try:
                snap = self.runtime.controller.dispatcher.snapshot
                payload["config_generation"] = snap.revision
                payload["n_rules"] = len(snap.rules)
            except Exception as exc:
                ok, err = False, f"no published snapshot: {exc}"
            if self.runtime.batcher._closed:
                ok, err = False, "batcher closed"
            elif ok:
                ok, err = self._batcher_health()
        payload["status"] = "ready" if ok else "unready"
        if err:
            payload["error"] = err
        self._send_json(req, payload, 200 if ok else 503)

    def _h_config(self, req: BaseHTTPRequestHandler) -> None:
        if self.runtime is None:
            self._send_json(req, {"error": "no runtime attached"}, 503)
            return
        ctl = self.runtime.controller
        d = ctl.dispatcher
        snap = d.snapshot
        args = self.runtime.args
        payload = {
            "generation": snap.revision,
            "n_rules": len(snap.rules),
            "n_instances": len(snap.instances),
            "n_handlers": len(d.handlers),
            "errors": [str(e) for e in snap.errors],
            "identity_attr": d.identity_attr,
            "fused": d.fused is not None,
            "has_apa": d.has_apa,
            "buckets": list(d.buckets),
            "batch_window_s": args.batch_window_s,
            "pipeline": args.pipeline,
            "report_batching": args.report_batching,
            "quota_in_step": args.quota_in_step,
            "mesh_shape": args.mesh_shape,
        }
        if d.fused is not None:
            payload["fused_deny"] = d.fused.fused_deny
            payload["fused_lists"] = d.fused.fused_lists
            payload["host_overlay_rules"] = \
                len(d.fused.host_rule_idx)
        self._send_json(req, payload)

    def _h_queues(self, req: BaseHTTPRequestHandler) -> None:
        from istio_tpu.runtime import monitor

        payload: dict[str, Any] = {
            "latency": monitor.latency_snapshot(),
        }
        if self.runtime is not None:
            payload["check"] = self.runtime.batcher.stats()
            rb = self.runtime._report_batcher
            if rb is not None:
                payload["report"] = rb.stats()
        self._send_json(req, payload)

    def _h_roofline(self, req: BaseHTTPRequestHandler) -> None:
        """Roofline accounting for the LIVE snapshot's fused step
        (compiler/roofline.py): per serving bucket, bytes/op counts
        derived from the compiled shapes — and, when the stage
        decomposition has observations, the live device_step median
        judged against the platform roof (achieved GB/s / TOPS,
        fraction_of_roof, binding resource). ?batch=N models one
        extra shape."""
        import jax

        from istio_tpu.compiler import roofline
        from istio_tpu.runtime import monitor

        platform = jax.devices()[0].platform
        payload: dict[str, Any] = {
            "platform": platform,
            "peaks": roofline.peaks_for(platform),
        }
        d = self.runtime.controller.dispatcher \
            if self.runtime is not None else None
        if d is None or d.fused is None:
            payload["note"] = "no fused plan (generic path serving)"
            self._send_json(req, payload)
            return
        plan = d.fused
        buckets = list(d.buckets) or [self.runtime.args.max_batch]
        # the live p50 is judged against the largest SERVING bucket —
        # a ?batch=N model is what-if only (no served batch ever ran
        # at a non-bucket shape, so judging the p50 against it would
        # be nonsense)
        judged = max(buckets) if buckets else None
        try:
            extra = int(self._query(req).get("batch", 0))
        except ValueError:
            extra = 0
        if extra > 0:
            buckets = sorted(set(buckets) | {extra})
        dev = monitor.latency_snapshot()["stages"].get(
            "device_step", {})
        step_ms = dev.get("p50_ms")
        payload["device_step_p50_ms"] = step_ms
        payload["str_tiers"] = list(plan.str_tiers)
        # byte-plane width the served batches ACTUALLY ran (latency-
        # tier narrowing): judging the live p50 against the worst-case
        # max_str_len model when every batch was tier-narrowed inflates
        # achieved GB/s / fraction_of_roof for the byte-dominated
        # components. Use the dominant served width; fall back to the
        # full plane when nothing has been counted yet.
        tier_counts = dict(plan._tier_served)
        payload["tier_served_batches"] = {
            str(w): n for w, n in sorted(tier_counts.items())}
        live_width = max(tier_counts, key=tier_counts.get) \
            if tier_counts else None
        per: dict[str, Any] = {}
        # the device_step histogram aggregates EVERY served batch
        # shape, so judging each bucket's (very different) byte model
        # against the one p50 would mislabel all but the shape that
        # dominates the window — attach the live judgment only to the
        # largest serving bucket (what sustained load pads to)
        if step_ms:
            payload["vs_live_note"] = (
                "device_step_p50_ms aggregates all served batch "
                f"shapes; vs_live_device_step is attached to bucket "
                f"{judged} only (the shape sustained load pads to), "
                f"modeled at the dominant served byte-plane width "
                f"{live_width} — per-bucket walls need a shape-keyed "
                "histogram")
        for b in buckets:
            model = roofline.model_check_step(plan.engine, b,
                                              plan=plan)
            entry = model.asdict()
            if step_ms and b == judged:
                live_model = model if live_width is None else \
                    roofline.model_check_step(plan.engine, b,
                                              plan=plan,
                                              str_len=live_width)
                entry["vs_live_device_step"] = live_model.report(
                    step_ms / 1e3)
                entry["vs_live_str_len"] = live_width
            per[str(b)] = entry
        payload["buckets"] = per
        self._send_json(req, payload)

    def _h_report(self, req: BaseHTTPRequestHandler) -> None:
        """Telemetry ingestion plane view (the report analog of
        /debug/queues + /debug/resilience in one page): live six-stage
        pipeline p50/p95/p99 (wire_decode → coalesce_wait → tensorize
        → device_field_eval → intern_decode → adapter_dispatch),
        record-conservation state (accepted == exported + rejected;
        in_flight is the transient difference), coalescer occupancy,
        per-template record totals, per-exporter delivery/drop/lag
        stats, and the most recent typed-drop reasons. Serves
        zero-shaped before the first record — an idle plane must be
        distinguishable from a missing one."""
        from istio_tpu.runtime import monitor

        payload: dict[str, Any] = {
            **monitor.report_latency_snapshot(),
            **monitor.report_counters(),
        }
        if self.runtime is not None:
            rb = self.runtime._report_batcher
            payload["coalescer"] = rb.stats() if rb is not None \
                else {"inline": True,
                      "note": "report_batching=False — records "
                              "dispatch inline, no coalescer"}
            args = self.runtime.args
            payload["policy"] = {
                "report_batching": args.report_batching,
                # the coalescer's OWN normalized cap (None =
                # unbounded, no coalescer = no cap) — never re-derive
                # the default here and risk disagreeing with the
                # coalescer block above
                "report_queue_cap": rb.max_queue
                if rb is not None else None,
                "max_batch": args.max_batch,
                "buckets": list(getattr(
                    self.runtime.controller.dispatcher, "buckets",
                    ())),
            }
            d = self.runtime.controller.dispatcher
            if d.fused is not None:
                rl = d.fused.report_lowering
                payload["lowering"] = {
                    "report_rules": len(d.fused.report_rules),
                    "device_instances":
                        len(rl.specs) if rl is not None else 0,
                    "host_instances":
                        len(rl.host_instances) if rl is not None
                        else None,
                    "field_programs":
                        rl.n_fields if rl is not None else 0,
                }
        self._send_json(req, payload)

    def _h_cache(self, req: BaseHTTPRequestHandler) -> None:
        payload: dict[str, Any] = {}
        if self.runtime is not None:
            d = self.runtime.controller.dispatcher
            if d.fused is not None:
                payload["compile"] = d.fused.cache_stats()
            rs = d.snapshot.ruleset
            interner = getattr(rs, "interner", None)
            vals = getattr(interner, "_values", None)
            if vals is not None:
                # intern-table occupancy (compile-time constants; a
                # growing number here across swaps is config growth,
                # never request traffic — InternTable's contract)
                payload["interner_values"] = len(vals)
        for name, fn in self._cache_stats.items():
            try:
                payload[name] = fn()
            except Exception as exc:
                payload[name] = f"error: {exc}"
        if self.native is not None:
            payload["native_resp_memo"] = len(self.native._resp_memo)
            payload["native_ref_cache"] = len(self.native._ref_cache)
        self._send_json(req, payload)

    def _h_shards(self, req: BaseHTTPRequestHandler) -> None:
        """Sharded serving plane view (istio_tpu/sharding): the last
        shard-plan decision + balance, per-bank rule counts / resident
        bank bytes / rows routed, per-replica lane queue depth and
        batch-latency percentiles, and the router stage decomposition
        (shard_dispatch / bank_check / fold). Zero-shaped before the
        first routed batch per the promtext doctrine; {"enabled":
        false} on a monolithic server."""
        from istio_tpu.runtime import monitor

        payload: dict[str, Any] = {"enabled": False}
        rt = self.runtime
        state = getattr(rt, "_sharded", None) if rt is not None \
            else None
        rr = getattr(rt, "_replica_router", None) if rt is not None \
            else None
        if state is None or rr is None:
            self._send_json(req, payload)
            return
        plan = state["plan"]
        payload = {
            "enabled": True,
            "mode": state.get("mode"),
            "fallback_reason": state.get("fallback_reason") or None,
            "revision": state.get("revision"),
            "last_decision": {
                **plan.to_json(),
                "build_wall_ms": round(
                    state.get("build_wall_s", 0.0) * 1e3, 3),
                "built_wall": state.get("built_wall"),
            },
            # delta compilation (compiler/cache.py + the content-
            # addressed bank cache): which banks the last publish
            # carried vs recompiled, the cumulative rebuild ledger —
            # including the LAST REBUILD ERROR and the generation it
            # struck (a failed rebuild keeps the previous generation
            # serving; this is where that state is visible) — and the
            # persistent-cache / decomposition-memo counters
            "delta": state.get("delta") or {
                "reused": [], "recompiled": [], "plan_stability": {}},
            "rebuild": dict(getattr(rt, "_rebuild_status", {})),
            "banks": [b.stats() for b in state.get("banks", ())],
            "replicas": [],
            "stages": monitor.shard_latency_snapshot()["stages"],
        }
        try:
            from istio_tpu.compiler import cache as compile_cache
            cc = {"persistent_cache_dir":
                  getattr(rt, "_compile_cache_dir", None),
                  "xla_cache_events":
                      compile_cache.cache_event_counts()}
            dc = getattr(rt.controller.dispatcher.snapshot,
                         "decomp_cache", None)
            if dc is not None:
                cc["decomp_cache"] = dc.stats()
            payload["compile_cache"] = cc
        except Exception as exc:   # accounting never breaks the view
            payload["compile_cache"] = f"error: {exc}"
        rep_lat = monitor.replica_snapshot()
        routers = {r.replica: r for r in rr.routers}
        for i, lane in enumerate(rr.lanes):
            st = lane.stats()
            entry = {
                "replica": i,
                "queue_depth": st["depth"],
                "oldest_wait_ms": st["oldest_wait_ms"],
                "in_flight": st["in_flight"],
                "healthy": st["healthy"],
                # zero-shaped latency block before the first batch
                "batch_latency": rep_lat.get(str(i), {
                    "batches": 0, "sum_ms": 0.0, "p50_ms": 0.0,
                    "p95_ms": 0.0, "p99_ms": 0.0}),
            }
            r = routers.get(i)
            if r is not None:
                entry["router"] = r.stats()
            payload["replicas"].append(entry)
        # cross-lane routing aggregate (rows per shard / occupancy /
        # misroutes): ReplicaRouter.routing_stats is the single home
        # shared with the fleet bench and the shard smoke
        routing = rr.routing_stats()
        payload["rows_per_shard"] = routing["rows_per_shard"]
        payload["occupancy"] = routing["occupancy"]
        payload["misrouted"] = routing["misrouted"]
        self._send_json(req, payload)

    def _h_discovery(self, req: BaseHTTPRequestHandler) -> None:
        """Pilot discovery serving plane view (pilot/discovery.py):
        snapshot generation, cache occupancy + hit/miss/carried/
        invalidated accounting, node-group counts per endpoint, the
        namespace→shard scope plan (balance + stability), shard watch
        versions + parked watcher count, push fan-out percentiles and
        the pilot_discovery_stage_seconds decomposition. {"enabled":
        false} when no DiscoveryService is attached."""
        if self.discovery is None:
            self._send_json(req, {"enabled": False})
            return
        self._send_json(req, {"enabled": True,
                              **self.discovery.debug_view()})

    def _h_executor(self, req: BaseHTTPRequestHandler) -> None:
        """Adapter-executor plane view (runtime/executor.py): per-
        handler bulkhead lanes (queue depth / in-flight / oldest
        running / breaker state), the host-action conservation
        counters (submitted == sum of typed outcomes), the chaos seam
        state, and the maintenance registry — per-provider refresh
        totals/failures and last-refresh age (a provider gone stale
        must be visible here, because the last good list keeps
        serving silently). Zero-shaped before the first host action;
        {"enabled": false} when the executor is off."""
        from istio_tpu.runtime import monitor
        from istio_tpu.runtime.resilience import CHAOS

        payload: dict[str, Any] = {
            "enabled": False,
            "counters": monitor.host_action_counters(),
        }
        ex = getattr(self.runtime, "executor", None) \
            if self.runtime is not None else None
        if ex is not None:
            payload = {"enabled": True, **ex.snapshot()}
        payload["chaos"] = {
            k: v for k, v in CHAOS.snapshot().items()
            if k.startswith(("adapter", "injected_adapter"))}
        # per-handler provider freshness straight from the live
        # handlers (refresh_stats) — the maintenance registry above
        # carries the scheduler's view; this is the adapter's own
        if self.runtime is not None:
            providers: dict[str, Any] = {}
            try:
                d = self.runtime.controller.dispatcher
                for name, h in d.handlers.items():
                    stats = getattr(h, "refresh_stats", None)
                    if callable(stats):
                        st = stats()
                        if st.get("provider"):
                            providers[name] = st
            except Exception as exc:
                providers = {"error": str(exc)}
            payload["providers"] = providers
        self._send_json(req, payload)

    def _h_resilience(self, req: BaseHTTPRequestHandler) -> None:
        """Overload-resilience view: breaker state machine, shed /
        expired / fallback counters, admission-control config and the
        batcher watchdog — the page an on-call loads when the shed
        counters start moving."""
        from istio_tpu.runtime import monitor

        payload: dict[str, Any] = {
            "counters": monitor.resilience_counters(),
        }
        if self.runtime is not None:
            res = getattr(self.runtime, "resilience", None)
            if res is not None:
                payload.update(res.snapshot())
            # sharded serving bypasses the monolithic checker: the
            # page must say so and show the PER-BANK breakers that
            # actually see traffic (detail in /debug/shards)
            state = getattr(self.runtime, "_sharded", None)
            if state is not None:
                payload["sharded"] = {
                    "note": "sharded serving: check traffic rides "
                            "per-bank resilience (one breaker + "
                            "oracle fallback per bank); the "
                            "monolithic breaker above sees no "
                            "check batches",
                    "bank_breakers": {
                        str(b.shard_id): b.checker.breaker.snapshot()
                        for b in state.get("banks", ())
                        if b.checker is not None},
                }
            args = self.runtime.args
            payload["policy"] = {
                "default_check_deadline_ms":
                    getattr(args, "default_check_deadline_ms", 0.0),
                "check_queue_cap":
                    getattr(args, "check_queue_cap", None),
                "brownout": getattr(args, "brownout", False),
                "check_fail_policy":
                    getattr(args, "check_fail_policy", "closed"),
                "breaker_failures":
                    getattr(args, "breaker_failures", None),
                "breaker_reset_s":
                    getattr(args, "breaker_reset_s", None),
            }
            # stats() is the single home of batcher state (depth read
            # under the queue mutex, watchdog health included)
            st = self.runtime.batcher.stats()
            payload["batcher"] = {
                k: st.get(k) for k in ("depth", "max_queue",
                                       "brownout", "healthy",
                                       "health_error")}
        self._send_json(req, payload)

    def _analysis_for(self, snap) -> dict:
        """Memoized analyzer report for `snap` (one run per config
        generation — shared by /debug/analysis and the rulestats
        never-hit cross-check)."""
        cached = self._analysis_cache
        if cached is None or cached[0] != snap.revision:
            from istio_tpu.analysis import analyze_snapshot
            report = analyze_snapshot(snap, pair_budget=50_000)
            cached = (snap.revision, report.to_dict())
            self._analysis_cache = cached
        return cached[1]

    def _h_analysis(self, req: BaseHTTPRequestHandler) -> None:
        """Static-analysis report for the LAST published snapshot
        (istio_tpu/analysis): findings with severities, rule ids and
        oracle-confirmed witnesses. Computed on first request per
        config generation and memoized — an admin page must never put
        analysis cost on the serving path."""
        if self.runtime is None:
            self._send_json(req, {"error": "no runtime attached"}, 503)
            return
        snap = self.runtime.controller.dispatcher.snapshot
        payload = self._analysis_for(snap)
        self._send_json(req, {"generation": snap.revision, **payload})

    def _h_rulestats(self, req: BaseHTTPRequestHandler) -> None:
        """Rule-level telemetry view (runtime/rulestats.py): top-K hot
        rules with per-namespace deny rates and decision exemplars
        (trace ids join /debug/traces), plus never-hit rules
        cross-checked against the static analyzer's shadowed-rule
        findings — a dead rule shows whether it is provably dead
        (analyzer agrees) or merely unexercised. Query params:
        `k` (top-K size, default 10), `shadow=0` (skip the analyzer
        cross-check — it runs the memoized per-generation analysis).
        The handler drains on demand, so the view is current even
        between the background drainer's intervals."""
        if self.runtime is None:
            self._send_json(req, {"error": "no runtime attached"}, 503)
            return
        agg = getattr(self.runtime, "rulestats", None)
        if agg is None:
            self._send_json(req,
                            {"error": "rule telemetry not wired"}, 503)
            return
        q = self._query(req)
        try:
            agg.drain()
        except Exception:
            log.exception("on-demand rulestats drain failed")
        shadowed: set = set()
        if q.get("shadow", "1") != "0":
            try:
                snap = self.runtime.controller.dispatcher.snapshot
                report = self._analysis_for(snap)
                for f in report.get("findings", ()):
                    if f.get("code") == "shadowed-rule" and \
                            f.get("rules"):
                        # rules=(covering, shadowed); analyzer names
                        # are bare — snapshot() matches them against
                        # qualified names with an ambiguity guard
                        shadowed.add(f["rules"][-1])
            except Exception:
                log.exception("rulestats analyzer cross-check failed")
        payload = agg.snapshot(
            top_k=int(q.get("k", 0) or 0) or None, shadowed=shadowed)
        self._send_json(req, payload)

    def _h_canary(self, req: BaseHTTPRequestHandler) -> None:
        """Config-canary view (istio_tpu/canary): recorder occupancy,
        gate config, and the last N shadow-replay reports — per-rule
        divergence counts with exemplars whose trace ids join
        /debug/traces and whose `bag` field replays via `mixs canary`.
        Diverging rules are cross-checked against the memoized static
        analysis (`analyzer_overlap`): a rule that both flips recorded
        decisions AND carries a shadow/overlap/plane finding is drift
        with independent static evidence. `?shadow=0` skips the
        cross-check (the analysis run is memoized per generation but
        not free)."""
        if self.runtime is None:
            self._send_json(req, {"error": "no runtime attached"}, 503)
            return
        canary = getattr(self.runtime, "canary", None)
        if canary is None:
            self._send_json(
                req, {"error": "canary not enabled "
                               "(ServerArgs.canary / --canary)"}, 503)
            return
        payload = canary.snapshot()
        ctl = self.runtime.controller
        rej = getattr(ctl, "last_canary_rejection", None)
        if rej is not None:
            payload["last_rejection"] = str(rej)
        if self._query(req).get("shadow", "1") != "0":
            try:
                snap = ctl.dispatcher.snapshot
                analysis = self._analysis_for(snap)
                # analyzer findings name compiler rules "name.ns"
                # (config._qualify); canary per_rule keys are "ns/name"
                # (Snapshot.qualified_rule_names) — index findings
                # under both forms plus the bare name so the join
                # works regardless of which surface produced the id
                def _canon(rid: str) -> str:
                    name, sep, ns = rid.rpartition(".")
                    return f"{ns}/{name}" if sep else rid

                flagged: dict[str, list] = {}
                for f in analysis.get("findings", ()):
                    if f.get("code") not in (
                            "shadowed-rule", "allow-deny-conflict",
                            "plane-divergence"):
                        continue
                    for r in f.get("rules") or ():
                        for key in {r, _canon(r)}:
                            flagged.setdefault(key, []).append(
                                f["code"])
                for rep in payload["reports"]:
                    overlap = []
                    for name in rep.get("per_rule", {}):
                        # exact forms only: a bare-name fallback would
                        # attach a default-namespace finding to a
                        # same-named rule in ANY namespace — a wrong
                        # cross-link an operator may act on
                        codes = flagged.get(name)
                        if codes:
                            overlap.append({"rule": name,
                                            "codes": sorted(set(codes))})
                    rep["analyzer_overlap"] = overlap
            except Exception:
                log.exception("canary analyzer cross-check failed")
        self._send_json(req, payload)

    def _h_traces(self, req: BaseHTTPRequestHandler) -> None:
        """Recent finished spans, chronological (RingReporter).
        `?status=X` filters by the span `status` tag: `status=failed`
        keeps every span whose status is set and not ok/0 (the check
        spans tag their google.rpc code), a specific value keeps exact
        matches. `?min_ms=N` keeps spans at least that long (the tail
        complement of ?status — a slow span is rarely a failed one),
        and `?trace=ID` keeps one trace's spans — the deep link the
        /debug/slow exemplars carry."""
        if self._ring is None:
            self._send_json(req, {"error": "trace ring not installed"},
                            503)
            return
        # filter over the FULL retained ring, THEN truncate: a failed
        # span must stay visible in ?status=failed for as long as the
        # ring holds it, even behind a burst of newer ok spans
        spans = self._ring.snapshot()
        q = self._query(req)
        want = q.get("status")
        if want == "failed":
            spans = [s for s in spans
                     if (s.get("tags") or {}).get("status")
                     not in (None, "ok", "0")]
        elif want:
            spans = [s for s in spans
                     if (s.get("tags") or {}).get("status") == want]
        trace = q.get("trace")
        if trace:
            spans = [s for s in spans if s.get("traceId") == trace]
        try:
            min_ms = float(q.get("min_ms", 0) or 0)
        except ValueError:
            min_ms = 0.0
        if min_ms > 0:
            # span durations are zipkin µs
            spans = [s for s in spans
                     if s.get("duration", 0) >= min_ms * 1000.0]
        self._send_json(req, {
            "dropped": self._ring.dropped,
            "spans": spans[-128:],
        })

    # -- forensics plane (runtime/forensics.py) ------------------------

    def _h_slow(self, req: BaseHTTPRequestHandler) -> None:
        """Flight-recorder view: the top-K slowest retained requests,
        each with its per-stage attribution (queue_wait / tensorize /
        h2d / device_step / fold / grant / respond / per-handler host
        waits / wire_decode), the control-plane events that overlapped
        its lifetime, and a /debug/traces deep link by trace id.
        `?k=N` sizes the list (default 10). Zero-shaped on a clean
        server: threshold/config always serve, `slowest` is empty."""
        from istio_tpu.runtime import forensics

        q = self._query(req)
        try:
            k = int(q.get("k", 10) or 10)
        except ValueError:
            k = 10
        self._send_json(req, forensics.RECORDER.snapshot(top_k=k))

    def _h_events(self, req: BaseHTTPRequestHandler) -> None:
        """Mesh event timeline: the bounded ring of control-plane
        events (config publishes, canary verdicts, bank rebuilds,
        prewarm start/end per shape, breaker transitions, quota
        flushes, grant revocations, provider refreshes, chaos arms,
        audit violations, quiesce/shutdown). `?kind=X` (alias
        `?type=X`) filters by event kind, `?since_s=S` keeps only
        events recorded within the last S seconds, `?n=N` bounds
        (default 128). The same ring annotates /debug/slow
        exemplars."""
        from istio_tpu.runtime import forensics, monitor

        q = self._query(req)
        try:
            n = int(q.get("n", 128) or 128)
        except ValueError:
            n = 128
        events = forensics.EVENTS.snapshot(
            kind=q.get("kind") or q.get("type"), limit=n)
        since_s = q.get("since_s")
        if since_s is not None:
            try:
                horizon = time.time() - float(since_s)
                events = [e for e in events if e["wall"] >= horizon]
            except ValueError:
                pass
        self._send_json(req, {
            "retained": len(forensics.EVENTS),
            "counters": monitor.forensics_counters(),
            "events": events,
        })

    # -- mesh audit plane (runtime/audit.py) ---------------------------

    def _h_audit(self, req: BaseHTTPRequestHandler) -> None:
        """Live invariant auditor: the six mesh-wide AuditCheck
        verdicts (report/check/quota conservation, grant coherence,
        plane agreement, shard routing) with evidence and the
        generation checked at, plus the fault-explainability scorer's
        records and rate. `?refresh=1` forces a fresh evaluation
        before serving (the background thread evaluates on its own
        interval otherwise). Serves `{"enabled": false}` when no
        audit plane is attached."""
        aud = getattr(self.runtime, "audit", None)
        if aud is None:
            self._send_json(req, {"enabled": False})
            return
        q = self._query(req)
        if q.get("refresh") or not aud.snapshot()["evaluations"]:
            self._send_json(req, aud.evaluate())
            return
        self._send_json(req, aud.snapshot())

    def _h_identity(self, req: BaseHTTPRequestHandler) -> None:
        """Secure-plane view: the zero-shaped mixer_identity_* counter
        families (issue/rotate/expiry × ok/failed, authenticated
        checks, typed UNAUTHENTICATED admissions), the serving
        WorkloadIdentity's live stats when one is registered on the
        executor maintenance lane, and this front's ServingCerts
        generation when TLS is on."""
        from istio_tpu.runtime import monitor
        payload: dict = {"counters": monitor.identity_counters()}
        if self._tls is not None:
            payload["serving_cert_generation"] = self._tls.generation
        ex = getattr(self.runtime, "executor", None)
        wi = None
        if ex is not None:
            wi = getattr(ex, "_persistent_refresh",
                         {}).get("workload_identity")
        if wi is not None and hasattr(wi, "stats"):
            payload["workload_identity"] = wi.stats()
        self._send_json(req, payload)

    def _h_slo(self, req: BaseHTTPRequestHandler) -> None:
        """One fused per-plane SLO scorecard: check wire p99 vs its
        target, report export lag + in-flight ledger, discovery push
        fan-out p99, quota flush age, and the audit plane's own
        healthy/explainability verdicts. Each plane reports
        ok / miss / no_data; `overall` is the worst verdict."""
        from istio_tpu.runtime import forensics, monitor
        from istio_tpu.runtime.slo import scorecard

        aud = getattr(self.runtime, "audit", None)
        self._send_json(req, scorecard(
            monitor, forensics,
            audit=aud.snapshot() if aud is not None else None,
            discovery=self.discovery))

    def _h_profile(self, req: BaseHTTPRequestHandler) -> None:
        """On-demand device profiling: `?seconds=N` (default 1, max
        60) drives one jax.profiler trace capture into the configured
        directory (ServerArgs.profile_dir / MIXS_PROFILE_DIR / a fresh
        tempdir) and returns the artifact listing. The handler thread
        blocks for the capture window (admin surface — serving is
        untouched); concurrent captures answer 409. Fail-soft where
        the profiler is unavailable ({"available": false})."""
        import os

        from istio_tpu.runtime import forensics

        q = self._query(req)
        try:
            seconds = float(q.get("seconds", 1.0) or 1.0)
        except ValueError:
            seconds = 1.0
        directory = None
        if self.runtime is not None:
            directory = getattr(self.runtime.args, "profile_dir",
                                None)
        # None → capture_profile mkdtemps lazily (only once the lock
        # is held and the profiler imports — no tempdir litter from
        # busy/unavailable polls)
        directory = directory or os.environ.get("MIXS_PROFILE_DIR") \
            or None
        try:
            payload = forensics.capture_profile(directory, seconds)
        except forensics.ProfileBusy as exc:
            self._send_json(req, {"error": str(exc)}, 409)
            return
        self._send_json(req, payload,
                        200 if payload.get("available") else 503)

    def _h_threads(self, req: BaseHTTPRequestHandler) -> None:
        """Host-side thread-stack dump (sys._current_frames): every
        live thread's python stack, keyed by name — the wedged-pump /
        wedged-lane diagnostic that otherwise needs gdb on a serving
        process."""
        from istio_tpu.runtime import forensics

        self._send_json(req, forensics.thread_stacks())
