"""On-demand build of the native shim (protoc --cpp_out + g++).

Build artifacts live in _build/ which is NOT under version control
(reviewable source only — a committed binary can't be audited);
staleness is a content hash of the sources, not mtimes (mtimes are
arbitrary after a fresh clone)."""
from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_SO = os.path.join(_BUILD, "libmixer_shim.so")
_HASH = os.path.join(_BUILD, ".srchash")
_HTTPD_SO = os.path.join(_BUILD, "libmixer_httpd.so")
_HTTPD_HASH = os.path.join(_BUILD, ".httpd_srchash")
_H2LOAD = os.path.join(_BUILD, "h2load")
_H2LOAD_HASH = os.path.join(_BUILD, ".h2load_srchash")
_PROTO_DIR = os.path.join(_DIR, "..", "api", "proto")
_lock = threading.Lock()


class NativeBuildError(RuntimeError):
    pass


def _source_hash(*paths: str) -> str:
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def ensure_built() -> str:
    """Compile (once) and return the shared-library path."""
    src = os.path.join(_DIR, "shim.cpp")
    proto_src = os.path.join(_PROTO_DIR, "mixer.proto")
    want = _source_hash(src, proto_src)
    with _lock:
        if os.path.exists(_SO) and os.path.exists(_HASH):
            with open(_HASH, encoding="ascii") as f:
                if f.read().strip() == want:
                    return _SO
        os.makedirs(_BUILD, exist_ok=True)
        try:
            subprocess.run(
                ["protoc", f"-I{_PROTO_DIR}", "-I/usr/include",
                 f"--cpp_out={_BUILD}", proto_src],
                check=True, capture_output=True, text=True)
            subprocess.run(
                ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                 f"-I{_BUILD}", src,
                 os.path.join(_BUILD, "mixer.pb.cc"),
                 "-lprotobuf", "-o", _SO],
                check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as exc:
            raise NativeBuildError(
                f"native shim build failed:\n{exc.stderr}") from exc
        except FileNotFoundError as exc:
            raise NativeBuildError(f"toolchain missing: {exc}") from exc
        with open(_HASH, "w", encoding="ascii") as f:
            f.write(want + "\n")
        return _SO


def _build_one(srcs: list[str], out: str, hash_path: str,
               extra_args: list[str],
               hash_extra: list[str] | None = None) -> str:
    """Hash-gated g++ build of one native artifact."""
    want = _source_hash(*srcs, *(hash_extra or []))
    with _lock:
        if os.path.exists(out) and os.path.exists(hash_path):
            with open(hash_path, encoding="ascii") as f:
                if f.read().strip() == want:
                    return out
        os.makedirs(_BUILD, exist_ok=True)
        try:
            subprocess.run(
                ["g++", "-O2", "-std=c++17", *extra_args, *srcs,
                 "-o", out],
                check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as exc:
            raise NativeBuildError(
                f"native build failed for {out}:\n{exc.stderr}") from exc
        except FileNotFoundError as exc:
            raise NativeBuildError(f"toolchain missing: {exc}") from exc
        with open(hash_path, "w", encoding="ascii") as f:
            f.write(want + "\n")
        return out


def ensure_httpd_built() -> str:
    """Compile the native HTTP/2 front-end (httpd.cpp) → .so path."""
    return _build_one(
        [os.path.join(_DIR, "httpd.cpp")], _HTTPD_SO, _HTTPD_HASH,
        ["-fPIC", "-shared", "-pthread", f"-I{_DIR}"],
        hash_extra=[os.path.join(_DIR, "hpack_tables.h"),
                    os.path.join(_DIR, "h2_frame.h")])


def ensure_h2load_built() -> str:
    """Compile the C++ load client (h2load.cpp) → binary path."""
    return _build_one(
        [os.path.join(_DIR, "h2load.cpp")], _H2LOAD, _H2LOAD_HASH,
        [f"-I{_DIR}"],
        hash_extra=[os.path.join(_DIR, "h2_frame.h")])
