"""On-demand build of the native shim (protoc --cpp_out + g++)."""
from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_SO = os.path.join(_BUILD, "libmixer_shim.so")
_PROTO_DIR = os.path.join(_DIR, "..", "api", "proto")
_lock = threading.Lock()


class NativeBuildError(RuntimeError):
    pass


def _newer(a: str, b: str) -> bool:
    return os.path.getmtime(a) > os.path.getmtime(b)


def ensure_built() -> str:
    """Compile (once) and return the shared-library path."""
    src = os.path.join(_DIR, "shim.cpp")
    with _lock:
        if os.path.exists(_SO) and not _newer(src, _SO):
            return _SO
        os.makedirs(_BUILD, exist_ok=True)
        proto = os.path.join(_PROTO_DIR, "mixer.proto")
        try:
            subprocess.run(
                ["protoc", f"-I{_PROTO_DIR}", "-I/usr/include",
                 f"--cpp_out={_BUILD}", proto],
                check=True, capture_output=True, text=True)
            subprocess.run(
                ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                 f"-I{_BUILD}", src,
                 os.path.join(_BUILD, "mixer.pb.cc"),
                 "-lprotobuf", "-o", _SO],
                check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as exc:
            raise NativeBuildError(
                f"native shim build failed:\n{exc.stderr}") from exc
        except FileNotFoundError as exc:
            raise NativeBuildError(f"toolchain missing: {exc}") from exc
        return _SO
