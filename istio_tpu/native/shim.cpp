// Native wire→tensor shim.
//
// Parses serialized istio.mixer.v1.CompressedAttributes records and
// fills the AttributeBatch buffers (ids / present / map_present /
// str_bytes / str_lens) exactly like the Python Tensorizer
// (istio_tpu/compiler/layout.py), which is the conformance oracle.
// The intern table is authoritative HERE once the shim is in use:
// Python seeds it with compile-time constants and imports any new
// entries after each batch (export API below).
//
// C ABI only — loaded via ctypes (no pybind11 in this image).
#include <cstdint>
#include <cstring>
#include <cmath>
#include <map>
#include <string>
#include <vector>

#include "mixer.pb.h"

using istio::mixer::v1::CompressedAttributes;

namespace {

constexpr int32_t ID_INVALID = 0;
constexpr int32_t ID_FALSE = 1;
constexpr int32_t ID_TRUE = 2;

// canonical intern key: 1 type-tag byte + canonical payload
// (mirrors layout.py _normalize)
using Key = std::string;

Key key_bool(bool v) { return std::string("b") + (v ? '\1' : '\0'); }
Key key_i64(int64_t v) {
  std::string k("i");
  k.append(reinterpret_cast<const char*>(&v), 8);
  return k;
}
Key key_f64(double v) {
  std::string k("d");
  k.append(reinterpret_cast<const char*>(&v), 8);
  return k;
}
Key key_str(const std::string& v) { return "s" + v; }
Key key_bytes(const std::string& raw) {
  // v4 → v4-in-v6 canonical form (net.IP.Equal semantics)
  std::string v = raw;
  if (v.size() == 4) {
    std::string mapped(10, '\0');
    mapped += "\xff\xff";
    mapped += v;
    v = mapped;
  }
  return "p" + v;
}
Key key_dur_ns(int64_t ns) {
  std::string k("D");
  k.append(reinterpret_cast<const char*>(&ns), 8);
  return k;
}
Key key_ts_ns(int64_t ns) {
  std::string k("t");
  k.append(reinterpret_cast<const char*>(&ns), 8);
  return k;
}

// Python normalizes datetimes/timedeltas through float seconds
// (round(value.timestamp() * 1e9)); replicate the same IEEE ops so ids
// agree bit-for-bit. Proto → datetime truncates to microseconds.
int64_t ts_ns_like_python(int64_t seconds, int32_t nanos) {
  double ts = static_cast<double>(seconds) +
              static_cast<double>(nanos / 1000) / 1e6;
  return llround(ts * 1e9);
}
int64_t dur_ns_like_python(int64_t seconds, int32_t nanos) {
  double total = static_cast<double>(seconds) +
                 static_cast<double>(nanos / 1000) / 1e6;
  return llround(total * 1e9);
}

struct Layout {
  uint32_t max_str_len = 128;
  std::vector<std::string> global_words;
  std::map<std::string, int32_t> scalar_slots;          // attr → col
  std::map<std::string, int32_t> map_slots;             // map attr → mcol
  std::map<std::pair<std::string, std::string>, int32_t> derived;  // (map,key)→col
  std::map<std::string, int32_t> byte_attr;             // attr → bcol
  // encoding per attr byte slot: 0 utf-8, 2 int64 / 3 double /
  // 4 duration-ns / 5 timestamp-ns ORDER KEYS (the 8-byte
  // order-preserving encodings of layout.order_key_bytes — ordered
  // comparisons on device read these planes)
  std::map<std::string, uint8_t> byte_kind;
  std::map<std::pair<std::string, std::string>, int32_t> byte_pair;
  uint32_t n_columns = 0, n_maps = 0, n_byte = 0;
};

struct Shim {
  Layout layout;
  std::map<Key, int32_t> interns;
  std::vector<Key> intern_order;   // ids 3.. in assignment order
  std::string error;

  // ids: 0 invalid, 1 false, 2 true, then sequential
  int32_t intern(const Key& k) {
    auto it = interns.find(k);
    if (it != interns.end()) return it->second;
    int32_t id = next_id_++;
    interns.emplace(k, id);
    intern_order.push_back(k);
    return id;
  }
  int32_t next_id_ = 3;
};

// ---- little binary reader for the layout blob Python packs ----
struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  uint32_t u32() {
    if (p + 4 > end) { ok = false; return 0; }
    uint32_t v;
    memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  uint8_t u8() {
    if (p >= end) { ok = false; return 0; }
    return *p++;
  }
  std::string str() {
    uint32_t n = u32();
    if (!ok || p + n > end) { ok = false; return ""; }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

const std::string* resolve_word(const Shim& sh,
                                const CompressedAttributes& msg,
                                int32_t index) {
  if (index < 0) {
    size_t gi = static_cast<size_t>(-index - 1);
    if (gi >= sh.layout.global_words.size()) return nullptr;
    return &sh.layout.global_words[gi];
  }
  if (index >= msg.words_size()) return nullptr;
  return &msg.words(index);
}

}  // namespace

extern "C" {

void* shim_create(const uint8_t* blob, size_t len) {
  auto* sh = new Shim();
  Reader r{blob, blob + len};
  uint32_t magic = r.u32();
  if (magic != 0x49545032) {  // "ITP2": byte slots carry a kind
    delete sh;
    return nullptr;
  }
  Layout& L = sh->layout;
  L.max_str_len = r.u32();
  uint32_t n = r.u32();
  for (uint32_t i = 0; i < n; i++) L.global_words.push_back(r.str());
  n = r.u32();
  for (uint32_t i = 0; i < n; i++) {
    int32_t col = static_cast<int32_t>(r.u32());
    L.scalar_slots[r.str()] = col;
  }
  n = r.u32();
  for (uint32_t i = 0; i < n; i++) {
    int32_t col = static_cast<int32_t>(r.u32());
    L.map_slots[r.str()] = col;
  }
  n = r.u32();
  for (uint32_t i = 0; i < n; i++) {
    int32_t col = static_cast<int32_t>(r.u32());
    std::string m = r.str(), k = r.str();
    L.derived[{m, k}] = col;
  }
  n = r.u32();
  for (uint32_t i = 0; i < n; i++) {
    int32_t bcol = static_cast<int32_t>(r.u32());
    uint8_t kind = r.u8();
    std::string a = r.str();
    if (kind == 1) {
      std::string k = r.str();
      L.byte_pair[{a, k}] = bcol;
    } else {
      L.byte_attr[a] = bcol;
      L.byte_kind[a] = kind;
    }
  }
  L.n_columns = r.u32();
  L.n_maps = r.u32();
  L.n_byte = r.u32();
  // seed interns (tag + canonical payload, pre-keyed by Python)
  n = r.u32();
  sh->interns[key_bool(false)] = ID_FALSE;
  sh->interns[key_bool(true)] = ID_TRUE;
  for (uint32_t i = 0; i < n; i++) {
    std::string key = r.str();
    if (sh->interns.find(key) == sh->interns.end()) {
      sh->interns[key] = sh->next_id_++;
      sh->intern_order.push_back(key);   // keeps export indexable
    }
  }
  if (!r.ok) {
    delete sh;
    return nullptr;
  }
  return sh;
}

void shim_destroy(void* h) { delete static_cast<Shim*>(h); }

const char* shim_error(void* h) {
  return static_cast<Shim*>(h)->error.c_str();
}

int32_t shim_intern_count(void* h) {
  return static_cast<Shim*>(h)->next_id_;
}

// Drop interned entries with id >= keep_count (runtime-observed
// values); compile-time seeds stay. Bounds a long-running server's
// intern memory — Python flushes in lockstep with its remap table.
void shim_flush_interns(void* h, int32_t keep_count) {
  auto* sh = static_cast<Shim*>(h);
  if (keep_count < 3 || keep_count >= sh->next_id_) return;
  for (int32_t id = keep_count; id < sh->next_id_; id++) {
    sh->interns.erase(sh->intern_order[id - 3]);
  }
  sh->intern_order.resize(keep_count - 3);
  sh->next_id_ = keep_count;
}

// Export canonical keys for ids in [from_id, next_id): packed as
// u32 len + bytes per key. Returns bytes written or -needed.
int64_t shim_export_interns(void* h, int32_t from_id, uint8_t* buf,
                            size_t cap) {
  auto* sh = static_cast<Shim*>(h);
  size_t need = 0;
  std::vector<const Key*> keys;
  for (int32_t id = from_id; id < sh->next_id_; id++) {
    const Key& k = sh->intern_order[id - 3];
    keys.push_back(&k);
    need += 4 + k.size();
  }
  if (need > cap) return -static_cast<int64_t>(need);
  uint8_t* p = buf;
  for (auto* k : keys) {
    uint32_t n = static_cast<uint32_t>(k->size());
    memcpy(p, &n, 4);
    p += 4;
    memcpy(p, k->data(), n);
    p += n;
  }
  return static_cast<int64_t>(need);
}

// Stable 31-bit content hash of a canonical key (FNV-1a); must match
// stable_hash31 in compiler/layout.py — quota buckets key on it.
static int32_t fnv1a31(const Key& k) {
  uint32_t h = 0x811C9DC5u;
  for (unsigned char c : k) {
    h = (h ^ c) * 0x01000193u;
  }
  return static_cast<int32_t>(h & 0x7FFFFFFFu);
}

// Tensorize a batch of serialized CompressedAttributes.
// Buffers (caller-allocated, zeroed):
//   ids        int32 [n, n_columns]
//   hash_ids   int32 [n, n_columns]   stable content hash per slot
//   present    uint8 [n, n_columns]
//   map_present uint8 [n, max(n_maps,1)]
//   str_bytes  uint8 [n, max(n_byte,1), max_str_len]
//   str_lens   int32 [n, max(n_byte,1)]
// Returns 0 on success, <0 on parse error (row index encoded).
int32_t shim_tensorize(void* h, const uint8_t* const* msgs,
                       const int64_t* msg_lens, int32_t n,
                       int32_t* ids, int32_t* hash_ids,
                       uint8_t* present,
                       uint8_t* map_present, uint8_t* str_bytes,
                       int32_t* str_lens) {
  auto* sh = static_cast<Shim*>(h);
  const Layout& L = sh->layout;
  const size_t ncol = L.n_columns;
  const size_t nmap = L.n_maps ? L.n_maps : 1;
  const size_t nbyte = L.n_byte ? L.n_byte : 1;
  const size_t slen = L.max_str_len;

  CompressedAttributes msg;
  for (int32_t i = 0; i < n; i++) {
    msg.Clear();
    if (!msg.ParseFromArray(msgs[i], static_cast<int>(msg_lens[i]))) {
      sh->error = "parse failure at record " + std::to_string(i);
      return -(i + 1);
    }
    int32_t* row_ids = ids + i * ncol;
    int32_t* row_h = hash_ids + i * ncol;
    uint8_t* row_p = present + i * ncol;
    uint8_t* row_mp = map_present + i * nmap;
    uint8_t* row_sb = str_bytes + i * nbyte * slen;
    int32_t* row_sl = str_lens + i * nbyte;

    auto set_scalar = [&](const std::string& name, const Key& key) {
      auto it = L.scalar_slots.find(name);
      if (it == L.scalar_slots.end()) return;
      row_ids[it->second] = sh->intern(key);
      row_h[it->second] = fnv1a31(key);
      row_p[it->second] = 1;
    };
    auto set_bytes_slot = [&](int32_t bcol, const std::string& value) {
      size_t m = value.size() < slen ? value.size() : slen;
      memcpy(row_sb + bcol * slen, value.data(), m);
      row_sl[bcol] = static_cast<int32_t>(m);
    };
    // 8-byte big-endian order key (layout.order_key_bytes parity)
    auto set_key8 = [&](int32_t bcol, uint64_t bits) {
      uint8_t* p = row_sb + bcol * slen;
      for (int b = 0; b < 8; b++)
        p[b] = static_cast<uint8_t>(bits >> (56 - 8 * b));
      row_sl[bcol] = 8;
    };
    // len-1 marker: value not encodable for this slot's kind (the
    // python tensorizer's ORDER_KEY_ERROR; device reads it as err)
    auto set_key_error = [&](int32_t bcol) {
      row_sb[bcol * slen] = 0;
      row_sl[bcol] = 1;
    };
    auto i64_bits = [](int64_t v) {
      return static_cast<uint64_t>(v) ^ 0x8000000000000000ull;
    };
    // numeric value → key by SLOT kind; returns false for NaN (slot
    // stays len-0: the "compares False" marker)
    auto set_numeric_key = [&](int32_t bcol, uint8_t kind, double dv,
                               int64_t iv, bool from_double) {
      if (kind == 3) {                       // double order key
        double d = from_double ? dv : static_cast<double>(iv);
        if (d != d) { row_sl[bcol] = 0; return; }   // NaN
        if (d == 0.0) d = 0.0;               // -0.0 == +0.0
        uint64_t bits;
        memcpy(&bits, &d, 8);
        bits = (bits >> 63) ? ~bits : (bits | 0x8000000000000000ull);
        set_key8(bcol, bits);
        return;
      }
      // int64 / duration-ns / timestamp-ns all key the integer value
      int64_t v = from_double ? static_cast<int64_t>(dv) : iv;
      if (from_double && dv != dv) { row_sl[bcol] = 0; return; }
      set_key8(bcol, i64_bits(v));
    };

    for (const auto& kv : msg.strings()) {
      const std::string* name = resolve_word(*sh, msg, kv.first);
      const std::string* value = resolve_word(*sh, msg, kv.second);
      if (!name || !value) continue;
      set_scalar(*name, key_str(*value));
      auto bit = L.byte_attr.find(*name);
      if (bit != L.byte_attr.end()) {
        uint8_t kind = L.byte_kind.at(*name);
        if (kind == 0) set_bytes_slot(bit->second, *value);
        else set_key_error(bit->second);   // string under numeric slot
      }
    }
    for (const auto& kv : msg.int64s()) {
      const std::string* name = resolve_word(*sh, msg, kv.first);
      if (!name) continue;
      set_scalar(*name, key_i64(kv.second));
      auto bit = L.byte_attr.find(*name);
      if (bit != L.byte_attr.end()) {
        uint8_t kind = L.byte_kind.at(*name);
        if (kind == 0) continue;           // int under string slot
        set_numeric_key(bit->second, kind, 0.0, kv.second, false);
      }
    }
    for (const auto& kv : msg.doubles()) {
      const std::string* name = resolve_word(*sh, msg, kv.first);
      if (!name) continue;
      set_scalar(*name, key_f64(kv.second));
      auto bit = L.byte_attr.find(*name);
      if (bit != L.byte_attr.end()) {
        uint8_t kind = L.byte_kind.at(*name);
        if (kind == 0) continue;
        set_numeric_key(bit->second, kind, kv.second, 0, true);
      }
    }
    for (const auto& kv : msg.bools()) {
      const std::string* name = resolve_word(*sh, msg, kv.first);
      if (!name) continue;
      auto it = L.scalar_slots.find(*name);
      if (it == L.scalar_slots.end()) continue;
      row_ids[it->second] = kv.second ? ID_TRUE : ID_FALSE;
      row_h[it->second] = fnv1a31(key_bool(kv.second));
      row_p[it->second] = 1;
    }
    for (const auto& kv : msg.bytes()) {
      const std::string* name = resolve_word(*sh, msg, kv.first);
      if (!name) continue;
      set_scalar(*name, key_bytes(kv.second));
      auto bit = L.byte_attr.find(*name);
      if (bit != L.byte_attr.end()) {
        uint8_t kind = L.byte_kind.at(*name);
        // raw bytes ride the byte plane (CIDR list lowering compares
        // IP bytes in v6-mapped space — layout._byte_source_value
        // parity); bytes under a numeric order-key slot are
        // unencodable
        if (kind == 0) set_bytes_slot(bit->second, kv.second);
        else set_key_error(bit->second);
      }
    }
    for (const auto& kv : msg.timestamps()) {
      const std::string* name = resolve_word(*sh, msg, kv.first);
      if (!name) continue;
      int64_t ns = ts_ns_like_python(kv.second.seconds(),
                                     kv.second.nanos());
      set_scalar(*name, key_ts_ns(ns));
      auto bit = L.byte_attr.find(*name);
      if (bit != L.byte_attr.end() && L.byte_kind.at(*name) != 0)
        set_numeric_key(bit->second, L.byte_kind.at(*name), 0.0, ns,
                        false);
    }
    for (const auto& kv : msg.durations()) {
      const std::string* name = resolve_word(*sh, msg, kv.first);
      if (!name) continue;
      int64_t ns = dur_ns_like_python(kv.second.seconds(),
                                      kv.second.nanos());
      set_scalar(*name, key_dur_ns(ns));
      auto bit = L.byte_attr.find(*name);
      if (bit != L.byte_attr.end() && L.byte_kind.at(*name) != 0)
        set_numeric_key(bit->second, L.byte_kind.at(*name), 0.0, ns,
                        false);
    }
    for (const auto& kv : msg.string_maps()) {
      const std::string* mname = resolve_word(*sh, msg, kv.first);
      if (!mname) continue;
      auto mit = L.map_slots.find(*mname);
      if (mit != L.map_slots.end()) row_mp[mit->second] = 1;
      for (const auto& ekv : kv.second.entries()) {
        const std::string* key = resolve_word(*sh, msg, ekv.first);
        const std::string* value = resolve_word(*sh, msg, ekv.second);
        if (!key || !value) continue;
        auto dit = L.derived.find({*mname, *key});
        if (dit != L.derived.end()) {
          row_ids[dit->second] = sh->intern(key_str(*value));
          row_h[dit->second] = fnv1a31(key_str(*value));
          row_p[dit->second] = 1;
        }
        auto bit = L.byte_pair.find({*mname, *key});
        if (bit != L.byte_pair.end()) set_bytes_slot(bit->second, *value);
      }
    }
  }
  return 0;
}

}  // extern "C"
