"""ctypes wrapper: NativeTensorizer — wire bytes → AttributeBatch.

Drop-in accelerated replacement for compiler/layout.Tensorizer on the
serving path: input is serialized istio.mixer.v1.CompressedAttributes
records (what Check RPCs carry), output is the same AttributeBatch the
device step consumes. The shim owns the authoritative intern table; new
entries are mirrored back into the Python InternTable after every batch
(so compiled constants and verdict decode stay consistent).
"""
from __future__ import annotations

import ctypes
import datetime
import struct
from typing import Any, Sequence

import numpy as np

from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST
from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import (AttributeBatch, BatchLayout,
                                       InternTable, _normalize,
                                       canonical_bytes)
from istio_tpu.native.build import ensure_built

_MAGIC = 0x49545032   # v2: byte-slot records carry an encoding kind

# byte-slot encoding kinds (shim.cpp must mirror): 0 utf-8 attr,
# 1 utf-8 (map,key), then numeric order-key slots
_BYTE_KINDS = {ValueType.INT64: 2, ValueType.DOUBLE: 3,
               ValueType.DURATION: 4, ValueType.TIMESTAMP: 5}


_canonical_key = canonical_bytes     # shared canonical encoding


def _decode_key(raw: bytes) -> Any:
    tag, payload = chr(raw[0]), raw[1:]
    if tag == "b":
        return payload == b"\x01"
    if tag == "i":
        return struct.unpack("<q", payload)[0]
    if tag == "d":
        return struct.unpack("<d", payload)[0]
    if tag == "s":
        return payload.decode("utf-8")
    if tag == "p":
        return payload
    if tag == "D":
        ns = struct.unpack("<q", payload)[0]
        return datetime.timedelta(microseconds=ns / 1000)
    if tag == "t":
        ns = struct.unpack("<q", payload)[0]
        return datetime.datetime.fromtimestamp(ns / 1e9,
                                               datetime.timezone.utc)
    raise ValueError(f"unknown intern tag {tag}")


def _pack_str(s: str | bytes) -> bytes:
    raw = s.encode("utf-8") if isinstance(s, str) else bytes(s)
    return struct.pack("<I", len(raw)) + raw


def _layout_blob(layout: BatchLayout, interner: InternTable) -> bytes:
    out = [struct.pack("<II", _MAGIC, layout.max_str_len)]
    out.append(struct.pack("<I", len(GLOBAL_WORD_LIST)))
    out += [_pack_str(w) for w in GLOBAL_WORD_LIST]
    out.append(struct.pack("<I", len(layout.slots)))
    for name, col in layout.slots.items():
        out.append(struct.pack("<I", col) + _pack_str(name))
    out.append(struct.pack("<I", len(layout.map_slots)))
    for name, col in layout.map_slots.items():
        out.append(struct.pack("<I", col) + _pack_str(name))
    out.append(struct.pack("<I", len(layout.derived_slots)))
    for (m, k), col in layout.derived_slots.items():
        out.append(struct.pack("<I", col) + _pack_str(m) + _pack_str(k))
    out.append(struct.pack("<I", len(layout.byte_slots)))
    for src, bcol in layout.byte_slots.items():
        if isinstance(src, tuple):
            # kind 1: (map, key) utf-8 slot
            out.append(struct.pack("<IB", bcol, 1) + _pack_str(src[0]) +
                       _pack_str(src[1]))
        else:
            # kind 0: utf-8 attr; kinds 2-5: numeric slots carrying the
            # 8-byte order key (layout.order_key_bytes — the shim must
            # produce IDENTICAL bytes so ordered comparisons agree)
            kind = _BYTE_KINDS.get(layout.manifest.get(src), 0)
            out.append(struct.pack("<IB", bcol, kind) + _pack_str(src))
    out.append(struct.pack("<III", layout.n_columns, layout.n_maps,
                           layout.n_byte_slots))
    # seed interns in id order (ids 3..)
    with interner._lock:
        keys = [_canonical_key(key) for key, idx in
                sorted(interner._by_key.items(), key=lambda kv: kv[1])
                if idx >= 3]
    out.append(struct.pack("<I", len(keys)))
    out += [_pack_str(k) for k in keys]
    return b"".join(out)


class NativeTensorizer:
    """Wire → AttributeBatch via the C++ shim, with ZERO-COPY staging
    for hot batch shapes: the shim writes word values / string bytes
    straight into persistent, page-aligned slot-tensor staging buffers
    (a ring per batch shape, rotated per decode), so the dominant
    shapes pay no per-batch numpy allocation and no astype copies —
    presence planes are returned as dtype VIEWS of the staging bytes.

    Buffer lifecycle contract: the arrays inside a returned
    AttributeBatch stay valid for the next `staging_depth - 1`
    decodes of the SAME shape on this tensorizer. The serving path
    honors the bound by construction — the batcher pipelines at most
    `pipeline` (< staging_depth; RuntimeServer._bound_staging_depth
    raises the ring depth to cover a user-raised pipeline) batches
    and every consumer finishes its host reads before the batch
    future resolves. At most _STAGING_SHAPES shapes keep rings,
    evicted least-recently-used — eviction is safe because in-flight
    batches keep the old slots alive by reference; the evicted
    shape's next decode simply re-allocates."""

    # distinct batch shapes that keep staging rings (the serving
    # bucket ladder is 3-4 shapes; LRU-evicted past the cap so
    # adversarial shape churn can neither leak memory nor pin the
    # rings on cold shapes)
    _STAGING_SHAPES = 4

    def __init__(self, layout: BatchLayout, interner: InternTable,
                 staging_depth: int = 8):
        import threading
        self.layout = layout
        self.interner = interner
        self.staging_depth = max(int(staging_depth), 2)
        # shape key (n rows) → (next slot idx, [slot dicts])
        self._staging: dict[int, list] = {}
        self._call_lock = threading.Lock()
        lib = ctypes.CDLL(ensure_built())
        lib.shim_create.restype = ctypes.c_void_p
        lib.shim_create.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.shim_destroy.argtypes = [ctypes.c_void_p]
        lib.shim_error.restype = ctypes.c_char_p
        lib.shim_error.argtypes = [ctypes.c_void_p]
        lib.shim_intern_count.restype = ctypes.c_int32
        lib.shim_intern_count.argtypes = [ctypes.c_void_p]
        lib.shim_export_interns.restype = ctypes.c_int64
        lib.shim_export_interns.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_size_t]
        lib.shim_flush_interns.restype = None
        lib.shim_flush_interns.argtypes = [ctypes.c_void_p,
                                           ctypes.c_int32]
        lib.shim_tensorize.restype = ctypes.c_int32
        lib.shim_tensorize.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        self._lib = lib
        if layout.extern_slots:
            raise RuntimeError(
                "layout has ingest-converted extern columns "
                f"({sorted(layout.extern_slots)}); the native shim "
                "cannot run extern conversions")
        blob = _layout_blob(layout, interner)
        self._h = lib.shim_create(blob, len(blob))
        if not self._h:
            raise RuntimeError("shim_create failed (bad layout blob)")
        self._seed_count = lib.shim_intern_count(self._h)
        self._known_ids = self._seed_count
        # shim id → python id. Seeds preserve python id order (identity
        # prefix). Runtime-observed shim ids map to NEGATIVE per-batch
        # ephemeral ids (-1 - k) indexing `_runtime_values[k]` — they
        # never enter the python intern table (bounded memory; see
        # InternTable docstring). `_runtime_values` is replaced, not
        # mutated, on flush so in-flight batches keep their snapshot.
        self._remap = np.arange(self._seed_count, dtype=np.int32)
        self._runtime_values: list = []
        self._flush_threshold = 1 << 17   # ~131k distinct values
        self._staged_decodes = 0

    def tensorize_wire(self, records: Sequence[bytes]) -> AttributeBatch:
        # one decode at a time: the shim handle's intern table and the
        # remap array are shared mutable state (pipelined batches may
        # arrive concurrently from the batcher pool) — and the lock is
        # what makes the staging-ring rotation race-free
        with self._call_lock:
            return self._tensorize_wire_locked(records)

    @staticmethod
    def _aligned_zeros(shape: tuple, dtype) -> np.ndarray:
        """Page-aligned persistent staging buffer: the h2d engine can
        DMA-map a 4096-aligned region without the bounce copy an
        arbitrary numpy heap pointer may force."""
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        if nbytes == 0:
            return np.zeros(shape, dtype)
        raw = np.zeros(nbytes + 4096, np.uint8)
        off = (-raw.ctypes.data) % 4096
        return raw[off:off + nbytes].view(dtype).reshape(shape)

    def _fresh_buffers(self, n: int, aligned: bool = False) -> dict:
        lay = self.layout
        nmap = max(lay.n_maps, 1)
        nbyte = max(lay.n_byte_slots, 1)
        alloc = self._aligned_zeros if aligned else np.zeros
        return {
            "ids": alloc((n, lay.n_columns), np.int32),
            "hash_ids": alloc((n, lay.n_columns), np.int32),
            "present_u8": alloc((n, max(lay.n_columns, 0)), np.uint8),
            "map_present_u8": alloc((n, nmap), np.uint8),
            "str_bytes": alloc((n, nbyte, lay.max_str_len), np.uint8),
            "str_lens": alloc((n, nbyte), np.int32),
        }

    def _buffers_for(self, n: int) -> dict:
        """Staging-ring slot for batch shape `n` (zeroed, ready for
        the shim). Ring slots are allocated lazily up to
        staging_depth, then reused round-robin — the reuse bound
        callers rely on. The shape→ring map is LRU-bounded: a new
        shape past the cap evicts the least-recently-used ring (dict
        insertion order = access order; in-flight batches keep
        evicted slots alive by reference, so eviction never clobbers
        a live buffer — the evicted shape just re-allocates next
        time). Note the serving path decodes BUCKET-padded batches,
        so the live shape set is the bucket ladder, not raw arrival
        counts."""
        ring = self._staging.pop(n, None)
        if ring is not None and ring["depth"] != self.staging_depth:
            # depth changed mid-life (RuntimeServer raising the bound
            # for a deeper pipeline): re-anchoring `next` onto a new
            # modulus can shrink the reuse distance below the old
            # bound, so start a FRESH ring instead — in-flight
            # batches keep the old slots alive by reference, exactly
            # like LRU eviction
            ring = None
        if ring is None:
            if len(self._staging) >= self._STAGING_SHAPES:
                # evict the least-recently-used shape's ring
                evicted = next(iter(self._staging))
                del self._staging[evicted]
            ring = {"next": 0, "slots": [],
                    "depth": self.staging_depth}
        self._staging[n] = ring   # (re)insert at the MRU end
        idx = ring["next"] % self.staging_depth
        ring["next"] += 1
        if idx >= len(ring["slots"]):
            slot = self._fresh_buffers(n, aligned=True)
            ring["slots"].append(slot)
        else:
            slot = ring["slots"][idx]
            for arr in slot.values():
                arr[...] = 0
        self._staged_decodes += 1
        return slot

    def staging_stats(self) -> dict:
        return {"shapes": {n: len(r["slots"])
                           for n, r in self._staging.items()},
                "depth": self.staging_depth,
                "staged_decodes": self._staged_decodes}

    def _tensorize_wire_locked(self, records: Sequence[bytes]
                               ) -> AttributeBatch:
        lay = self.layout
        n = len(records)
        buf_set = self._buffers_for(n)
        ids = buf_set["ids"]
        hash_ids = buf_set["hash_ids"]
        present_u8 = buf_set["present_u8"]
        map_present_u8 = buf_set["map_present_u8"]
        str_bytes = buf_set["str_bytes"]
        str_lens = buf_set["str_lens"]

        bufs = (ctypes.c_char_p * n)(*records)
        lens = (ctypes.c_int64 * n)(*[len(r) for r in records])
        rc = self._lib.shim_tensorize(
            self._h, bufs, lens, n,
            ids.ctypes.data_as(ctypes.c_void_p),
            hash_ids.ctypes.data_as(ctypes.c_void_p),
            present_u8.ctypes.data_as(ctypes.c_void_p),
            map_present_u8.ctypes.data_as(ctypes.c_void_p),
            str_bytes.ctypes.data_as(ctypes.c_void_p),
            str_lens.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise ValueError(self._lib.shim_error(self._h).decode())
        self._sync_interns()
        ephemeral = self._runtime_values
        if ids.size:
            # translate shim id space → python id space so the ids plane
            # compares equal against compiled constants / list entries
            np.take(self._remap, ids, out=ids)
        if len(ephemeral) > self._flush_threshold:
            # bound intern memory: drop runtime entries from the shim
            # and start a fresh side table; `ephemeral` (this batch's
            # snapshot) stays alive as long as the batch does
            self._lib.shim_flush_interns(self._h, self._seed_count)
            self._known_ids = self._seed_count
            self._remap = np.arange(self._seed_count, dtype=np.int32)
            self._runtime_values = []
        # presence planes are dtype VIEWS of the staging bytes (bool
        # is 1 byte) — zero copies on the decode path; the view shares
        # the ring slot's lifecycle like every other plane
        return AttributeBatch(ids=ids, present=present_u8.view(bool),
                              map_present=map_present_u8.view(bool),
                              str_bytes=str_bytes, str_lens=str_lens,
                              hash_ids=hash_ids,
                              ephemeral_values=ephemeral)

    def _sync_interns(self) -> None:
        """Extend the shim→python id remap with newly observed values.

        New shim ids are runtime values (every compile-time constant
        was seeded): each maps to the negative ephemeral id of its
        slot in `_runtime_values` — stable across batches until the
        flush replaces the side table."""
        count = self._lib.shim_intern_count(self._h)
        if count == self._known_ids:
            return
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            got = self._lib.shim_export_interns(self._h, self._known_ids,
                                                buf, cap)
            if got >= 0:
                raw = buf.raw[:got]
                break
            cap = -got
        off = 0
        new_ids = []
        while off < len(raw):
            (k_len,) = struct.unpack_from("<I", raw, off)
            off += 4
            key = raw[off:off + k_len]
            off += k_len
            new_ids.append(-1 - len(self._runtime_values))
            self._runtime_values.append(_decode_key(key))
        self._remap = np.concatenate(
            [self._remap, np.asarray(new_ids, np.int32)])
        self._known_ids = count

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.shim_destroy(h)
