"""ctypes wrapper: NativeTensorizer — wire bytes → AttributeBatch.

Drop-in accelerated replacement for compiler/layout.Tensorizer on the
serving path: input is serialized istio.mixer.v1.CompressedAttributes
records (what Check RPCs carry), output is the same AttributeBatch the
device step consumes. The shim owns the authoritative intern table; new
entries are mirrored back into the Python InternTable after every batch
(so compiled constants and verdict decode stay consistent).
"""
from __future__ import annotations

import ctypes
import datetime
import struct
from typing import Any, Sequence

import numpy as np

from istio_tpu.attribute.global_dict import GLOBAL_WORD_LIST
from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import (AttributeBatch, BatchLayout,
                                       InternTable, _normalize,
                                       canonical_bytes)
from istio_tpu.native.build import ensure_built

_MAGIC = 0x49545032   # v2: byte-slot records carry an encoding kind

# byte-slot encoding kinds (shim.cpp must mirror): 0 utf-8 attr,
# 1 utf-8 (map,key), then numeric order-key slots
_BYTE_KINDS = {ValueType.INT64: 2, ValueType.DOUBLE: 3,
               ValueType.DURATION: 4, ValueType.TIMESTAMP: 5}


_canonical_key = canonical_bytes     # shared canonical encoding


def _decode_key(raw: bytes) -> Any:
    tag, payload = chr(raw[0]), raw[1:]
    if tag == "b":
        return payload == b"\x01"
    if tag == "i":
        return struct.unpack("<q", payload)[0]
    if tag == "d":
        return struct.unpack("<d", payload)[0]
    if tag == "s":
        return payload.decode("utf-8")
    if tag == "p":
        return payload
    if tag == "D":
        ns = struct.unpack("<q", payload)[0]
        return datetime.timedelta(microseconds=ns / 1000)
    if tag == "t":
        ns = struct.unpack("<q", payload)[0]
        return datetime.datetime.fromtimestamp(ns / 1e9,
                                               datetime.timezone.utc)
    raise ValueError(f"unknown intern tag {tag}")


def _pack_str(s: str | bytes) -> bytes:
    raw = s.encode("utf-8") if isinstance(s, str) else bytes(s)
    return struct.pack("<I", len(raw)) + raw


def _layout_blob(layout: BatchLayout, interner: InternTable) -> bytes:
    out = [struct.pack("<II", _MAGIC, layout.max_str_len)]
    out.append(struct.pack("<I", len(GLOBAL_WORD_LIST)))
    out += [_pack_str(w) for w in GLOBAL_WORD_LIST]
    out.append(struct.pack("<I", len(layout.slots)))
    for name, col in layout.slots.items():
        out.append(struct.pack("<I", col) + _pack_str(name))
    out.append(struct.pack("<I", len(layout.map_slots)))
    for name, col in layout.map_slots.items():
        out.append(struct.pack("<I", col) + _pack_str(name))
    out.append(struct.pack("<I", len(layout.derived_slots)))
    for (m, k), col in layout.derived_slots.items():
        out.append(struct.pack("<I", col) + _pack_str(m) + _pack_str(k))
    out.append(struct.pack("<I", len(layout.byte_slots)))
    for src, bcol in layout.byte_slots.items():
        if isinstance(src, tuple):
            # kind 1: (map, key) utf-8 slot
            out.append(struct.pack("<IB", bcol, 1) + _pack_str(src[0]) +
                       _pack_str(src[1]))
        else:
            # kind 0: utf-8 attr; kinds 2-5: numeric slots carrying the
            # 8-byte order key (layout.order_key_bytes — the shim must
            # produce IDENTICAL bytes so ordered comparisons agree)
            kind = _BYTE_KINDS.get(layout.manifest.get(src), 0)
            out.append(struct.pack("<IB", bcol, kind) + _pack_str(src))
    out.append(struct.pack("<III", layout.n_columns, layout.n_maps,
                           layout.n_byte_slots))
    # seed interns in id order (ids 3..)
    with interner._lock:
        keys = [_canonical_key(key) for key, idx in
                sorted(interner._by_key.items(), key=lambda kv: kv[1])
                if idx >= 3]
    out.append(struct.pack("<I", len(keys)))
    out += [_pack_str(k) for k in keys]
    return b"".join(out)


class NativeTensorizer:
    def __init__(self, layout: BatchLayout, interner: InternTable):
        import threading
        self.layout = layout
        self.interner = interner
        self._call_lock = threading.Lock()
        lib = ctypes.CDLL(ensure_built())
        lib.shim_create.restype = ctypes.c_void_p
        lib.shim_create.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.shim_destroy.argtypes = [ctypes.c_void_p]
        lib.shim_error.restype = ctypes.c_char_p
        lib.shim_error.argtypes = [ctypes.c_void_p]
        lib.shim_intern_count.restype = ctypes.c_int32
        lib.shim_intern_count.argtypes = [ctypes.c_void_p]
        lib.shim_export_interns.restype = ctypes.c_int64
        lib.shim_export_interns.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_size_t]
        lib.shim_flush_interns.restype = None
        lib.shim_flush_interns.argtypes = [ctypes.c_void_p,
                                           ctypes.c_int32]
        lib.shim_tensorize.restype = ctypes.c_int32
        lib.shim_tensorize.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        self._lib = lib
        if layout.extern_slots:
            raise RuntimeError(
                "layout has ingest-converted extern columns "
                f"({sorted(layout.extern_slots)}); the native shim "
                "cannot run extern conversions")
        blob = _layout_blob(layout, interner)
        self._h = lib.shim_create(blob, len(blob))
        if not self._h:
            raise RuntimeError("shim_create failed (bad layout blob)")
        self._seed_count = lib.shim_intern_count(self._h)
        self._known_ids = self._seed_count
        # shim id → python id. Seeds preserve python id order (identity
        # prefix). Runtime-observed shim ids map to NEGATIVE per-batch
        # ephemeral ids (-1 - k) indexing `_runtime_values[k]` — they
        # never enter the python intern table (bounded memory; see
        # InternTable docstring). `_runtime_values` is replaced, not
        # mutated, on flush so in-flight batches keep their snapshot.
        self._remap = np.arange(self._seed_count, dtype=np.int32)
        self._runtime_values: list = []
        self._flush_threshold = 1 << 17   # ~131k distinct values

    def tensorize_wire(self, records: Sequence[bytes]) -> AttributeBatch:
        # one decode at a time: the shim handle's intern table and the
        # remap array are shared mutable state (pipelined batches may
        # arrive concurrently from the batcher pool)
        with self._call_lock:
            return self._tensorize_wire_locked(records)

    def _tensorize_wire_locked(self, records: Sequence[bytes]
                               ) -> AttributeBatch:
        lay = self.layout
        n = len(records)
        ncol = max(lay.n_columns, 1)
        nmap = max(lay.n_maps, 1)
        nbyte = max(lay.n_byte_slots, 1)
        ids = np.zeros((n, lay.n_columns), np.int32) \
            if lay.n_columns else np.zeros((n, 0), np.int32)
        hash_ids = np.zeros_like(ids)
        present_u8 = np.zeros((n, max(lay.n_columns, 0)), np.uint8)
        map_present_u8 = np.zeros((n, nmap), np.uint8)
        str_bytes = np.zeros((n, nbyte, lay.max_str_len), np.uint8)
        str_lens = np.zeros((n, nbyte), np.int32)

        bufs = (ctypes.c_char_p * n)(*records)
        lens = (ctypes.c_int64 * n)(*[len(r) for r in records])
        rc = self._lib.shim_tensorize(
            self._h, bufs, lens, n,
            ids.ctypes.data_as(ctypes.c_void_p),
            hash_ids.ctypes.data_as(ctypes.c_void_p),
            present_u8.ctypes.data_as(ctypes.c_void_p),
            map_present_u8.ctypes.data_as(ctypes.c_void_p),
            str_bytes.ctypes.data_as(ctypes.c_void_p),
            str_lens.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise ValueError(self._lib.shim_error(self._h).decode())
        self._sync_interns()
        ephemeral = self._runtime_values
        if ids.size:
            # translate shim id space → python id space so the ids plane
            # compares equal against compiled constants / list entries
            np.take(self._remap, ids, out=ids)
        if len(ephemeral) > self._flush_threshold:
            # bound intern memory: drop runtime entries from the shim
            # and start a fresh side table; `ephemeral` (this batch's
            # snapshot) stays alive as long as the batch does
            self._lib.shim_flush_interns(self._h, self._seed_count)
            self._known_ids = self._seed_count
            self._remap = np.arange(self._seed_count, dtype=np.int32)
            self._runtime_values = []
        return AttributeBatch(ids=ids, present=present_u8.astype(bool),
                              map_present=map_present_u8.astype(bool),
                              str_bytes=str_bytes, str_lens=str_lens,
                              hash_ids=hash_ids,
                              ephemeral_values=ephemeral)

    def _sync_interns(self) -> None:
        """Extend the shim→python id remap with newly observed values.

        New shim ids are runtime values (every compile-time constant
        was seeded): each maps to the negative ephemeral id of its
        slot in `_runtime_values` — stable across batches until the
        flush replaces the side table."""
        count = self._lib.shim_intern_count(self._h)
        if count == self._known_ids:
            return
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            got = self._lib.shim_export_interns(self._h, self._known_ids,
                                                buf, cap)
            if got >= 0:
                raw = buf.raw[:got]
                break
            cap = -got
        off = 0
        new_ids = []
        while off < len(raw):
            (k_len,) = struct.unpack_from("<I", raw, off)
            off += 4
            key = raw[off:off + k_len]
            off += k_len
            new_ids.append(-1 - len(self._runtime_values))
            self._runtime_values.append(_decode_key(key))
        self._remap = np.concatenate(
            [self._remap, np.asarray(new_ids, np.int32)])
        self._known_ids = count

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.shim_destroy(h)
