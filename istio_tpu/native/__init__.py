"""Native (C++) runtime components.

The reference's load-bearing native code is the Envoy data plane + the
mixerclient filter (SURVEY.md §2.9) — the pieces that sit on the wire
and feed the policy engine. This package is their TPU-native
equivalent: a C++ shim that parses dictionary-compressed
istio.mixer.v1 attribute batches straight off the wire and fills the
AttributeBatch tensor buffers the device step consumes, bypassing the
Python per-request decode/intern loop (~30µs/request → ~1µs/request).

Built on demand with g++ against the system libprotobuf; the Python
Tensorizer (compiler/layout.py) is the semantics oracle it is
conformance-tested against byte-for-byte.
"""
from istio_tpu.native.build import NativeBuildError, ensure_built
from istio_tpu.native.tensorizer import NativeTensorizer

__all__ = ["NativeTensorizer", "ensure_built", "NativeBuildError"]
