// Closed-loop load client for the native Mixer front-end (httpd.cpp).
//
// The box's python-grpc client stack costs ~0.4ms of CPU per unary
// RPC — measuring a C++ server with a python client measures the
// client. This tool speaks the same wire protocol (HTTP/2 h2c +
// gRPC framing, unary istio.mixer.v1.Mixer/Check) from C++: one
// connection, `depth` streams in flight, payloads cycled from a file
// of u32-length-prefixed serialized CheckRequest messages (built by
// the python bench from the same request dicts the grpc phases use).
//
// Header blocks are encoded literal-without-indexing (stateless HPACK,
// legal per RFC 7541) so the per-request block is a constant string;
// the server exercises its full HPACK decoder against python-grpcio
// clients in the interop tests instead.
//
// Output: ONE JSON line {checks_per_sec, p50_ms, p99_ms, n, errors,
// duration_s, warmup_completions}.
//
// Usage: h2load <port> <payload_file> <n_record> <depth> <warmup_s>
#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "h2_frame.h"

namespace {

void lit_header(std::string* b, const std::string& name,
                const std::string& v) {
  b->push_back(0x00);
  b->push_back(static_cast<char>(name.size()));
  *b += name;
  b->push_back(static_cast<char>(v.size()));
  *b += v;
}

double now_s() { return mono_s(); }

}  // namespace

int main(int argc, char** argv) {
  if (argc < 6) {
    fprintf(stderr,
            "usage: h2load <port> <payload_file> <n_record> <depth> "
            "<warmup_s> [:path]\n");
    return 2;
  }
  int port = atoi(argv[1]);
  const char* payload_path = argv[2];
  long n_record = atol(argv[3]);
  int depth = atoi(argv[4]);
  double warmup_s = atof(argv[5]);
  // optional gRPC method path (default Check): the Report bench
  // drives /istio.mixer.v1.Mixer/Report with ReportRequest payloads
  std::string method_path = argc > 6 ? argv[6]
                                     : "/istio.mixer.v1.Mixer/Check";

  // load payloads (u32 len prefix each)
  std::vector<std::string> payloads;
  {
    FILE* f = fopen(payload_path, "rb");
    if (!f) { perror("payload file"); return 2; }
    while (true) {
      uint32_t n;
      if (fread(&n, 4, 1, f) != 1) break;
      std::string p(n, '\0');
      if (fread(p.data(), 1, n, f) != n) break;
      payloads.push_back(std::move(p));
    }
    fclose(f);
  }
  if (payloads.empty()) { fprintf(stderr, "no payloads\n"); return 2; }

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr))) {
    perror("connect");
    return 2;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  std::string out;
  out.append("PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n");
  // SETTINGS: INITIAL_WINDOW_SIZE 1GB; then 1GB connection window
  put_frame_header(&out, 6, F_SETTINGS, 0, 0);
  out.push_back(0);
  out.push_back(4);
  uint32_t w = htonl(1u << 30);
  out.append(reinterpret_cast<char*>(&w), 4);
  put_frame_header(&out, 4, F_WINUPD, 0, 0);
  uint32_t inc = htonl((1u << 30) - 65535);
  out.append(reinterpret_cast<char*>(&inc), 4);

  // constant request header block (stateless hpack)
  std::string hdr;
  lit_header(&hdr, ":method", "POST");
  lit_header(&hdr, ":scheme", "http");
  lit_header(&hdr, ":path", method_path);
  lit_header(&hdr, ":authority", "localhost");
  lit_header(&hdr, "content-type", "application/grpc");
  lit_header(&hdr, "te", "trailers");

  uint32_t next_stream = 1;
  size_t next_payload = 0;
  std::unordered_map<uint32_t, double> inflight;
  std::vector<double> lat;
  lat.reserve(n_record);
  long completions = 0, errors = 0, warmup_completions = 0;
  bool recording = false;
  double t_start = now_s(), t_rec_start = 0, t_rec_end = 0;

  auto send_one = [&]() {
    uint32_t sid = next_stream;
    next_stream += 2;
    const std::string& body = payloads[next_payload];
    next_payload = (next_payload + 1) % payloads.size();
    put_frame_header(&out, hdr.size(), F_HEADERS, FL_END_HEADERS, sid);
    out += hdr;
    put_frame_header(&out, 5 + body.size(), F_DATA, FL_END_STREAM, sid);
    out.push_back('\0');
    uint32_t n = htonl(static_cast<uint32_t>(body.size()));
    out.append(reinterpret_cast<char*>(&n), 4);
    out += body;
    inflight[sid] = now_s();
  };
  for (int i = 0; i < depth; i++) send_one();

  std::string in;
  char buf[65536];
  while (static_cast<long>(lat.size()) < n_record) {
    // write what we can, then read
    if (!out.empty()) {
      ssize_t n = write(fd, out.data(), out.size());
      if (n > 0) out.erase(0, n);
      else if (n < 0 && errno != EAGAIN) { perror("write"); return 2; }
    }
    pollfd p{fd, static_cast<short>(POLLIN | (out.empty() ? 0 : POLLOUT)),
             0};
    if (poll(&p, 1, 5000) <= 0) {
      fprintf(stderr, "poll timeout/err with %zu inflight\n",
              inflight.size());
      return 2;
    }
    if (p.revents & POLLIN) {
      ssize_t n = read(fd, buf, sizeof(buf));
      if (n <= 0) { fprintf(stderr, "server closed\n"); return 2; }
      in.append(buf, n);
    }
    size_t pos = 0;
    while (in.size() - pos >= 9) {
      const uint8_t* hp = reinterpret_cast<const uint8_t*>(in.data()) +
                          pos;
      uint32_t len = (hp[0] << 16) | (hp[1] << 8) | hp[2];
      if (in.size() - pos < 9 + len) break;
      uint8_t type = hp[3], flags = hp[4];
      uint32_t sid;
      memcpy(&sid, hp + 5, 4);
      sid = ntohl(sid) & 0x7fffffffu;
      if (type == F_SETTINGS && !(flags & FL_ACK)) {
        put_frame_header(&out, 0, F_SETTINGS, FL_ACK, 0);
      } else if (type == F_PING && !(flags & FL_ACK)) {
        put_frame_header(&out, 8, F_PING, FL_ACK, 0);
        out.append(reinterpret_cast<const char*>(hp) + 9, 8);
      } else if (type == F_GOAWAY) {
        fprintf(stderr, "server goaway\n");
        return 2;
      } else if (type == F_HEADERS && (flags & FL_END_STREAM)) {
        // trailers: scan the (literal-encoded) block for grpc-status
        const char* blk = reinterpret_cast<const char*>(hp) + 9;
        std::string block(blk, len);
        size_t at = block.find("grpc-status");
        bool ok = false;
        if (at != std::string::npos &&
            at + 11 + 2 <= block.size()) {
          uint8_t vlen = block[at + 11];
          ok = vlen == 1 && block[at + 12] == '0';
        }
        auto it = inflight.find(sid);
        if (it != inflight.end()) {
          double dt = now_s() - it->second;
          inflight.erase(it);
          completions++;
          // errors cover the SAME window as n/checks_per_sec — a
          // warmup-phase blip must not taint the recorded figures
          if (!ok && recording) errors++;
          if (recording) {
            lat.push_back(dt);
          } else if (now_s() - t_start >= warmup_s) {
            recording = true;
            warmup_completions = completions - 1;
            t_rec_start = now_s();
          }
          send_one();
        }
      }
      pos += 9 + len;
    }
    if (pos) in.erase(0, pos);
  }
  t_rec_end = now_s();
  close(fd);

  if (lat.empty()) {
    // a window with zero recorded completions cannot report
    // quantiles — fail loudly (the harness raises PerfError) instead
    // of indexing an empty vector / dividing by zero
    fprintf(stderr, "no recorded completions\n");
    return 2;
  }
  std::sort(lat.begin(), lat.end());
  double dur = t_rec_end - t_rec_start;
  // full client-side quantile ladder from the exact per-request
  // latency vector — the INDEPENDENT check on the server's wire
  // histogram (two clocks, two codebases; they must agree to within
  // the client's queueing skew)
  auto q = [&](double frac) {
    return lat[std::min(lat.size() - 1,
                        static_cast<size_t>(lat.size() * frac))] * 1e3;
  };
  double mean = 0;
  for (double v : lat) mean += v;
  mean = mean / lat.size() * 1e3;
  printf(
      "{\"checks_per_sec\": %.1f, \"p50_ms\": %.3f, \"p90_ms\": %.3f, "
      "\"p95_ms\": %.3f, \"p99_ms\": %.3f, \"p999_ms\": %.3f, "
      "\"mean_ms\": %.3f, \"min_ms\": %.3f, \"max_ms\": %.3f, "
      "\"n\": %zu, \"errors\": %ld, \"duration_s\": %.3f, "
      "\"warmup_completions\": %ld, \"depth\": %d}\n",
      lat.size() / dur, q(0.50), q(0.90), q(0.95), q(0.99), q(0.999),
      mean, lat.front() * 1e3, lat.back() * 1e3, lat.size(), errors,
      dur, warmup_completions, depth);
  return 0;
}
