// Shared HTTP/2 framing primitives for the native front-end server
// (httpd.cpp) and its load client (h2load.cpp) — one home for the
// frame header layout, type/flag constants and the monotonic clock,
// so the bench client can never desynchronize from the server wire.
#pragma once
#include <arpa/inet.h>
#include <time.h>

#include <cstdint>
#include <cstring>
#include <string>

constexpr uint8_t F_DATA = 0x0, F_HEADERS = 0x1, F_PRIORITY = 0x2,
                  F_RST = 0x3, F_SETTINGS = 0x4, F_PUSH = 0x5,
                  F_PING = 0x6, F_GOAWAY = 0x7, F_WINUPD = 0x8,
                  F_CONT = 0x9;
constexpr uint8_t FL_END_STREAM = 0x1, FL_END_HEADERS = 0x4,
                  FL_PADDED = 0x8, FL_PRIORITY_FLAG = 0x20,
                  FL_ACK = 0x1;

inline void put_frame_header(std::string* out, uint32_t len,
                             uint8_t type, uint8_t flags,
                             uint32_t stream) {
  char h[9];
  h[0] = static_cast<char>((len >> 16) & 0xff);
  h[1] = static_cast<char>((len >> 8) & 0xff);
  h[2] = static_cast<char>(len & 0xff);
  h[3] = static_cast<char>(type);
  h[4] = static_cast<char>(flags);
  uint32_t s = htonl(stream & 0x7fffffffu);
  memcpy(h + 5, &s, 4);
  out->append(h, 9);
}

inline int64_t mono_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000ll + ts.tv_nsec;
}

inline double mono_s() { return mono_ns() * 1e-9; }
