// Native sidecar-facing Mixer front-end: a from-scratch HTTP/2 (h2c
// prior-knowledge) + HPACK + gRPC-framing server speaking the REAL
// unary istio.mixer.v1.Mixer/Check|Report protocol at the wire.
//
// Role (SURVEY §2.9 implication (a), VERDICT r4 item 1): the reference
// terminates sidecar gRPC in Go (mixer/pkg/api/grpcServer.go:118) and
// its per-request cost is goroutine-cheap; this repo's python-grpc
// front caps the box at ~2.4k RPC/s of pure transport. Here the wire
// lives in C++: connections, HTTP/2 framing, HPACK state, request
// envelope splitting and BATCH formation all happen off the GIL;
// python only runs the per-batch engine step (decode → tensorize →
// device → verdicts) through the existing fused path and returns
// serialized CheckResponse bytes that this layer frames back onto the
// wire. Done deliberately WITHOUT a grpc dependency: the image has no
// C++ gRPC/nghttp2 headers, and the subset HTTP/2 a unary gRPC server
// needs (SETTINGS/HEADERS/CONTINUATION/DATA/WINDOW_UPDATE/PING/
// RST_STREAM/GOAWAY + full HPACK decode incl. Huffman and the dynamic
// table) is small enough to own — and owning it is what makes the
// front-end auditable as the data-plane component the survey owes.
//
// Threading model: ONE IO thread owns every socket (poll loop; writes
// and protocol state never race). Decoded requests are queued; python
// "pump" threads block in h2srv_take() (ctypes releases the GIL) and
// receive whole batches under an adaptive policy — a batch dispatches
// when it reaches `min_fill`, when `window_us` has passed since its
// first request, or instantly when a pump is idle and anything is
// queued. Completions enter via h2srv_complete() from pump threads,
// are handed to the IO thread over an eventfd-signalled queue, and are
// framed + written there.
//
// C ABI only (ctypes; no pybind11 in this image).
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "h2_frame.h"
#include "hpack_tables.h"

namespace {

// ------------------------------ HPACK ------------------------------

struct HuffNode {
  int16_t next[2];   // child node index, -1 none
  int16_t sym;       // decoded symbol (0..256), -1 internal
};

struct HuffTrie {
  std::vector<HuffNode> nodes;
  HuffTrie() {
    nodes.push_back({{-1, -1}, -1});
    for (int s = 0; s < 257; s++) {
      uint32_t code = kHuffCodes[s];
      int len = kHuffLens[s];
      int at = 0;
      for (int b = len - 1; b >= 0; b--) {
        int bit = (code >> b) & 1;
        if (nodes[at].next[bit] < 0) {
          nodes[at].next[bit] = static_cast<int16_t>(nodes.size());
          nodes.push_back({{-1, -1}, -1});
        }
        at = nodes[at].next[bit];
      }
      nodes[at].sym = static_cast<int16_t>(s);
    }
  }
};

const HuffTrie& huff_trie() {
  static HuffTrie t;
  return t;
}

bool huff_decode(const uint8_t* p, size_t n, std::string* out) {
  const HuffTrie& t = huff_trie();
  int at = 0;
  int bits_since_sym = 0;
  for (size_t i = 0; i < n; i++) {
    for (int b = 7; b >= 0; b--) {
      int bit = (p[i] >> b) & 1;
      at = t.nodes[at].next[bit];
      if (at < 0) return false;
      bits_since_sym++;
      int sym = t.nodes[at].sym;
      if (sym >= 0) {
        if (sym == 256) return false;  // EOS in data is an error
        out->push_back(static_cast<char>(sym));
        at = 0;
        bits_since_sym = 0;
      }
    }
  }
  // padding: ≤7 bits, all 1s (a prefix of EOS) — lenient on content,
  // strict on length
  return bits_since_sym <= 7;
}

struct HpackDecoder {
  // dynamic table, newest first (RFC 7541 §2.3.2 addressing)
  std::deque<std::pair<std::string, std::string>> dyn;
  size_t dyn_size = 0;
  size_t max_dyn = 4096;   // our advertised SETTINGS_HEADER_TABLE_SIZE

  void evict() {
    while (dyn_size > max_dyn && !dyn.empty()) {
      dyn_size -= dyn.back().first.size() + dyn.back().second.size() + 32;
      dyn.pop_back();
    }
  }
  void add(const std::string& n, const std::string& v) {
    dyn_size += n.size() + v.size() + 32;
    dyn.emplace_front(n, v);
    evict();
  }
  bool lookup(uint64_t idx, std::string* n, std::string* v) {
    if (idx == 0) return false;
    if (idx <= 61) {
      *n = kHpackStatic[idx - 1].name;
      *v = kHpackStatic[idx - 1].value;
      return true;
    }
    size_t di = idx - 62;
    if (di >= dyn.size()) return false;
    *n = dyn[di].first;
    *v = dyn[di].second;
    return true;
  }
};

bool hpack_int(const uint8_t*& p, const uint8_t* end, int prefix,
               uint64_t* out) {
  if (p >= end) return false;
  uint64_t max = (1u << prefix) - 1;
  uint64_t v = *p++ & max;
  if (v < max) { *out = v; return true; }
  int shift = 0;
  while (p < end) {
    uint8_t b = *p++;
    v += static_cast<uint64_t>(b & 0x7f) << shift;
    if (v > (1ull << 32)) return false;   // sanity bound
    if (!(b & 0x80)) { *out = v; return true; }
    shift += 7;
    if (shift > 35) return false;
  }
  return false;
}

bool hpack_str(const uint8_t*& p, const uint8_t* end, std::string* out) {
  if (p >= end) return false;
  bool huff = (*p & 0x80) != 0;
  uint64_t len;
  if (!hpack_int(p, end, 7, &len)) return false;
  if (p + len > end) return false;
  out->clear();
  if (huff) {
    if (!huff_decode(p, len, out)) return false;
  } else {
    out->assign(reinterpret_cast<const char*>(p), len);
  }
  p += len;
  return true;
}

// Decode a complete header block; collects every header (table state
// depends on all of them) and reports the few the server routes on —
// plus the W3C traceparent, which rides the take blob so the python
// engine's rpc.check root span joins the client's trace.
bool hpack_block(HpackDecoder* dec, const uint8_t* p, size_t n,
                 std::string* path, std::string* content_type,
                 std::string* te, std::string* traceparent) {
  const uint8_t* end = p + n;
  while (p < end) {
    uint8_t b = *p;
    std::string name, value;
    if (b & 0x80) {                       // indexed field
      uint64_t idx;
      if (!hpack_int(p, end, 7, &idx)) return false;
      if (!dec->lookup(idx, &name, &value)) return false;
    } else if ((b & 0xe0) == 0x20) {      // dynamic table size update
      uint64_t sz;
      if (!hpack_int(p, end, 5, &sz)) return false;
      if (sz > 4096) return false;        // above our advertised max
      dec->max_dyn = sz;
      dec->evict();
      continue;
    } else {
      bool incremental = (b & 0xc0) == 0x40;
      int prefix = incremental ? 6 : 4;
      uint64_t idx;
      if (!hpack_int(p, end, prefix, &idx)) return false;
      if (idx) {
        std::string ignored;
        if (!dec->lookup(idx, &name, &ignored)) return false;
      } else if (!hpack_str(p, end, &name)) {
        return false;
      }
      if (!hpack_str(p, end, &value)) return false;
      if (incremental) dec->add(name, value);
    }
    if (name == ":path") *path = value;
    else if (name == "content-type") *content_type = value;
    else if (name == "te") *te = value;
    else if (name == "traceparent" && traceparent) *traceparent = value;
  }
  return true;
}

// --------------------------- HTTP/2 bits ---------------------------
// frame constants + put_frame_header live in h2_frame.h (shared with
// the h2load client)

const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kPrefaceLen = 24;
constexpr uint32_t kOurWindow = 1u << 30;

// response header blocks are STATELESS hpack (no dynamic-table adds):
// indexed :status 200 + literal-without-indexing content-type
std::string resp_headers_block() {
  std::string b;
  b.push_back(static_cast<char>(0x88));        // :status 200 (static 8)
  b.push_back(static_cast<char>(0x0f));        // literal w/o idx, name
  b.push_back(static_cast<char>(31 - 15));     //   = static 31
  const char ct[] = "application/grpc";
  b.push_back(static_cast<char>(sizeof(ct) - 1));
  b.append(ct, sizeof(ct) - 1);
  return b;
}

void lit_header(std::string* b, const char* name, const std::string& v) {
  b->push_back(0x00);                          // literal w/o idx, new name
  b->push_back(static_cast<char>(strlen(name)));
  b->append(name);
  // values here are short (status ints / messages ≤ 126 bytes after
  // truncation below); keep 7-bit length encoding valid
  std::string vv = v.size() > 120 ? v.substr(0, 120) : v;
  b->push_back(static_cast<char>(vv.size()));
  b->append(vv);
}

// ------------------------- protobuf walking ------------------------
// The request ENVELOPE (CheckRequest / ReportRequest top level) is
// split with a hand varint walker — the payload `attributes` bytes
// pass through to the python/engine side untouched (the shim's
// protobuf decode happens once, there).

struct PbReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }
  bool skip(uint32_t wt) {
    switch (wt) {
      case 0: varint(); return ok;
      case 1: if (end - p < 8) return ok = false; p += 8; return true;
      case 2: {
        uint64_t n = varint();
        if (!ok || static_cast<uint64_t>(end - p) < n) return ok = false;
        p += n;
        return true;
      }
      case 5: if (end - p < 4) return ok = false; p += 4; return true;
      default: return ok = false;
    }
  }
  bool bytes_field(std::string* out) {
    uint64_t n = varint();
    if (!ok || static_cast<uint64_t>(end - p) < n) return ok = false;
    out->assign(reinterpret_cast<const char*>(p), n);
    p += n;
    return true;
  }
};

struct QuotaParam {
  std::string name;
  int64_t amount = 0;
  uint8_t best_effort = 0;
};

struct CheckEnvelope {
  std::string attributes;   // raw CompressedAttributes bytes
  uint32_t global_word_count = 0;
  std::string dedup;
  std::vector<QuotaParam> quotas;
};

bool parse_check_envelope(const uint8_t* p, size_t n, CheckEnvelope* out) {
  PbReader r{p, p + n};
  while (r.ok && r.p < r.end) {
    uint64_t tag = r.varint();
    if (!r.ok) return false;
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = tag & 7;
    if (field == 1 && wt == 2) {
      if (!r.bytes_field(&out->attributes)) return false;
    } else if (field == 2 && wt == 0) {
      out->global_word_count = static_cast<uint32_t>(r.varint());
    } else if (field == 3 && wt == 2) {
      if (!r.bytes_field(&out->dedup)) return false;
    } else if (field == 4 && wt == 2) {
      std::string entry;
      if (!r.bytes_field(&entry)) return false;
      QuotaParam q;
      PbReader er{reinterpret_cast<const uint8_t*>(entry.data()),
                  reinterpret_cast<const uint8_t*>(entry.data()) +
                      entry.size()};
      while (er.ok && er.p < er.end) {
        uint64_t etag = er.varint();
        if (!er.ok) return false;
        if ((etag >> 3) == 1 && (etag & 7) == 2) {
          if (!er.bytes_field(&q.name)) return false;
        } else if ((etag >> 3) == 2 && (etag & 7) == 2) {
          std::string params;
          if (!er.bytes_field(&params)) return false;
          PbReader pr{reinterpret_cast<const uint8_t*>(params.data()),
                      reinterpret_cast<const uint8_t*>(params.data()) +
                          params.size()};
          while (pr.ok && pr.p < pr.end) {
            uint64_t ptag = pr.varint();
            if (!pr.ok) return false;
            if ((ptag >> 3) == 1 && (ptag & 7) == 0) {
              q.amount = static_cast<int64_t>(pr.varint());
            } else if ((ptag >> 3) == 2 && (ptag & 7) == 0) {
              q.best_effort = pr.varint() ? 1 : 0;
            } else if (!pr.skip(ptag & 7)) {
              return false;
            }
          }
        } else if (!er.skip(etag & 7)) {
          return false;
        }
      }
      out->quotas.push_back(std::move(q));
    } else if (!r.skip(wt)) {
      return false;
    }
  }
  return r.ok;
}

// ------------------------------ server -----------------------------

struct Stream {
  std::string path;
  std::string traceparent;   // W3C trace context request header
  std::string body;          // gRPC-framed request bytes
  bool headers_done = false;
  bool dispatched = false;   // handed to the pump queue
  bool closed = false;       // RST/error — completion is discarded
  int64_t send_window = 65535;
  // wire-to-verdict timestamp: set the instant the request's gRPC
  // frame is fully decoded (enqueue_request), read when the response
  // frames are queued for write — the latency histogram measures
  // EVERYTHING between (queue wait, batch formation, python pump,
  // tensorize, device step, response build), which python-side timers
  // structurally cannot (they never see the C++ queue or framing)
  int64_t t_decode_ns = 0;
  std::string pending_out;   // DATA bytes parked on flow control
  bool trailers_after_data = false;
  std::string trailer_buf;   // trailers to emit once pending_out drains
};

struct PendingItem {
  uint64_t tag;
  uint8_t kind;   // 0 Check, 1 Report
  CheckEnvelope env;
  std::string report_raw;   // kind 1: full ReportRequest bytes
  std::string traceparent;  // request's W3C trace context (may be "")
  int64_t t_enq_ns;
};

struct Completion {
  uint64_t tag;
  int32_t grpc_status;
  std::string msg;   // resp proto (status 0) | grpc-message text
};

struct Conn {
  int fd = -1;
  uint32_t gen = 0;
  std::string in;            // unparsed inbound bytes
  std::string out;           // outbound bytes awaiting write
  bool preface_done = false;
  bool goaway_sent = false;
  bool broken = false;       // protocol error seen; drain out + close
  HpackDecoder hpack;
  std::unordered_map<uint32_t, Stream> streams;
  // CONTINUATION state
  uint32_t cont_stream = 0;
  uint8_t cont_flags = 0;
  std::string cont_block;
  bool in_cont = false;
  int64_t send_window = 65535;           // connection-level, theirs
  int64_t remote_initial_window = 65535;
  uint32_t remote_max_frame = 16384;
  uint64_t recv_since_update = 0;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  int wake_fd = -1;
  std::thread io;
  std::atomic<bool> stopping{false};
  // intake stopped (h2srv_quiesce): new wire requests answer
  // UNAVAILABLE immediately, already-queued rows dispatch to pumps
  // without holding for min_fill/window — the graceful-drain phase
  std::atomic<bool> draining{false};
  // threads currently inside an ABI call on this handle (take/
  // complete/counters/port): stop waits for this to reach zero before
  // freeing the server, so a straggling pump can never use-after-free
  std::atomic<int> abi_calls{0};

  int32_t max_batch = 1024;
  int32_t min_fill = 256;
  int64_t window_us = 2000;
  int32_t n_pumps = 1;
  // continuous batching (the latency lane): an idle pump takes
  // whatever is queued IMMEDIATELY — no min_fill / window_us hold —
  // so a request never waits for a batch to fill; in-flight step
  // pipelining is bounded by n_pumps (each pump runs one step)
  bool continuous = false;
  bool echo = false;
  std::string echo_resp;

  std::mutex mu;                      // guards queue + hist
  std::condition_variable cv;
  std::deque<PendingItem> queue;
  int64_t first_enq_ns = 0;
  int idle_pumps = 0;

  std::mutex cmu;                     // completion queue (pump → IO)
  std::deque<Completion> completions;

  // counters: [0] requests_decoded [1] responses_sent [2] batches
  // [3] batch_rows [4] in_flight [5] conns_opened [6] conns_closed
  // [7] protocol_errors [8] bytes_in [9] bytes_out
  std::atomic<int64_t> counters[10] = {};
  int64_t hist[16] = {0};
  // wire-to-verdict latency histogram: 192 log-spaced buckets, bucket
  // i covers latencies up to 1µs·2^(i/8) (ratio 2^(1/8) ≈ 1.09, so a
  // quantile read interpolates within ±4.5%); covers 1µs .. ~16s.
  // Relaxed atomics, same pattern as counters[]: written only by the
  // IO thread per response — a mutex here would put lock traffic on
  // the exact hot path this histogram exists to measure. Read (rare)
  // by h2srv_latency without locking; single-writer makes the
  // min/max read-modify-write races a non-issue.
  static constexpr int kLatBuckets = 192;
  std::atomic<int64_t> lat_hist[kLatBuckets] = {};
  std::atomic<int64_t> lat_min_ns{0};   // 0 = no observation yet
  std::atomic<int64_t> lat_max_ns{0};

  std::unordered_map<uint32_t, Conn*> conns;   // by gen
  uint32_t next_gen = 1;
};

// ------------------------- lifecycle registry -----------------------
// Live-handle set: h2srv_stop erases first (double-stop on the same
// handle becomes a no-op instead of a use-after-free), ABI entry
// points check membership before touching the pointer, and an atexit
// sweep quiesces anything python never stopped so process teardown is
// orderly (no IO thread mid-poll while the runtime unloads). Leaky
// singletons: static-destruction order must never free these while a
// straggler thread is still checking in.

std::mutex& reg_mu() {
  static std::mutex* m = new std::mutex();
  return *m;
}

std::unordered_set<Server*>& live_servers() {
  static std::unordered_set<Server*>* s = new std::unordered_set<Server*>();
  return *s;
}

// RAII abi-call token; acquire() under reg_mu so a stop that already
// erased the handle is seen (the caller then backs off, never touching
// freed memory)
bool abi_enter(Server* srv) {
  std::lock_guard<std::mutex> lk(reg_mu());
  if (!live_servers().count(srv)) return false;
  srv->abi_calls.fetch_add(1, std::memory_order_acq_rel);
  return true;
}

void abi_exit(Server* srv) {
  srv->abi_calls.fetch_sub(1, std::memory_order_acq_rel);
}

void stop_server(Server* srv, bool at_exit);
int64_t take_impl(Server* srv, int32_t timeout_ms, uint8_t* buf,
                  int64_t cap);

void stop_all_at_exit() {
  std::vector<Server*> all;
  {
    std::lock_guard<std::mutex> lk(reg_mu());
    for (Server* s : live_servers()) all.push_back(s);
    live_servers().clear();
  }
  for (Server* s : all) stop_server(s, /*at_exit=*/true);
}

void conn_error(Server* srv, Conn* c, uint32_t code) {
  if (!c->goaway_sent) {
    std::string f;
    put_frame_header(&f, 8, F_GOAWAY, 0, 0);
    uint32_t last = htonl(0), ec = htonl(code);
    f.append(reinterpret_cast<char*>(&last), 4);
    f.append(reinterpret_cast<char*>(&ec), 4);
    c->out += f;
    c->goaway_sent = true;
  }
  srv->counters[7]++;
}

// emit DATA in frames capped at the client's SETTINGS_MAX_FRAME_SIZE
void put_data_frames(Conn* c, uint32_t stream_id,
                     const std::string& data) {
  size_t off = 0;
  do {
    size_t chunk = std::min(data.size() - off,
                            static_cast<size_t>(c->remote_max_frame));
    put_frame_header(&c->out, chunk, F_DATA, 0, stream_id);
    c->out.append(data, off, chunk);
    off += chunk;
  } while (off < data.size());
}

// wire-to-verdict latency observation (IO thread only; lock-free —
// see the lat_hist declaration). Bucket i holds latencies in
// (1µs·2^((i-1)/8), 1µs·2^(i/8)]. Only DISPATCHED streams record:
// pre-dispatch error fast paths (malformed frame, unknown method,
// draining UNAVAILABLE) answer in microseconds and would drag the
// served-verdict quantiles toward zero — the histogram's one job is
// the wire-to-VERDICT number.
void record_latency(Server* srv, Stream* st) {
  if (!st->t_decode_ns || !st->dispatched) return;
  int64_t ns = mono_ns() - st->t_decode_ns;
  st->t_decode_ns = 0;
  if (ns < 1) ns = 1;
  double us = static_cast<double>(ns) / 1000.0;
  int idx = us <= 1.0 ? 0
                      : static_cast<int>(std::ceil(std::log2(us) * 8));
  if (idx < 0) idx = 0;
  if (idx >= Server::kLatBuckets) idx = Server::kLatBuckets - 1;
  srv->lat_hist[idx].fetch_add(1, std::memory_order_relaxed);
  int64_t mn = srv->lat_min_ns.load(std::memory_order_relaxed);
  if (!mn || ns < mn)
    srv->lat_min_ns.store(ns, std::memory_order_relaxed);
  if (ns > srv->lat_max_ns.load(std::memory_order_relaxed))
    srv->lat_max_ns.store(ns, std::memory_order_relaxed);
}

// frame up one gRPC response onto the stream (headers + DATA +
// trailers), honoring send windows; parks DATA when blocked
void write_response(Server* srv, Conn* c, uint32_t stream_id,
                    int32_t grpc_status, const std::string& msg) {
  auto it = c->streams.find(stream_id);
  if (it == c->streams.end()) return;
  if (it->second.closed) {   // RST'd while dispatched: drop, reclaim
    c->streams.erase(it);
    return;
  }
  Stream& st = it->second;
  record_latency(srv, &st);

  static const std::string hdr_block = resp_headers_block();
  put_frame_header(&c->out, hdr_block.size(), F_HEADERS, FL_END_HEADERS,
                   stream_id);
  c->out += hdr_block;

  std::string trailers;
  {
    std::string tb;
    lit_header(&tb, "grpc-status", std::to_string(grpc_status));
    if (grpc_status != 0 && !msg.empty())
      lit_header(&tb, "grpc-message", msg);
    put_frame_header(&trailers, tb.size(), F_HEADERS,
                     FL_END_HEADERS | FL_END_STREAM, stream_id);
    trailers += tb;
  }

  if (grpc_status == 0) {
    std::string data;
    data.push_back('\0');
    uint32_t n = htonl(static_cast<uint32_t>(msg.size()));
    data.append(reinterpret_cast<char*>(&n), 4);
    data += msg;
    int64_t len = static_cast<int64_t>(data.size());
    if (st.send_window >= len && c->send_window >= len) {
      st.send_window -= len;
      c->send_window -= len;
      put_data_frames(c, stream_id, data);
      c->out += trailers;
      c->streams.erase(it);
      srv->counters[1]++;
      return;
    }
    // parked: tiny responses only hit this when the client starves
    // its windows; drained on WINDOW_UPDATE/SETTINGS
    st.pending_out = std::move(data);
    st.trailers_after_data = true;
    st.trailer_buf = std::move(trailers);
    return;
  }
  c->out += trailers;
  c->streams.erase(it);
  srv->counters[1]++;
}

void flush_parked(Server* srv, Conn* c) {
  for (auto it = c->streams.begin(); it != c->streams.end();) {
    Stream& st = it->second;
    if (!st.trailers_after_data || st.pending_out.empty()) {
      ++it;
      continue;
    }
    int64_t len = static_cast<int64_t>(st.pending_out.size());
    if (st.send_window >= len && c->send_window >= len) {
      st.send_window -= len;
      c->send_window -= len;
      put_data_frames(c, it->first, st.pending_out);
      c->out += st.trailer_buf;
      srv->counters[1]++;
      it = c->streams.erase(it);
    } else {
      ++it;
    }
  }
}

void enqueue_request(Server* srv, Conn* c, uint32_t stream_id,
                     Stream* st) {
  // frame-decode timestamp: the wire-to-verdict clock starts here —
  // the complete gRPC frame just arrived, nothing downstream has
  // touched it yet (write_response stops the clock)
  st->t_decode_ns = mono_ns();
  // unary gRPC: exactly one length-prefixed message in the body
  if (st->body.size() < 5 || st->body[0] != 0) {
    write_response(srv, c, stream_id, 12,
                   st->body.empty() ? "empty body"
                                    : "compressed requests unsupported");
    return;
  }
  uint32_t mlen;
  memcpy(&mlen, st->body.data() + 1, 4);
  mlen = ntohl(mlen);
  if (st->body.size() < 5 + static_cast<size_t>(mlen)) {
    write_response(srv, c, stream_id, 13, "truncated grpc frame");
    return;
  }
  const uint8_t* msg =
      reinterpret_cast<const uint8_t*>(st->body.data()) + 5;

  uint8_t kind;
  PendingItem item;
  if (st->path == "/istio.mixer.v1.Mixer/Check") {
    kind = 0;
    if (!parse_check_envelope(msg, mlen, &item.env)) {
      write_response(srv, c, stream_id, 13, "bad CheckRequest");
      return;
    }
  } else if (st->path == "/istio.mixer.v1.Mixer/Report") {
    kind = 1;
    item.report_raw.assign(reinterpret_cast<const char*>(msg), mlen);
  } else {
    write_response(srv, c, stream_id, 12, "unknown method " + st->path);
    return;
  }
  if (srv->draining.load(std::memory_order_relaxed)) {
    // intake stopped (graceful drain): a TYPED rejection, never a
    // silent connection drop — the client sees UNAVAILABLE and can
    // retry against a peer. Not dispatched → not latency-recorded
    // (a drain's instant rejections must not drag the verdict
    // quantiles).
    write_response(srv, c, stream_id, 14, "server draining");
    return;
  }

  if (srv->echo) {   // wire-ceiling mode: respond in C++, no engine
    srv->counters[0]++;
    write_response(srv, c, stream_id, 0, srv->echo_resp);
    return;
  }

  // dispatched = handed to the pump queue — set only now, past the
  // error/draining/echo fast paths, so record_latency's dispatched
  // gate admits exactly the wire-to-VERDICT population
  st->dispatched = true;
  st->body.clear();
  st->body.shrink_to_fit();

  item.tag = (static_cast<uint64_t>(c->gen) << 32) | stream_id;
  item.kind = kind;
  item.traceparent = st->traceparent;
  item.t_enq_ns = mono_ns();
  {
    std::lock_guard<std::mutex> lk(srv->mu);
    if (srv->queue.empty()) srv->first_enq_ns = item.t_enq_ns;
    srv->queue.push_back(std::move(item));
  }
  srv->counters[0]++;
  srv->counters[4]++;
  srv->cv.notify_one();
}

// a complete header block arrived (HEADERS or final CONTINUATION):
// initial headers open the stream; a second block on the same stream
// is client trailers — decoded for HPACK table state, content dropped
bool finish_header_block(Server* srv, Conn* c, uint32_t stream_id,
                         uint8_t flags) {
  Stream& st = c->streams[stream_id];
  if (st.headers_done) {
    std::string a, b2, d;
    if (!hpack_block(&c->hpack,
                     reinterpret_cast<const uint8_t*>(
                         c->cont_block.data()),
                     c->cont_block.size(), &a, &b2, &d, nullptr))
      return false;
    if ((flags & FL_END_STREAM) && !st.dispatched)
      enqueue_request(srv, c, stream_id, &st);
    return true;
  }
  std::string ct, te;
  if (!hpack_block(&c->hpack,
                   reinterpret_cast<const uint8_t*>(
                       c->cont_block.data()),
                   c->cont_block.size(), &st.path, &ct, &te,
                   &st.traceparent))
    return false;
  st.headers_done = true;
  st.send_window = c->remote_initial_window;
  if (flags & FL_END_STREAM)
    enqueue_request(srv, c, stream_id, &st);
  return true;
}

// parse as many complete frames as the inbound buffer holds
bool process_in(Server* srv, Conn* c) {
  if (!c->preface_done) {
    if (c->in.size() < kPrefaceLen) return true;
    if (memcmp(c->in.data(), kPreface, kPrefaceLen) != 0) return false;
    c->in.erase(0, kPrefaceLen);
    c->preface_done = true;
  }
  size_t pos = 0;   // cursor: one erase per call, not per frame
  while (c->in.size() - pos >= 9) {
    const uint8_t* hp =
        reinterpret_cast<const uint8_t*>(c->in.data()) + pos;
    uint32_t len = (hp[0] << 16) | (hp[1] << 8) | hp[2];
    if (len > (1u << 24)) return false;
    if (c->in.size() - pos < 9 + len) break;
    uint8_t type = hp[3], flags = hp[4];
    uint32_t stream_id;
    memcpy(&stream_id, hp + 5, 4);
    stream_id = ntohl(stream_id) & 0x7fffffffu;
    const uint8_t* payload = hp + 9;

    if (c->in_cont && type != F_CONT) return false;

    switch (type) {
      case F_SETTINGS: {
        if (flags & FL_ACK) break;
        if (len % 6) return false;
        for (uint32_t off = 0; off + 6 <= len; off += 6) {
          uint16_t id = (payload[off] << 8) | payload[off + 1];
          uint32_t val;
          memcpy(&val, payload + off + 2, 4);
          val = ntohl(val);
          if (id == 4) {   // INITIAL_WINDOW_SIZE
            int64_t delta = static_cast<int64_t>(val) -
                            c->remote_initial_window;
            c->remote_initial_window = val;
            for (auto& kv : c->streams) kv.second.send_window += delta;
          } else if (id == 5 && val >= 16384) {   // MAX_FRAME_SIZE
            c->remote_max_frame = val;
          }
        }
        put_frame_header(&c->out, 0, F_SETTINGS, FL_ACK, 0);
        flush_parked(srv, c);
        break;
      }
      case F_PING: {
        if (len != 8) return false;
        if (!(flags & FL_ACK)) {
          put_frame_header(&c->out, 8, F_PING, FL_ACK, 0);
          c->out.append(reinterpret_cast<const char*>(payload), 8);
        }
        break;
      }
      case F_WINUPD: {
        if (len != 4) return false;
        uint32_t inc;
        memcpy(&inc, payload, 4);
        inc = ntohl(inc) & 0x7fffffffu;
        if (stream_id == 0) {
          c->send_window += inc;
        } else {
          auto it = c->streams.find(stream_id);
          if (it != c->streams.end()) it->second.send_window += inc;
        }
        flush_parked(srv, c);
        break;
      }
      case F_HEADERS: {
        if (stream_id == 0) return false;
        const uint8_t* p = payload;
        uint32_t n = len;
        if (flags & FL_PADDED) {
          if (!n) return false;
          uint8_t pad = *p++;
          n--;
          if (pad > n) return false;
          n -= pad;
        }
        if (flags & FL_PRIORITY_FLAG) {
          if (n < 5) return false;
          p += 5;
          n -= 5;
        }
        c->cont_stream = stream_id;
        c->cont_flags = flags;
        c->cont_block.assign(reinterpret_cast<const char*>(p), n);
        if (flags & FL_END_HEADERS) {
          c->in_cont = false;
          if (!finish_header_block(srv, c, stream_id, flags))
            return false;
        } else {
          c->in_cont = true;
        }
        break;
      }
      case F_CONT: {
        if (!c->in_cont || stream_id != c->cont_stream) return false;
        c->cont_block.append(reinterpret_cast<const char*>(payload),
                             len);
        if (flags & FL_END_HEADERS) {
          c->in_cont = false;
          if (!finish_header_block(srv, c, stream_id, c->cont_flags))
            return false;
        }
        break;
      }
      case F_DATA: {
        if (stream_id == 0) return false;
        const uint8_t* p = payload;
        uint32_t n = len;
        if (flags & FL_PADDED) {
          if (!n) return false;
          uint8_t pad = *p++;
          n--;
          if (pad > n) return false;
          n -= pad;
        }
        auto it = c->streams.find(stream_id);
        if (it != c->streams.end() && !it->second.dispatched) {
          it->second.body.append(reinterpret_cast<const char*>(p), n);
          if (it->second.body.size() > (1u << 24)) return false;
          if (flags & FL_END_STREAM)
            enqueue_request(srv, c, stream_id, &it->second);
        }
        // connection window top-up (we granted 1GB upfront)
        c->recv_since_update += len;
        if (c->recv_since_update >= (1u << 20)) {
          put_frame_header(&c->out, 4, F_WINUPD, 0, 0);
          uint32_t inc = htonl(
              static_cast<uint32_t>(c->recv_since_update));
          c->out.append(reinterpret_cast<char*>(&inc), 4);
          c->recv_since_update = 0;
        }
        break;
      }
      case F_RST: {
        if (len != 4 || stream_id == 0) return false;
        auto it = c->streams.find(stream_id);
        if (it != c->streams.end()) {
          it->second.closed = true;
          if (!it->second.dispatched) c->streams.erase(it);
        }
        break;
      }
      case F_GOAWAY:
        break;   // client is draining; keep serving open streams
      case F_PRIORITY:
      case F_PUSH:
      default:
        break;   // ignore (PUSH from a client is protocol-noise)
    }
    srv->counters[8] += 9 + len;
    pos += 9 + len;
  }
  if (pos) c->in.erase(0, pos);
  return true;
}

void close_conn(Server* srv, Conn* c) {
  srv->conns.erase(c->gen);
  if (c->fd >= 0) close(c->fd);
  srv->counters[6]++;
  delete c;
}

void io_loop(Server* srv) {
  std::vector<pollfd> pfds;
  std::vector<Conn*> order;
  while (!srv->stopping.load(std::memory_order_relaxed)) {
    pfds.clear();
    order.clear();
    pfds.push_back({srv->listen_fd, POLLIN, 0});
    pfds.push_back({srv->wake_fd, POLLIN, 0});
    for (auto& kv : srv->conns) {
      short ev = POLLIN;
      if (!kv.second->out.empty()) ev |= POLLOUT;
      pfds.push_back({kv.second->fd, ev, 0});
      order.push_back(kv.second);
    }
    int rc = poll(pfds.data(), pfds.size(), 100);
    if (rc < 0 && errno != EINTR) break;

    // batch-window wakeups: a pump waiting out a window needs a
    // notify when the window expires even with no IO
    srv->cv.notify_all();

    if (pfds[1].revents & POLLIN) {
      uint64_t x;
      while (read(srv->wake_fd, &x, 8) > 0) {}
    }
    // drain completions (frame + queue bytes on the owning conn)
    {
      std::deque<Completion> done;
      {
        std::lock_guard<std::mutex> lk(srv->cmu);
        done.swap(srv->completions);
      }
      for (auto& comp : done) {
        uint32_t gen = static_cast<uint32_t>(comp.tag >> 32);
        uint32_t sid = static_cast<uint32_t>(comp.tag & 0xffffffffu);
        auto it = srv->conns.find(gen);
        srv->counters[4]--;
        if (it != srv->conns.end())
          write_response(srv, it->second, sid, comp.grpc_status,
                         comp.msg);
      }
    }
    if (pfds[0].revents & POLLIN) {
      while (true) {
        int fd = accept4(srv->listen_fd, nullptr, nullptr,
                         SOCK_NONBLOCK);
        if (fd < 0) break;
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        Conn* c = new Conn();
        c->fd = fd;
        c->gen = srv->next_gen++;
        srv->conns[c->gen] = c;
        srv->counters[5]++;
        // server preface: SETTINGS + big connection window
        std::string f;
        put_frame_header(&f, 12, F_SETTINGS, 0, 0);
        const uint16_t ids[2] = {4, 3};      // INITIAL_WINDOW, MAX_STREAMS
        const uint32_t vals[2] = {kOurWindow, 65535};
        for (int i = 0; i < 2; i++) {
          char s[6];
          s[0] = static_cast<char>(ids[i] >> 8);
          s[1] = static_cast<char>(ids[i] & 0xff);
          uint32_t v = htonl(vals[i]);
          memcpy(s + 2, &v, 4);
          f.append(s, 6);
        }
        put_frame_header(&f, 4, F_WINUPD, 0, 0);
        uint32_t inc = htonl(kOurWindow - 65535);
        f.append(reinterpret_cast<char*>(&inc), 4);
        c->out += f;
      }
    }
    // per-conn IO
    for (size_t i = 2; i < pfds.size(); i++) {
      Conn* c = order[i - 2];
      if (pfds[i].revents & (POLLERR | POLLHUP | POLLNVAL)) {
        close_conn(srv, c);
        continue;
      }
      if (pfds[i].revents & POLLIN) {
        char buf[65536];
        bool dead = false;
        while (true) {
          ssize_t n = read(c->fd, buf, sizeof(buf));
          if (n > 0) {
            if (!c->broken) c->in.append(buf, n);
            if (c->in.size() > (1u << 26)) { dead = true; break; }
          } else if (n == 0) {
            dead = true;
            break;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) dead = true;
            break;
          }
        }
        if (!dead && !c->broken && !process_in(srv, c)) {
          conn_error(srv, c, 1);   // PROTOCOL_ERROR
          c->broken = true;
          dead = c->out.empty();
        }
        if (dead) {
          close_conn(srv, c);
          continue;
        }
      }
      if (!c->out.empty()) {
        ssize_t n = write(c->fd, c->out.data(), c->out.size());
        if (n > 0) {
          srv->counters[9] += n;
          c->out.erase(0, n);
        } else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
          close_conn(srv, c);
          continue;
        }
        if ((c->goaway_sent || c->broken) && c->out.empty())
          close_conn(srv, c);
      }
    }
  }
  // shutdown: answer everything already completed (including the
  // typed rejections stop_server queued for rows no pump will take),
  // then best-effort flush outbound bytes so clients SEE their
  // responses before the close — a silently dropped in-flight request
  // is the failure mode this drain exists to prevent
  {
    std::deque<Completion> done;
    {
      std::lock_guard<std::mutex> lk(srv->cmu);
      done.swap(srv->completions);
    }
    for (auto& comp : done) {
      uint32_t gen = static_cast<uint32_t>(comp.tag >> 32);
      uint32_t sid = static_cast<uint32_t>(comp.tag & 0xffffffffu);
      auto it = srv->conns.find(gen);
      srv->counters[4]--;
      if (it != srv->conns.end())
        write_response(srv, it->second, sid, comp.grpc_status,
                       comp.msg);
    }
  }
  // bounded flush (~200ms): a client that starves its flow-control
  // windows must not hold the stop hostage
  int64_t flush_deadline = mono_ns() + 200 * 1000000LL;
  bool pending = true;
  while (pending && mono_ns() < flush_deadline) {
    pending = false;
    for (auto& kv : srv->conns) {
      Conn* c = kv.second;
      if (c->out.empty()) continue;
      ssize_t n = write(c->fd, c->out.data(), c->out.size());
      if (n > 0) {
        srv->counters[9] += n;
        c->out.erase(0, static_cast<size_t>(n));
      } else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
        c->out.clear();
        continue;
      }
      if (!c->out.empty()) pending = true;
    }
    if (pending)
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
  std::vector<Conn*> all;
  for (auto& kv : srv->conns) all.push_back(kv.second);
  for (Conn* c : all) close_conn(srv, c);
}

void put_u32(std::string* s, uint32_t v) {
  s->append(reinterpret_cast<char*>(&v), 4);
}
void put_u64(std::string* s, uint64_t v) {
  s->append(reinterpret_cast<char*>(&v), 8);
}

// Ordered teardown (the graceful-lifecycle plane's native leg):
//   1. stop intake + mark stopping (pumps in take return -1, the IO
//      loop exits its poll cycle);
//   2. convert rows no pump will ever take into typed UNAVAILABLE
//      completions (drained + flushed by the IO thread's shutdown
//      path — zero silently dropped in-flight requests);
//   3. join the IO thread;
//   4. wait for every in-flight ABI caller to leave before freeing —
//      a pump wedged inside take gets the handle LEAKED, never freed
//      under it (a stall must stay a stall, not become a segfault).
// Callers must have erased the handle from live_servers() first (the
// double-stop guard), so no NEW abi_enter can succeed while we wait.
void stop_server(Server* srv, bool at_exit) {
  srv->draining.store(true);
  srv->stopping.store(true);
  srv->cv.notify_all();
  {
    std::lock_guard<std::mutex> lk(srv->mu);
    std::lock_guard<std::mutex> lk2(srv->cmu);
    while (!srv->queue.empty()) {
      Completion comp;
      comp.tag = srv->queue.front().tag;
      comp.grpc_status = 14;
      comp.msg = "server shutting down";
      srv->completions.push_back(std::move(comp));
      srv->queue.pop_front();
    }
  }
  uint64_t one = 1;
  ssize_t ignored = write(srv->wake_fd, &one, 8);
  (void)ignored;
  if (srv->io.joinable()) srv->io.join();
  for (int i = 0; i < 5000; i++) {   // ~5s bound
    if (srv->abi_calls.load(std::memory_order_acquire) == 0) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  if (srv->abi_calls.load(std::memory_order_acquire) > 0) {
    // a straggler is still inside take/complete: leak the server (fds
    // included — closing them could hand recycled fd numbers to its
    // next syscall) rather than free memory under a live thread
    return;
  }
  close(srv->listen_fd);
  close(srv->wake_fd);
  if (!at_exit) delete srv;
  // at exit: frozen interpreter threads may still hold the pointer —
  // the process is dying, the leak is free, the UAF would not be
}

}  // namespace

extern "C" {

void* h2srv_start(int32_t port, int32_t max_batch, int32_t min_fill,
                  int64_t window_us, int32_t n_pumps,
                  int32_t echo_mode, int32_t continuous) {
  Server* srv = new Server();
  srv->max_batch = max_batch > 0 ? max_batch : 1024;
  srv->min_fill = min_fill > 0 ? min_fill : 256;
  srv->window_us = window_us > 0 ? window_us : 2000;
  srv->n_pumps = n_pumps > 0 ? n_pumps : 1;
  srv->continuous = continuous != 0;
  srv->echo = echo_mode != 0;
  if (srv->echo) {
    // fixed OK CheckResponse: precondition{status{} dur{5s} uses 10000}
    // (field 2 msg: {1:{},2:{1:5},3:10000})
    const uint8_t resp[] = {0x12, 0x09, 0x0a, 0x00, 0x12, 0x02, 0x08,
                            0x05, 0x18, 0x90, 0x4e};
    srv->echo_resp.assign(reinterpret_cast<const char*>(resp),
                          sizeof(resp));
  }

  srv->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one,
             sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr),
           sizeof(addr)) != 0 ||
      listen(srv->listen_fd, 512) != 0) {
    close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr),
              &alen);
  srv->port = ntohs(addr.sin_port);
  srv->wake_fd = eventfd(0, EFD_NONBLOCK);
  srv->io = std::thread(io_loop, srv);
  {
    std::lock_guard<std::mutex> lk(reg_mu());
    live_servers().insert(srv);
    static bool atexit_registered = false;
    if (!atexit_registered) {
      atexit_registered = true;
      std::atexit(stop_all_at_exit);
    }
  }
  return srv;
}

int32_t h2srv_port(void* h) {
  Server* srv = static_cast<Server*>(h);
  if (!abi_enter(srv)) return 0;
  int32_t p = srv->port;
  abi_exit(srv);
  return p;
}

// Graceful-drain entry (ordered shutdown step 1, callable long before
// h2srv_stop): stop intake — new wire requests answer UNAVAILABLE
// immediately, queued rows dispatch to pumps without holding for
// min_fill/window. Connections stay open and in-flight rows complete
// normally; the caller polls counters()[in_flight] down to zero, THEN
// stops pumps and calls h2srv_stop.
void h2srv_quiesce(void* h) {
  Server* srv = static_cast<Server*>(h);
  if (!abi_enter(srv)) return;
  srv->draining.store(true);
  srv->cv.notify_all();
  uint64_t one = 1;
  ssize_t ignored = write(srv->wake_fd, &one, 8);
  (void)ignored;
  abi_exit(srv);
}

// Blocking batch take (pump side). Adaptive policy (the saturation-
// batcher fix the python batcher's fixed window lacked): dispatch when
// the queue reaches min_fill; dispatch IMMEDIATELY when every pump is
// idle (nothing in flight → a waiting request buys nothing by
// waiting — light-load latency is one trip); otherwise a trip is in
// flight, and this pump holds out for min_fill or window_us — tiny
// trips never ride a busy transport. Returns bytes written, 0 on
// timeout, -needed if the buffer is too small, -1 on shutdown.
int64_t h2srv_take(void* h, int32_t timeout_ms, uint8_t* buf,
                   int64_t cap) {
  Server* srv = static_cast<Server*>(h);
  if (!abi_enter(srv)) return -1;   // already stopped: shutdown signal
  int64_t rc = take_impl(srv, timeout_ms, buf, cap);
  abi_exit(srv);
  return rc;
}

}  // extern "C"

namespace {

int64_t take_impl(Server* srv, int32_t timeout_ms, uint8_t* buf,
                  int64_t cap) {
  std::unique_lock<std::mutex> lk(srv->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  srv->idle_pumps++;
  while (true) {
    if (srv->stopping.load(std::memory_order_relaxed)) {
      srv->idle_pumps--;
      return -1;
    }
    if (!srv->queue.empty()) {
      int64_t waited_us = (mono_ns() - srv->first_enq_ns) / 1000;
      if (srv->continuous ||
          static_cast<int32_t>(srv->queue.size()) >= srv->min_fill ||
          srv->idle_pumps == srv->n_pumps ||
          waited_us >= srv->window_us ||
          srv->draining.load(std::memory_order_relaxed)) {
        // continuous: the latency lane — an idle pump launches the
        // next step the moment anything is queued (the previous step
        // is already dispatched on another pump; in-flight depth is
        // bounded by n_pumps). A request NEVER waits for a batch to
        // fill. draining: already-queued rows dispatch IMMEDIATELY —
        // a shutdown must never hold submitted work for min_fill.
        break;   // this pump takes the batch
      }
      // wait out the window (bounded; re-checked on every enqueue)
      srv->cv.wait_for(lk, std::chrono::microseconds(
                               srv->window_us - waited_us + 100));
      continue;
    }
    if (srv->cv.wait_until(lk, deadline) == std::cv_status::timeout &&
        srv->queue.empty()) {
      srv->idle_pumps--;
      return 0;
    }
  }
  srv->idle_pumps--;

  int32_t n = static_cast<int32_t>(srv->queue.size());
  if (n > srv->max_batch) n = srv->max_batch;
  // size pass
  int64_t need = 8;
  for (int32_t i = 0; i < n; i++) {
    const PendingItem& it = srv->queue[i];
    need += 8 + 1 + 4 + 4 + 4 + 4 + 2;
    need += it.kind ? it.report_raw.size() : it.env.attributes.size();
    need += it.env.dedup.size();
    need += it.traceparent.size();
    for (const auto& q : it.env.quotas) need += 4 + q.name.size() + 9;
  }
  if (need > cap) return -need;

  std::string out;
  out.reserve(need);
  put_u32(&out, static_cast<uint32_t>(srv->counters[2]));
  put_u32(&out, static_cast<uint32_t>(n));
  for (int32_t i = 0; i < n; i++) {
    PendingItem& it = srv->queue.front();
    put_u64(&out, it.tag);
    out.push_back(static_cast<char>(it.kind));
    const std::string& payload =
        it.kind ? it.report_raw : it.env.attributes;
    put_u32(&out, static_cast<uint32_t>(payload.size()));
    out += payload;
    put_u32(&out, it.env.global_word_count);
    put_u32(&out, static_cast<uint32_t>(it.env.dedup.size()));
    out += it.env.dedup;
    put_u32(&out, static_cast<uint32_t>(it.traceparent.size()));
    out += it.traceparent;
    uint16_t nq = static_cast<uint16_t>(it.env.quotas.size());
    out.append(reinterpret_cast<char*>(&nq), 2);
    for (const auto& q : it.env.quotas) {
      put_u32(&out, static_cast<uint32_t>(q.name.size()));
      out += q.name;
      put_u64(&out, static_cast<uint64_t>(q.amount));
      out.push_back(static_cast<char>(q.best_effort));
    }
    srv->queue.pop_front();
  }
  if (!srv->queue.empty()) srv->first_enq_ns = mono_ns();
  srv->counters[2]++;
  srv->counters[3] += n;
  int b = 0;
  while ((1 << b) < n && b < 15) b++;
  srv->hist[b]++;
  memcpy(buf, out.data(), out.size());
  return static_cast<int64_t>(out.size());
}

}  // namespace

extern "C" {

// Completion blob: u32 n, then per item u64 tag, i32 grpc_status,
// u32 len, bytes (resp proto when status 0, else grpc-message text).
void h2srv_complete(void* h, const uint8_t* blob, int64_t len) {
  Server* srv = static_cast<Server*>(h);
  if (!abi_enter(srv)) return;   // stopped under a deferred completion
  const uint8_t* p = blob;
  const uint8_t* end = blob + len;
  if (end - p < 4) {
    abi_exit(srv);
    return;
  }
  uint32_t n;
  memcpy(&n, p, 4);
  p += 4;
  std::deque<Completion> out;
  for (uint32_t i = 0; i < n && p + 16 <= end; i++) {
    Completion comp;
    memcpy(&comp.tag, p, 8);
    p += 8;
    memcpy(&comp.grpc_status, p, 4);
    p += 4;
    uint32_t mlen;
    memcpy(&mlen, p, 4);
    p += 4;
    if (p + mlen > end) break;
    comp.msg.assign(reinterpret_cast<const char*>(p), mlen);
    p += mlen;
    out.push_back(std::move(comp));
  }
  {
    std::lock_guard<std::mutex> lk(srv->cmu);
    for (auto& comp : out) srv->completions.push_back(std::move(comp));
  }
  uint64_t one = 1;
  ssize_t ignored = write(srv->wake_fd, &one, 8);
  (void)ignored;
  abi_exit(srv);
}

void h2srv_counters(void* h, int64_t* out, int64_t* hist) {
  Server* srv = static_cast<Server*>(h);
  if (!abi_enter(srv)) {
    memset(out, 0, 10 * sizeof(int64_t));
    memset(hist, 0, 16 * sizeof(int64_t));
    return;
  }
  for (int i = 0; i < 10; i++)
    out[i] = srv->counters[i].load(std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> lk(srv->mu);
    memcpy(hist, srv->hist, sizeof(srv->hist));
  }
  abi_exit(srv);
}

// Wire-to-verdict latency histogram snapshot: 192 log-spaced bucket
// counts (bucket i ≤ 1µs·2^(i/8)) into `out`, observed [min_ns,
// max_ns] into `minmax[2]`. Counts are CUMULATIVE since start — the
// python side computes per-window quantiles from snapshot deltas.
void h2srv_latency(void* h, int64_t* out, int64_t* minmax) {
  Server* srv = static_cast<Server*>(h);
  if (!abi_enter(srv)) {
    memset(out, 0, Server::kLatBuckets * sizeof(int64_t));
    minmax[0] = minmax[1] = 0;
    return;
  }
  for (int i = 0; i < Server::kLatBuckets; i++)
    out[i] = srv->lat_hist[i].load(std::memory_order_relaxed);
  minmax[0] = srv->lat_min_ns.load(std::memory_order_relaxed);
  minmax[1] = srv->lat_max_ns.load(std::memory_order_relaxed);
  abi_exit(srv);
}

void h2srv_stop(void* h) {
  Server* srv = static_cast<Server*>(h);
  {
    // double-stop guard: only the caller that actually erases the
    // live entry tears the server down; any later stop (or a stop
    // racing the atexit sweep) is a no-op instead of a use-after-free
    std::lock_guard<std::mutex> lk(reg_mu());
    if (!live_servers().erase(srv)) return;
  }
  stop_server(srv, /*at_exit=*/false);
}

}  // extern "C"
