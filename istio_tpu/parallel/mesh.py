"""Mesh construction + sharding rules for the batched policy step.

Sharding layout for the RuleSetProgram gather pipeline
(compiler/ruleset.py) with axes ("dp", "mp"):

    lit        [B, 2A+1]   → P("dp")        batch over dp, atoms replicated
    lit_idx    [n_conj, L] replicated
    sat        [B, n_conj] → P("dp")
    conj_*_idx [R, K]      → P("mp")        rules over mp
    matched    [B, R]      → P("dp", "mp")

Sharding RULES (an un-contracted output dim) over "mp" keeps the request
path collective-free: each mp shard owns a rule slice end-to-end. The
final per-request verdict fold (deny/allow over rules) contracts the
sharded R axis, so XLA inserts exactly one small psum over "mp" — the
only ICI traffic per step. Batch stays on "dp" throughout.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """dp × mp factorization of the available devices."""
    dp: int
    mp: int = 1

    def build(self, devices: Sequence[Any] | None = None) -> Mesh:
        devs = list(devices if devices is not None else jax.devices())
        need = self.dp * self.mp
        if len(devs) < need:
            raise ValueError(f"need {need} devices, have {len(devs)}")
        arr = np.asarray(devs[:need]).reshape(self.dp, self.mp)
        return Mesh(arr, axis_names=("dp", "mp"))


def policy_mesh(n_devices: int | None = None, rule_shards: int = 1) -> Mesh:
    """Default mesh: dp × mp with `rule_shards` cores on the rule axis."""
    n = n_devices if n_devices is not None else len(jax.devices())
    if n % rule_shards:
        raise ValueError(f"{n} devices not divisible by mp={rule_shards}")
    return MeshSpec(dp=n // rule_shards, mp=rule_shards).build()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Prefix sharding for an AttributeBatch pytree: leading batch dim on
    dp, everything else replicated."""
    return NamedSharding(mesh, P("dp"))


def shard_batch(mesh: Mesh, batch) -> Any:
    """Place an AttributeBatch pytree with its batch dim over dp."""
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def param_shardings(mesh: Mesh, engine) -> dict:
    """THE param-sharding policy for the dp/mp layout: every non-rule-
    axis param (lit_idx, the fused gather-compare eqc_* tensors, any
    future addition) replicates; only the [R, K] conjunction matrices
    shard their rule axis over mp. One home — shard_engine_check and
    mesh_stage_probe must agree or the probe's jit fails with a
    sharding/pytree mismatch when the param set changes."""
    rep = NamedSharding(mesh, P())
    mp_rules = NamedSharding(mesh, P("mp"))
    param_shard = {k: rep for k in engine.params}
    param_shard["conj_m_idx"] = mp_rules
    param_shard["conj_n_idx"] = mp_rules
    return param_shard


def mesh_stage_probe(mesh: Mesh, engine, batch, req_ns,
                     steps: int = 3, reps: int = 2) -> dict:
    """Per-stage timers for the sharded check step (the mesh bench's
    honesty satellite): on a 1-core host the end-to-end scaling ratio
    is time-slicing noise, but the STAGES still attribute where the
    sharding machinery spends —

      shard_dispatch_ms    host→device placement of the batch under
                           the dp sharding (per step)
      match_ms             the ruleset match program alone, outputs
                           left dp×mp-sharded: collective-FREE (each
                           mp shard owns its rule slice end-to-end)
      full_step_ms         match + verdict fold; the fold contracts
                           the sharded rule axis, so XLA inserts the
                           step's only psum over mp here
      fold_collectives_ms  full − match: the verdict fold plus every
                           collective it forces

    Returns median-of-reps wall times per chained step."""
    import time

    dp = NamedSharding(mesh, P("dp"))
    dpmp = NamedSharding(mesh, P("dp", "mp"))
    rep = NamedSharding(mesh, P())
    match_fn = jax.jit(lambda p, b: engine.ruleset.fn(p, b),
                       in_shardings=(param_shardings(mesh, engine), dp),
                       out_shardings=(dpmp, dpmp, dpmp))
    full_fn = shard_engine_check(mesh, engine)

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    # shard dispatch: the per-step host→device placement cost
    disp = []
    for _ in range(reps + 1):
        t0 = time.perf_counter()
        for _ in range(steps):
            placed = shard_batch(mesh, batch)
            jax.block_until_ready(placed)
        disp.append((time.perf_counter() - t0) / steps)
    placed = shard_batch(mesh, batch)
    ns = jax.device_put(np.asarray(req_ns), dp)
    counts = jax.device_put(np.asarray(engine.quota_counts), rep)

    def timed(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(*args)
            jax.block_until_ready(out)
            ts.append((time.perf_counter() - t0) / steps)
        return med(ts)

    t_match = timed(match_fn, engine.params, placed)
    t_full = timed(full_fn, engine.params, placed, ns, counts)
    return {
        "shard_dispatch_ms": round(med(disp[1:]) * 1e3, 3),
        "match_ms": round(t_match * 1e3, 3),
        "full_step_ms": round(t_full * 1e3, 3),
        "fold_collectives_ms": round(max(t_full - t_match, 0.0) * 1e3,
                                     3),
    }


def shard_engine_check(mesh: Mesh, engine) -> Callable:
    """jit a PolicyEngine.raw_step under the dp/mp layout.

    batch + req_ns shard over dp; quota counters replicate (each dp
    replica is a best-effort quota domain, exactly the reference's
    per-replica memquota stance); matched/err verdict planes + the
    rule-dimension params (RM/RN columns) shard rules over mp. Returns
    fn(params, batch, req_ns, quota_counts) → (CheckVerdict, counts)."""
    from istio_tpu.models.policy_engine import CheckVerdict
    dp = NamedSharding(mesh, P("dp"))
    dpmp = NamedSharding(mesh, P("dp", "mp"))
    rep = NamedSharding(mesh, P())
    param_shard = param_shardings(mesh, engine)
    out_verdict = CheckVerdict(status=dp, valid_duration_s=dp,
                               valid_use_count=dp, referenced=dp,
                               matched=dpmp, err=dpmp, deny_rule=dp,
                               err_count=rep)
    return jax.jit(engine.raw_step,
                   in_shardings=(param_shard, dp, dp, rep),
                   out_shardings=(out_verdict, rep))
