"""Mesh construction + sharding rules for the batched policy step.

Sharding layout for the RuleSetProgram gather pipeline
(compiler/ruleset.py) with axes ("dp", "mp"):

    lit        [B, 2A+1]   → P("dp")        batch over dp, atoms replicated
    lit_idx    [n_conj, L] replicated
    sat        [B, n_conj] → P("dp")
    conj_*_idx [R, K]      → P("mp")        rules over mp
    matched    [B, R]      → P("dp", "mp")

Sharding RULES (an un-contracted output dim) over "mp" keeps the request
path collective-free: each mp shard owns a rule slice end-to-end. The
final per-request verdict fold (deny/allow over rules) contracts the
sharded R axis, so XLA inserts exactly one small psum over "mp" — the
only ICI traffic per step. Batch stays on "dp" throughout.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """dp × mp factorization of the available devices."""
    dp: int
    mp: int = 1

    def build(self, devices: Sequence[Any] | None = None) -> Mesh:
        devs = list(devices if devices is not None else jax.devices())
        need = self.dp * self.mp
        if len(devs) < need:
            raise ValueError(f"need {need} devices, have {len(devs)}")
        arr = np.asarray(devs[:need]).reshape(self.dp, self.mp)
        return Mesh(arr, axis_names=("dp", "mp"))


def policy_mesh(n_devices: int | None = None, rule_shards: int = 1) -> Mesh:
    """Default mesh: dp × mp with `rule_shards` cores on the rule axis."""
    n = n_devices if n_devices is not None else len(jax.devices())
    if n % rule_shards:
        raise ValueError(f"{n} devices not divisible by mp={rule_shards}")
    return MeshSpec(dp=n // rule_shards, mp=rule_shards).build()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Prefix sharding for an AttributeBatch pytree: leading batch dim on
    dp, everything else replicated."""
    return NamedSharding(mesh, P("dp"))


def shard_batch(mesh: Mesh, batch) -> Any:
    """Place an AttributeBatch pytree with its batch dim over dp."""
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def shard_engine_check(mesh: Mesh, engine) -> Callable:
    """jit a PolicyEngine.raw_step under the dp/mp layout.

    batch + req_ns shard over dp; quota counters replicate (each dp
    replica is a best-effort quota domain, exactly the reference's
    per-replica memquota stance); matched/err verdict planes + the
    rule-dimension params (RM/RN columns) shard rules over mp. Returns
    fn(params, batch, req_ns, quota_counts) → (CheckVerdict, counts)."""
    from istio_tpu.models.policy_engine import CheckVerdict
    dp = NamedSharding(mesh, P("dp"))
    dpmp = NamedSharding(mesh, P("dp", "mp"))
    rep = NamedSharding(mesh, P())
    mp_rules = NamedSharding(mesh, P("mp"))   # [R, K] rule dim over mp
    param_shard = {"lit_idx": rep,
                   "conj_m_idx": mp_rules, "conj_n_idx": mp_rules}
    out_verdict = CheckVerdict(status=dp, valid_duration_s=dp,
                               valid_use_count=dp, referenced=dp,
                               matched=dpmp, err=dpmp, deny_rule=dp,
                               err_count=rep)
    return jax.jit(engine.raw_step,
                   in_shardings=(param_shard, dp, dp, rep),
                   out_shardings=(out_verdict, rep))
