"""Sequence-parallel DFA matching — long strings sharded across chips.

The byte-predicate device path stores at most `max_str_len` bytes per
slot (layout.py); longer values fall back to the host oracle
(tensor_expr truncation routing — still the serving behavior). This
module is the long-context building block for lifting that limit:
shard the byte axis over a `sp` mesh axis and run the SAME dense DFAs
(ops/regex_dfa.py) with one collective.

The trick is the associativity of DFA execution (the ring-attention
analog for byte matching, SURVEY §5.7): a chunk of input induces a
transition MAP f: S → S ("enter the chunk in state s, leave in
f[s]"), and maps compose — so each device scans only its local chunk
(computing the map for every possible entry state at once, a [B, S]
state matrix through a length-L/C scan), and one `all_gather` of the
tiny [B, S] maps plus an in-register composition replaces scanning
the full string anywhere. Acceptance stays a final-state lookup
because compiled unanchored DFAs make accepting states sticky
(regex_dfa.py:358).

Cost model: a single device scans L bytes with state width 1; each of
C devices scans L/C bytes with state width S. Wall-clock wins whenever
S < C (typical: S ≈ 4-40, C = chip count) and the collective is one
[C, B, S] int32 all_gather over ICI.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def chunk_transition_map(chunk: jnp.ndarray, chunk_lens: jnp.ndarray,
                         transitions: jnp.ndarray) -> jnp.ndarray:
    """Per-row transition map of one chunk: out[b, s] = state after
    feeding row b's chunk bytes starting from state s.

    chunk [B, L] uint8, chunk_lens [B] int32, transitions [S, 256]
    int32 → [B, S] int32.
    """
    b, l = chunk.shape
    s = transitions.shape[0]
    flat = transitions.reshape(-1)

    def step(state, inp):
        byte, pos = inp                       # [B], scalar-broadcast
        nxt = flat[state * 256 + byte.astype(jnp.int32)[:, None]]
        state = jnp.where((pos < chunk_lens)[:, None], nxt, state)
        return state, None

    # derive the carry from the (possibly device-varying) input so the
    # scan carry's sharding metadata matches under shard_map
    zero = chunk[:, :1].astype(jnp.int32) * 0          # [B, 1]
    init = jnp.arange(s, dtype=jnp.int32)[None, :] + zero
    positions = jnp.arange(l, dtype=jnp.int32)
    final, _ = jax.lax.scan(step, init, (chunk.T, positions))
    return final


def compose_maps(maps: jnp.ndarray) -> jnp.ndarray:
    """Left-to-right composition of per-chunk maps [C, B, S] → [B, S]:
    out[b, s] = f_{C-1}(... f_1(f_0(s))). An associative_scan would
    give all prefixes; matching needs only the total, so a fori_loop
    of gathers (C is the chip count — tiny) is cheaper."""
    c, b, s = maps.shape

    def body(i, acc):                          # acc [B, S]
        nxt = maps[i]                          # [B, S]
        return jnp.take_along_axis(nxt, acc, axis=1)

    init = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :],
                            (b, s))
    return jax.lax.fori_loop(0, c, body, init)


_RUN_CACHE: dict = {}


def _runner(mesh: Mesh, axis: str, c: int, lc: int):
    """jitted matcher memoized per (mesh, axis, chunk geometry) —
    jax.jit caches key on function identity, so a fresh closure per
    call would recompile the shard_map program every time."""
    key = (mesh, axis, c, lc)
    cached = _RUN_CACHE.get(key)
    if cached is not None:
        return cached
    n_shards = mesh.shape[axis]
    per_dev = c // n_shards
    chunk_starts = np.arange(c, dtype=np.int32) * lc

    @jax.jit
    def run(data_j, lens_j, trans_j, accept_j):
        def local(chunk, starts, lens_all):   # [B, per_dev, Lc], ...
            # compose this device's chunks left-to-right — a device
            # may hold several when C > mesh size
            fmap = None
            for i in range(per_dev):
                local_lens = jnp.clip(lens_all - starts[i], 0, lc)
                m = chunk_transition_map(chunk[:, i, :], local_lens,
                                         trans_j)
                fmap = m if fmap is None else \
                    jnp.take_along_axis(m, fmap, axis=1)
            return fmap[None]                 # [1, B, S] shard

        maps = jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(None, axis, None), P(axis), P()),
            out_specs=P(axis))(
                data_j, jnp.asarray(chunk_starts), lens_j)
        final = compose_maps(maps)[:, 0]      # entry state 0
        return accept_j[final]

    _RUN_CACHE[key] = run
    return run


def sharded_dfa_match(mesh: Mesh, axis: str,
                      data: np.ndarray, lens: np.ndarray,
                      transitions: np.ndarray,
                      accept: np.ndarray) -> jnp.ndarray:
    """Match one DFA over rows whose byte axis is sharded over
    `axis`: data [B, C, Lc] (chunk-major), lens [B] TOTAL lengths.

    Each device computes its chunks' composed [B, S] map; one
    all_gather + composition yields the final state; accept is a [B]
    gather. C must be a multiple of the mesh axis size.
    """
    c, lc = data.shape[1], data.shape[2]
    n_shards = mesh.shape[axis]
    if c % n_shards:
        raise ValueError(f"chunk count {c} must be a multiple of the "
                         f"'{axis}' axis size {n_shards}")
    run = _runner(mesh, axis, c, lc)
    sharded = jax.device_put(
        data, NamedSharding(mesh, P(None, axis, None)))
    return run(sharded, jnp.asarray(lens), jnp.asarray(transitions),
               jnp.asarray(accept))
