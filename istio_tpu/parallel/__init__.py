"""Device-mesh parallelism for the policy engine.

The reference scales horizontally with stateless replicas behind k8s
Services (SURVEY.md §5.8 — no collectives of any kind). The TPU-native
design replaces that with SPMD over a `jax.sharding.Mesh`:

  axis "dp"  — data parallel over the request batch (the natural axis:
               requests are independent; rule tensors replicate).
  axis "mp"  — model parallel over RULES when a snapshot's tensors
               exceed per-core VMEM (10k+ rules). The per-rule gather/
               reduce stages shard on the rule dimension, so the only
               collective on the request path is the final per-request
               verdict combine (a small psum over "mp"), riding ICI.

Multi-host: replicate dp groups across hosts over DCN; rule tensors are
pure functions of config so every host compiles the same snapshot —
there is no training state to synchronize (checkpoint = config hash,
SURVEY.md §5.4).
"""
from istio_tpu.parallel.mesh import (MeshSpec, policy_mesh, shard_batch,
                                     shard_engine_check)

__all__ = ["MeshSpec", "policy_mesh", "shard_batch", "shard_engine_check"]
