"""Batched quota allocation kernel — exact memquota alloc semantics.

Reference: mixer/adapter/memquota/memquota.go:118 alloc — sequential
per-request: avail = max - used; best-effort grants min(amount, avail),
all-or-nothing grants amount iff avail >= amount; granted adds to used.

This kernel allocates a whole BATCH against device-resident counters in
one XLA program with the same sequential-within-batch semantics the
host oracle produces when requests arrive one at a time (tests hold the
two paths equal under contention): requests are sorted by bucket and a
`lax.scan` threads the consumed-so-far carry through each bucket run —
a grant-dependent recurrence (an all-or-nothing denial consumes
NOTHING, so a later smaller request may still succeed), which is why
this is a scan and not a prefix-sum.

Shapes are static: [B] buckets/amounts in, [B] granted out, counters
[n_buckets] donated through. The scan is O(B) sequential steps of
scalar work — irrelevant next to the batched gather/scatter around it,
and quota batches ride the serving batcher's bucket shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def make_alloc_step(n_buckets: int, jit: bool = True):
    """→ (scan_fn, fast_fn), each
    fn(counts[i32 n_buckets], buckets[i32 B], amounts[i32 B],
    best_effort[bool B], max_amounts[i32 B], active[bool B])
    → (granted[i32 B], new_counts).

    `max_amounts` rides per-request (different quota names share one
    counter pool, each bucket with its own limit). Inactive rows
    (padding) consume nothing and grant 0 whatever their bucket says.
    fast_fn is exact only for batches with no duplicate active bucket —
    the caller picks per batch (runtime/device_quota.py _flush)."""

    def step_fast(counts, buckets, amounts, best_effort, max_amounts,
                  active):
        """Vectorized variant — EXACT only when every active bucket
        appears at most once in the batch (the overwhelmingly common
        case at 100k-key scale); the caller checks for duplicates
        host-side and falls back to the scan variant."""
        counts = jnp.asarray(counts)
        used = counts[buckets]
        avail = max_amounts - used
        g_be = jnp.clip(jnp.minimum(amounts, avail), 0)
        g_ao = jnp.where(avail >= amounts, amounts, 0)
        g = jnp.where(active,
                      jnp.where(best_effort, g_be, g_ao),
                      0).astype(jnp.int32)
        new_counts = counts.at[buckets].add(g)
        return g, new_counts

    def step(counts, buckets, amounts, best_effort, max_amounts, active):
        counts = jnp.asarray(counts)
        buckets = jnp.asarray(buckets)
        active = jnp.asarray(active)
        b = buckets.shape[0]
        order = jnp.argsort(buckets, stable=True)
        sb = buckets[order]
        sa = jnp.where(active, amounts, 0)[order]
        se = best_effort[order]
        sm = max_amounts[order]
        sact = active[order]
        newseg = jnp.concatenate(
            [jnp.ones(1, bool), sb[1:] != sb[:-1]])
        base_used = counts[sb]            # used BEFORE this batch

        def body(carry, x):
            consumed = carry
            new, used0, amt, be, mx, act = x
            consumed = jnp.where(new, 0, consumed)
            avail = mx - used0 - consumed
            g_be = jnp.clip(jnp.minimum(amt, avail), 0)
            g_ao = jnp.where(avail >= amt, amt, 0)
            g = jnp.where(act, jnp.where(be, g_be, g_ao), 0)
            return consumed + g, g

        _, sg = lax.scan(
            body, jnp.int32(0),
            (newseg, base_used, sa, se, sm, sact))
        granted = jnp.zeros(b, jnp.int32).at[order].set(sg)
        new_counts = counts.at[buckets].add(
            jnp.where(active, granted, 0))
        return granted, new_counts

    if jit:
        return (jax.jit(step, donate_argnums=(0,)),
                jax.jit(step_fast, donate_argnums=(0,)))
    return step, step_fast
