"""Batched quota allocation kernel — exact memquota alloc semantics.

Reference: mixer/adapter/memquota/memquota.go:118 alloc — sequential
per-request: avail = max - used; best-effort grants min(amount, avail),
all-or-nothing grants amount iff avail >= amount; granted adds to used.

This kernel allocates a whole BATCH against device-resident counters in
one XLA program with the same sequential-within-batch semantics the
host oracle produces when requests arrive one at a time (tests hold the
two paths equal under contention): requests are sorted by bucket and a
`lax.scan` threads the consumed-so-far carry through each bucket run —
a grant-dependent recurrence (an all-or-nothing denial consumes
NOTHING, so a later smaller request may still succeed), which is why
this is a scan and not a prefix-sum.

Shapes are static: [B] buckets/amounts in, [B] granted out, counters
[n_buckets] donated through. The scan is O(B) sequential steps of
scalar work — irrelevant next to the batched gather/scatter around it,
and quota batches ride the serving batcher's bucket shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Device-path bound on a SINGLE request's quota amount (step_seg): at
# B ≤ 512 rows per flush, cumsums of clamped amounts stay int32-exact
# (512 × 2^21 = 2^30). Over-domain all-or-nothing rows are denied;
# best-effort rows cap here. memquota amounts are per-request counts,
# so real traffic is orders of magnitude below this.
DOMAIN_MAX = 1 << 21


def batch_rank(key):
    """rank[i] = #{j < i in stable sort order : key[j] == key[i]} — the
    occurrence index of each element within its key group (shared with
    the engine's fused quota step; sentinel keys get unused ranks)."""
    n = key.shape[0]
    order = jnp.argsort(key, stable=True)
    sk = key[order]
    idx = jnp.arange(n, dtype=jnp.int32)
    newseg = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    seg_first = lax.associative_scan(jnp.maximum,
                                     jnp.where(newseg, idx, 0))
    rank_sorted = idx - seg_first
    return jnp.zeros(n, jnp.int32).at[order].set(rank_sorted)


def make_alloc_step(n_buckets: int, jit: bool = True):
    """→ (scan_fn, fast_fn), each
    fn(counts[i32 n_buckets], buckets[i32 B], amounts[i32 B],
    best_effort[bool B], max_amounts[i32 B], active[bool B])
    → (granted[i32 B], new_counts).

    `max_amounts` rides per-request (different quota names share one
    counter pool, each bucket with its own limit). Inactive rows
    (padding) consume nothing and grant 0 whatever their bucket says.
    fast_fn is exact only for batches with no duplicate active bucket —
    the caller picks per batch (runtime/device_quota.py _flush)."""

    def step_fast(counts, buckets, amounts, best_effort, max_amounts,
                  active):
        """Vectorized variant — EXACT only when every active bucket
        appears at most once in the batch (the overwhelmingly common
        case at 100k-key scale); the caller checks for duplicates
        host-side and falls back to the scan variant."""
        counts = jnp.asarray(counts)
        used = counts[buckets]
        avail = max_amounts - used
        g_be = jnp.clip(jnp.minimum(amounts, avail), 0)
        # grants never go negative: the host adapter clamps to 0 and
        # commits nothing (_Window.alloc / _Exact.alloc) — without the
        # amounts > 0 guard a wire-supplied negative amount would
        # DRAIN the counter below real usage
        g_ao = jnp.where((avail >= amounts) & (amounts > 0), amounts, 0)
        g = jnp.where(active,
                      jnp.where(best_effort, g_be, g_ao),
                      0).astype(jnp.int32)
        new_counts = counts.at[buckets].add(g)
        return g, new_counts

    def step(counts, buckets, amounts, best_effort, max_amounts, active):
        counts = jnp.asarray(counts)
        buckets = jnp.asarray(buckets)
        active = jnp.asarray(active)
        b = buckets.shape[0]
        order = jnp.argsort(buckets, stable=True)
        sb = buckets[order]
        sa = jnp.where(active, amounts, 0)[order]
        se = best_effort[order]
        sm = max_amounts[order]
        sact = active[order]
        newseg = jnp.concatenate(
            [jnp.ones(1, bool), sb[1:] != sb[:-1]])
        base_used = counts[sb]            # used BEFORE this batch

        def body(carry, x):
            consumed = carry
            new, used0, amt, be, mx, act = x
            consumed = jnp.where(new, 0, consumed)
            avail = mx - used0 - consumed
            g_be = jnp.clip(jnp.minimum(amt, avail), 0)
            g_ao = jnp.where((avail >= amt) & (amt > 0), amt, 0)
            g = jnp.where(act, jnp.where(be, g_be, g_ao), 0)
            return consumed + g, g

        _, sg = lax.scan(
            body, jnp.int32(0),
            (newseg, base_used, sa, se, sm, sact))
        granted = jnp.zeros(b, jnp.int32).at[order].set(sg)
        new_counts = counts.at[buckets].add(
            jnp.where(active, granted, 0))
        return granted, new_counts

    if jit:
        return (jax.jit(step, donate_argnums=(0,)),
                jax.jit(step_fast, donate_argnums=(0,)))
    return step, step_fast


def seg_scan(op, v, newseg):
    """Segmented inclusive scan: op over runs delimited by `newseg`
    (True at each run's first element). Standard segmented-scan
    operator — (v1,f1)⊕(v2,f2) = (v2 if f2 else op(v1,v2), f1|f2) —
    which is associative, so the whole thing is one parallel
    lax.associative_scan instead of an O(B) sequential loop."""
    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf
    out, _ = lax.associative_scan(comb, (v, newseg))
    return out


def make_rolling_alloc_step(n_buckets: int, k_ticks: int,
                            jit: bool = True):
    """Rolling-window variant: counters are per-(bucket, tick-slot)
    planes [n_buckets, K]; a batch first ROLLS each touched bucket
    (reclaiming slots whose ticks left the window — memquota.go
    rollingWindow.roll), then allocates against
    avail = max - sum(live slots) and commits grants into the current
    tick's slot (rollingWindow.alloc :118).

    → (scan_fn, fast_fn, unit_fn, seg_fn), each
    fn(slots[i32 n_buckets×K], buckets[i32 B], amounts[i32 B],
       best_effort[bool B], max_amounts[i32 B], active[bool B],
       ticks[i32 B], last_ticks[i32 B], rolling[bool B])
    → (granted[i32 B], new_slots). scan_fn is the sequential parity
    ORACLE (tests/bench only — the serving path never selects it);
    fast_fn needs unique active buckets, unit_fn all-ones amounts,
    seg_fn handles any contended mixed batch in parallel.

    Ticks are caller-rebased ints (host: floor(now / tick_len) minus a
    per-bucket base — int32-safe and boundary-exact vs the host
    adapter's absolute ticks). rolling=False rows (exact cells, padding)
    never roll and commit to slot 0 — slot 0 of an exact bucket IS its
    counter, so exact and rolling cells share one plane. Rows sharing a
    bucket within a batch carry identical (tick, last) — the roll is
    idempotent under the duplicate multiply-scatter."""

    def _roll_and_used(slots, buckets, ticks, last, rolling, active):
        p = jnp.arange(k_ticks, dtype=jnp.int32)
        delta = jnp.clip(ticks - last, 0, k_ticks)
        delta = jnp.where(rolling & active, delta, 0)
        zmask = ((p[None, :] - last[:, None] - 1) % k_ticks) \
            < delta[:, None]
        keep = 1 - zmask.astype(slots.dtype)
        slots = slots.at[buckets].mul(keep)
        used = slots[buckets].sum(axis=1)
        return slots, used

    def _commit(slots, buckets, ticks, rolling, granted):
        col = jnp.where(rolling, ticks % k_ticks, 0)
        return slots.at[buckets, col].add(granted)

    def step_fast(slots, buckets, amounts, best_effort, max_amounts,
                  active, ticks, last_ticks, rolling):
        """EXACT only when every active bucket appears at most once in
        the batch (caller checks host-side)."""
        slots = jnp.asarray(slots)
        slots, used = _roll_and_used(slots, buckets, ticks, last_ticks,
                                     rolling, active)
        avail = max_amounts - used
        g_be = jnp.clip(jnp.minimum(amounts, avail), 0)
        # negative-amount clamp — see make_alloc_step.step_fast
        g_ao = jnp.where((avail >= amounts) & (amounts > 0), amounts, 0)
        g = jnp.where(active,
                      jnp.where(best_effort, g_be, g_ao),
                      0).astype(jnp.int32)
        return g, _commit(slots, buckets, ticks, rolling, g)

    def step(slots, buckets, amounts, best_effort, max_amounts,
             active, ticks, last_ticks, rolling):
        """Sequential-within-batch parity under contention (same
        grant-dependent scan as make_alloc_step)."""
        slots = jnp.asarray(slots)
        buckets = jnp.asarray(buckets)
        active = jnp.asarray(active)
        slots, used = _roll_and_used(slots, buckets, ticks, last_ticks,
                                     rolling, active)
        b = buckets.shape[0]
        order = jnp.argsort(buckets, stable=True)
        sb = buckets[order]
        sa = jnp.where(active, amounts, 0)[order]
        se = best_effort[order]
        sm = max_amounts[order]
        sact = active[order]
        newseg = jnp.concatenate(
            [jnp.ones(1, bool), sb[1:] != sb[:-1]])
        base_used = used[order]

        def body(carry, x):
            consumed = carry
            new, used0, amt, be, mx, act = x
            consumed = jnp.where(new, 0, consumed)
            avail = mx - used0 - consumed
            g_be = jnp.clip(jnp.minimum(amt, avail), 0)
            g_ao = jnp.where((avail >= amt) & (amt > 0), amt, 0)
            g = jnp.where(act, jnp.where(be, g_be, g_ao), 0)
            return consumed + g, g

        _, sg = lax.scan(
            body, jnp.int32(0),
            (newseg, base_used, sa, se, sm, sact))
        granted = jnp.zeros(b, jnp.int32).at[order].set(sg)
        return granted, _commit(slots, buckets, ticks, rolling,
                                jnp.where(active, granted, 0))

    def step_seg(slots, buckets, amounts, best_effort, max_amounts,
                 active, ticks, last_ticks, rolling):
        """Contended MIXED-amount batches without an O(B) scan
        (VERDICT r4 item 4): the serving path fixes the intra-window
        serialization order to (bucket, all-or-nothing before
        best-effort, amount ascending) — the window collects ~10ms of
        raced arrivals, so any deterministic order is as faithful to
        the reference's mutex as arrival order was — and under THAT
        order sequential memquota semantics (memquota.go:118) have a
        closed form:

          * all-or-nothing, amounts ascending: a denial consumes
            nothing, and every later request is ≥ the denied one with
            the same remaining budget, so denial is a prefix-sum
            threshold — grant a_i iff cumsum_incl_i ≤ avail;
          * best-effort rows (after every ao row): consumption equals
            their amount-cumsum until the budget saturates, so
            g_i = clip(min(a_i, avail − consumed_ao − becum_before_i)).

        Equals the sequential scan kernel run over the lexsorted batch
        bit-for-bit (pinned by tests) WITHIN the device quota domain:
        single-request amounts are bounded at DOMAIN_MAX = 2^21 so a
        512-row run's amount-cumsum stays int32-exact (jax here runs
        without x64 — an int64 astype would silently truncate, and an
        adversarial wire amount near INT32_MAX could wrap the cumsum
        into an over-grant). Over-domain rows fail SAFE: all-or-
        nothing above 2^21 is denied (never a wrong partial grant);
        best-effort caps at the bound. memquota amounts are
        per-request counts — real traffic sits many orders below.

        PRECONDITION: max_amounts is uniform within each bucket run
        (the pool keys buckets by (quota name, dims), one limit per
        bucket — device_quota._bucket_for). The prefix threshold reads
        each row's own savail; a mixed-max run would let a denied
        small-max row's amount inflate cum_ao against a later
        larger-max row. The scan/fast kernels stay fully general."""
        slots = jnp.asarray(slots)
        slots, used = _roll_and_used(slots, buckets, ticks, last_ticks,
                                     rolling, active)
        b = buckets.shape[0]
        domain_max = jnp.int32(DOMAIN_MAX)
        over = amounts > domain_max
        a_pos = jnp.clip(amounts, 0, domain_max)
        key_bucket = jnp.where(active, buckets,
                               jnp.iinfo(jnp.int32).max)
        order = jnp.lexsort((a_pos, best_effort, key_bucket))
        sb = key_bucket[order]
        sa = a_pos[order]
        sbe = best_effort[order]
        sact = active[order]
        sover = over[order]
        savail = (max_amounts - used)[order]
        newseg = jnp.concatenate(
            [jnp.ones(1, bool), sb[1:] != sb[:-1]])
        # all-or-nothing sub-run: prefix-sum threshold. Over-domain
        # rows are excluded from the cumsum too — they are denied
        # unconditionally and a denial consumes NOTHING, so letting
        # their clipped amounts inflate cum_ao would wrongly deny a
        # later legit row (review r5 finding)
        v_ao = jnp.where(sact & ~sbe & ~sover, sa, 0)
        cum_ao = seg_scan(jnp.add, v_ao, newseg)
        grant_ao = sact & ~sbe & ~sover & (sa > 0) & (cum_ao <= savail)
        # budget the ao rows consumed, as seen by every later row of
        # the run (a running max: denied rows contribute nothing)
        consumed_ao = seg_scan(jnp.maximum,
                               jnp.where(grant_ao, cum_ao, 0), newseg)
        # best-effort sub-run (sorts after ao): partial at the
        # boundary. Intermediates are clamped non-negative BEFORE each
        # subtraction — savail can sit anywhere in int32 (a shrunken
        # limit leaves used > max), and a raw savail-consumed-cum
        # chain could wrap negative→positive into an over-grant.
        v_be = jnp.where(sact & sbe, sa, 0)
        cum_be_before = seg_scan(jnp.add, v_be, newseg) - v_be
        rem_after_ao = jnp.maximum(jnp.maximum(savail, 0) - consumed_ao,
                                   0)
        g_be = jnp.clip(
            jnp.minimum(sa, rem_after_ao - cum_be_before), 0)
        sg = jnp.where(grant_ao, sa,
                       jnp.where(sact & sbe, g_be, 0)).astype(jnp.int32)
        granted = jnp.zeros(b, jnp.int32).at[order].set(sg)
        return granted, _commit(slots, buckets, ticks, rolling,
                                jnp.where(active, granted, 0))

    def step_unit(slots, buckets, amounts, best_effort, max_amounts,
                  active, ticks, last_ticks, rolling):
        """Contended batches where EVERY active amount == 1 (the
        dominant serving shape — rate limits allocate one unit per
        request): best-effort and all-or-nothing coincide, and the
        sequential-within-bucket grant reduces to `rank within bucket
        run < avail` — one parallel sort instead of an O(B) scan.
        `amounts`/`best_effort` ride the signature for symmetry; the
        caller guarantees amounts[active] == 1."""
        slots = jnp.asarray(slots)
        slots, used = _roll_and_used(slots, buckets, ticks, last_ticks,
                                     rolling, active)
        avail = max_amounts - used
        key = jnp.where(active, buckets,
                        jnp.iinfo(jnp.int32).max)
        rank = batch_rank(key)
        g = (active & (rank < avail)).astype(jnp.int32)
        return g, _commit(slots, buckets, ticks, rolling, g)

    if jit:
        return (jax.jit(step, donate_argnums=(0,)),
                jax.jit(step_fast, donate_argnums=(0,)),
                jax.jit(step_unit, donate_argnums=(0,)),
                jax.jit(step_seg, donate_argnums=(0,)))
    return step, step_fast, step_unit, step_seg
