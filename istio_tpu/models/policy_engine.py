"""PolicyEngine — the fused batched Check()/Quota() device step.

Reference call stack being replaced (SURVEY.md §3.1, per request,
sequential): grpcServer.Check → dispatcher.Resolve (IL-interpret every
rule's match predicate, resolver.go:202-238) → per-action template
ProcessCheck (IL-interpret every instance field) → adapter Handle*
(denier.go, list.go:68, memquota.go:107) → combineResults
(dispatcher.go:322 — AND statuses, min TTLs).

Here the WHOLE pipeline for a batch of B requests is one XLA program:

    ruleset match          atom eval + index gathers  [B, R] 3-valued
    × namespace mask       broadcast compare          [B, R]
    deny actions           masked min-reduce          [B]
    listentry membership   gather + equality scan     [B, n_lists]
    quota alloc            scatter-add on counters    [B] (device state)
    referenced attrs       one more int8 matmul       [B, n_cols]
    combine                AND of statuses, min TTLs  CheckVerdict

Adapter semantics fused on device:
  * denier (mixer/adapter/denier): per-rule fixed status + TTLs.
  * list   (mixer/adapter/list): whitelist/blacklist membership of one
    expression value, lowered per entry type (ListEntrySpec): exact
    STRINGS as an interned-id equality scan over a padded
    [n_lists, max_entries] matrix, static REGEX entries as packed
    per-byte-slot DFA banks, IP_ADDRESSES as CIDR prefix compares in
    v6-mapped space. Case-insensitive and provider-refreshed lists
    keep list.go's host semantics via the runtime overlay.
  * memquota (mixer/adapter/memquota): token-bucket-style windowed
    counters resident on device; a batch allocates with a scatter-add
    and reads back grants (best-effort per replica, exactly like the
    reference's per-replica memquota).

Rules whose predicate cannot lower run host-side via the ruleset
program's oracle fallback; the runtime overlays their verdicts.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import (AttributeBatch, InternTable, Tensorizer)
from istio_tpu.compiler.ruleset import Rule, RuleSetProgram, compile_ruleset
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.ops import bytes_ops
from istio_tpu.utils.log import scope

log = scope("models.policy_engine")

# istio.mixer.v1 / google.rpc status codes used on the check path.
OK = 0
NOT_FOUND = 5
PERMISSION_DENIED = 7
RESOURCE_EXHAUSTED = 8
INTERNAL = 13
UNAVAILABLE = 14
_BIG = np.float32(3.4e38)
# adapter CheckResult defaults (adapters/sdk.py) — INTERNAL results
# min these into the TTL fold, host-_combine parity
DEFAULT_DUR = np.float32(5.0)
DEFAULT_USES = np.int32(10_000)


# occurrence rank within key groups — single-sourced with the rolling
# quota kernels (models/quota_alloc.batch_rank)
from istio_tpu.models.quota_alloc import batch_rank as _batch_rank  # noqa: E402


@dataclasses.dataclass(frozen=True)
class DenySpec:
    """denier adapter wiring for one rule (denier.go params)."""
    rule: int                      # rule index in the ruleset
    status: int = PERMISSION_DENIED
    valid_duration_s: float = 5.0
    valid_use_count: int = 10000


@dataclasses.dataclass(frozen=True)
class ListEntrySpec:
    """list adapter wiring for one rule (listentry template +
    mixer/adapter/list): check `value_attr`'s membership in a fixed
    list. Three device lowerings by entry_type (list.go ListEntryType):

      STRINGS       — interned-id equality scan (exact match)
      REGEX         — packed byte-DFA bank over the value's byte slot
                      (Go regexp search semantics, ops/regex_dfa);
                      truncated values with no definitive prefix hit
                      mark the rule's err bit (the byte-predicate
                      truncation contract) and suppress the deny
      IP_ADDRESSES  — CIDR prefix compare over the value's IP bytes in
                      v6-mapped space, with v4/v6 version matching
                      (host parity: list_adapter._member)

    CASE_INSENSITIVE_STRINGS and provider-refreshed lists stay host-
    side (runtime/fused.py enumerates them as unfusable)."""
    rule: int
    value_attr: str                # attribute (or (map,key)) whose value is checked
    entries: Sequence[Any]         # list payload per entry_type
    blacklist: bool = False       # True: member → deny; False: non-member → deny
    valid_duration_s: float = 5.0
    valid_use_count: int = 10000
    entry_type: str = "STRINGS"


@dataclasses.dataclass(frozen=True)
class RbacSpec:
    """rbac adapter wiring for one rule (mixer/adapter/rbac rbac.go:181
    HandleAuthorization): the policy's (binding, subject, role-rule)
    triples were lowered to pseudo-rule rows (compiler/rbac_lower.py);
    the request is allowed iff ANY `allow_rows` row matched. The
    `guard_row` tracks host instance-evaluation errors: when it is not
    definitely-true, the host path would have failed the instance build
    with INTERNAL (dispatcher _safe_check), so the device reports the
    same."""
    rule: int
    allow_rows: tuple[int, ...]
    guard_row: int = -1            # -1: instance can never error
    valid_duration_s: float = 60.0  # handler caching_ttl_s


@dataclasses.dataclass(frozen=True)
class QuotaSpec:
    """memquota wiring for one rule: fixed-window rate limit keyed by an
    attribute's interned id (memquota.go rolling window simplified to
    fixed windows device-side; dedup stays in the runtime layer)."""
    rule: int
    key_attr: str
    max_amount: int = 100
    n_buckets: int = 4096          # hash space for keys


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CheckVerdict:
    """Batched check result (adapter.CheckResult semantics, check.go:28)."""
    status: Any            # int32 [B] — google.rpc code
    valid_duration_s: Any  # float32 [B]
    valid_use_count: Any   # int32 [B]
    referenced: Any        # bool [B, n_columns] attribute-use bitmap
    matched: Any           # bool [B, R] (diagnostics + host overlay)
    err: Any               # bool [B, R]
    deny_rule: Any         # int32 [B] — lowest rule idx that produced a
    #                        non-OK status; INT32_MAX when status is OK.
    #                        The serving overlay merges host adapter
    #                        results against this in rule order.
    err_count: Any         # int32 [] — total namespace-visible predicate
    #                        errors in the batch (monitoring; lets the
    #                        host skip converting the full err plane)

    def tree_flatten(self):
        return ((self.status, self.valid_duration_s, self.valid_use_count,
                 self.referenced, self.matched, self.err, self.deny_rule,
                 self.err_count), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class PolicyEngine:
    """Compiled fused policy step for one config snapshot.

    Construction compiles the ruleset + action tensors; `check(batch,
    ns_ids)` runs the fused device program. Quota state lives in
    `self.quota_counts` (donated through each step).
    """

    def __init__(self, rules: Sequence[Rule] | None = None,
                 finder: AttributeDescriptorFinder | None = None,
                 deny: Sequence[DenySpec] = (),
                 lists: Sequence[ListEntrySpec] = (),
                 quotas: Sequence[QuotaSpec] = (),
                 rbacs: Sequence[RbacSpec] = (),
                 interner: InternTable | None = None,
                 max_str_len: int | None = None,
                 jit: bool = True,
                 ruleset: RuleSetProgram | None = None,
                 count_rules: int | None = None):
        if ruleset is None:
            assert rules is not None and finder is not None
            # REGEX/CIDR lists match value BYTES — their value attrs
            # need byte (and, for map reads, derived) layout slots
            # (the snapshot builder does the same, runtime/config.py)
            lsrcs = [l.value_attr for l in lists
                     if l.entry_type in ("REGEX", "IP_ADDRESSES")]
            ruleset = compile_ruleset(
                rules, finder, interner=interner, max_str_len=max_str_len,
                jit=False,
                extra_derived_keys=[r for r in lsrcs
                                    if isinstance(r, tuple)],
                extra_byte_sources=sorted(set(lsrcs), key=str))
        self.ruleset = ruleset
        self.finder = finder
        lay = self.ruleset.layout
        interner = self.ruleset.interner
        # rule-axis width INCLUDING mp-sharding padding (ruleset
        # rule_pad) — every per-rule tensor and the matched/err planes
        # share it; rs.n_rules counts real rules only
        R = int(self.ruleset.rule_ns.shape[0])
        # err accounting covers only real config rules: pseudo-rule rows
        # (rbac lowering) err routinely on requests missing instance
        # attrs, which maps to adapter-level INTERNAL, not a predicate
        # resolve error (host parity: RESOLVE_ERRORS vs DISPATCH_ERRORS)
        if count_rules is None or count_rules >= R:
            err_rule_mask = None
        else:
            err_rule_mask = np.zeros(R, bool)
            err_rule_mask[:count_rules] = True

        # --- denier tensors ---
        deny_mask = np.zeros(R, bool)
        deny_status = np.full(R, OK, np.int32)
        deny_dur = np.full(R, _BIG, np.float32)
        deny_uses = np.full(R, np.iinfo(np.int32).max, np.int32)
        for d in deny:
            deny_mask[d.rule] = True
            deny_status[d.rule] = d.status
            deny_dur[d.rule] = d.valid_duration_s
            deny_uses[d.rule] = d.valid_use_count

        # --- list tensors ---
        n_lists = len(lists)
        max_entries = max((len(l.entries) for l in lists
                           if l.entry_type == "STRINGS"), default=1) or 1
        list_ids = np.zeros((max(n_lists, 1), max_entries), np.int64)
        list_rule = np.zeros(max(n_lists, 1), np.int32)
        list_slot = np.zeros(max(n_lists, 1), np.int32)
        list_black = np.zeros(max(n_lists, 1), bool)
        list_code = np.full(max(n_lists, 1), PERMISSION_DENIED, np.int32)
        list_dur = np.full(max(n_lists, 1), _BIG, np.float32)
        list_uses = np.full(max(n_lists, 1), np.iinfo(np.int32).max, np.int32)
        for i, l in enumerate(lists):
            if l.entry_type == "STRINGS":
                ids = [interner.intern(e) for e in l.entries]
                list_ids[i, :len(ids)] = ids
                # pad with ID_INVALID: a present slot's id is never 0
                # (constants ≥ 1, ephemerals ≤ -1), and absent slots are
                # masked by `present`, so padding can never match
                list_ids[i, len(ids):] = 0
            # REGEX/IP rows keep all-zero id entries (member False from
            # the id scan; their member columns are overwritten by the
            # byte-level paths below)
            list_rule[i] = l.rule
            list_slot[i] = self._slot_for(l.value_attr)
            list_black[i] = l.blacklist
            # host-path parity (adapters/list_adapter.py): blacklist hit
            # → PERMISSION_DENIED, whitelist miss → NOT_FOUND
            list_code[i] = PERMISSION_DENIED if l.blacklist else NOT_FOUND
            list_dur[i] = l.valid_duration_s
            list_uses[i] = l.valid_use_count
        rx_banks = self._build_regex_banks(lists)
        cidr_bank = self._build_cidr_bank(lists)

        # --- rbac tensors ---
        n_rbac = len(rbacs)
        k_allow = max((len(r.allow_rows) for r in rbacs), default=1) or 1
        # indices into m_ext = [matched | FALSE col | TRUE col]:
        # padding of allow rows points at FALSE (OR identity), a missing
        # guard points at TRUE (instance can never error)
        FALSE_COL = R
        TRUE_COL = R + 1
        rb_rule = np.zeros(max(n_rbac, 1), np.int32)
        rb_dur = np.full(max(n_rbac, 1), _BIG, np.float32)
        rb_guard = np.full(max(n_rbac, 1), TRUE_COL, np.int32)
        rb_allow = np.full((max(n_rbac, 1), k_allow), FALSE_COL,
                           np.int32)
        for i, r in enumerate(rbacs):
            rb_rule[i] = r.rule
            rb_dur[i] = r.valid_duration_s
            if r.guard_row >= 0:
                rb_guard[i] = r.guard_row
            for s, row in enumerate(r.allow_rows):
                rb_allow[i, s] = row

        # --- quota tensors ---
        n_quotas = len(quotas)
        q_rule = np.zeros(max(n_quotas, 1), np.int32)
        q_slot = np.zeros(max(n_quotas, 1), np.int32)
        q_max = np.zeros(max(n_quotas, 1), np.int32)
        q_nb = np.ones(max(n_quotas, 1), np.int32)
        n_buckets = max((q.n_buckets for q in quotas), default=1)
        if n_quotas * n_buckets >= np.iinfo(np.int32).max:
            raise ValueError(
                f"quota hash space too large: {n_quotas} quotas × "
                f"{n_buckets} buckets must stay below 2^31-1 (int32 "
                "composite sort keys)")
        self._quota_slots = frozenset(
            self._slot_for(q.key_attr) for q in quotas)
        for i, q in enumerate(quotas):
            q_rule[i] = q.rule
            q_slot[i] = self._slot_for(q.key_attr)
            q_max[i] = q.max_amount
            q_nb[i] = q.n_buckets   # per-quota hash space (counter rows
            #                         are padded to the widest quota)
        self.quota_counts = jnp.zeros((max(n_quotas, 1), n_buckets),
                                      jnp.int32)
        self._has_quota = n_quotas > 0

        ruleset_run = self.ruleset.fn   # fn(ruleset_params, batch)
        # referenced-attr literal mask rides as BIT LANES (pack_bits)
        # and unpacks to int8 on device once per step — the [R, C]
        # int8 mask at 50k rules was MBs of resident weight for one
        # bit of information per cell
        from istio_tpu.ops.bytes_ops import pack_bits
        n_attr_cols = int(self.ruleset.attr_mask.shape[1])
        attr_mask_bits = jnp.asarray(pack_bits(self.ruleset.attr_mask))
        rule_ns = jnp.asarray(self.ruleset.rule_ns)
        default_ns = self.ruleset.ns_ids[""]
        deny_mask_j = jnp.asarray(deny_mask)
        deny_status_j = jnp.asarray(deny_status)
        deny_dur_j = jnp.asarray(deny_dur)
        deny_uses_j = jnp.asarray(deny_uses)
        has_lists = n_lists > 0
        max_len = self.ruleset.layout.max_str_len
        list_ids_j = jnp.asarray(list_ids)
        list_rule_j = jnp.asarray(list_rule)
        list_slot_j = jnp.asarray(list_slot)
        list_black_j = jnp.asarray(list_black)
        list_code_j = jnp.asarray(list_code)
        list_dur_j = jnp.asarray(list_dur)
        list_uses_j = jnp.asarray(list_uses)
        q_rule_j = jnp.asarray(q_rule)
        q_slot_j = jnp.asarray(q_slot)
        q_max_j = jnp.asarray(q_max)
        q_nb_j = jnp.asarray(q_nb)
        has_rbac = n_rbac > 0
        rb_rule_j = jnp.asarray(rb_rule)
        rb_dur_j = jnp.asarray(rb_dur)
        rb_guard_j = jnp.asarray(rb_guard)
        rb_allow_j = jnp.asarray(rb_allow)
        err_rule_mask_j = None if err_rule_mask is None \
            else jnp.asarray(err_rule_mask)
        dims = (((1,), (0,)), ((), ()))

        # Value-carrying bank tensors ride in PARAMS (traced
        # arguments), never as closure constants: intern ids and
        # config values (status codes, TTLs, list membership ids,
        # quota limits, per-rule namespaces) change under config
        # deltas without changing any shape, and baking them into the
        # HLO would change the compiled program's identity — defeating
        # jax's jit cache across swaps and the persistent compilation
        # cache across restarts (compiler/cache.py: a constant-only
        # config edit must keep every HLO bit-identical). Only
        # structure-bearing banks (packed regex DFAs, CIDR tables)
        # stay closure-bound — editing those changes shapes, which is
        # a legitimate recompile.
        pe_params = {
            "pe_rule_ns": rule_ns,
            "pe_attr_mask_bits": attr_mask_bits,
            "pe_deny_mask": deny_mask_j,
            "pe_deny_status": deny_status_j,
            "pe_deny_dur": deny_dur_j,
            "pe_deny_uses": deny_uses_j,
            "pe_list_ids": list_ids_j,
            "pe_list_rule": list_rule_j,
            "pe_list_slot": list_slot_j,
            "pe_list_black": list_black_j,
            "pe_list_code": list_code_j,
            "pe_list_dur": list_dur_j,
            "pe_list_uses": list_uses_j,
            "pe_q_rule": q_rule_j,
            "pe_q_slot": q_slot_j,
            "pe_q_max": q_max_j,
            "pe_q_nb": q_nb_j,
            "pe_rb_rule": rb_rule_j,
            "pe_rb_dur": rb_dur_j,
            "pe_rb_guard": rb_guard_j,
            "pe_rb_allow": rb_allow_j,
        }
        if err_rule_mask_j is not None:
            pe_params["pe_err_rule_mask"] = err_rule_mask_j

        def step(params: Any, batch: AttributeBatch, req_ns: Any,
                 quota_counts: Any):
            rule_ns = params["pe_rule_ns"]
            attr_mask_bits = params["pe_attr_mask_bits"]
            deny_mask_j = params["pe_deny_mask"]
            deny_status_j = params["pe_deny_status"]
            deny_dur_j = params["pe_deny_dur"]
            deny_uses_j = params["pe_deny_uses"]
            list_ids_j = params["pe_list_ids"]
            list_rule_j = params["pe_list_rule"]
            list_slot_j = params["pe_list_slot"]
            list_black_j = params["pe_list_black"]
            list_code_j = params["pe_list_code"]
            list_dur_j = params["pe_list_dur"]
            list_uses_j = params["pe_list_uses"]
            q_rule_j = params["pe_q_rule"]
            q_slot_j = params["pe_q_slot"]
            q_max_j = params["pe_q_max"]
            q_nb_j = params["pe_q_nb"]
            rb_rule_j = params["pe_rb_rule"]
            rb_dur_j = params["pe_rb_dur"]
            rb_guard_j = params["pe_rb_guard"]
            rb_allow_j = params["pe_rb_allow"]
            err_rule_mask_j = params.get("pe_err_rule_mask")
            b = batch.ids.shape[0]
            matched, not_matched, err = ruleset_run(params, batch)
            ns_ok = (rule_ns[None, :] == default_ns) | \
                    (rule_ns[None, :] == req_ns[:, None])
            active = matched & ns_ok                      # [B, R]

            # Status combining is LOWEST-RULE-INDEX-WINS, the same
            # deterministic rule as the host dispatcher (_combine keeps
            # the first non-OK result, and the host iterates rules in
            # ascending index order). google.rpc codes are not
            # severity-ordered, so a max() over codes would diverge from
            # the host path on multi-deny requests. Ties within one rule
            # resolve deny → list → quota. TTLs take the min over every
            # ACTIVE fused rule (dispatcher.go:322 semantics).
            BIGI = jnp.iinfo(jnp.int32).max
            rule_idx = jnp.arange(active.shape[1], dtype=jnp.int32)

            dmask = active & deny_mask_j[None, :]
            d_key = jnp.where(dmask, rule_idx[None, :], BIGI)
            d_arg = jnp.argmin(d_key, axis=1)
            cand_rule = jnp.min(d_key, axis=1)
            cand_status = deny_status_j[d_arg]
            dur = jnp.min(jnp.where(dmask, deny_dur_j[None, :], _BIG), axis=1)
            uses = jnp.min(jnp.where(dmask, deny_uses_j[None, :],
                                     np.iinfo(np.int32).max), axis=1)

            if has_lists:
                sym = batch.ids[:, list_slot_j]           # [B, L]
                sym_ok = batch.present[:, list_slot_j]
                member = jnp.any(
                    sym[:, :, None] == list_ids_j[None, :, :], axis=2)
                # und exists ONLY when regex banks do: the err
                # scatter-max below is a [B, R]-operand scatter, and
                # running it with an identically-False mask faulted
                # the TPU at 50k rules (r4 regression; XLA kernel
                # fault) while buying nothing
                und = jnp.zeros_like(member) if rx_banks else None
                for bank in rx_banks:
                    # one packed DFA scan per value byte slot answers
                    # every REGEX list over that subject. MXU one-hot
                    # formulations win at EVERY batch size (profiled
                    # r4/r5: the per-step [B, N] gather is latency-
                    # bound regardless of B — it alone held the B=64
                    # latency tier over the 1ms budget)
                    s_data = batch.str_bytes[:, bank["bslot"]]
                    s_lens = batch.str_lens[:, bank["bslot"]]
                    if bank["packed"] is not None:
                        m = bytes_ops.dfa_match_many_onehot(
                            s_data, s_lens, bank["packed"])
                    elif bank["packed_blk"] is not None:
                        m = bytes_ops.dfa_match_many_onehot_blocked(
                            s_data, s_lens, bank["packed_blk"])
                    else:
                        m = bytes_ops.dfa_match_many(
                            s_data, s_lens, bank["trans"],
                            bank["accept"])
                    m8 = m.astype(jnp.int8)
                    hit = lax.dot_general(
                        m8, bank["M"], dims,
                        preferred_element_type=jnp.int32) > 0
                    dec = lax.dot_general(
                        m8, bank["M_def"], dims,
                        preferred_element_type=jnp.int32) > 0
                    # truncation contract (= byte predicates): a $-free
                    # prefix hit is definitive; anything else on a
                    # truncated value is undecidable → err the rule's
                    # row, suppress the deny (fail-open, counted)
                    trunc = (s_lens >= max_len)[:, None]
                    member = member.at[:, bank["pos"]].set(
                        jnp.where(trunc, dec, hit))
                    und = und.at[:, bank["pos"]].set(trunc & ~dec)
                bad = None        # present-but-unusable values
                if cidr_bank is not None:
                    vb = batch.str_bytes[:, cidr_bank["bslots"], :16]
                    vl = batch.str_lens[:, cidr_bank["bslots"]]
                    mapped = jnp.zeros_like(vb)
                    mapped = mapped.at[:, :, 10:12].set(255)
                    mapped = mapped.at[:, :, 12:16].set(vb[:, :, 0:4])
                    is4 = vl == 4
                    v6m_pre = jnp.concatenate(
                        [jnp.zeros(10, jnp.uint8),
                         jnp.full(2, 255, jnp.uint8)])
                    val_mapped = jnp.all(
                        vb[:, :, :12] == v6m_pre[None, None, :], axis=2)
                    v = jnp.where(is4[:, :, None], mapped, vb)
                    val_ok = is4 | (vl == 16)
                    val_v4 = is4 | ((vl == 16) & val_mapped)
                    hit_e = jnp.all(
                        (v[:, :, None, :] & cidr_bank["mask"][None]) ==
                        cidr_bank["prefix"][None], axis=3)
                    hit_e &= cidr_bank["valid"][None]
                    hit_e &= (val_v4[:, :, None] ==
                              cidr_bank["ent_v4"][None])
                    member = member.at[:, cidr_bank["pos"]].set(
                        jnp.any(hit_e, axis=2) & val_ok)
                    # malformed present IP bytes (length not 4/16):
                    # the host adapter raises before membership →
                    # INTERNAL (handle_check's bytes normalization)
                    bad = jnp.zeros_like(member).at[
                        :, cidr_bank["pos"]].set(~val_ok)
                # host parity for unusable values: an ACTIVE list rule
                # whose value is absent (instance build EvalError) or
                # malformed takes the _safe_check INTERNAL path — the
                # device must not silently fail open
                l_rule_act = active[:, list_rule_j]
                l_internal = l_rule_act & ~sym_ok
                l_eval = l_rule_act & sym_ok
                if bad is not None:
                    l_internal |= l_rule_act & sym_ok & bad
                    l_eval &= ~bad
                if und is not None:
                    l_eval &= ~und
                    err = err.at[:, list_rule_j].max(und)
                l_hit = l_internal | (
                    l_eval & (member == list_black_j[None, :]))
                l_key = jnp.where(l_hit, list_rule_j[None, :], BIGI)
                l_arg = jnp.argmin(l_key, axis=1)
                l_rule = jnp.min(l_key, axis=1)
                winner_internal = jnp.take_along_axis(
                    l_internal, l_arg[:, None], axis=1)[:, 0]
                take_l = l_rule < cand_rule     # strict: deny wins ties
                cand_status = jnp.where(
                    take_l,
                    jnp.where(winner_internal, INTERNAL,
                              list_code_j[l_arg]),
                    cand_status)
                cand_rule = jnp.minimum(cand_rule, l_rule)
                dur = jnp.minimum(dur, jnp.min(
                    jnp.where(l_eval, list_dur_j[None, :], _BIG), axis=1))
                uses = jnp.minimum(uses, jnp.min(
                    jnp.where(l_eval, list_uses_j[None, :],
                              np.iinfo(np.int32).max), axis=1))
                # an INTERNAL result carries the CheckResult DEFAULTS
                # into the TTL min (host _combine parity)
                any_internal = jnp.any(l_internal, axis=1)
                dur = jnp.where(any_internal,
                                jnp.minimum(dur, DEFAULT_DUR), dur)
                uses = jnp.where(any_internal,
                                 jnp.minimum(uses, DEFAULT_USES), uses)

            if has_rbac:
                # allowed iff ANY lowered (binding, subject, role-rule)
                # pseudo-rule matched; guard row not definitely-true →
                # the host instance build would have errored → INTERNAL
                # (rbac.go:181 + dispatcher _safe_check parity)
                m_ext = jnp.concatenate(
                    [matched, jnp.zeros((b, 1), bool),
                     jnp.ones((b, 1), bool)], axis=1)
                allow = jnp.any(m_ext[:, rb_allow_j], axis=2)
                guard_ok = m_ext[:, rb_guard_j]
                r_active = active[:, rb_rule_j]
                r_deny = r_active & guard_ok & ~allow
                r_bad = r_deny | (r_active & ~guard_ok)
                rb_key = jnp.where(r_bad, rb_rule_j[None, :], BIGI)
                rb_arg = jnp.argmin(rb_key, axis=1)
                rb_rule_min = jnp.min(rb_key, axis=1)
                rb_status = jnp.where(
                    jnp.take_along_axis(r_deny, rb_arg[:, None],
                                        axis=1)[:, 0],
                    PERMISSION_DENIED, INTERNAL)
                take_rb = rb_rule_min < cand_rule   # deny/list win ties
                cand_status = jnp.where(take_rb, rb_status, cand_status)
                cand_rule = jnp.minimum(cand_rule, rb_rule_min)
                # the handler returns caching_ttl on allow AND deny
                # verdicts alike; on INTERNAL the host CheckResult
                # carries only defaults (no-op under min) — skip it
                dur = jnp.minimum(dur, jnp.min(
                    jnp.where(r_active & guard_ok, rb_dur_j[None, :],
                              _BIG), axis=1))
            status = jnp.where(cand_rule < BIGI, cand_status, OK)

            if self._has_quota:
                # bucket = stable content hash mod hash space; fixed
                # window. Uses hash_ids, not ids: ephemeral ids vary
                # with encounter order while the counter window
                # persists across batches. Quota is dispatched only
                # when the precondition check passed
                # (grpcServer.go:188-230 runs the quota loop after a
                # successful Check) — denied requests must not consume
                # tokens.
                key = batch.hash_ids[:, q_slot_j]         # [B, Q]
                key_ok = batch.present[:, q_slot_j]
                q_active = active[:, q_rule_j] & key_ok & \
                    (status == OK)[:, None]               # [B, Q]
                bucket = (key % q_nb_j[None, :]).astype(jnp.int32)
                # sequential-within-batch grant: request i granted iff
                # prior_count + its rank among same-bucket active peers
                # < max. One flattened stable sort over [Q·B] composite
                # keys ranks every quota at once (the naive [B, B, Q]
                # pairwise compare cost 8ms/step at B=2048).
                # composite int32 keys; the inactive sentinel INT32_MAX
                # sorts past every real key (constructor bounds
                # n_quotas·n_buckets < INT32_MAX — jnp has no int64
                # without x64 mode)
                n_q = quota_counts.shape[0]
                qoff = jnp.arange(n_q, dtype=jnp.int32)[None, :] * \
                    quota_counts.shape[1]
                ckey = jnp.where(q_active, bucket + qoff,
                                 jnp.iinfo(jnp.int32).max)
                if b <= 256:
                    # latency tier: the flattened sort costs ~0.2ms of
                    # fixed latency; a strict-lower-triangle pairwise
                    # count is B²·Q trivial compares at small static B
                    eq = ckey[None, :, :] == ckey[:, None, :]  # [B,B,Q]
                    lower = (jnp.arange(b)[None, :] <
                             jnp.arange(b)[:, None])[:, :, None]
                    rank = jnp.sum(eq & lower, axis=1,
                                   dtype=jnp.int32)            # [B, Q]
                else:
                    rank = _batch_rank(
                        ckey.T.reshape(-1)).reshape(n_q, b).T
                prior_per_req = quota_counts[
                    jnp.arange(n_q)[None, :], bucket]            # [B, Q]
                granted = q_active & (prior_per_req + rank < q_max_j[None, :])
                over = q_active & ~granted
                # quota only runs where status is still OK (q_active
                # gating above), so a RESOURCE_EXHAUSTED here is always
                # the lowest-index non-OK source for that request
                any_over = jnp.any(over, axis=1)
                status = jnp.where(any_over, RESOURCE_EXHAUSTED, status)
                cand_rule = jnp.where(
                    any_over,
                    jnp.min(jnp.where(over, q_rule_j[None, :], BIGI),
                            axis=1),
                    cand_rule)
                # commit grants: scatter-add per (quota, bucket)
                flat = bucket + jnp.arange(bucket.shape[1])[None, :] * \
                    quota_counts.shape[1]
                add = jnp.zeros(quota_counts.size, jnp.int32).at[
                    flat.reshape(-1)].add(
                        granted.astype(jnp.int32).reshape(-1))
                quota_counts = quota_counts + add.reshape(quota_counts.shape)

            attr_mask = bytes_ops.unpack_bits(
                attr_mask_bits, n_attr_cols).astype(jnp.int8)
            referenced = lax.dot_general(
                ns_ok.astype(jnp.int8), attr_mask, dims,
                preferred_element_type=jnp.int32) > 0
            verdict = CheckVerdict(status=status.astype(jnp.int32),
                                   valid_duration_s=dur,
                                   valid_use_count=uses,
                                   referenced=referenced,
                                   matched=matched, err=err,
                                   deny_rule=jnp.where(
                                       status == OK, BIGI, cand_rule),
                                   err_count=jnp.sum(
                                       ((err & ns_ok) if err_rule_mask_j
                                        is None else
                                        (err & ns_ok &
                                         err_rule_mask_j[None, :]))
                                       .astype(jnp.int32)))
            return verdict, quota_counts

        # ---- compiled-shape geometry for the roofline accounting
        # layer (compiler/roofline.py): every entry derives from the
        # ACTUAL device tensors built above, never hand constants
        def _banks_geom() -> list:
            out = []
            for bank in rx_banks:
                g = {"m_bytes": int(bank["M"].nbytes)
                     + int(bank["M_def"].nbytes),
                     "n_lists": int(bank["M"].shape[1])}
                if bank["packed"] is not None:
                    p = bank["packed"]
                    g.update(kind="dense", s_tot=int(p["n_states"]),
                             n_cls=int(p["n_classes"]),
                             step_bytes=int(p["step_bits"].nbytes),
                             n_pats=int(p["accept"].shape[1]))
                elif bank["packed_blk"] is not None:
                    p = bank["packed_blk"]
                    g.update(kind="blocked",
                             s_max=int(p["n_states_max"]),
                             n_cls=int(p["n_classes"]),
                             step_bytes=int(p["step_bits"].nbytes),
                             n_pats=int(p["n_pats"]))
                else:
                    g.update(kind="gather",
                             step_bytes=int(bank["trans"].nbytes),
                             n_pats=int(bank["trans"].shape[0]),
                             s_max=int(bank["trans"].shape[1]))
                out.append(g)
            return out

        self.geometry = {
            "n_rows": R,
            "n_deny": len(deny),
            "deny_bytes": int(deny_mask_j.nbytes + deny_status_j.nbytes
                              + deny_dur_j.nbytes + deny_uses_j.nbytes),
            "n_lists": n_lists,
            "list_max_entries": int(list_ids.shape[1]),
            "list_table_bytes": int(list_ids_j.nbytes)
            if has_lists else 0,
            "rx_banks": _banks_geom(),
            "cidr_entries": 0 if cidr_bank is None else
            int(cidr_bank["prefix"].shape[0]
                * cidr_bank["prefix"].shape[1]),
            "cidr_bytes": 0 if cidr_bank is None else
            int(cidr_bank["prefix"].nbytes + cidr_bank["mask"].nbytes),
            "n_rbac": n_rbac,
            "rbac_k_allow": k_allow,
            "n_quotas": n_quotas,
            "quota_buckets": int(n_buckets),
            "attr_mask_bits_bytes": int(attr_mask_bits.nbytes),
            "n_attr_cols": n_attr_cols,
        }

        self.raw_step = step   # unjitted: for entry()/sharded wrappers
        # ruleset index tensors + the engine bank tensors above — one
        # argument pytree every step entry (jit, sharded, bench)
        # passes through; parallel/mesh.param_shardings replicates
        # unknown keys, so the pe_* banks need no policy entry there
        self.params = {**self.ruleset.params, **pe_params}
        # donate the quota buffer only when quota state actually
        # threads through the step: donation invalidates the input
        # buffer, which breaks concurrent (pipelined) batches that all
        # read the same dummy counts array
        donate = (3,) if self._has_quota else ()
        self._step = jax.jit(step, donate_argnums=donate) if jit else step

    def _slot_for(self, attr: Any) -> int:
        lay = self.ruleset.layout
        if isinstance(attr, tuple):
            if attr not in lay.derived_slots:
                raise ValueError(f"no derived slot for {attr}; reference it "
                                 "in a rule or add it to derived_keys")
            return lay.derived_slots[attr]
        return lay.slot_of(attr)

    def _byte_slot_for(self, l: ListEntrySpec) -> int:
        bslot = self.ruleset.layout.byte_slots.get(l.value_attr)
        if bslot is None:
            raise ValueError(
                f"{l.entry_type} list value {l.value_attr!r} has no byte "
                "slot; pass it via compile_ruleset(extra_byte_sources=...)")
        return bslot

    def _build_regex_banks(self, lists: Sequence[ListEntrySpec]) -> list:
        """REGEX lists grouped by value byte slot → one packed DFA bank
        per slot; patterns deduplicated within a bank (1,000 rules
        sharing one handler share ONE DFA, not 1,000). Raises
        UnsupportedRegex for patterns outside the DFA subset — callers
        (runtime/fused.py) gate fusability on that."""
        from istio_tpu.ops.regex_dfa import (compile_regex,
                                             pack_dfas_tiered)

        groups: dict[int, dict] = {}
        for i, l in enumerate(lists):
            if l.entry_type != "REGEX":
                continue
            bslot = self._byte_slot_for(l)
            g = groups.setdefault(bslot, {"pat_idx": {}, "dfas": [],
                                          "dollar": [], "lists": []})
            idxs = []
            for e in l.entries:
                e = str(e)
                j = g["pat_idx"].get(e)
                if j is None:
                    j = len(g["dfas"])
                    g["pat_idx"][e] = j
                    g["dfas"].append(compile_regex(e))
                    g["dollar"].append("$" in e)
                idxs.append(j)
            g["lists"].append((i, idxs))
        banks = []
        for bslot in sorted(groups):
            g = groups[bslot]
            tiers = pack_dfas_tiered(g["dfas"])
            dollar = np.asarray(g["dollar"], bool)
            # [n_pats, n_lists_in_bank] membership, transposed for
            # dot_general; M_def keeps only $-free patterns (whose
            # prefix hits are definitive on truncated values)
            m = np.zeros((len(g["dfas"]), len(g["lists"])), np.int8)
            for r, (_, idxs) in enumerate(g["lists"]):
                m[idxs, r] = 1
            banks.append({
                "bslot": bslot,
                "trans": None if tiers["trans"] is None
                else jnp.asarray(tiers["trans"]),
                "accept": None if tiers["accept"] is None
                else jnp.asarray(tiers["accept"]),
                "packed": tiers["packed"],
                "packed_blk": tiers["packed_blk"],
                "M": jnp.asarray(m),
                "M_def": jnp.asarray(m * (~dollar[:, None])),
                "pos": jnp.asarray([i for i, _ in g["lists"]],
                                   jnp.int32),
            })
        return banks

    def _build_cidr_bank(self, lists: Sequence[ListEntrySpec]):
        """IP_ADDRESSES lists → per-entry (prefix, mask) byte planes in
        v6-mapped space. v4 nets map to ::ffff:0:0/96+len; membership
        additionally requires the value's v4/v6 version to equal the
        entry's (ipaddress `addr in net` is version-strict — host
        parity with list_adapter._member)."""
        import ipaddress

        items = [(i, l) for i, l in enumerate(lists)
                 if l.entry_type == "IP_ADDRESSES"]
        if not items:
            return None
        n_c = len(items)
        e_max = max((len(l.entries) for _, l in items), default=1) or 1
        prefix = np.zeros((n_c, e_max, 16), np.uint8)
        mask = np.zeros((n_c, e_max, 16), np.uint8)
        valid = np.zeros((n_c, e_max), bool)
        ent_v4 = np.zeros((n_c, e_max), bool)
        bslots = np.zeros(n_c, np.int32)
        pos = np.zeros(n_c, np.int32)
        for r, (i, l) in enumerate(items):
            bslots[r] = self._byte_slot_for(l)
            pos[r] = i
            for e_i, e in enumerate(l.entries):
                net = ipaddress.ip_network(str(e), strict=False)
                if net.version == 4:
                    plen = net.prefixlen + 96
                    addr = (b"\x00" * 10 + b"\xff\xff" +
                            net.network_address.packed)
                    ent_v4[r, e_i] = True
                else:
                    plen = net.prefixlen
                    addr = net.network_address.packed
                m_int = (((1 << plen) - 1) << (128 - plen)) if plen else 0
                mbytes = m_int.to_bytes(16, "big")
                prefix[r, e_i] = np.frombuffer(
                    bytes(a & mm for a, mm in zip(addr, mbytes)),
                    np.uint8)
                mask[r, e_i] = np.frombuffer(mbytes, np.uint8)
                valid[r, e_i] = True
        return {"prefix": jnp.asarray(prefix), "mask": jnp.asarray(mask),
                "valid": jnp.asarray(valid),
                "ent_v4": jnp.asarray(ent_v4),
                "bslots": jnp.asarray(bslots),
                "pos": jnp.asarray(pos)}

    # ------------------------------------------------------------------
    def check(self, batch: AttributeBatch, req_ns: Any) -> CheckVerdict:
        """NOTE: with device quotas this is a read-modify-write on
        quota_counts and must not run concurrently; the quota-free
        serving engine (runtime/fused.py) is safe under the batcher's
        pipelined workers."""
        verdict, counts = self._step(self.params, batch, req_ns,
                                     self.quota_counts)
        if self._has_quota:
            self.quota_counts = counts
        return verdict

    def reset_quota(self) -> None:
        """New quota window (the runtime calls this on a timer —
        memquota's window roll)."""
        self.quota_counts = jnp.zeros_like(self.quota_counts)

    @property
    def tensorizer(self) -> Tensorizer:
        # hash exactly the quota key slots — the only consumers of the
        # stable-hash plane (hashing every cell costs ~10× the
        # tensorize itself in Python)
        return Tensorizer(self.ruleset.layout, self.ruleset.interner,
                          hash_slots=self._quota_slots)
