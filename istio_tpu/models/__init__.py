"""Flagship fused policy-engine models.

`PolicyEngine` is the TPU replacement for the reference's entire Mixer
Check() hot path (SURVEY.md §3.1): resolver rule filtering + template
instance construction + check-adapter verdicts, fused into ONE jitted
device step over a request batch.
"""
from istio_tpu.models.policy_engine import (CheckVerdict, DenySpec,
                                            ListEntrySpec, PolicyEngine,
                                            QuotaSpec)

__all__ = ["PolicyEngine", "CheckVerdict", "DenySpec", "ListEntrySpec",
           "QuotaSpec"]
