"""Structured findings — the analyzer's output contract.

Every check in istio_tpu/analysis emits Finding records so the three
consumers (the `analyze` CLI, the admission hook, the introspect
/debug/analysis view) and CI gates share one severity/shape vocabulary
instead of parsing prose. Network-config practice (Batfish answer
rows) is the model: a finding names WHAT is wrong (code), HOW bad
(severity), WHERE (rule ids), and — for semantic claims like overlap —
a concrete WITNESS input that reproduces it through the oracle.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Mapping


class Severity(enum.IntEnum):
    """Ordered so gates can threshold (`sev >= WARNING`)."""
    INFO = 0       # advisory: host fallback, non-total predicate
    WARNING = 1    # degraded/suspicious but serveable config
    ERROR = 2      # wrong by construction: reject before device compile


# finding codes — single vocabulary across checks, tests and gates
TYPE_ERROR = "type-error"              # ill-typed / unknown attr / arity
NON_TOTAL = "non-total-predicate"      # can evaluate to error at runtime
SHADOWED_RULE = "shadowed-rule"        # fully covered by another rule
ALLOW_DENY_CONFLICT = "allow-deny-conflict"
SHADOWED_ROUTE = "shadowed-route"      # route row that can never win
STATE_BUDGET = "state-budget"          # regex DFA exceeds the state cap
DNF_BUDGET = "dnf-budget"              # predicate DNF past dnf_cap
TILE_BUDGET = "tile-budget"            # index tensors past device budget
BANK_BUDGET = "dfa-bank-budget"        # regex bank past one-hot tiers
PLANE_DIVERGENCE = "plane-divergence"  # pilot vs mixer disagree
PLANE_UNPROVEN = "plane-unproven"      # equivalence not established
HOST_FALLBACK = "host-fallback"        # rule serves via the CPU oracle
ANALYSIS_TRUNCATED = "analysis-truncated"
CONFIG_ERROR = "config-error"          # snapshot builder soft error


@dataclasses.dataclass
class Finding:
    """One analysis verdict.

    `witness` is an attribute-bag mapping (attr → value; string-map
    attrs map to dicts) that REPRODUCES the claim when replayed through
    expr/oracle.py — mandatory for overlap/divergence findings, set
    whenever derivable otherwise. `confirmed` records that the analyzer
    itself replayed the witness before reporting (candidate findings
    that fail replay are dropped, never reported)."""
    code: str
    severity: Severity
    message: str
    rules: tuple[str, ...] = ()
    witness: Mapping[str, Any] | None = None
    confirmed: bool = False

    def to_dict(self) -> dict:
        return {"code": self.code,
                "severity": self.severity.name,
                "message": self.message,
                "rules": list(self.rules),
                "witness": dict(self.witness)
                if self.witness is not None else None,
                "confirmed": self.confirmed}


@dataclasses.dataclass
class AnalysisReport:
    """A whole snapshot's findings plus the stats gates key on."""
    findings: list[Finding] = dataclasses.field(default_factory=list)
    n_rules: int = 0
    wall_ms: float = 0.0
    truncated: bool = False

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings) -> None:
        self.findings.extend(findings)

    def by_severity(self, sev: Severity) -> list[Finding]:
        return [f for f in self.findings if f.severity == sev]

    @property
    def errors(self) -> list[Finding]:
        return self.by_severity(Severity.ERROR)

    @property
    def warnings(self) -> list[Finding]:
        return self.by_severity(Severity.WARNING)

    @property
    def has_errors(self) -> bool:
        return any(f.severity == Severity.ERROR for f in self.findings)

    def codes(self) -> set[str]:
        return {f.code for f in self.findings}

    def to_dict(self) -> dict:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.code] = counts.get(f.code, 0) + 1
        return {"n_rules": self.n_rules,
                "wall_ms": round(self.wall_ms, 3),
                "truncated": self.truncated,
                "n_errors": len(self.errors),
                "n_warnings": len(self.warnings),
                "counts_by_code": counts,
                "findings": [f.to_dict() for f in self.findings]}
