"""Rule reachability, shadowing and ALLOW/DENY conflict analysis.

Batfish-style reasoning specialized to the mesh's predicate language:
every rule's match clause decomposes into the compiler's own monotone
M/N DNFs over primitive atoms (`compiler/ruleset._decompose` — the
exact structure the device executes), and pairwise claims reduce to
conjunction-level implication / disjointness over `analysis/atoms`
semantics, with regex/prefix/glob literals decided by product-DFA
construction on `ops/regex_dfa` transition tensors.

Soundness contract: a SHADOW claim is proof-based (DNF implication —
universally quantified) plus a non-vacuity witness; an OVERLAP claim
(allow/deny conflict) is witness-based only — a candidate pair that
cannot produce a bag on which BOTH predicates oracle-evaluate True is
never reported. False positives are structurally excluded; missed
findings (opaque atoms, budget exhaustion) are the accepted trade.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

from istio_tpu.analysis import atoms as A
from istio_tpu.analysis.findings import (ALLOW_DENY_CONFLICT,
                                         ANALYSIS_TRUNCATED, Finding,
                                         NON_TOTAL, SHADOWED_ROUTE,
                                         SHADOWED_RULE, Severity)
from istio_tpu.attribute.bag import DictBag
from istio_tpu.compiler.ruleset import (DEFAULT_DNF_CAP, _AtomTable,
                                        _decompose)
from istio_tpu.compiler.tensor_expr import HostFallback
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.expr.exprs import Expression
from istio_tpu.expr.oracle import OracleProgram

DEFAULT_PAIR_CHECK_BUDGET = 250_000
IDENTITY_ATTR = "destination.service"


@dataclasses.dataclass
class PredInfo:
    """One rule's analyzable form."""
    index: int
    name: str
    namespace: str
    ast: Expression
    m_dnf: list[list[tuple[int, str]]] | None   # None = not decomposable
    # fast pruning map per conjunction: positive-eq subject → value
    eq_maps: list[dict] | None = None


class RuleUniverse:
    """Shared decomposition + atom semantics for a rule list."""

    def __init__(self, rules: Sequence[tuple[str, str, Expression]],
                 finder: AttributeDescriptorFinder,
                 dnf_cap: int = DEFAULT_DNF_CAP):
        self.finder = finder
        self.table = _AtomTable()
        self.preds: list[PredInfo] = []
        self._sem_cache: dict[tuple[int, str], A.AtomSem] = {}
        self._impl_cache: dict[tuple, bool | None] = {}
        self._disj_cache: dict[tuple, bool | None] = {}
        for idx, (name, ns, ast) in enumerate(rules):
            try:
                mark = self.table.mark()
                m, _n = _decompose(ast, self.table, dnf_cap)
                m_dnf = [sorted(conj) for conj in m]
            except HostFallback:
                self.table.revert(mark)
                m_dnf = None
            info = PredInfo(index=idx, name=name, namespace=ns,
                            ast=ast, m_dnf=m_dnf)
            if m_dnf is not None:
                info.eq_maps = [self._eq_map(conj) for conj in m_dnf]
            self.preds.append(info)

    # -- atom-level, memoized --

    def sem(self, lit: tuple[int, str]) -> A.AtomSem:
        cached = self._sem_cache.get(lit)
        if cached is None:
            aidx, kind = lit
            cached = A.atom_sem(self.table.asts[aidx], self.finder)
            if kind == "n":
                cached = A.negate(cached)
            self._sem_cache[lit] = cached
        return cached

    def _eq_map(self, conj) -> dict:
        out = {}
        for lit in conj:
            sem = self.sem(lit)
            if sem.kind == "eq" and not sem.negated \
                    and sem.subject is not None:
                out[sem.subject.id] = sem.value
        return out

    def _lit_implies(self, la, lb) -> bool | None:
        if la == lb:
            return True
        key = (la, lb)
        if key not in self._impl_cache:
            self._impl_cache[key] = A.atom_implies(self.sem(la),
                                                   self.sem(lb))
        return self._impl_cache[key]

    def _lit_disjoint(self, la, lb) -> bool | None:
        key = (min(la, lb), max(la, lb))
        if key not in self._disj_cache:
            self._disj_cache[key] = A.atoms_disjoint(self.sem(la),
                                                     self.sem(lb))
        return self._disj_cache[key]

    # -- conjunction-level --

    def conj_implies(self, ca, cb) -> bool:
        """Proved: every input satisfying ca satisfies cb."""
        for lb in cb:
            if not any(self._lit_implies(la, lb) is True for la in ca):
                return False
        return True

    def conj_disjoint(self, ca, cb) -> bool:
        """Proved: no input satisfies both."""
        for la in ca:
            for lb in cb:
                if self._lit_disjoint(la, lb) is True:
                    return True
        return False

    # -- rule-level --

    def shadows(self, i: int, j: int) -> bool:
        """Proved: every input matching rule j also matches rule i
        (predicate inclusion; namespace visibility checked by caller)."""
        pi, pj = self.preds[i], self.preds[j]
        if pi.m_dnf is None or pj.m_dnf is None or not pj.m_dnf:
            return False
        for cj in pj.m_dnf:
            if not any(self.conj_implies(cj, ci) for ci in pi.m_dnf):
                return False
        return True

    def overlap_candidates(self, i: int, j: int):
        """Conjunction pairs not provably disjoint, cheapest-first —
        witness construction order for overlap confirmation."""
        pi, pj = self.preds[i], self.preds[j]
        if pi.m_dnf is None or pj.m_dnf is None:
            return
        for a, ci in enumerate(pi.m_dnf):
            for b, cj in enumerate(pj.m_dnf):
                em_i, em_j = pi.eq_maps[a], pj.eq_maps[b]
                if any(em_j.get(k, v) != v for k, v in em_i.items()):
                    continue              # eq constants clash
                if self.conj_disjoint(ci, cj):
                    continue
                yield ci, cj

    # -- witnesses --

    def witness_for(self, conjs: Sequence[Sequence[tuple[int, str]]]
                    ) -> dict[str, Any] | None:
        """Attribute bag satisfying the UNION of the conjunctions, or
        None (unsat / unknown)."""
        sems = [self.sem(lit) for conj in conjs for lit in conj]
        try:
            return A.solve_subjects(sems, self.finder)
        except (A.WitnessUnsat, A.WitnessUnknown):
            return None

    def confirm(self, bag: dict[str, Any], *indices: int) -> bool:
        """Oracle replay: every listed rule's predicate evaluates True
        on the bag AND every rule is namespace-visible to the request
        the bag describes. The final soundness filter before a finding
        ships."""
        ns = _request_ns(bag)
        for idx in indices:
            p = self.preds[idx]
            if p.namespace and p.namespace != ns:
                return False
            try:
                if OracleProgram.from_ast(
                        p.ast, self.finder).evaluate(DictBag(bag)) \
                        is not True:
                    return False
            except Exception:
                return False
        return True

    def pin_namespace(self, bag: dict[str, Any],
                      i: int, j: int) -> dict[str, Any] | None:
        """Make the request's namespace compatible with both rules: if
        neither predicate pinned the identity attribute, synthesize
        one addressed to the (single) non-default namespace."""
        ns_i, ns_j = self.preds[i].namespace, self.preds[j].namespace
        specific = {ns for ns in (ns_i, ns_j) if ns}
        if len(specific) > 1 and ns_i != ns_j:
            return None
        if IDENTITY_ATTR in bag:
            return bag
        if specific:
            ns = next(iter(specific))
            bag = dict(bag)
            bag[IDENTITY_ATTR] = f"analyzer.{ns}.svc.cluster.local"
        return bag


def _request_ns(bag: dict[str, Any]) -> str:
    v = bag.get(IDENTITY_ATTR)
    if not isinstance(v, str):
        return ""
    parts = v.split(".")
    return parts[1] if len(parts) >= 2 and parts[1] else ""


def _ns_covers(ns_i: str, ns_j: str) -> bool:
    """Rule i visible whenever rule j is."""
    return ns_i == "" or ns_i == ns_j


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def find_shadowed(uni: RuleUniverse,
                  eligible: Callable[[int, int], bool],
                  *, code: str = SHADOWED_RULE,
                  weight: Sequence[int] | None = None,
                  pair_budget: int = DEFAULT_PAIR_CHECK_BUDGET
                  ) -> tuple[list[Finding], bool]:
    """Rules fully covered by another rule.

    `eligible(i, j)` gates which ordered pairs are semantically
    shadow-capable (same deny action, earlier config order, ...);
    `weight` switches to route semantics: i shadows j only when
    weight[i] > weight[j] (higher-precedence rule always wins).
    Returns (findings, truncated)."""
    out: list[Finding] = []
    checked = 0
    truncated = False
    n = len(uni.preds)
    shadowed: set[int] = set()
    for j in range(n):
        if uni.preds[j].m_dnf is None:
            continue
        for i in range(n):
            if i == j or j in shadowed:
                continue
            if weight is not None and weight[i] <= weight[j]:
                continue
            if weight is None and i > j:
                continue     # report against the earlier rule only
            if not _ns_covers(uni.preds[i].namespace,
                              uni.preds[j].namespace):
                continue
            if not eligible(i, j):
                continue
            checked += 1
            if checked > pair_budget:
                truncated = True
                break
            if not uni.shadows(i, j):
                continue
            # non-vacuity witness: a bag rule j actually matches
            # (and therefore rule i matches too)
            bag = None
            for cj in uni.preds[j].m_dnf:
                bag = uni.witness_for([cj])
                if bag is None:
                    continue
                bag = uni.pin_namespace(bag, i, j)
                if bag is not None and uni.confirm(bag, i, j):
                    break
                bag = None
            if bag is None:
                continue          # unsat/unknown: withhold the claim
            pi, pj = uni.preds[i], uni.preds[j]
            out.append(Finding(
                code=code, severity=Severity.ERROR,
                message=(f"rule {pj.name!r} is fully shadowed by "
                         f"{pi.name!r}: every request it matches "
                         f"already matches the covering rule"),
                rules=(pi.name, pj.name), witness=bag, confirmed=True))
            shadowed.add(j)
        if truncated:
            break
    return out, truncated


def find_conflicts(uni: RuleUniverse,
                   deny_idx: Sequence[int], allow_idx: Sequence[int],
                   *, pair_budget: int = DEFAULT_PAIR_CHECK_BUDGET
                   ) -> tuple[list[Finding], bool]:
    """ALLOW/DENY overlaps: a deny rule and an allow(list) rule that
    can match the SAME request — the allow verdict is unreachable for
    the overlap (deny always wins in combineResults), which is policy
    wrong by construction. Witness-confirmed only."""
    out: list[Finding] = []
    checked = 0
    truncated = False
    for d in deny_idx:
        for a in allow_idx:
            if d == a:
                continue     # one rule carrying both is explicit config
            ns_d = uni.preds[d].namespace
            ns_a = uni.preds[a].namespace
            if ns_d and ns_a and ns_d != ns_a:
                continue     # never visible together
            found = False
            for cd, ca in uni.overlap_candidates(d, a):
                checked += 1
                if checked > pair_budget:
                    truncated = True
                    break
                bag = uni.witness_for([cd, ca])
                if bag is None:
                    continue
                bag = uni.pin_namespace(bag, d, a)
                if bag is None or not uni.confirm(bag, d, a):
                    continue
                pd, pa = uni.preds[d], uni.preds[a]
                out.append(Finding(
                    code=ALLOW_DENY_CONFLICT, severity=Severity.ERROR,
                    message=(f"deny rule {pd.name!r} and allow rule "
                             f"{pa.name!r} both match the witness "
                             f"request: the allow verdict is dead for "
                             f"the overlap"),
                    rules=(pd.name, pa.name), witness=bag,
                    confirmed=True))
                found = True
                break
            if found or truncated:
                break
        if truncated:
            break
    if truncated:
        out.append(Finding(
            code=ANALYSIS_TRUNCATED, severity=Severity.INFO,
            message=f"conflict analysis stopped after {checked} "
                    f"conjunction pairs (budget)"))
    return out, truncated


# ---------------------------------------------------------------------------
# totality
# ---------------------------------------------------------------------------

def _hard_refs(e: Expression, soft: bool, out: set) -> None:
    """Attribute references evaluated in HARD context (absence is a
    runtime error, not a fallback) — mirrors oracle.py's nmJmpOnValue
    reach: soft mode covers only Var / INDEX / nested-OR shapes."""
    if e.var is not None:
        if not soft:
            out.add(e.var.name)
        return
    f = e.fn
    if f is None:
        return
    if f.name == "OR":
        _hard_refs(f.args[0], True, out)
        _hard_refs(f.args[1], soft, out)
        return
    if f.name == "INDEX":
        _hard_refs(f.args[0], soft, out)
        _hard_refs(f.args[1], False, out)
        return
    if f.name in ("LAND", "LOR"):
        # short-circuit CAN mask right-side errors, but only data-
        # dependently; left side is always evaluated
        _hard_refs(f.args[0], False, out)
        for arg in f.args[1:]:
            _hard_refs(arg, False, out)
        return
    if f.target is not None:
        _hard_refs(f.target, False, out)
    for a in f.args:
        _hard_refs(a, False, out)


def find_non_total(rules: Sequence[tuple[str, str, Expression]],
                   finder: AttributeDescriptorFinder) -> list[Finding]:
    """Predicates that can evaluate to ERROR at runtime (an absent
    attribute read in hard context). Advisory: the runtime counts these
    as resolve errors, not matches — but a predicate that is total by
    construction (`(attr | default) == ...`) never burns an error
    budget. Confirmed by oracle replay on the empty bag."""
    out: list[Finding] = []
    for name, _ns, ast in rules:
        refs: set = set()
        _hard_refs(ast, False, refs)
        if not refs:
            continue
        try:
            OracleProgram.from_ast(ast, finder).evaluate(DictBag({}))
            continue          # evaluated fine: masked by short-circuit
        except Exception:
            pass
        out.append(Finding(
            code=NON_TOTAL, severity=Severity.INFO,
            message=(f"rule {name!r} errors when "
                     f"{sorted(refs)} are absent (no `|` fallback)"),
            rules=(name,), witness={}, confirmed=True))
    return out


__all__ = ["RuleUniverse", "find_shadowed", "find_conflicts",
           "find_non_total", "SHADOWED_ROUTE", "DEFAULT_PAIR_CHECK_BUDGET"]
