"""State/tile budget prediction — reject explosions BEFORE device compile.

Hyperscan-style ahead-of-time feasibility: every constant regex in a
snapshot is compiled to its dense DFA on the host (cheap — subset
construction is capped) and the analyzer predicts what the device
compile would pay: per-pattern state counts against the
`ops/regex_dfa` state cap, per-subject bank totals against the one-hot
packing tiers, and the ruleset's padded conjunction/rule index-tensor
footprint against a device budget. A pattern that would blow the state
cap is an ERROR before `compiler/ruleset.compile_ruleset` ever runs;
a bank that degrades to the latency-bound gather scan is a WARNING.
"""
from __future__ import annotations

from typing import Sequence

from istio_tpu.analysis.findings import (BANK_BUDGET, DNF_BUDGET, Finding,
                                         Severity, STATE_BUDGET,
                                         TILE_BUDGET)
from istio_tpu.compiler.ruleset import (DEFAULT_DNF_CAP, DnfBlowup,
                                        _AtomTable, _decompose)
from istio_tpu.compiler.tensor_expr import HostFallback
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.expr.exprs import Expression
from istio_tpu.ops.regex_dfa import (UnsupportedRegex, _MAX_DFA_STATES,
                                     compile_regex)

# one-hot packing feasibility (mirrors ops/regex_dfa.pack_dfas_tiered)
DENSE_ONEHOT_BUDGET = 4_000_000
BLOCKED_ONEHOT_BUDGET = 8_000_000
# padded conjunction/rule index tensors (lit_idx + conj matrices),
# int32 entries — beyond this the snapshot's HLO params stop being
# "small" for remote compilation
TILE_ENTRY_BUDGET = 16_000_000


def _regex_atoms(ast: Expression, out: list) -> None:
    """(subject text, pattern) per constant-pattern `matches` atom."""
    f = ast.fn
    if f is None:
        return
    if f.name == "matches" and f.target is not None \
            and f.target.const_ is not None and f.args:
        out.append((str(f.args[0]), str(f.target.const_.value)))
    if f.target is not None:
        _regex_atoms(f.target, out)
    for a in f.args:
        _regex_atoms(a, out)


def _slot_shaped(e: Expression, finder=None) -> bool:
    """Syntactic mirror of ruleset._slot_ref: a bare attribute or a
    constant-string-key map index — the shapes that resolve to a
    slot. The parser builds INDEX as FunctionCall(args=[map, key])
    with NO target (parser.py), exactly what _slot_ref matches.
    `finder` applies _slot_ref's type gate on bare attributes —
    undeclared or STRING_MAP attrs never resolve to a slot there."""
    if e.var is not None:
        if finder is None:
            return True
        try:
            from istio_tpu.attribute.types import ValueType
            vt = finder.get_attribute(e.var.name)
            return vt is not None and vt != ValueType.STRING_MAP
        except Exception:
            return False
    f = e.fn
    return (f is not None and f.name == "INDEX" and len(f.args) == 2
            and f.args[0].var is not None
            and f.args[1].const_ is not None
            and isinstance(f.args[1].const_.value, str))


def _const_shaped(e: Expression) -> bool:
    """Mirror of the compiler's _const_id eligibility: a literal
    constant, or a foldable ip()/timestamp() over one. An ExternError
    during folding routes the atom to the general path there, so it is
    NOT const-shaped here either."""
    if e.const_ is not None:
        return True
    try:
        from istio_tpu.compiler.ruleset import _fold_time_const
        return _fold_time_const(e) is not None
    except Exception:
        return False


def _eq_shaped(e: Expression, finder) -> bool:
    """Layout-free mirror of the compiler's tier-1 EQ classification
    (compile_ruleset's fused gather-compare eligibility): a bare BOOL
    attribute, or EQ/NEQ between a slot-shaped ref and a constant
    (incl. folded ip()/timestamp() constants, per _const_id)."""
    if e.var is not None:
        try:
            from istio_tpu.attribute.types import ValueType
            return finder.get_attribute(e.var.name) == ValueType.BOOL
        except Exception:
            return False
    f = e.fn
    if f is None or f.name not in ("EQ", "NEQ") or len(f.args) != 2:
        return False
    for x, y in ((f.args[0], f.args[1]), (f.args[1], f.args[0])):
        if _slot_shaped(x, finder) and _const_shaped(y):
            return True
    return False


def check_budgets(rules: Sequence[tuple[str, str, Expression]],
                  finder: AttributeDescriptorFinder,
                  dnf_cap: int = DEFAULT_DNF_CAP) -> list[Finding]:
    findings: list[Finding] = []
    # --- per-pattern DFA state prediction + per-subject bank totals ---
    banks: dict[str, dict[str, object]] = {}   # subject → pattern → DFA
    seen_patterns: dict[str, object] = {}
    for name, _ns, ast in rules:
        pats: list = []
        _regex_atoms(ast, pats)
        for subject, pattern in pats:
            if pattern not in seen_patterns:
                try:
                    seen_patterns[pattern] = compile_regex(pattern)
                except UnsupportedRegex as exc:
                    seen_patterns[pattern] = None
                    if "exceeds" in str(exc):
                        findings.append(Finding(
                            code=STATE_BUDGET, severity=Severity.ERROR,
                            message=(f"rule {name!r}: regex "
                                     f"{pattern!r} explodes past the "
                                     f"{_MAX_DFA_STATES}-state DFA "
                                     f"budget ({exc})"),
                            rules=(name,)))
                except Exception:
                    seen_patterns[pattern] = None
            dfa = seen_patterns[pattern]
            if dfa is not None:
                banks.setdefault(subject, {})[pattern] = dfa
    for subject, by_pattern in banks.items():
        dfas = list(by_pattern.values())
        # EXACT feasibility — the same class computation and tier
        # thresholds ops/regex_dfa.pack_dfas_tiered applies at compile
        from istio_tpu.ops.regex_dfa import pack_dfas_classes
        classes = pack_dfas_classes(dfas)
        s_tot, n_cls = classes["n_states"], classes["n_classes"]
        s_max = max(d.n_states for d in dfas)
        dense_ok = s_tot ** 2 * n_cls <= DENSE_ONEHOT_BUDGET
        blocked_ok = len(dfas) * s_max ** 2 * n_cls \
            <= BLOCKED_ONEHOT_BUDGET
        if not dense_ok and not blocked_ok:
            findings.append(Finding(
                code=BANK_BUDGET, severity=Severity.WARNING,
                message=(f"DFA bank over {subject!r} totals {s_tot} "
                         f"states x {n_cls} classes: past both "
                         f"one-hot packing tiers, matching degrades "
                         f"to the latency-bound gather scan")))

    # --- DNF conjunction growth + padded index-tensor footprint ---
    # Mirrors compile_ruleset's fused/legacy conjunction split: all-EQ
    # conjunctions compile to the eqc_* gather-compare tensors (two
    # int32 + two bool lanes ≈ 2.5 int32 entries per padded literal,
    # padded to the FUSED l_max), the rest to lit_idx rows (one int32
    # per literal at the LEGACY l_max) — one global l_max over both
    # blocks would over-gate mixed snapshots and under-count the eqc
    # tensors entirely.
    table = _AtomTable()
    n_fused = n_legacy = 0
    l_max_f = l_max_l = 1
    k_max = 1
    for name, _ns, ast in rules:
        try:
            mark = table.mark()
            m, n = _decompose(ast, table, dnf_cap)
        except DnfBlowup as exc:
            table.revert(mark)
            findings.append(Finding(
                code=DNF_BUDGET, severity=Severity.WARNING,
                message=(f"rule {name!r}: predicate DNF exceeds "
                         f"dnf_cap={dnf_cap} ({exc}); the rule will "
                         f"serve via the CPU oracle"),
                rules=(name,)))
            continue
        except HostFallback:
            table.revert(mark)
            continue
        conjs = m | n
        for conj in conjs:
            if all(_eq_shaped(table.asts[aidx], finder)
                   for aidx, _kind in conj):
                n_fused += 1
                l_max_f = max(l_max_f, max(len(conj), 1))
            else:
                n_legacy += 1
                l_max_l = max(l_max_l, max(len(conj), 1))
        k_max = max(k_max, max(len(m), len(n)))
    n_rows = max(len(rules), 1)
    tile_entries = (n_fused * l_max_f * 5 + 1) // 2 \
        + n_legacy * l_max_l + 2 * n_rows * k_max
    if tile_entries > TILE_ENTRY_BUDGET:
        findings.append(Finding(
            code=TILE_BUDGET, severity=Severity.ERROR,
            message=(f"predicted index tensors need ~{tile_entries} "
                     f"int32-equivalent entries ({n_fused} fused "
                     f"conjs × {l_max_f} eqc lanes + {n_legacy} "
                     f"legacy conjs × {l_max_l} literals + {n_rows} "
                     f"rules × {k_max} conjs), past the "
                     f"{TILE_ENTRY_BUDGET} device budget")))
    return findings
