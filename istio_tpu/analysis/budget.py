"""State/tile budget prediction — reject explosions BEFORE device compile.

Hyperscan-style ahead-of-time feasibility: every constant regex in a
snapshot is compiled to its dense DFA on the host (cheap — subset
construction is capped) and the analyzer predicts what the device
compile would pay: per-pattern state counts against the
`ops/regex_dfa` state cap, per-subject bank totals against the one-hot
packing tiers, and the ruleset's padded conjunction/rule index-tensor
footprint against a device budget. A pattern that would blow the state
cap is an ERROR before `compiler/ruleset.compile_ruleset` ever runs;
a bank that degrades to the latency-bound gather scan is a WARNING.
"""
from __future__ import annotations

from typing import Sequence

from istio_tpu.analysis.findings import (BANK_BUDGET, DNF_BUDGET, Finding,
                                         Severity, STATE_BUDGET,
                                         TILE_BUDGET)
from istio_tpu.compiler.ruleset import (DEFAULT_DNF_CAP, DnfBlowup,
                                        _AtomTable, _decompose)
from istio_tpu.compiler.tensor_expr import HostFallback
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.expr.exprs import Expression
from istio_tpu.ops.regex_dfa import (UnsupportedRegex, _MAX_DFA_STATES,
                                     compile_regex)

# one-hot packing feasibility (mirrors ops/regex_dfa.pack_dfas_tiered)
DENSE_ONEHOT_BUDGET = 4_000_000
BLOCKED_ONEHOT_BUDGET = 8_000_000
# padded conjunction/rule index tensors (lit_idx + conj matrices),
# int32 entries — beyond this the snapshot's HLO params stop being
# "small" for remote compilation
TILE_ENTRY_BUDGET = 16_000_000


def _regex_atoms(ast: Expression, out: list) -> None:
    """(subject text, pattern) per constant-pattern `matches` atom."""
    f = ast.fn
    if f is None:
        return
    if f.name == "matches" and f.target is not None \
            and f.target.const_ is not None and f.args:
        out.append((str(f.args[0]), str(f.target.const_.value)))
    if f.target is not None:
        _regex_atoms(f.target, out)
    for a in f.args:
        _regex_atoms(a, out)


def check_budgets(rules: Sequence[tuple[str, str, Expression]],
                  finder: AttributeDescriptorFinder,
                  dnf_cap: int = DEFAULT_DNF_CAP) -> list[Finding]:
    findings: list[Finding] = []
    # --- per-pattern DFA state prediction + per-subject bank totals ---
    banks: dict[str, dict[str, object]] = {}   # subject → pattern → DFA
    seen_patterns: dict[str, object] = {}
    for name, _ns, ast in rules:
        pats: list = []
        _regex_atoms(ast, pats)
        for subject, pattern in pats:
            if pattern not in seen_patterns:
                try:
                    seen_patterns[pattern] = compile_regex(pattern)
                except UnsupportedRegex as exc:
                    seen_patterns[pattern] = None
                    if "exceeds" in str(exc):
                        findings.append(Finding(
                            code=STATE_BUDGET, severity=Severity.ERROR,
                            message=(f"rule {name!r}: regex "
                                     f"{pattern!r} explodes past the "
                                     f"{_MAX_DFA_STATES}-state DFA "
                                     f"budget ({exc})"),
                            rules=(name,)))
                except Exception:
                    seen_patterns[pattern] = None
            dfa = seen_patterns[pattern]
            if dfa is not None:
                banks.setdefault(subject, {})[pattern] = dfa
    for subject, by_pattern in banks.items():
        dfas = list(by_pattern.values())
        # EXACT feasibility — the same class computation and tier
        # thresholds ops/regex_dfa.pack_dfas_tiered applies at compile
        from istio_tpu.ops.regex_dfa import pack_dfas_classes
        classes = pack_dfas_classes(dfas)
        s_tot, n_cls = classes["n_states"], classes["n_classes"]
        s_max = max(d.n_states for d in dfas)
        dense_ok = s_tot ** 2 * n_cls <= DENSE_ONEHOT_BUDGET
        blocked_ok = len(dfas) * s_max ** 2 * n_cls \
            <= BLOCKED_ONEHOT_BUDGET
        if not dense_ok and not blocked_ok:
            findings.append(Finding(
                code=BANK_BUDGET, severity=Severity.WARNING,
                message=(f"DFA bank over {subject!r} totals {s_tot} "
                         f"states x {n_cls} classes: past both "
                         f"one-hot packing tiers, matching degrades "
                         f"to the latency-bound gather scan")))

    # --- DNF conjunction growth + padded index-tensor footprint ---
    table = _AtomTable()
    n_conjs = 0
    l_max = 1
    k_max = 1
    for name, _ns, ast in rules:
        try:
            mark = table.mark()
            m, n = _decompose(ast, table, dnf_cap)
        except DnfBlowup as exc:
            table.revert(mark)
            findings.append(Finding(
                code=DNF_BUDGET, severity=Severity.WARNING,
                message=(f"rule {name!r}: predicate DNF exceeds "
                         f"dnf_cap={dnf_cap} ({exc}); the rule will "
                         f"serve via the CPU oracle"),
                rules=(name,)))
            continue
        except HostFallback:
            table.revert(mark)
            continue
        conjs = m | n
        n_conjs += len(conjs)
        l_max = max(l_max, max((len(c) for c in conjs), default=1))
        k_max = max(k_max, max(len(m), len(n)))
    n_rows = max(len(rules), 1)
    tile_entries = n_conjs * l_max + 2 * n_rows * k_max
    if tile_entries > TILE_ENTRY_BUDGET:
        findings.append(Finding(
            code=TILE_BUDGET, severity=Severity.ERROR,
            message=(f"predicted index tensors need {tile_entries} "
                     f"int32 entries ({n_conjs} conjs × {l_max} "
                     f"literals + {n_rows} rules × {k_max} conjs), "
                     f"past the {TILE_ENTRY_BUDGET} device budget")))
    return findings
