"""Semantic interpretation of predicate atoms for static reasoning.

The ruleset compiler (`compiler/ruleset._decompose`) reduces every
predicate to monotone DNFs over primitive atoms; this module gives the
analyzer a DECISION layer over those atoms: when are two atoms
disjoint, when does one imply another, and how do you construct a
concrete attribute value satisfying one. String predicates
(matches/startsWith/endsWith/match-glob with constant patterns) all
normalize into the SAME dense byte DFAs the device executes
(`ops/regex_dfa`), so implication and disjointness between them are
product-DFA decisions (`analysis/dfa_ops`), Hyperscan-feasibility
style, not syntax comparisons.

Everything here is deliberately THREE-VALUED: `True` means proved,
`False` means disproved, `None` means unknown — callers must treat
unknown conservatively (no finding without a confirmed witness).
"""
from __future__ import annotations

import dataclasses
from typing import Any

from istio_tpu.analysis import dfa_ops
from istio_tpu.attribute.types import ValueType
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.expr.exprs import Expression
from istio_tpu.ops.regex_dfa import DFA, UnsupportedRegex, compile_regex

V = ValueType


def _escape_literal(s: str) -> str:
    """Literal string → regex matching exactly that string's bytes."""
    out = []
    for ch in s:
        if ch in ".*+?()[]{}|^$\\":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def _compile_checked(pattern: str) -> DFA | None:
    try:
        return compile_regex(pattern)
    except (UnsupportedRegex, Exception):
        return None


@dataclasses.dataclass(frozen=True)
class Subject:
    """Where an atom reads its value from, as a witness-bag setter.

    kind 'var'  → scalar attribute `name`
    kind 'map'  → string-map attribute `name`, constant key `key`
    `default` is the fallback constant of a `(ref | "dflt")` probe
    (None = no probe: absence makes the atom error, not default)."""
    kind: str
    name: str
    key: str | None = None
    default: Any = None
    has_default: bool = False

    @property
    def id(self) -> tuple:
        return (self.kind, self.name, self.key)


def subject_of(e: Expression) -> Subject | None:
    """Resolve an operand expression to a settable Subject: a variable,
    INDEX(map, const), or a `(x | const)` fallback probe over either.
    None = not a shape the witness builder can control."""
    if e.var is not None:
        return Subject("var", e.var.name)
    f = e.fn
    if f is None:
        return None
    if f.name == "INDEX" and f.args[0].var is not None \
            and f.args[1].const_ is not None \
            and isinstance(f.args[1].const_.value, str):
        return Subject("map", f.args[0].var.name,
                       key=f.args[1].const_.value)
    if f.name == "OR" and len(f.args) == 2 \
            and f.args[1].const_ is not None:
        inner = subject_of(f.args[0])
        if inner is not None and not inner.has_default:
            return dataclasses.replace(inner,
                                       default=f.args[1].const_.value,
                                       has_default=True)
    return None


@dataclasses.dataclass
class AtomSem:
    """Decidable meaning of one atom (polarity already applied).

    kind 'eq'   — subject == value (negated: subject != value)
    kind 'str'  — subject's string is accepted by `dfa`
    kind 'eqv'  — subject == other subject (slot vs slot)
    kind 'opaque' — no static model; witness replay is the only filter
    """
    kind: str
    subject: Subject | None = None
    value: Any = None
    negated: bool = False
    dfa: DFA | None = None
    other: Subject | None = None
    source: str = ""          # str(atom ast), for diagnostics


def _const_value(e: Expression) -> tuple[bool, Any]:
    if e.const_ is not None:
        return True, e.const_.value
    return False, None


def atom_sem(ast: Expression,
             finder: AttributeDescriptorFinder) -> AtomSem:
    """Atom AST → AtomSem. Unknown shapes come back 'opaque' — sound
    because every consumer treats opaque as undecidable."""
    src = str(ast)
    if ast.var is not None and finder.get_attribute(ast.var.name) == V.BOOL:
        return AtomSem("eq", subject=Subject("var", ast.var.name),
                       value=True, source=src)
    f = ast.fn
    if f is None:
        return AtomSem("opaque", source=src)

    if f.name in ("EQ", "NEQ") and len(f.args) == 2:
        neg = f.name == "NEQ"
        for x, y in ((f.args[0], f.args[1]), (f.args[1], f.args[0])):
            subj = subject_of(x)
            if subj is None:
                continue
            is_const, val = _const_value(y)
            if is_const:
                return AtomSem("eq", subject=subj, value=val,
                               negated=neg, source=src)
        sa, sb = subject_of(f.args[0]), subject_of(f.args[1])
        if sa is not None and sb is not None:
            return AtomSem("eqv", subject=sa, other=sb, negated=neg,
                           source=src)
        return AtomSem("opaque", source=src)

    # constant-pattern string predicates → device DFA semantics
    pattern: str | None = None
    subj_expr: Expression | None = None
    if f.name == "matches" and f.target is not None \
            and f.target.const_ is not None:
        pattern = str(f.target.const_.value)      # unanchored search
        subj_expr = f.args[0]
    elif f.name in ("startsWith", "endsWith") and f.target is not None \
            and f.args and f.args[0].const_ is not None:
        lit = _escape_literal(str(f.args[0].const_.value))
        pattern = f"^{lit}" if f.name == "startsWith" else f"{lit}$"
        subj_expr = f.target
    elif f.name == "match" and len(f.args) == 2 \
            and f.args[1].const_ is not None:
        # externs.go glob: trailing '*' = prefix, leading '*' = suffix,
        # else exact (suffix-star checked first)
        g = str(f.args[1].const_.value)
        if g.endswith("*"):
            pattern = "^" + _escape_literal(g[:-1])
        elif g.startswith("*"):
            pattern = _escape_literal(g[1:]) + "$"
        else:
            pattern = "^" + _escape_literal(g) + "$"
        subj_expr = f.args[0]
    if pattern is not None and subj_expr is not None:
        subj = subject_of(subj_expr)
        dfa = _compile_checked(pattern)
        if subj is not None and dfa is not None:
            return AtomSem("str", subject=subj, dfa=dfa, source=src)
    return AtomSem("opaque", source=src)


def negate(sem: AtomSem) -> AtomSem:
    """The 'n'-literal meaning: atom definitely false (no error)."""
    if sem.kind in ("eq", "eqv"):
        return dataclasses.replace(sem, negated=not sem.negated)
    if sem.kind == "str":
        return dataclasses.replace(sem, dfa=dfa_ops.complement(sem.dfa))
    return dataclasses.replace(sem, negated=not sem.negated)


def _dfa_accepts(dfa: DFA, value: Any) -> bool | None:
    if not isinstance(value, str):
        return None
    from istio_tpu.ops.regex_dfa import dfa_matches_host
    return dfa_matches_host(dfa, value.encode("utf-8"))


def atoms_disjoint(a: AtomSem, b: AtomSem, *,
                   pair_budget: int = dfa_ops.DEFAULT_PAIR_BUDGET
                   ) -> bool | None:
    """Can no input satisfy both? True = proved disjoint."""
    if a.kind == "opaque" or b.kind == "opaque":
        # opposite-polarity literals of the SAME atom never co-hold
        # (m = definitely-true, n = definitely-false)
        if a.kind == b.kind == "opaque" and a.source == b.source:
            return True if a.negated != b.negated else None
        return None
    if a.subject is None or b.subject is None \
            or a.subject.id != b.subject.id:
        return None
    if a.kind == "eq" and b.kind == "eq":
        if not a.negated and not b.negated:
            return a.value != b.value
        if a.negated != b.negated:
            return a.value == b.value
        return None                      # neq vs neq always overlap-ish
    if a.kind == "eq" and b.kind == "str":
        a, b = b, a
    if a.kind == "str" and b.kind == "eq":
        acc = _dfa_accepts(a.dfa, b.value)
        if acc is None:
            return None
        if not b.negated:
            return not acc
        return None                      # str ∧ (!= c): rarely empty
    if a.kind == "str" and b.kind == "str":
        return dfa_ops.languages_disjoint(a.dfa, b.dfa,
                                          pair_budget=pair_budget)
    return None


def atom_implies(a: AtomSem, b: AtomSem, *,
                 pair_budget: int = dfa_ops.DEFAULT_PAIR_BUDGET
                 ) -> bool | None:
    """Does every input satisfying `a` satisfy `b`? True = proved."""
    if a.kind == "opaque" or b.kind == "opaque":
        # identical source AND polarity only — the m- and n-literals
        # of one atom share a source but are mutually exclusive
        return True if (a.source == b.source
                        and a.negated == b.negated
                        and a.kind == b.kind) else None
    if a.kind == "eqv" or b.kind == "eqv":
        return (a.source == b.source and a.negated == b.negated
                and a.kind == b.kind) or None
    if a.subject is None or b.subject is None \
            or a.subject.id != b.subject.id:
        return None
    if a.kind == "eq" and not a.negated:
        if b.kind == "eq":
            if not b.negated:
                return a.value == b.value
            return a.value != b.value
        if b.kind == "str":
            return _dfa_accepts(b.dfa, a.value)
    if a.kind == "eq" and a.negated:
        if b.kind == "eq" and b.negated:
            return a.value == b.value
        return None
    if a.kind == "str":
        if b.kind == "str":
            return dfa_ops.language_includes(b.dfa, a.dfa,
                                             pair_budget=pair_budget)
        if b.kind == "eq" and b.negated:
            acc = _dfa_accepts(a.dfa, b.value)
            if acc is None:
                return None
            return not acc
    return None


# ---------------------------------------------------------------------------
# witness construction
# ---------------------------------------------------------------------------

class WitnessUnsat(Exception):
    """The constraint set provably has no satisfying assignment."""


class WitnessUnknown(Exception):
    """Couldn't construct an assignment (opaque atoms, exotic types)."""


_FRESH = "zz~w{n}"


def _fresh_value(vtype: ValueType | None, taken: set, n: int) -> Any:
    """A value of the subject's declared type distinct from `taken`."""
    for k in range(n, n + 64):
        if vtype in (None, V.STRING):
            v: Any = _FRESH.format(n=k)
        elif vtype == V.INT64:
            v = 10_000_019 + k
        elif vtype == V.DOUBLE:
            v = 10_000_019.5 + k
        elif vtype == V.BOOL:
            v = bool(k % 2)
        else:
            raise WitnessUnknown(f"no fresh generator for {vtype}")
        if v not in taken:
            return v
    raise WitnessUnknown("fresh-value space exhausted")


def solve_subjects(sems: list[AtomSem],
                   finder: AttributeDescriptorFinder) -> dict[str, Any]:
    """Constraint list (a conjunction of AtomSems) → attribute bag
    mapping satisfying it, or raise WitnessUnsat / WitnessUnknown.

    Per subject: at most one required eq value, a forbidden set from
    neq literals, and the product of all DFA constraints; eqv literals
    unify (or split) subjects after the per-subject solve."""
    by_subj: dict[tuple, dict] = {}
    eqv_pairs: list[AtomSem] = []
    for sem in sems:
        if sem.kind == "opaque":
            raise WitnessUnknown(f"opaque atom {sem.source}")
        if sem.kind == "eqv":
            eqv_pairs.append(sem)
            continue
        slot = by_subj.setdefault(sem.subject.id, {
            "subject": sem.subject, "eq": [], "neq": set(), "dfas": []})
        # keep the richest probe view (a later literal may carry the
        # defaulted form of the same subject)
        if sem.subject.has_default:
            slot["subject"] = sem.subject
        if sem.kind == "eq":
            (slot["eq"].append(sem.value) if not sem.negated
             else slot["neq"].add(sem.value))
        else:
            slot["dfas"].append(sem.dfa)

    values: dict[tuple, Any] = {}
    n = 0
    for sid, slot in by_subj.items():
        subj: Subject = slot["subject"]
        eqs = set(slot["eq"])
        if len(eqs) > 1:
            raise WitnessUnsat(f"conflicting eq on {sid}")
        if eqs:
            v = next(iter(eqs))
            if v in slot["neq"]:
                raise WitnessUnsat(f"eq/neq clash on {sid}")
            for dfa in slot["dfas"]:
                acc = _dfa_accepts(dfa, v)
                if acc is False:
                    raise WitnessUnsat(f"eq vs pattern clash on {sid}")
                if acc is None:
                    raise WitnessUnknown(f"non-string pattern on {sid}")
            values[sid] = v
        elif slot["dfas"]:
            dfa = slot["dfas"][0]
            for other in slot["dfas"][1:]:
                # narrow by product: enumerate from the intersection
                r = dfa_ops.product_intersect(dfa, other)
                if r.empty is True:
                    raise WitnessUnsat(f"empty pattern product on {sid}")
                if r.empty is None:
                    raise WitnessUnknown(f"pattern budget on {sid}")
            forbid = frozenset(v for v in slot["neq"]
                               if isinstance(v, str))
            found = None
            for w in dfa_ops.accepted_strings(
                    _product_all(slot["dfas"]), limit=8, forbid=forbid):
                try:
                    found = w.decode("utf-8")
                    break
                except UnicodeDecodeError:
                    continue
            if found is None:
                raise WitnessUnknown(f"no decodable witness for {sid}")
            values[sid] = found
        else:
            vtype = finder.get_attribute(subj.name) \
                if subj.kind == "var" else V.STRING
            if subj.kind == "map":
                vtype = V.STRING
            values[sid] = _fresh_value(vtype, slot["neq"], n)
            n += 1

    for sem in eqv_pairs:
        ida, idb = sem.subject.id, sem.other.id
        va, vb = values.get(ida), values.get(idb)
        if not sem.negated:
            if va is None and vb is None:
                vtype = finder.get_attribute(sem.subject.name) \
                    if sem.subject.kind == "var" else V.STRING
                va = vb = _fresh_value(vtype, set(), n)
                n += 1
            elif va is None:
                va = vb
            elif vb is None:
                vb = va
            elif va != vb:
                raise WitnessUnsat("eqv subjects pinned to different "
                                   "values")
            values[ida], values[idb] = va, vb
            by_subj.setdefault(ida, {"subject": sem.subject, "eq": [],
                                     "neq": set(), "dfas": []})
            by_subj.setdefault(idb, {"subject": sem.other, "eq": [],
                                     "neq": set(), "dfas": []})
        else:
            if va is not None and vb is not None and va == vb:
                raise WitnessUnsat("neqv subjects pinned equal")
            if va is None:
                vtype = finder.get_attribute(sem.subject.name) \
                    if sem.subject.kind == "var" else V.STRING
                values[ida] = _fresh_value(
                    vtype, {vb} if vb is not None else set(), n)
                n += 1
                by_subj.setdefault(ida, {"subject": sem.subject,
                                         "eq": [], "neq": set(),
                                         "dfas": []})
            if vb is None:
                vtype = finder.get_attribute(sem.other.name) \
                    if sem.other.kind == "var" else V.STRING
                values[idb] = _fresh_value(vtype, {values[ida]}, n)
                n += 1
                by_subj.setdefault(idb, {"subject": sem.other,
                                         "eq": [], "neq": set(),
                                         "dfas": []})

    bag: dict[str, Any] = {}
    for sid, v in values.items():
        subj = by_subj[sid]["subject"]
        if subj.has_default and v == subj.default:
            continue                 # absence yields the default value
        if subj.kind == "var":
            bag[subj.name] = v
        else:
            bag.setdefault(subj.name, {})[subj.key] = \
                v if isinstance(v, str) else str(v)
    return bag


def _product_all(dfas: list[DFA]) -> DFA:
    """Fold DFAs into one intersection automaton (explicit product;
    used only for witness enumeration, sizes pre-checked by caller)."""
    import numpy as np

    cur = dfas[0]
    for other in dfas[1:]:
        sa, sb = cur.transitions.shape[0], other.transitions.shape[0]
        if sa * sb > 4096:
            raise WitnessUnknown("witness product too large")
        trans = (cur.transitions[:, None, :] * sb
                 + other.transitions[None, :, :]).reshape(sa * sb, -1)
        accept = (cur.accept[:, None]
                  & other.accept[None, :]).reshape(-1)
        cur = DFA(transitions=trans.astype(np.int32), accept=accept,
                  pattern=f"({cur.pattern})&({other.pattern})")
    return cur
