"""Static verification of rule snapshots before they reach the TPU.

Batfish-for-the-mesh: config faults that today surface as a compile
blow-up or a silently-wrong answer under live traffic — ill-typed
expressions, fully-shadowed rules, ALLOW/DENY overlaps, regexes that
explode the padded NFA state budget, Pilot/Mixer plane divergence —
are statically decidable from the compiled artifacts. This package
decides them (see `analysis/analyzer.py` for the pass inventory) and
reports structured, witness-carrying findings (`analysis/findings.py`)
that the `mixs analyze` CLI, the admission webhook and the introspect
`/debug/analysis` view all consume.
"""
from istio_tpu.analysis.analyzer import (analyze_route_table,
                                         analyze_rules,
                                         analyze_snapshot,
                                         analyze_store)
from istio_tpu.analysis.findings import (AnalysisReport, Finding,
                                         Severity)
from istio_tpu.analysis.planes import check_plane_pairs

__all__ = [
    "AnalysisReport", "Finding", "Severity",
    "analyze_rules", "analyze_snapshot", "analyze_route_table",
    "analyze_store", "check_plane_pairs",
]
