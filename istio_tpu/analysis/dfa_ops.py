"""Product-DFA decision procedures over ops/regex_dfa transition tensors.

The reachability core of the snapshot analyzer: language emptiness,
intersection and inclusion over the SAME dense byte-DFA tables the
device kernels execute (`ops/regex_dfa.DFA`), so a static verdict
("these two route regexes overlap") is a statement about the automata
that will actually run, not about a re-parse.

All decisions are frontier-vectorized numpy: pair states explore by
bank-wide byte EQUIVALENCE CLASSES (the pack_dfas_classes trick — two
bytes with identical transition columns in BOTH automata are one
edge), so a product step costs O(frontier × classes) gathers instead
of O(frontier × 256). Witness strings come out of the same search via
parent pointers — every "non-empty" verdict can hand the caller a
concrete accepted input for oracle replay.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from istio_tpu.ops.regex_dfa import ALPHABET, DFA

# pair-state exploration budget: beyond this the analyzer reports
# "unknown" rather than stalling a config swap (callers treat unknown
# conservatively — no finding is emitted on an unproven claim)
DEFAULT_PAIR_BUDGET = 200_000


@dataclasses.dataclass
class ProductResult:
    """Decision outcome. `empty` is None when the pair budget ran out
    (unknown); `witness` is a shortest accepted byte string when the
    intersection is non-empty."""
    empty: bool | None
    witness: bytes | None = None
    pairs_explored: int = 0


def complement(dfa: DFA) -> DFA:
    """¬L: regex_dfa DFAs are complete (every state has all 256
    transitions, missing targets go to the explicit empty-set sink), so
    complement is accept-flip."""
    return DFA(transitions=dfa.transitions,
               accept=~dfa.accept, pattern=f"!({dfa.pattern})")


def _byte_classes(ta: np.ndarray, tb: np.ndarray) -> np.ndarray:
    """Representative bytes whose transition columns are pairwise
    distinct across BOTH automata — the product's byte alphabet."""
    stacked = np.concatenate([ta, tb], axis=0)      # [Sa+Sb, 256]
    _, idx = np.unique(stacked, axis=1, return_index=True)
    return np.sort(idx.astype(np.int64))


def product_intersect(a: DFA, b: DFA, *,
                      pair_budget: int = DEFAULT_PAIR_BUDGET
                      ) -> ProductResult:
    """Is L(a) ∩ L(b) non-empty? BFS over the product automaton from
    (0, 0); returns the shortest jointly-accepted string as witness."""
    ta, tb = a.transitions, b.transitions
    aa, ab = a.accept, b.accept
    sb = tb.shape[0]
    reps = _byte_classes(ta, tb)

    if aa[0] and ab[0]:
        return ProductResult(empty=False, witness=b"", pairs_explored=1)

    visited = np.zeros(ta.shape[0] * sb, dtype=bool)
    visited[0] = True
    # parent pointers for witness reconstruction: flat pair → (parent
    # flat pair, byte). int64 flat ids; -1 = root.
    parent = {0: (-1, 0)}
    frontier = np.array([0], dtype=np.int64)
    explored = 1

    while frontier.size:
        fa, fb = frontier // sb, frontier % sb
        next_ids: list[np.ndarray] = []
        for byte in reps:
            na = ta[fa, byte].astype(np.int64)
            nb = tb[fb, byte].astype(np.int64)
            flat = na * sb + nb
            fresh_mask = ~visited[flat]
            if not fresh_mask.any():
                continue
            fresh = flat[fresh_mask]
            src = frontier[fresh_mask]
            # first-writer wins within the wave (np.unique keeps one)
            fresh, first = np.unique(fresh, return_index=True)
            src = src[first]
            visited[fresh] = True
            for f, s in zip(fresh.tolist(), src.tolist()):
                parent[f] = (s, int(byte))
            hit = fresh[aa[fresh // sb] & ab[fresh % sb]]
            if hit.size:
                return ProductResult(
                    empty=False, witness=_walk(parent, int(hit[0])),
                    pairs_explored=explored + len(parent))
            next_ids.append(fresh)
        explored += sum(x.size for x in next_ids)
        if explored > pair_budget:
            return ProductResult(empty=None, pairs_explored=explored)
        frontier = (np.concatenate(next_ids) if next_ids
                    else np.array([], dtype=np.int64))
    return ProductResult(empty=True, pairs_explored=explored)


def _walk(parent: dict, flat: int) -> bytes:
    out = bytearray()
    while True:
        prev, byte = parent[flat]
        if prev < 0:
            break
        out.append(byte)
        flat = prev
    return bytes(reversed(out))


def language_includes(a: DFA, b: DFA, *,
                      pair_budget: int = DEFAULT_PAIR_BUDGET) -> bool | None:
    """L(b) ⊆ L(a)? (i.e. `b` implies `a`.) Decided as emptiness of
    L(b) ∩ ¬L(a); None = budget exhausted (unknown)."""
    r = product_intersect(b, complement(a), pair_budget=pair_budget)
    return r.empty


def languages_disjoint(a: DFA, b: DFA, *,
                       pair_budget: int = DEFAULT_PAIR_BUDGET
                       ) -> bool | None:
    """L(a) ∩ L(b) = ∅? None = unknown."""
    return product_intersect(a, b, pair_budget=pair_budget).empty


def accepted_strings(dfa: DFA, limit: int = 8,
                     forbid: frozenset[str] = frozenset(),
                     pair_budget: int = DEFAULT_PAIR_BUDGET
                     ) -> list[bytes]:
    """Up to `limit` short accepted strings (BFS order), skipping any
    whose utf-8 decoding lands in `forbid` — the witness enumerator for
    conjunctions that pin a subject with regex constraints AND exclude
    specific values (neq literals)."""
    ta, aa = dfa.transitions, dfa.accept
    out: list[bytes] = []

    def keep(w: bytes) -> bool:
        try:
            return w.decode("utf-8") not in forbid
        except UnicodeDecodeError:
            return True

    if aa[0] and keep(b""):
        out.append(b"")
        if len(out) >= limit:
            return out
    reps = _byte_classes(ta, ta)
    visited = np.zeros(ta.shape[0], dtype=bool)
    visited[0] = True
    paths: dict[int, bytes] = {0: b""}
    frontier = [0]
    explored = 1
    # prefer printable representative bytes so witnesses stay readable
    # (and utf-8 decodable) when the class allows it
    def printable(byte: int, state: int) -> int:
        tgt = ta[state, byte]
        cands = np.nonzero(ta[state] == tgt)[0]
        for c in cands:
            if 0x61 <= c <= 0x7A or 0x30 <= c <= 0x39 or c in (0x2E, 0x2F, 0x2D):
                return int(c)
        return int(byte)

    while frontier and len(out) < limit and explored < pair_budget:
        nxt: list[int] = []
        for state in frontier:
            for byte in reps:
                t = int(ta[state, byte])
                if visited[t]:
                    continue
                visited[t] = True
                explored += 1
                w = paths[state] + bytes([printable(int(byte), state)])
                paths[t] = w
                if aa[t]:
                    if keep(w):
                        out.append(w)
                    else:
                        # the representative's word is forbidden, but a
                        # SIBLING byte of the same class reaches the
                        # same accept state with a different spelling
                        for c in np.nonzero(ta[state] == t)[0]:
                            w2 = paths[state] + bytes([int(c)])
                            if keep(w2):
                                out.append(w2)
                                break
                    if len(out) >= limit:
                        return out
                nxt.append(t)
        frontier = nxt
    return out
