"""Snapshot analyzer — orchestrates every static pass over a config.

Runs BEFORE a snapshot is trusted: `compiler/ruleset.compile_ruleset`
tolerates bad rules by degrading them (host fallback, 'false'
replacement), and PR 2's resilience layer only degrades gracefully —
neither can reject a snapshot that is wrong by construction. The
passes, in order:

  1. expression checking — manifest-aware type/arity/extern validation
     on every match clause (`expr/checker.eval_type`), plus totality;
  2. reachability — fully-shadowed rules and ALLOW/DENY overlaps via
     DNF implication + product-DFA reasoning (`analysis/reach`), every
     semantic claim witness-confirmed through `expr/oracle`;
  3. budget prediction — DFA state caps, one-hot bank tiers, padded
     index-tensor footprint (`analysis/budget`);
  4. cross-plane consistency — Pilot route matchers vs Mixer
     predicates compiled from the same source (`analysis/planes`).

Consumers: `mixs analyze` (cmd/__main__.py, non-zero exit on ERROR),
`kube/admission.register_analysis_admission` (reject at write time),
and the introspect server's `/debug/analysis` view.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Sequence

from istio_tpu.analysis import budget as budget_mod
from istio_tpu.analysis import planes as planes_mod
from istio_tpu.analysis import reach
from istio_tpu.analysis.findings import (AnalysisReport, CONFIG_ERROR,
                                         Finding, HOST_FALLBACK,
                                         SHADOWED_ROUTE, Severity,
                                         TYPE_ERROR)
from istio_tpu.compiler.ruleset import Rule, _rule_ast
from istio_tpu.expr.checker import (AttributeDescriptorFinder,
                                    DEFAULT_FUNCS, TypeError_, eval_type)
from istio_tpu.expr.parser import ParseError
from istio_tpu.attribute.types import ValueType


def analyze_rules(rules: Sequence[Rule],
                  finder: AttributeDescriptorFinder,
                  *,
                  deny_idx: Sequence[int] = (),
                  allow_idx: Sequence[int] = (),
                  shadow_eligible: Callable[[int, int], bool] | None = None,
                  check_totality: bool = True,
                  pair_budget: int = reach.DEFAULT_PAIR_CHECK_BUDGET
                  ) -> AnalysisReport:
    """Static verification of a bare rule list (no action wiring —
    callers supply the deny/allow classification and, optionally, a
    shadow-eligibility gate; default: all pairs eligible)."""
    t0 = time.perf_counter()
    report = AnalysisReport(n_rules=len(rules))

    parsed: list[tuple[str, str, Any]] = []
    ok_index: dict[int, int] = {}        # original idx → parsed idx
    for idx, rule in enumerate(rules):
        try:
            ast = _rule_ast(rule)
            rtype = eval_type(ast, finder, DEFAULT_FUNCS)
            if rtype != ValueType.BOOL:
                raise TypeError_(f"match must be BOOL, got {rtype.name}")
        except (ParseError, TypeError_) as exc:
            report.add(Finding(
                code=TYPE_ERROR, severity=Severity.ERROR,
                message=f"rule {rule.name!r}: {exc}",
                rules=(rule.name,)))
            continue
        ok_index[idx] = len(parsed)
        parsed.append((rule.name, rule.namespace, ast))

    report.extend(budget_mod.check_budgets(parsed, finder))
    if check_totality:
        report.extend(reach.find_non_total(parsed, finder))

    uni = reach.RuleUniverse(parsed, finder)
    remap = lambda idxs: [ok_index[i] for i in idxs if i in ok_index]
    eligible = shadow_eligible or (lambda i, j: True)
    shadows, trunc1 = reach.find_shadowed(uni, eligible,
                                          pair_budget=pair_budget)
    report.extend(shadows)
    conflicts, trunc2 = reach.find_conflicts(
        uni, remap(deny_idx), remap(allow_idx),
        pair_budget=pair_budget)
    report.extend(conflicts)
    report.truncated = trunc1 or trunc2
    report.wall_ms = (time.perf_counter() - t0) * 1e3
    return report


# ---------------------------------------------------------------------------
# snapshot-level (action-aware) analysis
# ---------------------------------------------------------------------------

def _action_classes(snapshot) -> tuple[list[int], list[int], list[frozenset]]:
    """(deny rule idxs, allow rule idxs, per-rule action signatures)
    from the snapshot's handler wiring: denier adapters and blacklist
    lists deny; whitelist lists allow."""
    deny: list[int] = []
    allow: list[int] = []
    sigs: list[frozenset] = []
    for ridx, rc in enumerate(snapshot.rules):
        sig = set()
        is_deny = is_allow = False
        for action in rc.actions:
            hc = snapshot.handlers.get(action.handler)
            if hc is None:
                continue
            sig.add((action.handler, tuple(sorted(action.instances))))
            if hc.adapter == "denier":
                is_deny = True
            elif hc.adapter == "list":
                if bool(hc.params.get("blacklist", False)):
                    is_deny = True
                else:
                    is_allow = True
            elif hc.adapter == "opa":
                is_deny = True
        if is_deny:
            deny.append(ridx)
        if is_allow:
            allow.append(ridx)
        sigs.append(frozenset(sig))
    return deny, allow, sigs


def analyze_snapshot(snapshot, *,
                     pair_budget: int = reach.DEFAULT_PAIR_CHECK_BUDGET,
                     check_totality: bool = False) -> AnalysisReport:
    """Full static verification of a built `runtime/config.Snapshot`.

    Shadow analysis is ACTION-AWARE here: rule j is only shadow-
    eligible under rule i when j's action set is a subset of i's (a
    narrower rule with different actions is layered policy, not dead
    config). Totality is off by default at snapshot level: real mesh
    predicates routinely reference optional attributes and the runtime
    accounts those as resolve errors by design."""
    t0 = time.perf_counter()
    report = AnalysisReport(n_rules=len(snapshot.rules))

    for err in snapshot.errors:
        text = str(err)
        sev = Severity.INFO if "unknown refs" in text else Severity.ERROR
        report.add(Finding(code=CONFIG_ERROR, severity=sev,
                           message=text))

    n_cfg = snapshot.n_config_rules
    preds = snapshot.ruleset.rules[:n_cfg]
    deny, allow, sigs = _action_classes(snapshot)

    for ridx, reason in snapshot.ruleset.fallback_reason.items():
        if ridx < n_cfg:
            report.add(Finding(
                code=HOST_FALLBACK, severity=Severity.INFO,
                message=(f"rule {preds[ridx].name!r} serves via the "
                         f"CPU oracle: {reason}"),
                rules=(preds[ridx].name,)))

    def eligible(i: int, j: int) -> bool:
        return bool(sigs[j]) and sigs[j] <= sigs[i]

    sub = analyze_rules(preds, snapshot.finder,
                        deny_idx=deny, allow_idx=allow,
                        shadow_eligible=eligible,
                        check_totality=check_totality,
                        pair_budget=pair_budget)
    report.extend(sub.findings)
    report.truncated = sub.truncated
    report.wall_ms = (time.perf_counter() - t0) * 1e3
    return report


def analyze_route_table(route_table, *,
                        pair_budget: int = reach.DEFAULT_PAIR_CHECK_BUDGET
                        ) -> AnalysisReport:
    """Static verification of a compiled `pilot/route_nfa.RouteTable`:
    (a) cross-plane consistency — each entry's compiled predicate must
    stay language-equivalent to what `match_to_predicate` derives from
    its source rule spec today; (b) precedence shadowing — a route row
    covered by a higher-weight row can never win selection."""
    from istio_tpu.pilot.route_nfa import (ROUTE_FINDER,
                                           match_to_predicate)

    t0 = time.perf_counter()
    report = AnalysisReport(n_rules=len(route_table.entries))

    pairs = []
    parsed: list[tuple[str, str, Any]] = []
    weights: list[int] = []
    for i, entry in enumerate(route_table.entries):
        name = f"route{i}:{entry.rule.meta.name}"
        src = entry.rule.spec.get("match", {}).get("source") \
            if entry.rule.spec.get("match") else None
        derived = match_to_predicate(entry.service.hostname,
                                     entry.rule.spec.get("match"), src)
        pairs.append((name, derived, entry.predicate))
        try:
            parsed.append((name, "", _rule_ast(
                Rule(name=name, match=entry.predicate))))
            weights.append(int(route_table._weight[i]))
        except (ParseError, TypeError_):
            pass         # unparseable predicates already reported below
    report.extend(planes_mod.check_plane_pairs(pairs, ROUTE_FINDER))

    uni = reach.RuleUniverse(parsed, ROUTE_FINDER)
    shadows, truncated = reach.find_shadowed(
        uni, lambda i, j: True, code=SHADOWED_ROUTE, weight=weights,
        pair_budget=pair_budget)
    report.extend(shadows)
    report.truncated = truncated
    report.wall_ms = (time.perf_counter() - t0) * 1e3
    return report


def analyze_store(store, *,
                  default_manifest=None,
                  pair_budget: int = reach.DEFAULT_PAIR_CHECK_BUDGET
                  ) -> AnalysisReport:
    """Build a snapshot from a config store and analyze it — the
    one-call form the CLI and admission hook share."""
    from istio_tpu.attribute.global_dict import GLOBAL_MANIFEST
    from istio_tpu.runtime.config import SnapshotBuilder

    builder = SnapshotBuilder(default_manifest or GLOBAL_MANIFEST)
    snapshot = builder.build(store)
    return analyze_snapshot(snapshot, pair_budget=pair_budget)
