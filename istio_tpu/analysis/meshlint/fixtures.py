"""Seeded violation corpus — the gate that proves the gate works.

Each fixture is an in-memory module set (`callgraph.Universe.from_
sources`) seeded with exactly one violation class, plus the expected
finding code. `selftest()` runs every fixture through the real
passes and returns the discrepancies: a violation class the analyzer
stops catching, or noise appearing in the CLEAN fixture, fails
scripts/meshlint.py before it can fail a PR. A lint that cannot
demonstrate detection is indistinguishable from one that is broken.

Fixtures use the same manifest-override hooks tests use (hot_roots /
boundaries), so they exercise the production pass code — not a
parallel test-only path."""
from __future__ import annotations

import dataclasses

from istio_tpu.analysis.meshlint import model, run_meshlint


@dataclasses.dataclass
class Fixture:
    name: str
    sources: dict
    expect_codes: tuple[str, ...]      # must ALL appear
    forbid_codes: tuple[str, ...] = ()  # must NOT appear
    passes: tuple[str, ...] = ("lock", "hotpath", "metrics",
                               "rejections")
    hot_roots: tuple[str, ...] = ()
    boundaries: tuple = ()
    expect_errors: bool = True


_LOCK_CYCLE_SRC = '''
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.b = B()

    def fwd(self):
        with self._lock:
            self.b.grab(self)

class B:
    def __init__(self):
        self._lock = threading.Lock()

    def grab(self, a):
        with self._lock:
            pass

    def rev(self, a: "A"):
        with self._lock:
            with a._lock:
                pass
'''

_LOCK_INVERSION_SRC = '''
import threading

class DeviceQuotaPool:
    """Same lock names as the real pool: the declared order is
    _counts_lock THEN _lock."""
    def __init__(self):
        self._lock = threading.Lock()
        self._counts_lock = threading.Lock()

    def good(self):
        with self._counts_lock:
            with self._lock:
                pass

    def bad(self):
        with self._lock:
            with self._counts_lock:
                pass
'''

_LOCK_LEAF_SRC = '''
import threading

class ShardRouter:
    def __init__(self):
        self._stats_lock = threading.Lock()
        self._other = threading.Lock()

    def bad(self):
        with self._stats_lock:
            with self._other:
                pass
'''

_LOCK_SELF_SRC = '''
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self):
        with self._lock:
            with self._lock:
                pass
'''

_LOCK_PRAGMA_SRC = '''
import threading

class ShardRouter:
    def __init__(self):
        self._stats_lock = threading.Lock()
        self._other = threading.Lock()

    def annotated(self):
        with self._stats_lock:
            with self._other:   # meshlint: lock-ok fixture exception
                pass
'''

_HOTPATH_SRC = '''
import time
import numpy as np

class Engine:
    def step(self, dev):
        return self._pull(dev)

    def _pull(self, dev):
        time.sleep(0.01)
        return np.asarray(dev)

    def annotated(self, dev):
        return np.asarray(dev)   # hotpath: sync-ok designated pull
'''

_METRIC_SRC = '''
import prometheus_client
from istio_tpu.utils import metrics as hostmetrics

REGISTRY = prometheus_client.CollectorRegistry()

SHAPED = prometheus_client.Counter(
    "fx_shaped", "ok", ["reason"], registry=REGISTRY)
for _r in ("a", "b"):
    SHAPED.labels(reason=_r)

UNSHAPED = prometheus_client.Counter(
    "fx_unshaped", "never pre-touched", ["reason"], registry=REGISTRY)

NOT_A_FAMILY = object()

HOST_OK = hostmetrics.default_registry.counter("fx_host", "ok")
HOST_OK.inc(0)


def record(n):
    SHAPED.labels(reason="a").inc(n)
    NOT_A_FAMILY.inc(n)
    SHAPED.labels(wrong_key="a").inc(n)
'''

_REJECT_SRC = '''
class CheckRejected(RuntimeError):
    grpc_code = 2

class BadInput(Exception):
    """An in-universe rejection WITHOUT a wire code."""

class Front:
    def handler(self, req):
        try:
            return self._serve(req)
        except CheckRejected:
            return None

    def _serve(self, req):
        if not req:
            raise BadInput("bad request")
        if req == "shed":
            raise CheckRejected("typed is fine")
        return req

    def annotated_handler(self, req):
        raise ValueError("deliberate")   # meshlint: raise-ok fixture
'''

_CLEAN_SRC = '''
import threading

class Quiet:
    def __init__(self):
        self._lock = threading.Lock()

    def work(self, items):
        with self._lock:
            return [i * 2 for i in items]
'''


FIXTURES: tuple[Fixture, ...] = (
    Fixture(
        name="lock-cycle",
        sources={"fx.locks": _LOCK_CYCLE_SRC},
        passes=("lock",),
        expect_codes=(model.LOCK_CYCLE,)),
    Fixture(
        name="lock-inversion",
        sources={"fx.pool": _LOCK_INVERSION_SRC},
        passes=("lock",),
        expect_codes=(model.LOCK_INVERSION,)),
    Fixture(
        name="leaf-lock",
        sources={"fx.leaf": _LOCK_LEAF_SRC},
        passes=("lock",),
        expect_codes=(model.LOCK_LEAF,)),
    Fixture(
        name="self-deadlock",
        sources={"fx.selfdead": _LOCK_SELF_SRC},
        passes=("lock",),
        expect_codes=(model.LOCK_SELF,)),
    Fixture(
        name="lock-pragma-honored",
        sources={"fx.leafok": _LOCK_PRAGMA_SRC},
        passes=("lock",),
        expect_codes=(),
        forbid_codes=(model.LOCK_LEAF,),
        expect_errors=False),
    Fixture(
        name="hotpath-sync",
        sources={"fx.engine": _HOTPATH_SRC},
        passes=("hotpath",),
        hot_roots=("Engine.step", "Engine.annotated"),
        expect_codes=(model.HOTPATH_SYNC,)),
    Fixture(
        name="hotpath-root-missing",
        sources={"fx.engine": _HOTPATH_SRC},
        passes=("hotpath",),
        hot_roots=("Engine.vanished",),
        expect_codes=(model.HOTPATH_ROOT_MISSING,)),
    Fixture(
        name="metric-discipline",
        sources={"fx.metrics": _METRIC_SRC},
        passes=("metrics",),
        expect_codes=(model.METRIC_ZERO_SHAPE,
                      model.METRIC_UNREGISTERED,
                      model.METRIC_LABEL_MISMATCH)),
    Fixture(
        name="untyped-escape",
        sources={"fx.front": _REJECT_SRC},
        passes=("rejections",),
        boundaries=(("fx.front", "Front.handler"),
                    ("fx.front", "Front.annotated_handler")),
        expect_codes=(model.UNTYPED_ESCAPE,)),
    Fixture(
        name="clean",
        sources={"fx.quiet": _CLEAN_SRC},
        expect_codes=(),
        forbid_codes=(model.LOCK_CYCLE, model.LOCK_INVERSION,
                      model.LOCK_LEAF, model.LOCK_SELF,
                      model.HOTPATH_SYNC, model.METRIC_ZERO_SHAPE,
                      model.METRIC_UNREGISTERED,
                      model.UNTYPED_ESCAPE),
        hot_roots=("Quiet.work",),
        boundaries=(("fx.quiet", "Quiet.work"),),
        expect_errors=False),
)


def run_fixture(fx: Fixture) -> model.MeshlintReport:
    return run_meshlint(
        sources=fx.sources, passes=fx.passes,
        hot_roots=fx.hot_roots or None,
        boundaries=fx.boundaries or None)


def selftest() -> list[str]:
    """Run every fixture; return human-readable discrepancies
    (empty = the analyzer detects every seeded violation class and
    stays silent on the clean corpus)."""
    problems: list[str] = []
    for fx in FIXTURES:
        report = run_fixture(fx)
        codes = report.codes()
        for want in fx.expect_codes:
            hits = [f for f in report.findings if f.code == want]
            config_level = want in (model.HOTPATH_ROOT_MISSING,
                                    model.BOUNDARY_MISSING)
            if not hits:
                problems.append(
                    f"{fx.name}: expected {want}, not reported")
            elif not config_level \
                    and not all(f.line > 0 and f.path for f in hits):
                problems.append(
                    f"{fx.name}: {want} reported without a "
                    f"file:line witness")
        for bad in fx.forbid_codes:
            if bad in codes:
                problems.append(
                    f"{fx.name}: forbidden {bad} was reported "
                    f"(pragma/exemption not honored?)")
        if fx.expect_errors and not report.has_errors:
            problems.append(f"{fx.name}: expected ERROR findings, "
                            f"report came back clean")
        if not fx.expect_errors and report.has_errors:
            problems.append(
                f"{fx.name}: unexpected ERRORs: "
                + "; ".join(str(f) for f in report.errors))
    return problems
