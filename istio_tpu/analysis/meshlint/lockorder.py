"""Lock-order pass — static lock-acquisition graph vs declared order.

Lock identity is the DECLARATION SITE, named `Class._attr` (instance
locks assigned in the constructor) or `module._NAME` (module-level
locks). A `threading.Condition(self._lock)` wraps — and therefore IS —
the underlying lock: acquiring the condition aliases to the lock's id.
Semaphores are counted resources, not mutexes, and are excluded.

Per function we walk the statement tree lexically, tracking the held
set through `with` blocks and bare `.acquire()`/`.release()` pairs,
recording (a) every acquisition together with the locks already held
and (b) every resolvable call together with the held set at the call
site. A fixpoint over the call graph then yields, for every function,
the locks it may acquire TRANSITIVELY — each tagged with the first
call edge that reaches it, so a finding can replay the full
acquisition chain as its witness.

The verdicts, against the in-code manifest below:

  * `lock-order-cycle` (ERROR)      — the acquisition graph has a
    strongly-connected component: some interleaving can deadlock;
  * `lock-order-inversion` (ERROR)  — an edge contradicts a declared
    `(first, then)` pair;
  * `leaf-lock-violation` (ERROR)   — a lock acquired while a LEAF
    lock is held (leaves are terminal by doctrine: metric primitives,
    stats/ring locks — nothing may nest under them);
  * `lock-self-deadlock` (ERROR)    — a non-reentrant lock lexically
    re-entered in one function body (cross-function self edges are
    skipped: two frames usually mean two instances);
  * `lock-order-undeclared` (INFO)  — an observed edge the manifest
    has no opinion on; surfaced for review, never a gate failure.

`# meshlint: lock-ok` on the inner acquisition line (or on the call
line that imports the edge) suppresses ordering verdicts for that
edge — a reviewed, documented exception."""
from __future__ import annotations

import ast
import dataclasses

from istio_tpu.analysis.findings import Severity
from istio_tpu.analysis.meshlint import callgraph as cg
from istio_tpu.analysis.meshlint import model

# ---------------------------------------------------------------------------
# The lock-order manifest (lockorder.toml rendered as code so it ships,
# versions and reviews with the analyzer).
#
# DECLARED_ORDER: (first, then) pairs — taking `then` before `first`
# on any path is an inversion. The quota pool's discipline is written
# in prose at runtime/device_quota.py ("Lock order: ALWAYS
# _counts_lock then self._lock"); this is that sentence as data.
DECLARED_ORDER: frozenset[tuple[str, str]] = frozenset({
    ("DeviceQuotaPool._counts_lock", "DeviceQuotaPool._lock"),
    # quota futures are resolved while the pool lock is held
    ("DeviceQuotaPool._lock", "QuotaFuture._lock"),
    # discovery publish: publish serialization → cache invalidation /
    # pending-group set / watcher wake (discovery.py, PR 15)
    ("DiscoveryService._publish_lock", "SnapshotCache._lock"),
    ("DiscoveryService._publish_lock", "DiscoveryService._gen_lock"),
    ("DiscoveryService._publish_lock", "DiscoveryService._watch"),
    # batched RDS generation stores under the pending-group lock
    ("DiscoveryService._gen_lock", "SnapshotCache._lock"),
    # config rebuild serialization wraps the whole build: store list,
    # handler-table swap, and the native-extension build gate all
    # nest under _rebuild_serial (controller.py)
    ("Controller._rebuild_serial", "Store._lock"),
    ("Controller._rebuild_serial", "HandlerTable._lock"),
    ("Controller._rebuild_serial", "build._lock"),
})

# Leaf locks: terminal by doctrine. Metric primitives are taken on
# every hot-path sample; stats/ring locks guard fixed-size buffers.
# Holding ANY of these while acquiring another lock is a violation.
LEAF_LOCKS: frozenset[str] = frozenset({
    "Counter._lock", "Gauge._lock", "Histogram._lock",
    "SlidingWindow._lock", "Registry._lock",        # utils/metrics.py
    "ShardRouter._stats_lock",                      # sharding/router.py
    "EventTimeline._lock",                          # forensics ring
    # secure serving plane (istio_tpu/secure): the cert-bundle holder,
    # the node agent, the TLS lane's conn/stats lock and the peer-cert
    # parse cache are all terminal — rotation subscribers run OUTSIDE
    # WorkloadIdentity._lock precisely so nothing ever nests here
    "ServingCerts._lock",                           # secure/mtls.py
    "WorkloadIdentity._lock",                       # secure/identity.py
    "TlsTerminatingLane._lock",                     # secure/tlslane.py
    "mtls._PEER_CACHE_LOCK",                        # secure/mtls.py
})

# Reentrant locks (threading.RLock) — self edges are legal.
# Detected from the declaration site too; listed here so fixtures and
# out-of-universe declarations behave identically.
KNOWN_REENTRANT: frozenset[str] = frozenset()

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTOR = "Condition"


@dataclasses.dataclass
class LockDecl:
    lock_id: str            # "DeviceQuotaPool._lock" / "build._lock"
    path: str
    line: int
    reentrant: bool = False
    alias_of: str | None = None   # Condition(self._x) → underlying id


@dataclasses.dataclass
class Acquisition:
    lock: str
    path: str
    line: int
    func: str               # qualname of the acquiring function
    held: tuple[str, ...]   # locks already held at this site


@dataclasses.dataclass
class CallUnder:
    callee: str             # fqn
    path: str
    line: int
    func: str
    held: tuple[str, ...]


@dataclasses.dataclass
class LockEdge:
    """outer → inner acquisition, with a replayable witness chain."""
    outer: str
    inner: str
    path: str
    line: int               # the line that completes the edge
    func: str
    chain: tuple[str, ...]  # witness frames


class LockGraph:
    """Declarations, per-function acquisition facts, transitive
    closure and the resulting outer→inner edge set."""

    def __init__(self, u: cg.Universe) -> None:
        self.u = u
        self.decls: dict[str, LockDecl] = {}
        self.acquisitions: dict[str, list[Acquisition]] = {}
        self.calls_under: dict[str, list[CallUnder]] = {}
        self._collect_decls()
        for fi in u.functions.values():
            self._scan_function(fi)
        # transitive: fqn → {lock: (line, via_callee_fqn|None)}
        self.transitive: dict[str, dict[str, tuple[int, str | None]]] = {}
        self._fixpoint()
        self.edges: list[LockEdge] = self._build_edges()

    # -- declarations -------------------------------------------------

    def _collect_decls(self) -> None:
        for mi in self.u.modules.values():
            mod_tail = mi.name.rsplit(".", 1)[-1]
            # module-level locks
            for node in mi.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    d = self._lock_value(mi, node.value, owner=None)
                    if d is not None:
                        lock_id = f"{mod_tail}.{node.targets[0].id}"
                        kind, alias = d
                        self.decls[lock_id] = LockDecl(
                            lock_id, mi.path, node.lineno,
                            reentrant=(kind == "RLock"),
                            alias_of=alias)
        # instance locks from constructor bodies
        for fi in self.u.functions.values():
            if fi.cls is None or fi.name != "__init__":
                continue
            cls_name = self.u.classes[fi.cls].name
            mi = self.u.modules[fi.module]
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                chain = cg._dotted(node.targets[0])
                if not chain or len(chain) != 2 or chain[0] != "self":
                    continue
                d = self._lock_value(mi, node.value, owner=cls_name)
                if d is None:
                    continue
                kind, alias = d
                lock_id = f"{cls_name}.{chain[1]}"
                self.decls[lock_id] = LockDecl(
                    lock_id, mi.path, node.lineno,
                    reentrant=(kind == "RLock"), alias_of=alias)
        # resolve alias chains (Condition(self._lock) → _lock's id)
        for d in self.decls.values():
            seen = set()
            while d.alias_of and d.alias_of in self.decls \
                    and d.alias_of not in seen:
                seen.add(d.alias_of)
                tgt = self.decls[d.alias_of]
                if tgt.alias_of is None:
                    break
                d.alias_of = tgt.alias_of

    def _lock_value(self, mi: cg.ModuleInfo, value: ast.AST,
                    owner: str | None) -> tuple[str, str | None] | None:
        """`threading.Lock()`-shaped ctor → (kind, alias_of)."""
        if not isinstance(value, ast.Call):
            return None
        chain = cg._dotted(value.func)
        if chain is None:
            return None
        name = chain[-1]
        head_ok = len(chain) == 1 or chain[0] == "threading"
        if not head_ok:
            return None
        if name in _LOCK_CTORS:
            return (name, None)
        if name == _COND_CTOR:
            # Condition(self._x) aliases; Condition()/Condition(Lock())
            # owns a fresh lock
            if value.args:
                ach = cg._dotted(value.args[0])
                if ach and len(ach) == 2 and ach[0] == "self" and owner:
                    return ("Condition", f"{owner}.{ach[1]}")
            return ("Condition", None)
        return None

    def canonical(self, lock_id: str) -> str:
        d = self.decls.get(lock_id)
        if d is not None and d.alias_of:
            return d.alias_of
        return lock_id

    def _reentrant(self, lock_id: str) -> bool:
        d = self.decls.get(lock_id)
        return (d is not None and d.reentrant) \
            or lock_id in KNOWN_REENTRANT

    # -- per-function scan --------------------------------------------

    def _lock_of_expr(self, fi: cg.FunctionInfo, node: ast.AST,
                      local: dict[str, str]) -> str | None:
        """Expression in acquiring position → canonical lock id."""
        chain = cg._dotted(node)
        if chain is None:
            return None
        mi = self.u.modules[fi.module]
        # module-level lock by bare name or module alias
        if len(chain) == 1:
            cand = f"{fi.module.rsplit('.', 1)[-1]}.{chain[0]}"
            if cand in self.decls:
                return self.canonical(cand)
            if chain[0] in mi.sym_imports:
                m, sym = mi.sym_imports[chain[0]]
                cand = f"{m.rsplit('.', 1)[-1]}.{sym}"
                if cand in self.decls:
                    return self.canonical(cand)
            return None
        *base, attr = chain
        if base == ["self"] and fi.cls is not None:
            cls = self.u.classes[fi.cls]
            # walk the base chain: the lock may be declared by a parent
            for cname in self._class_names(fi.cls):
                cand = f"{cname}.{attr}"
                if cand in self.decls:
                    return self.canonical(cand)
            return f"{cls.name}.{attr}" if self._looks_lockish(attr) \
                else None
        if len(base) == 1 and base[0] in mi.mod_imports:
            cand = f"{mi.mod_imports[base[0]].rsplit('.', 1)[-1]}.{attr}"
            if cand in self.decls:
                return self.canonical(cand)
        # typed chains: self.pool._counts_lock / p._lock
        t = self.u._chain_type(fi, tuple(base), local)
        if t is not None:
            for cname in self._class_names(t):
                cand = f"{cname}.{attr}"
                if cand in self.decls:
                    return self.canonical(cand)
        return None

    def _class_names(self, cls_fqn: str) -> list[str]:
        out, stack, seen = [], [cls_fqn], set()
        while stack:
            c = stack.pop(0)
            if c in seen:
                continue
            seen.add(c)
            ci = self.u.classes.get(c)
            if ci is None:
                continue
            out.append(ci.name)
            stack.extend(ci.bases)
        return out

    @staticmethod
    def _looks_lockish(attr: str) -> bool:
        return attr.endswith(("_lock", "_cv", "_cond")) \
            or attr in ("_lock", "lock")

    def _scan_function(self, fi: cg.FunctionInfo) -> None:
        local = self.u.local_types(fi)
        acqs: list[Acquisition] = []
        calls: list[CallUnder] = []
        nested = set()
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fi.node:
                nested.add(n)

        def walk_body(body: list[ast.stmt], held: list[str]) -> None:
            manual: list[str] = []    # .acquire()d in THIS block
            for st in body:
                self._note_calls(st, fi, local, held, calls, nested)
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    got: list[str] = []
                    for item in st.items:
                        lk = self._lock_of_expr(
                            fi, item.context_expr, local)
                        if lk is not None:
                            acqs.append(Acquisition(
                                lk, fi.path, item.context_expr.lineno,
                                fi.qual, tuple(held + got)))
                            got.append(lk)
                    walk_body(st.body, held + got)
                elif isinstance(st, ast.Expr) \
                        and isinstance(st.value, ast.Call) \
                        and isinstance(st.value.func, ast.Attribute):
                    meth = st.value.func.attr
                    if meth in ("acquire", "release"):
                        lk = self._lock_of_expr(
                            fi, st.value.func.value, local)
                        if lk is not None:
                            if meth == "acquire":
                                acqs.append(Acquisition(
                                    lk, fi.path, st.lineno, fi.qual,
                                    tuple(held)))
                                held = held + [lk]
                                manual.append(lk)
                            elif lk in held:
                                held = [h for h in held if h != lk]
                                if lk in manual:
                                    manual.remove(lk)
                elif isinstance(st, (ast.If, ast.While, ast.For,
                                     ast.AsyncFor)):
                    walk_body(st.body, list(held))
                    walk_body(st.orelse, list(held))
                elif isinstance(st, ast.Try):
                    walk_body(st.body, list(held))
                    for h in st.handlers:
                        walk_body(h.body, list(held))
                    walk_body(st.orelse, list(held))
                    walk_body(st.finalbody, list(held))

        walk_body(list(fi.node.body), [])
        self.acquisitions[fi.fqn] = acqs
        self.calls_under[fi.fqn] = calls

    def _note_calls(self, st: ast.stmt, fi: cg.FunctionInfo,
                    local: dict[str, str], held: list[str],
                    out: list[CallUnder], nested: set) -> None:
        """Resolvable call sites in statement `st`'s own expressions
        (compound bodies are walked separately, with their held set)."""
        if isinstance(st, (ast.If, ast.While, ast.For, ast.AsyncFor,
                           ast.Try, ast.With, ast.AsyncWith)):
            # only the header expression(s), not the body
            headers: list[ast.AST] = []
            if isinstance(st, (ast.If, ast.While)):
                headers = [st.test]
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                headers = [st.iter]
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                headers = [i.context_expr for i in st.items]
            nodes: list[ast.AST] = []
            for h in headers:
                nodes.extend(ast.walk(h))
        else:
            skip: set[ast.AST] = set()
            for n in ast.walk(st):
                if n in nested:
                    for sub in ast.walk(n):
                        skip.add(sub)
            nodes = [n for n in ast.walk(st) if n not in skip]
        for n in nodes:
            if isinstance(n, ast.Call):
                callee = self.u.resolve_call(fi, n, local)
                if callee is not None and callee != fi.fqn:
                    out.append(CallUnder(callee, fi.path, n.lineno,
                                         fi.qual, tuple(held)))

    # -- transitive closure -------------------------------------------

    def _fixpoint(self) -> None:
        for fqn in self.u.functions:
            t: dict[str, tuple[int, str | None]] = {}
            for a in self.acquisitions.get(fqn, ()):
                t.setdefault(a.lock, (a.line, None))
            self.transitive[fqn] = t
        changed = True
        while changed:
            changed = False
            for fqn in self.u.functions:
                t = self.transitive[fqn]
                for c in self.calls_under.get(fqn, ()):
                    for lk in self.transitive.get(c.callee, ()):
                        if lk not in t:
                            t[lk] = (c.line, c.callee)
                            changed = True

    def chain_to(self, fqn: str, lock: str,
                 _depth: int = 0) -> list[str]:
        """Witness frames from `fqn` down to the acquisition of
        `lock`, following the recorded (line, via) back-pointers."""
        if _depth > 32:
            return ["… (chain truncated)"]
        entry = self.transitive.get(fqn, {}).get(lock)
        if entry is None:
            return []
        line, via = entry
        fi = self.u.functions[fqn]
        if via is None:
            return [f"{fi.path}:{line} {fi.qual} — acquires {lock}"]
        vi = self.u.functions[via]
        return [f"{fi.path}:{line} {fi.qual} — calls {vi.qual}"] \
            + self.chain_to(via, lock, _depth + 1)

    # -- edge construction --------------------------------------------

    def _build_edges(self) -> list[LockEdge]:
        edges: list[LockEdge] = []
        for fqn, fi in self.u.functions.items():
            lines = self.u.lines_of(fi)
            for a in self.acquisitions.get(fqn, ()):
                if model.has_pragma(lines, a.line, "lock-ok"):
                    continue
                for outer in a.held:
                    edges.append(LockEdge(
                        outer, a.lock, a.path, a.line, a.func,
                        chain=(f"{a.path}:{a.line} {a.func} — "
                               f"acquires {a.lock} while holding "
                               f"{outer}",)))
            for c in self.calls_under.get(fqn, ()):
                if not c.held:
                    continue
                if model.has_pragma(lines, c.line, "lock-ok"):
                    continue
                for inner in self.transitive.get(c.callee, ()):
                    for outer in c.held:
                        if inner == outer:
                            continue  # cross-frame self edges: skipped
                        callee_q = self.u.functions[c.callee].qual
                        chain = tuple(
                            [f"{c.path}:{c.line} {c.func} — holds "
                             f"{outer}, calls {callee_q}"]
                            + self.chain_to(c.callee, inner))
                        edges.append(LockEdge(
                            outer, inner, c.path, c.line, c.func,
                            chain=chain))
        return edges


def _cycles(edges: list[LockEdge]) -> list[list[str]]:
    """Tarjan SCCs of size > 1 over the distinct edge pairs."""
    graph: dict[str, set[str]] = {}
    for e in edges:
        graph.setdefault(e.outer, set()).add(e.inner)
        graph.setdefault(e.inner, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    onstack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in onstack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                onstack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def run(u: cg.Universe, report: model.MeshlintReport) -> LockGraph:
    g = LockGraph(u)
    seen: set[tuple] = set()

    # self-deadlock: lexical re-entry of a non-reentrant lock
    for fqn, acqs in g.acquisitions.items():
        fi = u.functions[fqn]
        lines = u.lines_of(fi)
        for a in acqs:
            if a.lock in a.held and not g._reentrant(a.lock):
                if model.has_pragma(lines, a.line, "lock-ok"):
                    continue
                key = (model.LOCK_SELF, a.path, a.line, a.lock)
                if key in seen:
                    continue
                seen.add(key)
                report.add(model.LintFinding(
                    model.LOCK_SELF, Severity.ERROR, a.path, a.line,
                    a.func,
                    f"non-reentrant lock {a.lock} re-acquired while "
                    f"already held in this function",
                    chain=(f"{a.path}:{a.line} {a.func} — re-enters "
                           f"{a.lock}",)))

    declared = set(DECLARED_ORDER)
    for e in g.edges:
        pair = (e.outer, e.inner)
        if (pair[1], pair[0]) in declared:
            key = (model.LOCK_INVERSION, e.path, e.line, pair)
            if key not in seen:
                seen.add(key)
                report.add(model.LintFinding(
                    model.LOCK_INVERSION, Severity.ERROR, e.path,
                    e.line, e.func,
                    f"lock order inversion: {e.inner} must be taken "
                    f"BEFORE {e.outer} (declared order "
                    f"{e.inner} -> {e.outer})", chain=e.chain))
        elif e.outer in LEAF_LOCKS:
            key = (model.LOCK_LEAF, e.path, e.line, pair)
            if key not in seen:
                seen.add(key)
                report.add(model.LintFinding(
                    model.LOCK_LEAF, Severity.ERROR, e.path, e.line,
                    e.func,
                    f"{e.inner} acquired while holding leaf lock "
                    f"{e.outer} (leaf locks are terminal)",
                    chain=e.chain))
        elif pair not in declared:
            key = (model.LOCK_UNDECLARED, pair)
            if key not in seen:
                seen.add(key)
                report.add(model.LintFinding(
                    model.LOCK_UNDECLARED, Severity.INFO, e.path,
                    e.line, e.func,
                    f"observed lock edge {e.outer} -> {e.inner} is "
                    f"not in the declared order", chain=e.chain))

    for comp in _cycles(g.edges):
        # pick a representative edge inside the SCC for anchoring
        rep = next((e for e in g.edges
                    if e.outer in comp and e.inner in comp), None)
        report.add(model.LintFinding(
            model.LOCK_CYCLE, Severity.ERROR,
            rep.path if rep else "<graph>",
            rep.line if rep else 0,
            rep.func if rep else "<graph>",
            "lock acquisition cycle: " + " <-> ".join(comp),
            chain=rep.chain if rep else ()))

    report.stats["lock_decls"] = len(g.decls)
    report.stats["lock_edges"] = len({(e.outer, e.inner)
                                      for e in g.edges})
    return g
