"""Typed-rejection pass — untyped raises must not escape the fronts.

Contract (runtime/resilience.py): rejections cross a front as
`CheckRejected` subclasses carrying `grpc_code`, and every front —
grpc sync + aio handlers, the native pump's batch legs, the discovery
HTTP front, the introspect admin front — maps them to wire status.
An exception WITHOUT a wire code escaping a boundary surfaces as
transport-default UNKNOWN with no shed/reject accounting: the exact
bug class PR 6's typed-shed work removed.

The pass computes, per function, the exception classes its explicit
`raise` statements can propagate (through the call graph, filtered by
enclosing `except` clauses — `except Exception`/bare catches all; a
bare `raise` inside `except X` re-raises X) and verifies each
FRONT_BOUNDARY function lets nothing untyped out. The typed set is
STRUCTURAL: any scanned class that defines or inherits a `grpc_code`
attribute.

Scope is deliberately bounded to keep verdicts actionable: escapes of
IN-UNIVERSE exception classes are tracked through the whole call
graph, while builtin raises (`ValueError(...)` etc.) are only flagged
when raised DIRECTLY in a boundary function — a ValueError deep in a
helper is a programming-error path (grpc's catch-all is the right
backstop), but an in-universe domain rejection crossing a front
untyped is a contract violation wherever it starts.

`# meshlint: raise-ok [reason]` on the raise line suppresses.
`front-boundary-missing` (ERROR) fires when a configured boundary no
longer resolves, so the manifest cannot rot silently."""
from __future__ import annotations

import ast

from istio_tpu.analysis.findings import Severity
from istio_tpu.analysis.meshlint import callgraph as cg
from istio_tpu.analysis.meshlint import model

# (module substring, qualname suffix) — resolved against the universe
FRONT_BOUNDARIES: tuple[tuple[str, str], ...] = (
    # grpc sync front
    ("api.grpc_server", "MixerGrpcServer._check"),
    ("api.grpc_server", "MixerGrpcServer._batch_check"),
    ("api.grpc_server", "MixerGrpcServer._report"),
    # grpc aio front
    ("api.grpc_server", "MixerAioGrpcServer._acheck"),
    ("api.grpc_server", "MixerAioGrpcServer._abatch_check"),
    ("api.grpc_server", "MixerAioGrpcServer._areport"),
    # native wire front: the pump thread and its dispatch legs
    ("api.native_server", "NativeMixerServer._pump_loop"),
    ("api.native_server", "NativeMixerServer._run_batch"),
    ("api.native_server", "NativeMixerServer._run_reports"),
    ("api.native_server", "NativeMixerServer._run_checks"),
    # discovery HTTP front (nested stdlib handler class)
    ("pilot.discovery", "Handler.do_GET"),
    # introspect admin front: do_GET delegates straight to _route
    ("introspect.server", "Handler.do_GET"),
    ("introspect.server", "IntrospectServer._route"),
)

# builtin exception hierarchy (the slice this codebase raises) — used
# to decide whether an `except` clause catches a class.
_BUILTIN_BASES: dict[str, str] = {
    "Exception": "BaseException",
    "ArithmeticError": "Exception", "ZeroDivisionError":
        "ArithmeticError", "AssertionError": "Exception",
    "AttributeError": "Exception", "BufferError": "Exception",
    "EOFError": "Exception", "ImportError": "Exception",
    "ModuleNotFoundError": "ImportError", "LookupError": "Exception",
    "IndexError": "LookupError", "KeyError": "LookupError",
    "MemoryError": "Exception", "NameError": "Exception",
    "OSError": "Exception", "IOError": "OSError",
    "FileNotFoundError": "OSError", "TimeoutError": "OSError",
    "ConnectionError": "OSError", "BrokenPipeError":
        "ConnectionError", "ReferenceError": "Exception",
    "RuntimeError": "Exception", "NotImplementedError":
        "RuntimeError", "RecursionError": "RuntimeError",
    "StopIteration": "Exception", "StopAsyncIteration": "Exception",
    "SyntaxError": "Exception", "SystemError": "Exception",
    "TypeError": "Exception", "ValueError": "Exception",
    "UnicodeError": "ValueError", "OverflowError": "ArithmeticError",
    "KeyboardInterrupt": "BaseException", "SystemExit":
        "BaseException", "GeneratorExit": "BaseException",
}


class EscapeIndex:
    """Per-function escaping-exception summaries with witness
    back-pointers, plus the structural typed set."""

    def __init__(self, u: cg.Universe) -> None:
        self.u = u
        # class fqn → raw base-name strings (pre-resolution)
        self.raw_bases: dict[str, list[str]] = {}
        # class fqns that define/inherit grpc_code
        self.typed: set[str] = set()
        self._collect_classes()
        # fqn → {exc_key: (line, via_fqn|None, raise_line)} where
        # exc_key is a class fqn or a builtin name; builtins only
        # recorded at depth 0 (via is None)
        self.escapes: dict[str, dict[str, tuple[int, str | None]]] = {}
        self._direct: dict[str, list[tuple[str, int, tuple]]] = {}
        self._calls: dict[str, list[tuple[str, int, tuple]]] = {}
        for fi in u.functions.values():
            self._scan(fi)
        self._fixpoint()

    # -- class facts --------------------------------------------------

    def _collect_classes(self) -> None:
        for mi in self.u.modules.values():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                fqns = [f for f, ci in self.u.classes.items()
                        if ci.module == mi.name
                        and ci.name.split(".")[-1] == node.name]
                raw = []
                for b in node.bases:
                    ch = cg._dotted(b)
                    if ch:
                        raw.append(ch[-1])
                defines = any(
                    (isinstance(st, ast.Assign)
                     and any(isinstance(t, ast.Name)
                             and t.id == "grpc_code"
                             for t in st.targets))
                    or (isinstance(st, ast.AnnAssign)
                        and isinstance(st.target, ast.Name)
                        and st.target.id == "grpc_code")
                    for st in node.body)
                for f in fqns:
                    self.raw_bases[f] = raw
                    if defines:
                        self.typed.add(f)
        # inheritance closure over scanned bases
        changed = True
        while changed:
            changed = False
            for f, ci in self.u.classes.items():
                if f in self.typed:
                    continue
                if any(b in self.typed for b in ci.bases):
                    self.typed.add(f)
                    changed = True

    def ancestors(self, exc_key: str) -> set[str]:
        """Simple-name ancestor set (self included) of a class fqn or
        builtin name — the vocabulary `except` clauses speak."""
        out: set[str] = set()
        stack = [exc_key]
        seen: set[str] = set()
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            if k in self.u.classes:
                ci = self.u.classes[k]
                out.add(ci.name.split(".")[-1])
                stack.extend(ci.bases)
                for rb in self.raw_bases.get(k, ()):
                    if rb in _BUILTIN_BASES or rb in ("BaseException",):
                        stack.append(rb)
            else:
                out.add(k)
                if k in _BUILTIN_BASES:
                    stack.append(_BUILTIN_BASES[k])
        return out

    def is_typed(self, exc_key: str) -> bool:
        return exc_key in self.typed

    def display(self, exc_key: str) -> str:
        if exc_key in self.u.classes:
            ci = self.u.classes[exc_key]
            return f"{ci.module.rsplit('.', 1)[-1]}.{ci.name}"
        return exc_key

    def _caught_by(self, exc_key: str,
                   handler_stack: tuple) -> bool:
        """handler_stack: tuple of frozensets of handler names active
        at the site; None inside a set = bare except."""
        anc = None
        for names in handler_stack:
            if None in names:
                return True
            if anc is None:
                anc = self.ancestors(exc_key)
            if anc & names:
                return True
        return False

    # -- per-function scan --------------------------------------------

    def _exc_key_of(self, fi: cg.FunctionInfo, node: ast.AST,
                    ) -> str | None:
        """raise operand → class fqn / builtin name / None."""
        if isinstance(node, ast.Call):
            node = node.func
        ch = cg._dotted(node)
        if ch is None:
            return None
        mi = self.u.modules[fi.module]
        fqn = self.u.resolve_class(mi, ".".join(ch))
        if fqn:
            return fqn
        tail = ch[-1]
        if tail in _BUILTIN_BASES or tail == "BaseException":
            return tail
        if not tail[:1].isupper():
            # `raise first` — a VARIABLE holding an exception whose
            # type is dynamic; model it as Exception (what a front's
            # catch-all would see), judged at the boundary only
            return "Exception"
        # unknown foreign class — keep its simple name so the catch
        # filter can still match `except Tail`
        return tail

    def _handler_names(self, handler: ast.ExceptHandler,
                       ) -> frozenset:
        if handler.type is None:
            return frozenset({None})
        types = handler.type.elts \
            if isinstance(handler.type, ast.Tuple) else [handler.type]
        names = set()
        for t in types:
            ch = cg._dotted(t)
            names.add(ch[-1] if ch else None)
        return frozenset(names)

    def _scan(self, fi: cg.FunctionInfo) -> None:
        u = self.u
        local = u.local_types(fi)
        direct: list[tuple[str, int, tuple]] = []
        calls: list[tuple[str, int, tuple]] = []
        nested: set[ast.AST] = set()
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fi.node:
                for sub in ast.walk(n):
                    nested.add(sub)

        def visit(node: ast.AST, stack: tuple, cur_handler: str | None,
                  handler_var: str | None) -> None:
            if node in nested:
                return
            if isinstance(node, ast.Try):
                hnames = tuple(self._handler_names(h)
                               for h in node.handlers)
                inner = stack + tuple(hnames)
                for st in node.body:
                    visit(st, inner, cur_handler, handler_var)
                for h in node.handlers:
                    ht = self._handler_names(h)
                    rep = next(iter(ht - {None}), None)
                    for st in h.body:
                        visit(st, stack, rep, h.name)
                for st in node.orelse + node.finalbody:
                    visit(st, stack, cur_handler, handler_var)
                return
            if isinstance(node, ast.Raise):
                if node.exc is None:
                    key = cur_handler or "BaseException"
                    direct.append((key, node.lineno, stack))
                elif isinstance(node.exc, ast.Name) \
                        and handler_var and node.exc.id == handler_var:
                    direct.append((cur_handler or "BaseException",
                                   node.lineno, stack))
                else:
                    key = self._exc_key_of(fi, node.exc)
                    if key is not None:
                        direct.append((key, node.lineno, stack))
                # fall through: the raise operand may contain calls
            if isinstance(node, ast.Call):
                callee = u.resolve_call(fi, node, local)
                if callee is not None and callee != fi.fqn:
                    calls.append((callee, node.lineno, stack))
            for child in ast.iter_child_nodes(node):
                visit(child, stack, cur_handler, handler_var)

        for st in fi.node.body:
            visit(st, (), None, None)
        self._direct[fi.fqn] = direct
        self._calls[fi.fqn] = calls

    # -- fixpoint -----------------------------------------------------

    def _fixpoint(self) -> None:
        for fqn in self.u.functions:
            esc: dict[str, tuple[int, str | None]] = {}
            lines = self.u.lines_of(self.u.functions[fqn])
            for key, line, stack in self._direct.get(fqn, ()):
                if model.has_pragma(lines, line, "raise-ok"):
                    continue
                if not self._caught_by(key, stack):
                    esc.setdefault(key, (line, None))
            self.escapes[fqn] = esc
        changed = True
        while changed:
            changed = False
            for fqn in self.u.functions:
                esc = self.escapes[fqn]
                for callee, line, stack in self._calls.get(fqn, ()):
                    for key in self.escapes.get(callee, ()):
                        # builtin / foreign names propagate one level
                        # only when tracked in-universe
                        if key not in self.u.classes \
                                and key not in _BUILTIN_BASES \
                                and key != "BaseException":
                            pass  # foreign simple name: still track
                        if key not in esc \
                                and not self._caught_by(key, stack):
                            esc[key] = (line, callee)
                            changed = True

    def chain_to(self, fqn: str, key: str, _depth: int = 0) -> list[str]:
        if _depth > 32:
            return ["… (chain truncated)"]
        entry = self.escapes.get(fqn, {}).get(key)
        if entry is None:
            return []
        line, via = entry
        fi = self.u.functions[fqn]
        if via is None:
            return [f"{fi.path}:{line} {fi.qual} — raises "
                    f"{self.display(key)}"]
        vi = self.u.functions[via]
        return [f"{fi.path}:{line} {fi.qual} — calls {vi.qual}"] \
            + self.chain_to(via, key, _depth + 1)


def resolve_boundaries(u: cg.Universe,
                       specs: tuple[tuple[str, str], ...]
                       = FRONT_BOUNDARIES,
                       ) -> tuple[list[cg.FunctionInfo], list[str]]:
    found: list[cg.FunctionInfo] = []
    missing: list[str] = []
    for mod_sub, suffix in specs:
        hits = [f for f in u.functions.values()
                if mod_sub in f.module
                and (f.qual == suffix
                     or f.qual.endswith("." + suffix))]
        if hits:
            found.extend(hits)
        else:
            missing.append(f"{mod_sub}::{suffix}")
    return found, missing


def run(u: cg.Universe, report: model.MeshlintReport,
        boundaries: tuple[tuple[str, str], ...] = FRONT_BOUNDARIES,
        ) -> EscapeIndex:
    idx = EscapeIndex(u)
    fronts, missing = resolve_boundaries(u, boundaries)
    for m in missing:
        report.add(model.LintFinding(
            model.BOUNDARY_MISSING, Severity.ERROR, "<config>", 0,
            "<config>",
            f"front boundary {m!r} no longer resolves — update "
            f"meshlint.rejections.FRONT_BOUNDARIES"))
    seen: set[tuple] = set()
    for fi in fronts:
        for key, (line, via) in sorted(idx.escapes.get(fi.fqn,
                                                       {}).items()):
            in_universe = key in u.classes
            if not in_universe and via is not None:
                continue    # builtins judged at the boundary only
            if idx.is_typed(key):
                continue
            dkey = (fi.fqn, key)
            if dkey in seen:
                continue
            seen.add(dkey)
            chain = tuple(idx.chain_to(fi.fqn, key))
            report.add(model.LintFinding(
                model.UNTYPED_ESCAPE, Severity.ERROR, fi.path, line,
                fi.qual,
                f"{idx.display(key)} can escape front boundary "
                f"{fi.qual} without a grpc_code — raise a typed "
                f"rejection (runtime.resilience.CheckRejected "
                f"subclass) or catch-and-map at the front",
                chain=chain))
    report.stats["front_boundaries"] = len(fronts)
    report.stats["typed_exceptions"] = len(idx.typed)
    return idx
