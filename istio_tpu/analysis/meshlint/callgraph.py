"""Shared AST universe + intraprocedural call graph for meshlint.

One parse of the package feeds all four passes: functions indexed by
qualified name, classes with resolved bases and inferred attribute
types, and a best-effort call-resolution oracle. Resolution is
deliberately CONSERVATIVE — a call that cannot be attributed to a
scanned function is simply not traversed (never guessed by method
name), and the load-bearing dynamic seams (constructor-injected
callbacks like the batcher's `run_batch`) are modeled as DECLARED
edges in the pass manifests, where they are reviewable data rather
than resolver magic.

What the resolver does understand:
  * bare names — module functions, `from x import f` symbols, local
    `f = Foo` class aliases (constructor call → `Foo.__init__`);
  * `self.method()` / `cls.method()` / `super().method()` through the
    scanned base-class chain;
  * `self.attr.method()` / `local.method()` where the attr/local's
    class was inferred from `self.attr = Foo(...)`, an annotated
    assignment, a constructor parameter annotation, or a dataclass
    field annotation;
  * `module.func()` / `module.Class(...)` through the import map.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Mapping

# the scanned sub-packages (repo-relative, under the package root) the
# passes run over by default; native/ is its python half (the C++ side
# has its own discipline), soak/ is the composition plane
DEFAULT_PACKAGES = (
    "istio_tpu/runtime", "istio_tpu/sharding", "istio_tpu/native",
    "istio_tpu/soak", "istio_tpu/canary", "istio_tpu/pilot",
    "istio_tpu/api", "istio_tpu/introspect", "istio_tpu/adapters",
    "istio_tpu/utils",
)


@dataclasses.dataclass
class FunctionInfo:
    fqn: str                     # "istio_tpu.runtime.batcher:CheckBatcher.submit"
    module: str                  # dotted module name
    path: str                    # repo-relative file path
    qual: str                    # "CheckBatcher.submit" / "helper"
    node: ast.AST                # FunctionDef | AsyncFunctionDef
    cls: str | None              # owning class fqn ("module:Class") or None

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclasses.dataclass
class ClassInfo:
    fqn: str                     # "module:Class"
    module: str
    name: str
    bases: list[str]             # resolved class fqns (scanned only)
    methods: dict = dataclasses.field(default_factory=dict)  # name → fqn
    attr_types: dict = dataclasses.field(default_factory=dict)  # attr → class fqn


@dataclasses.dataclass
class ModuleInfo:
    name: str                    # dotted
    path: str                    # repo-relative
    tree: ast.Module
    lines: list[str]
    # alias → dotted module ("np" → "numpy"); symbol alias → (module, name)
    mod_imports: dict = dataclasses.field(default_factory=dict)
    sym_imports: dict = dataclasses.field(default_factory=dict)
    functions: dict = dataclasses.field(default_factory=dict)  # qual → fqn
    classes: dict = dataclasses.field(default_factory=dict)    # name → fqn


def _dotted(node: ast.AST) -> tuple[str, ...] | None:
    """Attribute/Name chain → ('self', '_lock') / ('np', 'asarray')."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "super":
        parts.append("super()")
        return tuple(reversed(parts))
    return None


class Universe:
    """Parsed modules + indexes. Build from a directory tree
    (`Universe.from_root`) or from in-memory sources
    (`Universe.from_sources`) — fixtures and unit tests use the
    latter, so every pass is testable without touching disk."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}

    # -- construction -------------------------------------------------

    @classmethod
    def from_root(cls, root: str,
                  packages: Iterable[str] = DEFAULT_PACKAGES) -> "Universe":
        u = cls()
        for pkg in packages:
            base = os.path.join(root, pkg)
            if not os.path.isdir(base):
                continue
            for dirpath, _dirnames, filenames in os.walk(base):
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, root)
                    mod = rel[:-3].replace(os.sep, ".")
                    if mod.endswith(".__init__"):
                        mod = mod[:-len(".__init__")]
                    with open(path, encoding="utf-8") as f:
                        u._add_module(mod, rel, f.read())
        u._link()
        return u

    @classmethod
    def from_sources(cls, sources: Mapping[str, str]) -> "Universe":
        """sources: dotted module name → source text."""
        u = cls()
        for mod, src in sources.items():
            rel = mod.replace(".", os.sep) + ".py"
            u._add_module(mod, rel, src)
        u._link()
        return u

    def _add_module(self, mod: str, rel: str, source: str) -> None:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return
        mi = ModuleInfo(name=mod, path=rel, tree=tree,
                        lines=source.splitlines())
        self.modules[mod] = mi
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.mod_imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        mi.mod_imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                if node.level:      # relative import: resolve in-package
                    parts = mod.split(".")
                    base = ".".join(parts[:len(parts) - node.level]
                                    ) + ("." + node.module
                                         if node.module else "")
                for a in node.names:
                    mi.sym_imports[a.asname or a.name] = (base, a.name)
        # function imports INSIDE functions matter too (the runtime
        # defers imports to dodge cycles) — collect them module-wide
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and not node.level:
                for a in node.names:
                    mi.sym_imports.setdefault(a.asname or a.name,
                                              (node.module, a.name))
        self._index_scope(mi, tree, prefix="", cls=None)

    def _index_scope(self, mi: ModuleInfo, node: ast.AST, prefix: str,
                     cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                cfqn = f"{mi.name}:{prefix}{child.name}"
                self.classes[cfqn] = ClassInfo(
                    fqn=cfqn, module=mi.name,
                    name=f"{prefix}{child.name}",
                    bases=[b for b in (self._base_name(x)
                                       for x in child.bases) if b])
                if not prefix:
                    mi.classes[child.name] = cfqn
                self._index_scope(mi, child, f"{prefix}{child.name}.",
                                  cls=cfqn)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                fqn = f"{mi.name}:{qual}"
                self.functions[fqn] = FunctionInfo(
                    fqn=fqn, module=mi.name, path=mi.path, qual=qual,
                    node=child, cls=cls)
                mi.functions[qual] = fqn
                if cls is not None and cls in self.classes:
                    self.classes[cls].methods[child.name] = fqn
                # nested defs/classes (stdlib HTTP Handler classes live
                # inside factory methods) — index them too
                self._index_scope(mi, child, f"{qual}.", cls=None)
            else:
                self._index_scope(mi, child, prefix, cls)

    @staticmethod
    def _base_name(node: ast.AST) -> str | None:
        chain = _dotted(node)
        return ".".join(chain) if chain else None

    def _link(self) -> None:
        """Resolve class bases to scanned fqns + infer attribute
        types (constructor assigns, annotations, dataclass fields)."""
        for ci in self.classes.values():
            mi = self.modules[ci.module]
            resolved = []
            for b in ci.bases:
                fqn = self.resolve_class(mi, b)
                if fqn:
                    resolved.append(fqn)
            ci.bases = resolved
        for fi in self.functions.values():
            if fi.cls is None or fi.name != "__init__":
                continue
            ci = self.classes[fi.cls]
            mi = self.modules[fi.module]
            ann: dict[str, str] = {}
            args = fi.node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.annotation is not None:
                    t = self._ann_class(mi, a.annotation)
                    if t:
                        ann[a.arg] = t
            for node in ast.walk(fi.node):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        ch = _dotted(t)
                        if not ch or len(ch) != 2 or ch[0] != "self":
                            continue
                        attr = ch[1]
                        typ = None
                        if isinstance(node, ast.AnnAssign) \
                                and node.annotation is not None:
                            typ = self._ann_class(mi, node.annotation)
                        if typ is None and node.value is not None:
                            typ = self._value_class(mi, node.value, ann)
                        if typ and attr not in ci.attr_types:
                            ci.attr_types[attr] = typ
        # class-body annotations (dataclass fields)
        for ci in self.classes.values():
            mi = self.modules[ci.module]
            for mod_node in ast.walk(mi.tree):
                if isinstance(mod_node, ast.ClassDef) \
                        and f"{ci.module}:" in ci.fqn \
                        and ci.name.split(".")[-1] == mod_node.name:
                    for st in mod_node.body:
                        if isinstance(st, ast.AnnAssign) \
                                and isinstance(st.target, ast.Name):
                            t = self._ann_class(mi, st.annotation)
                            if t:
                                ci.attr_types.setdefault(st.target.id, t)

    def _ann_class(self, mi: ModuleInfo, node: ast.AST) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return self.resolve_class(mi, node.value.strip('"'))
        if isinstance(node, ast.Subscript):   # Optional[X] / list[X]
            return None
        if isinstance(node, ast.BinOp):       # X | None
            left = self._ann_class(mi, node.left)
            if left:
                return left
            return self._ann_class(mi, node.right)
        chain = _dotted(node)
        return self.resolve_class(mi, ".".join(chain)) if chain else None

    def _value_class(self, mi: ModuleInfo, node: ast.AST,
                     param_ann: dict[str, str]) -> str | None:
        if isinstance(node, ast.Call):
            chain = _dotted(node.func)
            if chain:
                return self.resolve_class(mi, ".".join(chain))
        elif isinstance(node, ast.Name):
            return param_ann.get(node.id)
        elif isinstance(node, ast.IfExp):
            return self._value_class(mi, node.body, param_ann) \
                or self._value_class(mi, node.orelse, param_ann)
        return None

    # -- resolution ---------------------------------------------------

    def resolve_class(self, mi: ModuleInfo, name: str) -> str | None:
        """Dotted name in `mi`'s namespace → scanned class fqn."""
        if not name:
            return None
        head, _, rest = name.partition(".")
        if not rest:
            if name in mi.classes:
                return mi.classes[name]
            if name in mi.sym_imports:
                m, sym = mi.sym_imports[name]
                tgt = self.modules.get(m)
                if tgt and sym in tgt.classes:
                    return tgt.classes[sym]
            return None
        if head in mi.mod_imports:
            m = mi.mod_imports[head]
            tgt = self.modules.get(m)
            if tgt and rest in tgt.classes:
                return tgt.classes[rest]
        if head in mi.sym_imports:     # imported class, nested attr
            m, sym = mi.sym_imports[head]
            tgt = self.modules.get(m)
            if tgt and f"{sym}.{rest}" in tgt.classes:
                return tgt.classes[f"{sym}.{rest}"]
        return None

    def method_of(self, cls_fqn: str, name: str,
                  _seen: frozenset = frozenset()) -> str | None:
        """Method lookup through the scanned base chain (MRO-ish)."""
        ci = self.classes.get(cls_fqn)
        if ci is None or cls_fqn in _seen:
            return None
        if name in ci.methods:
            return ci.methods[name]
        seen = _seen | {cls_fqn}
        for b in ci.bases:
            hit = self.method_of(b, name, seen)
            if hit:
                return hit
        return None

    def is_subclass(self, cls_fqn: str, ancestor_fqn: str,
                    _seen: frozenset = frozenset()) -> bool:
        if cls_fqn == ancestor_fqn:
            return True
        ci = self.classes.get(cls_fqn)
        if ci is None or cls_fqn in _seen:
            return False
        seen = _seen | {cls_fqn}
        return any(self.is_subclass(b, ancestor_fqn, seen)
                   for b in ci.bases)

    def local_types(self, fi: FunctionInfo) -> dict[str, str]:
        """var name → class fqn from `x = Foo(...)` / annotated
        assigns / annotated params inside one function."""
        mi = self.modules[fi.module]
        out: dict[str, str] = {}
        args = fi.node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.annotation is not None:
                t = self._ann_class(mi, a.annotation)
                if t:
                    out[a.arg] = t
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._value_class(mi, node.value, out)
                if t:
                    out[node.targets[0].id] = t
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                t = self._ann_class(mi, node.annotation)
                if t:
                    out[node.target.id] = t
        return out

    def _chain_type(self, fi: FunctionInfo, chain: tuple[str, ...],
                    local: dict[str, str]) -> str | None:
        """Type of the OBJECT a dotted chain names ('self','a','b') —
        walks attribute types class-to-class."""
        if not chain:
            return None
        if chain[0] == "self" and fi.cls is not None:
            cur = fi.cls
            rest = chain[1:]
        elif chain[0] in local:
            cur = local[chain[0]]
            rest = chain[1:]
        else:
            return None
        for attr in rest:
            ci = self.classes.get(cur)
            if ci is None:
                return None
            nxt = ci.attr_types.get(attr)
            if nxt is None:
                # search base classes' attr types too
                nxt = self._base_attr_type(ci, attr)
            if nxt is None:
                return None
            cur = nxt
        return cur

    def _base_attr_type(self, ci: ClassInfo, attr: str,
                        _seen: frozenset = frozenset()) -> str | None:
        for b in ci.bases:
            if b in _seen:
                continue
            bi = self.classes.get(b)
            if bi is None:
                continue
            if attr in bi.attr_types:
                return bi.attr_types[attr]
            hit = self._base_attr_type(bi, attr, _seen | {ci.fqn})
            if hit:
                return hit
        return None

    def resolve_call(self, fi: FunctionInfo, call: ast.Call,
                     local: dict[str, str] | None = None) -> str | None:
        """Best-effort: call expression inside `fi` → callee fqn (a
        scanned function) or None. Constructor calls resolve to the
        class's __init__ when scanned."""
        if local is None:
            local = self.local_types(fi)
        mi = self.modules[fi.module]
        fn = call.func
        if isinstance(fn, ast.Name):
            name = fn.id
            # local alias of a class → constructor
            cls = local.get(name) or self.resolve_class(mi, name)
            if cls:
                init = self.method_of(cls, "__init__")
                return init
            # module function (methods defined in the same class body
            # are NOT bare-name visible — python scoping)
            if name in mi.functions and "." not in name:
                return mi.functions[name]
            if name in mi.sym_imports:
                m, sym = mi.sym_imports[name]
                tgt = self.modules.get(m)
                if tgt and sym in tgt.functions:
                    return tgt.functions[sym]
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        chain = _dotted(fn)
        if chain is None:
            return None
        *base, meth = chain
        if not base:
            return None
        if base == ["super()"] and fi.cls is not None:
            ci = self.classes.get(fi.cls)
            for b in (ci.bases if ci else ()):
                hit = self.method_of(b, meth)
                if hit:
                    return hit
            return None
        if base[0] == "self" and len(base) == 1 and fi.cls is not None:
            return self.method_of(fi.cls, meth)
        # typed object chains: self.a.b.meth / local.meth
        t = self._chain_type(fi, tuple(base), local)
        if t:
            return self.method_of(t, meth)
        # module.func / module.Class(...)
        if len(base) == 1:
            head = base[0]
            if head in mi.mod_imports:
                tgt = self.modules.get(mi.mod_imports[head])
                if tgt:
                    if meth in tgt.functions:
                        return tgt.functions[meth]
                    if meth in tgt.classes:
                        return self.method_of(tgt.classes[meth],
                                              "__init__")
            cls = self.resolve_class(mi, head)
            if cls:       # Class.method staticly
                return self.method_of(cls, meth)
        elif len(base) == 2:
            # module.Class.method / package.module.func
            cls = self.resolve_class(mi, ".".join(base))
            if cls:
                return self.method_of(cls, meth)
            dotted = ".".join(base)
            if base[0] in mi.mod_imports:
                dotted = mi.mod_imports[base[0]] + "." + base[1]
            tgt = self.modules.get(dotted)
            if tgt and meth in tgt.functions:
                return tgt.functions[meth]
        return None

    def calls_in(self, fi: FunctionInfo) -> list[tuple[int, str]]:
        """All resolvable call sites in `fi` → [(line, callee fqn)].
        Nested defs are separate functions and are NOT included (they
        only run if called — and the call site resolves to them)."""
        local = self.local_types(fi)
        out: list[tuple[int, str]] = []
        nested = {n for n in ast.walk(fi.node)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                  and n is not fi.node}
        skip: set[ast.AST] = set()
        for n in nested:
            for sub in ast.walk(n):
                skip.add(sub)
        for node in ast.walk(fi.node):
            if node in skip or not isinstance(node, ast.Call):
                continue
            callee = self.resolve_call(fi, node, local)
            if callee is not None and callee != fi.fqn:
                out.append((node.lineno, callee))
        return out

    def lines_of(self, fi: FunctionInfo) -> list[str]:
        return self.modules[fi.module].lines

    def find(self, suffix: str) -> FunctionInfo | None:
        """Function lookup by 'module:Qual' fqn or bare 'Qual' suffix
        (unique across the universe)."""
        if suffix in self.functions:
            return self.functions[suffix]
        hits = [f for f in self.functions.values()
                if f.qual == suffix]
        if len(hits) == 1:
            return hits[0]
        return None
