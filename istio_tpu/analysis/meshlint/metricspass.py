"""Metric-discipline pass — every family registered, every labeled
family zero-shaped at import.

The promtext doctrine (pinned family-by-family in
tests/test_metrics_exposition.py, automated here): a scrape taken
BEFORE the first request must already show every series a dashboard
will ever join on, so rate() and absence-alerts never see a series
pop into existence mid-incident. Concretely:

  * prometheus_client families with labelnames are pre-touched at
    module import (`for _r in REASONS: FAM.labels(reason=_r)`);
  * homegrown `Registry.counter` families are zero-touched with
    `FAM.inc(0)` at module level;
  * homegrown Histograms auto-emit their zero bucket ladder, and
    gauges have NO boot-set convention (some deliberately boot to 1,
    e.g. the SLO-met gauge) — both are exempt;
  * unlabeled prometheus families expose 0 automatically — exempt.

What this pass checks, at every `FAM.inc/observe/set/labels(...)`
site whose receiver is an ALL_CAPS module-level binding it can
resolve inside the scanned universe:

  * `metric-unregistered` (ERROR) — the binding is not a metric
    family declaration (the name exists but is not built by a
    registry factory / prometheus ctor);
  * `metric-label-mismatch` (ERROR) — `.labels()` keys disagree with
    the family's declared labelnames (or `.labels()` on a homegrown
    family, which has no such method);
  * `metric-zero-shape` (ERROR, on the declaration) — a family that
    REQUIRES shaping (labeled prometheus counter/histogram, homegrown
    counter) has no module-level pretouch;
  * `metric-unshaped-series` (WARNING) — a literal label value at a
    use site that the module-level pretouch provably never created
    (single-label families only; dynamic values are not judged).

`# meshlint: metric-ok` on the declaration (for shaping) or the use
line suppresses."""
from __future__ import annotations

import ast
import dataclasses

from istio_tpu.analysis.findings import Severity
from istio_tpu.analysis.meshlint import callgraph as cg
from istio_tpu.analysis.meshlint import model

_PROM_CTORS = {"Counter": "counter", "Gauge": "gauge",
               "Histogram": "histogram", "Summary": "histogram"}
_HOST_FACTORIES = {"counter", "gauge", "histogram"}
_EXEMPT_CTORS = {"SlidingWindow", "CollectorRegistry", "Registry"}
_METRIC_METHODS = {"inc", "observe", "set", "labels"}


@dataclasses.dataclass
class Family:
    name: str               # binding name (ALL_CAPS)
    module: str
    path: str
    line: int
    source: str             # "prom" | "host"
    kind: str               # counter | gauge | histogram
    labelnames: tuple[str, ...] = ()
    shaped: bool = False
    # label value universe established by module-level pretouch
    # (single-label families only; None = not tracked)
    pretouched: set | None = None

    @property
    def needs_shaping(self) -> bool:
        if self.kind == "gauge":
            return False
        if self.source == "prom":
            return bool(self.labelnames)
        return self.kind == "counter"   # host histograms auto-ladder


def _const_strings(node: ast.AST) -> tuple[str, ...] | None:
    if isinstance(node, (ast.List, ast.Tuple)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


class MetricIndex:
    """Families + module constants + pretouch facts, per universe."""

    def __init__(self, u: cg.Universe) -> None:
        self.u = u
        # (module, NAME) → Family
        self.families: dict[tuple[str, str], Family] = {}
        # (module, NAME) → line of a non-family module binding
        self.other_bindings: dict[tuple[str, str], int] = {}
        # (module, NAME) → tuple of constant strings
        self.constants: dict[tuple[str, str], tuple[str, ...]] = {}
        for mi in u.modules.values():
            self._scan_declarations(mi)
        for mi in u.modules.values():
            self._scan_pretouch(mi)

    def _scan_declarations(self, mi: cg.ModuleInfo) -> None:
        for node in mi.tree.body:
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if not name.isupper():
                continue
            key = (mi.name, name)
            consts = _const_strings(node.value)
            if consts is not None:
                self.constants[key] = consts
                continue
            fam = self._family_of(mi, name, node)
            if fam is not None:
                self.families[key] = fam
            else:
                self.other_bindings[key] = node.lineno

    def _family_of(self, mi: cg.ModuleInfo, name: str,
                   node: ast.Assign) -> Family | None:
        if not isinstance(node.value, ast.Call):
            return None
        chain = cg._dotted(node.value.func)
        if chain is None:
            return None
        tail = chain[-1]
        if tail in _EXEMPT_CTORS:
            # metric-adjacent but not a family (sliding windows,
            # registries) — legal receiver, nothing to verify
            return Family(name, mi.name, mi.path, node.lineno,
                          source="exempt", kind="exempt")
        if tail in _PROM_CTORS:
            labels: tuple[str, ...] = ()
            if len(node.value.args) >= 3:
                labels = _const_strings(node.value.args[2]) or ()
            for kw in node.value.keywords:
                if kw.arg == "labelnames":
                    labels = _const_strings(kw.value) or ()
            return Family(name, mi.name, mi.path, node.lineno,
                          source="prom", kind=_PROM_CTORS[tail],
                          labelnames=labels)
        if tail in _HOST_FACTORIES and len(chain) > 1:
            return Family(name, mi.name, mi.path, node.lineno,
                          source="host", kind=tail)
        return None

    # -- pretouch -----------------------------------------------------

    def _scan_pretouch(self, mi: cg.ModuleInfo) -> None:
        def handle(st: ast.stmt, loop_vals: dict) -> None:
            if isinstance(st, ast.For):
                vals: tuple[str, ...] | None = None
                it = st.iter
                ich = cg._dotted(it) if not isinstance(it, (ast.Tuple,
                                                            ast.List)) \
                    else None
                if isinstance(it, (ast.Tuple, ast.List)):
                    vals = _const_strings(it)
                elif ich is not None and len(ich) == 1:
                    vals = self.constants.get((mi.name, ich[0]))
                inner = dict(loop_vals)
                if isinstance(st.target, ast.Name) and vals is not None:
                    inner[st.target.id] = vals
                for s in st.body:
                    handle(s, inner)
                return
            if isinstance(st, ast.If):
                for s in st.body + st.orelse:
                    handle(s, loop_vals)
                return
            if not isinstance(st, ast.Expr):
                return
            for call in ast.walk(st.value):
                if not isinstance(call, ast.Call) \
                        or not isinstance(call.func, ast.Attribute):
                    continue
                meth = call.func.attr
                fam = self.resolve_receiver(mi, call.func.value)
                if fam is None:
                    continue
                if fam.source == "prom" and meth == "labels":
                    fam.shaped = True
                    self._note_values(fam, call, loop_vals)
                elif fam.source == "host" and meth == "inc" \
                        and call.args \
                        and isinstance(call.args[0], ast.Constant) \
                        and call.args[0].value == 0:
                    fam.shaped = True
                    self._note_values(fam, call, loop_vals)

        for st in mi.tree.body:
            handle(st, {})

    def _note_values(self, fam: Family, call: ast.Call,
                     loop_vals: dict) -> None:
        kwargs = [kw for kw in call.keywords if kw.arg]
        if len(kwargs) != 1:
            fam.pretouched = None if fam.pretouched is None \
                else fam.pretouched
            return
        if fam.pretouched is None:
            fam.pretouched = set()
        v = kwargs[0].value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            fam.pretouched.add(v.value)
        elif isinstance(v, ast.Name) and v.id in loop_vals:
            fam.pretouched.update(loop_vals[v.id])
        else:
            fam.pretouched = None   # dynamic — can't enumerate

    # -- receiver resolution ------------------------------------------

    def resolve_receiver(self, mi: cg.ModuleInfo,
                         node: ast.AST) -> Family | None:
        key = self.receiver_key(mi, node)
        if key is None:
            return None
        return self.families.get(key)

    def receiver_key(self, mi: cg.ModuleInfo,
                     node: ast.AST) -> tuple[str, str] | None:
        """ALL_CAPS receiver expression → (declaring module, NAME)."""
        chain = cg._dotted(node)
        if chain is None:
            return None
        if len(chain) == 1:
            name = chain[0]
            if not name.isupper():
                return None
            if (mi.name, name) in self.families \
                    or (mi.name, name) in self.other_bindings \
                    or (mi.name, name) in self.constants:
                return (mi.name, name)
            if name in mi.sym_imports:
                m, sym = mi.sym_imports[name]
                if m in self.u.modules:
                    return (m, sym)
            return None
        if len(chain) == 2 and chain[1].isupper():
            head, name = chain
            mod = mi.mod_imports.get(head)
            if mod and mod in self.u.modules:
                return (mod, name)
            if head in mi.sym_imports:    # from istio_tpu.runtime import monitor
                m, sym = mi.sym_imports[head]
                dotted = f"{m}.{sym}"
                if dotted in self.u.modules:
                    return (dotted, name)
        return None


def _use_sites(mi: cg.ModuleInfo):
    """Every metric-method Call in the module (functions AND module
    level) → (call node, enclosing qualname)."""
    sites = []
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _METRIC_METHODS:
            sites.append(node)
    return sites


def run(u: cg.Universe, report: model.MeshlintReport) -> MetricIndex:
    idx = MetricIndex(u)

    # declaration-side: shaping contract
    n_checked = 0
    for fam in idx.families.values():
        if fam.source == "exempt":
            continue
        n_checked += 1
        if fam.needs_shaping and not fam.shaped:
            mi = u.modules[fam.module]
            if model.has_pragma(mi.lines, fam.line, "metric-ok"):
                continue
            what = f"labeled {fam.source} {fam.kind}" \
                if fam.source == "prom" else f"host {fam.kind}"
            how = "a module-level .labels(...) pretouch loop" \
                if fam.source == "prom" else \
                "a module-level .inc(0) zero-touch"
            report.add(model.LintFinding(
                model.METRIC_ZERO_SHAPE, Severity.ERROR, fam.path,
                fam.line, "<module>",
                f"family {fam.name} ({what}) is never zero-shaped — "
                f"add {how} so a pre-traffic scrape already shows "
                f"every series"))

    # use-side: registration, label keys, series universe
    seen: set[tuple] = set()
    for mi in u.modules.values():
        for call in _use_sites(mi):
            meth = call.func.attr
            key = idx.receiver_key(mi, call.func.value)
            if key is None:
                continue
            line = call.lineno
            if model.has_pragma(mi.lines, line, "metric-ok"):
                continue
            fam = idx.families.get(key)
            if fam is None:
                if key in idx.constants:
                    continue    # tuple constants never take these
                dkey = (model.METRIC_UNREGISTERED, mi.path, line)
                if dkey in seen:
                    continue
                seen.add(dkey)
                report.add(model.LintFinding(
                    model.METRIC_UNREGISTERED, Severity.ERROR,
                    mi.path, line, "<module>",
                    f"{key[1]}.{meth}() — {key[1]} is not a "
                    f"registered metric family (declared at "
                    f"{key[0]} without a registry factory)"))
                continue
            if fam.source == "exempt":
                continue
            if meth == "labels":
                if fam.source == "host":
                    report.add(model.LintFinding(
                        model.METRIC_LABEL_MISMATCH, Severity.ERROR,
                        mi.path, line, "<module>",
                        f"{fam.name}.labels() — host families take "
                        f"labels as inc/observe/set kwargs, not "
                        f".labels()"))
                    continue
                keys = tuple(sorted(kw.arg for kw in call.keywords
                                    if kw.arg))
                want = tuple(sorted(fam.labelnames))
                n_pos = len(call.args)
                if keys and keys != want:
                    report.add(model.LintFinding(
                        model.METRIC_LABEL_MISMATCH, Severity.ERROR,
                        mi.path, line, "<module>",
                        f"{fam.name}.labels({', '.join(keys)}) — "
                        f"declared labelnames are "
                        f"({', '.join(want) or 'none'})"))
                elif not keys and n_pos \
                        and n_pos != len(fam.labelnames):
                    report.add(model.LintFinding(
                        model.METRIC_LABEL_MISMATCH, Severity.ERROR,
                        mi.path, line, "<module>",
                        f"{fam.name}.labels() takes "
                        f"{len(fam.labelnames)} positional label "
                        f"values, got {n_pos}"))
                elif keys and len(fam.labelnames) == 1 \
                        and fam.pretouched is not None:
                    v = call.keywords[0].value
                    if isinstance(v, ast.Constant) \
                            and isinstance(v.value, str) \
                            and v.value not in fam.pretouched:
                        report.add(model.LintFinding(
                            model.METRIC_UNSHAPED_SERIES,
                            Severity.WARNING, mi.path, line,
                            "<module>",
                            f"{fam.name}.labels({keys[0]}="
                            f"{v.value!r}) — series not in the "
                            f"module-level pretouch universe "
                            f"{sorted(fam.pretouched)}"))
            elif meth in ("inc", "observe", "set") \
                    and fam.source == "host" \
                    and fam.kind == "counter" \
                    and len(call.keywords) == 1 \
                    and call.keywords[0].arg \
                    and fam.pretouched:
                v = call.keywords[0].value
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, str) \
                        and v.value not in fam.pretouched:
                    report.add(model.LintFinding(
                        model.METRIC_UNSHAPED_SERIES,
                        Severity.WARNING, mi.path, line, "<module>",
                        f"{fam.name}.{meth}({call.keywords[0].arg}="
                        f"{v.value!r}) — series not in the "
                        f"module-level zero-touch universe "
                        f"{sorted(fam.pretouched)}"))

    report.stats["metric_families"] = n_checked
    return idx
