"""meshlint output contract — findings with file:line + witness chains.

The snapshot analyzer (istio_tpu/analysis/findings.py) verifies CONFIG
before it reaches the device; meshlint verifies the CODEBASE itself —
the lock partial order, the hot-path sync discipline, the promtext
zero-shaping doctrine and the typed-rejection contract that every PR
since 1 has enforced by reviewer memory. Findings reuse the analyzer's
Severity vocabulary so `mixs lint` / CI gates threshold identically to
`mixs analyze`, but where a config finding names RULES and carries an
attribute-bag witness, a code finding names a FILE:LINE and carries an
acquisition/call CHAIN — the sequence of functions that realizes the
claim (e.g. the exact call path on which lock B is taken under A).

Pragma grammar (suppression is a reviewed decision, never silence):

    # meshlint: lock-ok [reason]     — this acquisition/call edge is a
                                       deliberate, documented ordering
                                       exception
    # meshlint: raise-ok [reason]    — this raise deliberately escapes
                                       a front boundary untyped
    # meshlint: metric-ok [reason]   — this family/series is exempt
                                       from the zero-shaping contract
    # hotpath: sync-ok [reason]      — pre-existing grammar, honored
                                       by the hot-path pass unchanged

A pragma applies to the physical line it sits on (the offending
statement's first line)."""
from __future__ import annotations

import dataclasses

from istio_tpu.analysis.findings import Severity

# finding codes — one vocabulary across passes, fixtures, gates
LOCK_CYCLE = "lock-order-cycle"          # cyclic lock-acquisition graph
LOCK_INVERSION = "lock-order-inversion"  # edge against the declared order
LOCK_LEAF = "leaf-lock-violation"        # lock taken under a leaf lock
LOCK_SELF = "lock-self-deadlock"         # non-reentrant lock re-entered
LOCK_UNDECLARED = "lock-order-undeclared"  # edge the manifest doesn't know
HOTPATH_SYNC = "hotpath-host-sync"       # host sync/blocking in hot code
HOTPATH_ROOT_MISSING = "hotpath-root-missing"  # configured root vanished
METRIC_UNREGISTERED = "metric-unregistered"    # use of an unknown family
METRIC_ZERO_SHAPE = "metric-zero-shape"  # family never zero-shaped
METRIC_LABEL_MISMATCH = "metric-label-mismatch"  # label keys disagree
METRIC_UNSHAPED_SERIES = "metric-unshaped-series"  # literal label value
#                                          outside the pretouch universe
UNTYPED_ESCAPE = "untyped-front-escape"  # raise escaping a front boundary
BOUNDARY_MISSING = "front-boundary-missing"  # configured boundary vanished

PRAGMA_PREFIX = "# meshlint:"
HOTPATH_PRAGMA = "hotpath: sync-ok"


def has_pragma(lines: list[str], lineno: int, tag: str) -> bool:
    """True when the physical line carries `# meshlint: <tag>` (or, for
    the hot-path pass, the pre-existing `# hotpath: sync-ok`)."""
    if not (0 < lineno <= len(lines)):
        return False
    line = lines[lineno - 1]
    return f"meshlint: {tag}" in line or \
        (tag == "sync-ok" and HOTPATH_PRAGMA in line)


@dataclasses.dataclass
class LintFinding:
    """One code-discipline verdict, anchored at file:line.

    `chain` is the witness: an ordered tuple of human-readable frames
    ("path:line func — what happened here") realizing the claim — the
    full acquisition chain for a lock finding, the entry-point call
    path for a hot-path finding, the propagation path for an escape."""
    code: str
    severity: Severity
    path: str          # repo-relative
    line: int
    func: str          # qualified function ("Class.method" or module scope)
    message: str
    chain: tuple[str, ...] = ()

    @property
    def where(self) -> str:
        return f"{self.path}:{self.line}"

    def __str__(self) -> str:
        s = f"{self.severity.name:7s} {self.code} {self.where} " \
            f"[{self.func}]: {self.message}"
        if self.chain:
            s += "\n" + "\n".join(f"        {i}. {c}"
                                  for i, c in enumerate(self.chain, 1))
        return s

    def to_dict(self) -> dict:
        return {"code": self.code, "severity": self.severity.name,
                "path": self.path, "line": self.line, "func": self.func,
                "message": self.message, "chain": list(self.chain)}


@dataclasses.dataclass
class MeshlintReport:
    """All passes' findings over one tree + the stats gates key on."""
    findings: list[LintFinding] = dataclasses.field(default_factory=list)
    n_modules: int = 0
    n_functions: int = 0
    wall_ms: float = 0.0
    # per-pass bookkeeping the smoke asserts on (e.g. inferred hot
    # coverage); passes stash JSON-able extras here
    stats: dict = dataclasses.field(default_factory=dict)

    def add(self, finding: LintFinding) -> None:
        self.findings.append(finding)

    def extend(self, findings) -> None:
        self.findings.extend(findings)

    def by_severity(self, sev: Severity) -> list[LintFinding]:
        return [f for f in self.findings if f.severity == sev]

    @property
    def errors(self) -> list[LintFinding]:
        return self.by_severity(Severity.ERROR)

    @property
    def warnings(self) -> list[LintFinding]:
        return self.by_severity(Severity.WARNING)

    @property
    def has_errors(self) -> bool:
        return any(f.severity == Severity.ERROR for f in self.findings)

    def codes(self) -> set[str]:
        return {f.code for f in self.findings}

    def to_dict(self) -> dict:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.code] = counts.get(f.code, 0) + 1
        return {"n_modules": self.n_modules,
                "n_functions": self.n_functions,
                "wall_ms": round(self.wall_ms, 3),
                "n_errors": len(self.errors),
                "n_warnings": len(self.warnings),
                "counts_by_code": counts,
                "stats": self.stats,
                "findings": [f.to_dict() for f in self.findings]}
