"""Inferred hot-path reachability pass.

`scripts/hotpath_lint.py` enforced the one-sync-per-batch doctrine
over a HAND-MAINTAINED file→function list that every PR had to
remember to extend. This pass replaces the list with REACHABILITY:
start from the hot ENTRY POINTS (the functions whose latency is the
serving SLO — batch admission, the batch step, the fused device
trips, shard/replica routing, adapter fan-out, discovery cache serve)
and walk the call graph; every function reachable from a root IS hot,
and the same host-sync/blocking/allocation checks apply to all of
them. New helpers called from hot code are covered the moment they
are called — no list to extend.

Two pieces of declared data remain, both reviewable:

  * DYNAMIC_EDGES — callback seams the AST cannot see (the batcher
    invokes `self._run_batch`, which the server wired to its batch
    hooks at construction; the resilient checker fans out to the
    device/oracle callables it was built with). Each entry is a
    (caller, callee) qualname pair mirroring one `=` in the wiring
    code.
  * COLD_BOUNDARIES — functions reachable from hot code that are, by
    design, OFF the latency path: scrape/serve surfaces, failure
    forensics dumps, drain/shutdown legs. Traversal stops there (the
    boundary function itself is still scanned unless listed in
    COLD_BOUNDARIES — stopping means its callees are not dragged in).

The old `# hotpath: sync-ok` pragma grammar is honored unchanged (and
`# meshlint: sync-ok` works too). Violations are ERRORs carrying the
root→function call chain as witness. `hotpath-root-missing` fires
when a configured root no longer resolves — config drift is a gate
failure, exactly like the old script's `<config>` violation."""
from __future__ import annotations

import ast

from istio_tpu.analysis.findings import Severity
from istio_tpu.analysis.meshlint import callgraph as cg
from istio_tpu.analysis.meshlint import model

# hot entry points — the functions a request's latency budget pays
# for. Qualnames are matched per module via Universe.find (unique
# suffix) so the manifest survives file moves.
HOT_ROOTS: tuple[str, ...] = (
    # batch admission + the batcher worker step
    "CheckBatcher.submit", "CheckBatcher._loop", "CheckBatcher._run_one",
    "CheckBatcher._drain_on_close",
    # dispatch: direct + fused check, report coalescer dispatch
    "Dispatcher.check", "Dispatcher._check_fused", "Dispatcher.report",
    # packed device trips
    "FusedPlan.packed_check", "FusedPlan.packed_report",
    "FusedPlan.packed_check_instep",
    # report ingestion (ack-after-enqueue admission + worker hook)
    "RuntimeServer.submit_report", "RuntimeServer._run_report_batch",
    # quota-plane worker flush (device trip under _counts_lock)
    "DeviceQuotaPool._flush",
    # adapter-executor plane
    "HandlerLane.submit", "AdapterExecutor.submit",
    "AdapterExecutor.resolve",
    # sharded serving plane
    "ShardRouter.check", "ReplicaRouter.submit",
    # discovery serving plane (poll-storm path)
    "SnapshotCache.lookup", "SnapshotCache.peek", "SnapshotCache.store",
    "DiscoveryService._serve_cached",
    "DiscoveryService._generate_rds_batch",
    "RouteScopeProgram.admit_rows",
    # canary tap + rule telemetry fold (run inside the batch step)
    "TrafficRecorder.tap",
    "RuleTelemetry.observe", "RuleTelemetry.add_host",
    "RuleTelemetry.sample", "RuleTelemetry.drain",
    # flight-recorder tape primitives (per-batch/per-stage)
    "FlightRecorder.batch_begin", "FlightRecorder.stage_mark",
    "FlightRecorder.host_wait", "FlightRecorder.note_wire_decode",
    "FlightRecorder.note_batch", "FlightRecorder.note_direct",
    "EventTimeline.record",
)

# callback seams: each pair mirrors one constructor/wiring assignment
# the resolver cannot follow. (caller, callee) — callee becomes
# reachable whenever caller is.
DYNAMIC_EDGES: tuple[tuple[str, str], ...] = (
    # CheckBatcher(self._run_check_batch) / CheckBatcher(
    #   self._run_report_batch) — the worker invokes self._run_batch
    ("CheckBatcher._run_one", "RuntimeServer._run_check_batch"),
    ("CheckBatcher._run_one", "RuntimeServer._run_report_batch"),
    # ResilientChecker(device=…, oracle=…) fan-out
    ("ResilientChecker.run_batch",
     "RuntimeServer._run_check_batch_device"),
    ("ResilientChecker.run_batch",
     "RuntimeServer._run_check_batch_oracle"),
    # executor lanes run registered adapter handlers via HandlerTable
    ("ReplicaRouter.submit", "ShardRouter.check"),
    # Dispatcher.fused is an untyped ctor param (plan = self.fused);
    # the swap-warm oracle bridge consults it on every served batch
    ("Dispatcher._check_fused", "FusedPlan.swap_warm_pending"),
)

# reachable-but-cold: traversal stops AT these functions and they are
# not scanned — scrape/serve/drain surfaces invoked from hot frames
# only on failure or at scrape rate.
COLD_BOUNDARIES: frozenset[str] = frozenset()

_SYNC_ATTRS = ("item", "block_until_ready")
_PULL_FUNCS = {("np", "asarray"), ("np", "array"),
               ("numpy", "asarray"), ("numpy", "array"),
               ("jax", "device_get")}
_CAST_FUNCS = {"float", "int", "bool"}
_BLOCKING_NAMES = {"open", "input", "print", "breakpoint"}
_BLOCKING_ATTRS = {("time", "sleep")}
_BLOCKING_MODULES = {"subprocess", "urllib", "requests", "socket"}
# cast-over-a-call is only a sync when the call can return a device
# scalar — container/string accessors are provably host work, so
# `int(spec.get("port", 80))` does not need a pragma
_HOST_ACCESSORS = {"get", "pop", "split", "rsplit", "strip", "lstrip",
                   "rstrip", "lower", "upper", "join", "items", "keys",
                   "values", "copy", "decode", "encode", "format",
                   "replace", "len"}


def sync_sites(fn_node: ast.AST, lines: list[str]) -> list[tuple[int, str]]:
    """(line, message) for every un-pragma'd host-sync/blocking site in
    one function body — nested defs INCLUDED (they run on the same
    thread when called; matching the old linter's semantics keeps the
    superset pin honest)."""
    out: list[tuple[int, str]] = []

    def pragma(node: ast.AST) -> bool:
        return model.has_pragma(lines, node.lineno, "sync-ok")

    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SYNC_ATTRS and not pragma(node):
                out.append((node.lineno,
                            f".{fn.attr}() is a host sync"))
            chain = cg._dotted(fn)
            if chain is not None:
                if chain[-2:] in _PULL_FUNCS or chain in _PULL_FUNCS:
                    arg = node.args[0] if node.args else None
                    if not isinstance(arg, (ast.List, ast.ListComp)) \
                            and not pragma(node):
                        out.append((node.lineno,
                                    f"{'.'.join(chain)}() pulls "
                                    f"device buffers to host"))
                if (chain[:2] in _BLOCKING_ATTRS
                        or chain[0] in _BLOCKING_MODULES) \
                        and not pragma(node):
                    out.append((node.lineno,
                                f"blocking call {'.'.join(chain)}()"))
        elif isinstance(fn, ast.Name):
            if fn.id in _CAST_FUNCS and node.args \
                    and isinstance(node.args[0], ast.Call) \
                    and not (isinstance(node.args[0].func,
                                        ast.Attribute)
                             and node.args[0].func.attr
                             in _HOST_ACCESSORS) \
                    and not (isinstance(node.args[0].func, ast.Name)
                             and node.args[0].func.id
                             in _HOST_ACCESSORS) \
                    and not pragma(node):
                out.append((node.lineno,
                            f"{fn.id}(<call>) syncs the wrapped "
                            f"computation"))
            if fn.id in _BLOCKING_NAMES and not pragma(node):
                out.append((node.lineno,
                            f"blocking builtin {fn.id}()"))
    return out


def reachable(u: cg.Universe,
              roots: tuple[str, ...] = HOT_ROOTS,
              dynamic_edges: tuple[tuple[str, str], ...] = DYNAMIC_EDGES,
              cold: frozenset[str] = COLD_BOUNDARIES,
              ) -> tuple[dict[str, tuple[str, ...]], list[str]]:
    """BFS from roots → {reached fqn: witness chain of frames},
    plus the list of roots that no longer resolve."""
    missing: list[str] = []
    dyn: dict[str, list[str]] = {}
    for caller, callee in dynamic_edges:
        c = u.find(caller)
        t = u.find(callee)
        if c is not None and t is not None:
            dyn.setdefault(c.fqn, []).append(t.fqn)
    chains: dict[str, tuple[str, ...]] = {}
    queue: list[str] = []
    for r in roots:
        fi = u.find(r)
        if fi is None:
            missing.append(r)
            continue
        if fi.fqn not in chains:
            chains[fi.fqn] = (f"{fi.path}:{fi.line} {fi.qual} — "
                              f"hot entry point",)
            queue.append(fi.fqn)
    while queue:
        fqn = queue.pop(0)
        fi = u.functions[fqn]
        if fi.qual in cold or fqn in cold:
            continue
        nxt: list[tuple[int, str]] = list(u.calls_in(fi))
        nxt += [(fi.line, d) for d in dyn.get(fqn, ())]
        for line, callee in nxt:
            ci = u.functions.get(callee)
            if ci is None or callee in chains:
                continue
            if ci.qual in cold or callee in cold:
                continue
            chains[callee] = chains[fqn] + (
                f"{fi.path}:{line} {fi.qual} — calls {ci.qual}",)
            queue.append(callee)
    return chains, missing


def run(u: cg.Universe, report: model.MeshlintReport,
        roots: tuple[str, ...] = HOT_ROOTS,
        dynamic_edges: tuple[tuple[str, str], ...] = DYNAMIC_EDGES,
        cold: frozenset[str] = COLD_BOUNDARIES) -> dict:
    chains, missing = reachable(u, roots, dynamic_edges, cold)
    for r in missing:
        report.add(model.LintFinding(
            model.HOTPATH_ROOT_MISSING, Severity.ERROR, "<config>", 0,
            "<config>",
            f"hot root {r!r} no longer resolves — update "
            f"meshlint.hotpath.HOT_ROOTS"))
    # scan parents only: nested defs are inside their parent's scan
    nested_of: set[str] = set()
    for fqn, fi in u.functions.items():
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fi.node:
                nested_of.add(f"{fi.module}:{fi.qual}.{n.name}")
    seen_sites: set[tuple[str, int, str]] = set()
    for fqn in sorted(chains):
        if fqn in nested_of:
            continue
        fi = u.functions[fqn]
        for line, message in sync_sites(fi.node, u.lines_of(fi)):
            key = (fi.path, line, message)
            if key in seen_sites:
                continue
            seen_sites.add(key)
            report.add(model.LintFinding(
                model.HOTPATH_SYNC, Severity.ERROR, fi.path, line,
                fi.qual, message, chain=chains[fqn]))
    coverage: dict[str, list[str]] = {}
    for fqn in chains:
        fi = u.functions[fqn]
        coverage.setdefault(fi.path, []).append(fi.qual)
    cov = {p: sorted(q) for p, q in sorted(coverage.items())}
    report.stats["hot_roots"] = len(roots)
    report.stats["hot_reachable"] = len(chains)
    report.stats["hot_coverage"] = cov
    return cov
