"""meshlint — repo-wide concurrency & discipline analyzer.

Four passes over one shared AST/call-graph universe
(`callgraph.Universe`), each encoding a doctrine previous PRs
enforced by review:

  * `lockorder`   — static lock-acquisition graph vs the declared
                    partial order + leaf-lock manifest;
  * `hotpath`     — host-sync/blocking discipline over INFERRED
                    reachability from the hot entry points (replaces
                    scripts/hotpath_lint.py's hand-kept list);
  * `metricspass` — every metric use resolves to a registered,
                    zero-shaped family;
  * `rejections`  — nothing untyped escapes a front boundary.

Entry points: `run_meshlint(root)` for the real tree, or
`run_meshlint(sources={...})` for in-memory corpora (fixtures,
tests). `mixs lint` and scripts/meshlint.py are thin callers."""
from __future__ import annotations

import time

from istio_tpu.analysis.meshlint import (callgraph, hotpath, lockorder,
                                         metricspass, model, rejections)
from istio_tpu.analysis.meshlint.model import (LintFinding,
                                               MeshlintReport)

__all__ = ["run_meshlint", "LintFinding", "MeshlintReport",
           "callgraph", "lockorder", "hotpath", "metricspass",
           "rejections", "model"]


def run_meshlint(root: str | None = None,
                 sources: dict[str, str] | None = None,
                 passes: tuple[str, ...] = ("lock", "hotpath",
                                            "metrics", "rejections"),
                 hot_roots: tuple[str, ...] | None = None,
                 boundaries: tuple[tuple[str, str], ...] | None = None,
                 ) -> MeshlintReport:
    """Run the configured passes and return one report.

    Exactly one of `root` (directory holding the istio_tpu package)
    or `sources` ({dotted module name: source text}) must be given.
    `hot_roots` / `boundaries` override the manifests — fixtures use
    this to point the passes at synthetic modules."""
    t0 = time.monotonic()
    if sources is not None:
        u = callgraph.Universe.from_sources(sources)
    elif root is not None:
        u = callgraph.Universe.from_root(root)
    else:
        raise ValueError("run_meshlint needs root= or sources=")
    report = MeshlintReport(n_modules=len(u.modules),
                            n_functions=len(u.functions))
    if "lock" in passes:
        lockorder.run(u, report)
    if "hotpath" in passes:
        hotpath.run(u, report,
                    roots=hot_roots if hot_roots is not None
                    else hotpath.HOT_ROOTS)
    if "metrics" in passes:
        metricspass.run(u, report)
    if "rejections" in passes:
        rejections.run(u, report,
                       boundaries=boundaries if boundaries is not None
                       else rejections.FRONT_BOUNDARIES)
    report.findings.sort(key=lambda f: (-int(f.severity), f.path,
                                        f.line, f.code))
    report.wall_ms = (time.monotonic() - t0) * 1000.0
    return report
