"""Cross-plane consistency: Pilot route matchers vs Mixer predicates.

The shared-automaton north star compiles ONE source of truth (a route
rule's match block) into two consumers: Pilot's `pilot/route_nfa`
RouteTable and the Mixer-side policy predicates embedded in a ruleset
(e.g. `testing/workloads.make_full_mesh`'s route rows). A divergence —
a tampered predicate, a stale recompile, a lowering change on one side
only — silently answers routing and policy from DIFFERENT languages
under live traffic. This pass proves pairs equivalent where it can
(mutual DNF implication over the shared atom semantics) and otherwise
hunts for a DIFFERENTIAL WITNESS: a request on which the two planes'
oracles disagree. A reported divergence always carries that witness.
"""
from __future__ import annotations

from typing import Any, Sequence

from istio_tpu.analysis.findings import (Finding, PLANE_DIVERGENCE,
                                         PLANE_UNPROVEN, Severity)
from istio_tpu.analysis.reach import RuleUniverse
from istio_tpu.attribute.bag import DictBag
from istio_tpu.expr.checker import (AttributeDescriptorFinder, TypeError_)
from istio_tpu.expr.exprs import Expression
from istio_tpu.expr.oracle import OracleProgram
from istio_tpu.expr.parser import ParseError, parse


def _to_ast(pred: "str | Expression") -> Expression:
    if isinstance(pred, Expression):
        return pred
    return parse(pred or "true")


def _verdict(ast: Expression, finder: AttributeDescriptorFinder,
             bag: dict[str, Any]) -> "bool | str":
    """True / False / 'error' under the oracle semantics."""
    try:
        return bool(OracleProgram.from_ast(ast, finder)
                    .evaluate(DictBag(bag)))
    except Exception:
        return "error"


def check_plane_pairs(pairs: Sequence[tuple[str, Any, Any]],
                      finder: AttributeDescriptorFinder,
                      *, max_samples: int = 8,
                      warn_unproven: bool = True) -> list[Finding]:
    """`pairs`: (name, pilot predicate, mixer predicate) — text or AST.
    Emits PLANE_DIVERGENCE (ERROR, witness-confirmed) when the two
    disagree on a concrete request, PLANE_UNPROVEN (WARNING) when
    equivalence can be neither proved nor refuted."""
    findings: list[Finding] = []
    for name, pilot, mixer in pairs:
        if isinstance(pilot, str) and isinstance(mixer, str) \
                and pilot.strip() == mixer.strip():
            continue
        try:
            past, mast = _to_ast(pilot), _to_ast(mixer)
        except (ParseError, TypeError_) as exc:
            findings.append(Finding(
                code=PLANE_DIVERGENCE, severity=Severity.ERROR,
                message=f"route {name!r}: plane predicate does not "
                        f"parse: {exc}",
                rules=(name,)))
            continue
        if str(past) == str(mast):
            continue
        uni = RuleUniverse([(f"{name}/pilot", "", past),
                            (f"{name}/mixer", "", mast)], finder)
        if uni.shadows(0, 1) and uni.shadows(1, 0):
            continue                       # proved language-equivalent
        witness = _hunt_divergence(uni, past, mast, finder,
                                   max_samples)
        if witness is not None:
            bag, vp, vm = witness
            findings.append(Finding(
                code=PLANE_DIVERGENCE, severity=Severity.ERROR,
                message=(f"route {name!r}: pilot and mixer planes "
                         f"disagree on the witness request (pilot="
                         f"{vp}, mixer={vm})"),
                rules=(name,), witness=bag, confirmed=True))
        elif warn_unproven:
            findings.append(Finding(
                code=PLANE_UNPROVEN, severity=Severity.WARNING,
                message=(f"route {name!r}: pilot and mixer predicates "
                         f"differ and equivalence could not be proved "
                         f"(opaque atoms or budget)"),
                rules=(name,)))
    return findings


def _hunt_divergence(uni: RuleUniverse, past: Expression,
                     mast: Expression,
                     finder: AttributeDescriptorFinder,
                     max_samples: int
                     ) -> tuple[dict, Any, Any] | None:
    """Probe bags drawn from BOTH sides' accepting conjunctions (each
    side's witnesses are exactly the inputs most likely to expose a
    one-sided match), plus the empty bag."""
    probes: list[dict] = [{}]
    for pred in uni.preds:
        if pred.m_dnf is None:
            continue
        for conj in pred.m_dnf[:max_samples]:
            bag = uni.witness_for([conj])
            if bag is not None:
                probes.append(bag)
    seen: set[str] = set()
    for bag in probes[: 2 * max_samples + 1]:
        key = repr(sorted(bag.items(), key=str))
        if key in seen:
            continue
        seen.add(key)
        vp = _verdict(past, finder, bag)
        vm = _verdict(mast, finder, bag)
        if vp != vm and (vp is True or vm is True):
            return bag, vp, vm
    return None
