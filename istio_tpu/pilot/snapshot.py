"""Versioned discovery snapshots — the registry/config world frozen at
a generation, with per-namespace content digests for scoped
invalidation.

The reference discovery server reads the LIVE registry and config
store on every cache miss and clears its whole response cache on any
event (discovery.go:489 clearCache — deliberately conservative). At
fleet scale that means a 10k-sidecar poll storm after any single
churn recomputes every node's config from live, lock-guarded state.
This module gives Pilot the same doctrine Mixer's serving plane
already follows (compile once, serve many):

  * `build_snapshot` freezes the registry (services + instances) and
    the config store (per-type lists, in the backing store's own list
    order — byte-parity with live generation is a test invariant)
    into an immutable, generation-stamped `DiscoverySnapshot`;
  * every namespace gets a CONTENT DIGEST (compiler/cache.stable_digest
    — the PR 10 content-hash machinery) over its services, instances
    and destination-scoped configs; `changed_scopes` diffs two
    snapshots into the exact namespace set whose content moved, which
    is what drives scoped cache invalidation and the shard-scoped
    delta-push wakeups in pilot/discovery.py;
  * the namespace→shard map comes from the sharding planner
    (sharding/planner.plan_shards, delta mode) so push fan-out state
    is bounded by K shards and STABLE across generations — a
    namespace keeps its shard, exactly the plan-stability contract
    the compiled-bank cache relies on;
  * per-host route-rule/destination-policy indexes make config
    generation O(scoped rules) instead of the live store's
    O(services x all rules) scan, and the source-admission half of
    the route match blocks is compiled ONCE into a
    `route_nfa.RouteScopeProgram` (carried across generations by
    content digest) so per-node route-rule filtering batches through
    one device step shared across all pending node groups.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Mapping, Sequence

from istio_tpu.compiler.cache import stable_digest
from istio_tpu.pilot.model import (Config, ConfigStore, IstioConfigStore,
                                   IstioConfigTypes, Node, Service,
                                   ServiceInstance, _match_source)
from istio_tpu.pilot.registry import ServiceDiscovery
from istio_tpu.sharding.planner import ShardPlan, plan_shards

# pseudo-namespace for mesh-wide inputs (egress/ingress rules, auth
# specs, services whose hostname carries no namespace label): entries
# depending on it invalidate whenever any mesh-scoped config moves
MESH_SCOPE = "~mesh"


def scope_of_hostname(hostname: str) -> str:
    """Namespace scope of a service hostname (`svc.ns.svc.domain` →
    `ns`); hostnames with no namespace label are mesh-scoped."""
    parts = hostname.split(".")
    return parts[1] if len(parts) > 1 and parts[1] else MESH_SCOPE


def config_scope(cfg: Config) -> str:
    """The namespace whose digest a config resource belongs to:
    destination-addressed kinds scope to the DESTINATION service's
    namespace (their content only ever appears in that namespace's
    generated config); everything else (egress, ingress, auth/quota
    specs) is mesh-wide."""
    if cfg.meta.type in ("route-rule", "v1alpha2-route-rule",
                        "destination-policy", "destination-rule"):
        host = IstioConfigStore._destination_hostname(cfg)
        return scope_of_hostname(host)
    return MESH_SCOPE


class FrozenConfigStore(ConfigStore):
    """Immutable ConfigStore view: per-type lists captured in the
    backing store's own list() order at freeze time."""

    def __init__(self, by_type: Mapping[str, Sequence[Config]]):
        self._by_type = {t: tuple(cfgs) for t, cfgs in by_type.items()}

    def get(self, typ: str, name: str, namespace: str = "") -> Config | None:
        for c in self._by_type.get(typ, ()):
            if c.meta.name == name and c.meta.namespace == namespace:
                return c
        return None

    def list(self, typ: str, namespace: str | None = None) -> list[Config]:
        return [c for c in self._by_type.get(typ, ())
                if namespace is None or c.meta.namespace == namespace]

    def create(self, config: Config) -> None:
        raise TypeError("snapshot config store is immutable")

    update = create

    def delete(self, typ: str, name: str, namespace: str = "") -> None:
        raise TypeError("snapshot config store is immutable")


def instance_order(inst: ServiceInstance) -> tuple:
    """Canonical colocated-instance ordering (hostname, port, port
    name, address). Live registries return host_instances in service
    INSERTION order — process-history state that a content-addressed
    cache must not depend on; both the snapshot serving path and the
    parity reference sort by this key so multi-service nodes generate
    identical bytes regardless of registration order."""
    return (inst.service.hostname, inst.endpoint.port,
            inst.endpoint.service_port.name, inst.endpoint.address)


class FrozenRegistry(ServiceDiscovery):
    """Immutable ServiceDiscovery view with an address index (node →
    colocated instances is a per-poll lookup at fleet scale, never a
    scan). host_instances returns the canonical `instance_order`."""

    def __init__(self, services: Sequence[Service],
                 instances_by_host: Mapping[str, Sequence[ServiceInstance]]):
        self._services = sorted(services, key=lambda s: s.hostname)
        self._by_host = {h: tuple(v) for h, v in instances_by_host.items()}
        self._by_addr: dict[str, list[ServiceInstance]] = {}
        for insts in self._by_host.values():
            for inst in insts:
                self._by_addr.setdefault(inst.endpoint.address,
                                         []).append(inst)
        for insts in self._by_addr.values():
            insts.sort(key=instance_order)
        self._svc_index = {s.hostname: s for s in self._services}

    def services(self) -> list[Service]:
        return list(self._services)

    def get_service(self, hostname: str) -> Service | None:
        return self._svc_index.get(hostname)

    def instances(self, hostname, ports=(), labels=None):
        out = []
        for inst in self._by_host.get(hostname, ()):
            if ports and inst.endpoint.service_port.name not in ports:
                continue
            if labels and any(inst.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            out.append(inst)
        return out

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        out = []
        for a in sorted(addrs):
            out.extend(self._by_addr.get(a, ()))
        out.sort(key=instance_order)
        return out


class SnapshotConfigView(IstioConfigStore):
    """IstioConfigStore whose hot queries (route_rules /
    destination_policy) read precomputed per-host indexes instead of
    re-scanning the full store per service — same results, same sort
    order, O(rules of host) per query."""

    def __init__(self, store: FrozenConfigStore,
                 rules_by_host: Mapping[str, Sequence[Config]],
                 policies_by_host: Mapping[str, Sequence[Config]]):
        super().__init__(store)
        self._rules_by_host = rules_by_host
        self._policies_by_host = policies_by_host

    def route_rules(self, destination, source=None, source_labels=None):
        return [c for c in self._rules_by_host.get(destination, ())
                if _match_source(c.spec, source, source_labels)]

    def destination_policy(self, destination, labels=None):
        for c in self._policies_by_host.get(destination, ()):
            dest = c.spec.get("destination", {})
            want = (dest.get("tags") or dest.get("labels") or {}) \
                if isinstance(dest, Mapping) else {}
            if want and labels is not None and \
                    any(labels.get(k) != v for k, v in want.items()):
                continue
            return c
        return None


@dataclasses.dataclass
class DiscoverySnapshot:
    """One immutable generation of the discovery world."""
    version: int
    registry: FrozenRegistry
    store: FrozenConfigStore
    config: SnapshotConfigView
    scope_digests: dict[str, str]
    rules_by_host: dict[str, tuple[Config, ...]]
    plan: ShardPlan
    scope: Any                      # route_nfa.RouteScopeProgram
    source_ports: frozenset[int]
    # http port → sorted hostnames serving it: the publish sweep diffs
    # this across generations so an RDS entry whose PORT MEMBERSHIP
    # changed invalidates even when the joining/leaving service lives
    # in a namespace the entry never depended on (a cross-namespace
    # service joining an already-cached port must not be masked by
    # namespace-scoped deps)
    port_services: dict[int, tuple[str, ...]] = \
        dataclasses.field(default_factory=dict)
    n_services: int = 0
    n_rules: int = 0
    build_wall_s: float = 0.0
    plan_wall_s: float = 0.0
    scope_reused: bool = False

    def rules_for(self, hostname: str) -> tuple[Config, ...]:
        """Precedence-sorted route rules destined to `hostname` —
        identical membership + order to
        `IstioConfigStore.route_rules(hostname)` with no source
        filter."""
        return self.rules_by_host.get(hostname, ())

    def scope_audit_pairs(self, limit: int = 256) -> list:
        """(name, served-plane predicate, compiled-plane predicate)
        pairs for the mesh audit plane (runtime/audit.py
        plane_agreement): the source constraints RE-DERIVED from the
        currently served rules_by_host against the constraints the
        carried RouteScopeProgram compiled. The scope program rides
        across generations whenever its content digest matches (PR 10
        carry-over) — this is the live check that a carried program
        still encodes the routes actually being served. A constraint
        present on one side only pairs against 'true', which the
        planes checker refutes with a witness."""
        served: dict[tuple, str] = {}
        for host in sorted(self.rules_by_host):
            for i, rule in enumerate(self.rules_by_host[host]):
                src = (rule.spec.get("match") or {}).get("source")
                if src:
                    served[(host, i)] = str(src)
        compiled = {pair: self.scope._sources[j]
                    for j, pair in enumerate(self.scope._constrained)}

        def _pred(src: str | None) -> str:
            if src is None:
                return "true"
            return 'source.service == "%s"' % src.replace('"', '\\"')

        pairs = []
        for host, i in sorted(set(served) | set(compiled)):
            pairs.append((f"{host}[{i}]",
                          _pred(served.get((host, i))),
                          _pred(compiled.get((host, i)))))
            if len(pairs) >= limit:
                break
        return pairs

    def node_instances(self, node: str) -> list[ServiceInstance]:
        return self.registry.host_instances(
            {Node.parse(node).ip_address})

    def node_source(self, node: str) -> str | None:
        """The node's primary colocated service hostname (route-rule
        source identity, route.go buildVirtualHost's `source`); None
        for nodes hosting nothing."""
        hosts = sorted({i.service.hostname
                        for i in self.node_instances(node)})
        return hosts[0] if hosts else None

    def node_namespace(self, node: str) -> str:
        hosts = sorted({scope_of_hostname(i.service.hostname)
                        for i in self.node_instances(node)})
        return hosts[0] if hosts else ""

    def shard_of_node(self, node: str) -> int:
        return self.plan.shard_of(self.node_namespace(node))

    def port_has_source_rules(self, port_num: int) -> bool:
        """True when any route rule destined to a service exposing
        http `port_num` carries a source constraint — the collapse
        rule for RDS node groups: ports with no source-constrained
        rules serve ONE shared config to every sidecar."""
        return port_num in self.source_ports


def _freeze_store(config_store: ConfigStore) -> FrozenConfigStore:
    if hasattr(config_store, "snapshot"):
        by_key = config_store.snapshot()
        by_type: dict[str, list[Config]] = {}
        for key in sorted(by_key):
            c = by_key[key]
            by_type.setdefault(c.meta.type, []).append(c)
        return FrozenConfigStore(by_type)
    return FrozenConfigStore({typ: config_store.list(typ)
                              for typ in IstioConfigTypes})


def _digest_scopes(services: Sequence[Service],
                   instances_by_host: Mapping[str, Sequence[ServiceInstance]],
                   by_type: Mapping[str, Sequence[Config]]
                   ) -> dict[str, str]:
    payload: dict[str, dict] = {}

    def bucket(ns: str) -> dict:
        return payload.setdefault(ns, {"services": [], "instances": [],
                                       "configs": []})

    for s in services:
        ns = scope_of_hostname(s.hostname)
        bucket(ns)["services"].append(
            (s.hostname, s.address,
             [(p.name, p.port, p.protocol) for p in s.ports],
             s.external_name, s.service_account))
        for i in instances_by_host.get(s.hostname, ()):
            bucket(ns)["instances"].append(
                (i.endpoint.address, i.endpoint.port,
                 i.endpoint.service_port.name,
                 sorted(i.labels.items()), i.availability_zone,
                 i.service_account))
    for typ in sorted(by_type):
        for c in by_type[typ]:
            ns = config_scope(c)
            bucket(ns)["configs"].append(
                (c.meta.type, c.meta.namespace, c.meta.name, c.spec))
    return {ns: stable_digest(p) for ns, p in payload.items()}


def changed_scopes(prev: DiscoverySnapshot | None,
                   cur: DiscoverySnapshot) -> set[str]:
    """Namespaces whose content digest moved between two snapshots
    (added/removed namespaces count as changed). prev=None → every
    scope of `cur` (plus the mesh scope) is 'changed'."""
    if prev is None:
        return set(cur.scope_digests) | {MESH_SCOPE}
    out = set()
    for ns in set(prev.scope_digests) | set(cur.scope_digests):
        if prev.scope_digests.get(ns) != cur.scope_digests.get(ns):
            out.add(ns)
    return out


def changed_http_ports(prev: DiscoverySnapshot | None,
                       cur: DiscoverySnapshot) -> set[int]:
    """HTTP ports whose SERVICE MEMBERSHIP moved between snapshots —
    the cross-namespace invalidation leg: an RDS entry depends on the
    namespaces that were on its port when it was generated, so a
    service from a NEW namespace joining the port would never
    intersect those deps; the publish sweep invalidates by port
    membership as well."""
    if prev is None:
        return set(cur.port_services)
    return {p for p in set(prev.port_services) | set(cur.port_services)
            if prev.port_services.get(p) != cur.port_services.get(p)}


class _NsUnit:
    """Planner placement unit: one namespace's worth of discovery
    content (plan_shards only reads `.namespace` when costs are
    supplied)."""
    __slots__ = ("namespace",)

    def __init__(self, namespace: str):
        self.namespace = namespace


def build_snapshot(registry: ServiceDiscovery, config_store: ConfigStore,
                   version: int, prev: DiscoverySnapshot | None = None,
                   n_shards: int = 8) -> DiscoverySnapshot:
    """Freeze the live world into a generation-`version` snapshot.

    Carry-over doctrine (PR 10): the compiled source-scope program is
    keyed by the content digest of its constraint set and reused from
    `prev` when unchanged — a churn storm that never touches a source
    constraint recompiles nothing; the shard plan is built in delta
    mode against `prev` so namespaces keep their shards (watchers'
    scope keys stay stable across generations)."""
    import numpy as np

    from istio_tpu.pilot.route_nfa import RouteScopeProgram

    t0 = time.perf_counter()
    services = registry.services()
    instances_by_host = {s.hostname: list(registry.instances(s.hostname))
                         for s in services}
    frozen = _freeze_store(config_store)
    store_by_type = {typ: frozen.list(typ) for typ in IstioConfigTypes}
    digests = _digest_scopes(services, instances_by_host, store_by_type)

    # per-host indexes (same membership + sort as the live queries)
    rules_by_host: dict[str, list[Config]] = {}
    for c in store_by_type.get("route-rule", ()):
        host = IstioConfigStore._destination_hostname(c)
        rules_by_host.setdefault(host, []).append(c)
    for host in rules_by_host:
        rules_by_host[host].sort(
            key=lambda c: (-int(c.spec.get("precedence", 0)),
                           c.meta.name))
    policies_by_host: dict[str, list[Config]] = {}
    for c in store_by_type.get("destination-policy", ()):
        host = IstioConfigStore._destination_hostname(c)
        policies_by_host.setdefault(host, []).append(c)

    frozen_rules = {h: tuple(v) for h, v in rules_by_host.items()}
    reg = FrozenRegistry(services, instances_by_host)
    view = SnapshotConfigView(frozen, frozen_rules, policies_by_host)

    # RDS group-collapse index: http ports carrying source-scoped rules
    constrained_hosts = {
        h for h, rules in frozen_rules.items()
        if any((r.spec.get("match") or {}).get("source") for r in rules)}
    source_ports = frozenset(
        p.port for s in services if s.hostname in constrained_hosts
        for p in s.ports if p.is_http)
    port_membership: dict[int, set[str]] = {}
    for s in services:
        for p in s.ports:
            if p.is_http:
                port_membership.setdefault(p.port, set()).add(
                    s.hostname)
    port_services = {p: tuple(sorted(v))
                     for p, v in port_membership.items()}

    # namespace → shard plan (delta mode: plan stability across
    # generations is the watch protocol's scope-key contract)
    ns_weight: dict[str, float] = {}
    for s in services:
        ns = scope_of_hostname(s.hostname)
        if ns != MESH_SCOPE:
            ns_weight[ns] = ns_weight.get(ns, 0.0) + 1.0
    for host, rules in frozen_rules.items():
        ns = scope_of_hostname(host)
        if ns != MESH_SCOPE:
            ns_weight[ns] = ns_weight.get(ns, 0.0) + float(len(rules))
    units = [_NsUnit(ns) for ns in sorted(ns_weight)]
    costs = np.asarray([ns_weight[u.namespace] for u in units],
                       np.float64)
    plan = plan_shards(units, None, n_shards, costs=costs,
                       revision=version,
                       prev=prev.plan if prev is not None else None)

    scope = RouteScopeProgram(frozen_rules)
    reused = False
    if prev is not None and prev.scope is not None \
            and prev.scope.digest == scope.digest:
        scope = prev.scope               # compiled program carry-over
        reused = True

    return DiscoverySnapshot(
        version=version, registry=reg, store=frozen, config=view,
        scope_digests=digests, rules_by_host=frozen_rules, plan=plan,
        scope=scope, source_ports=source_ports,
        port_services=port_services,
        n_services=len(services),
        n_rules=sum(len(v) for v in frozen_rules.values()),
        build_wall_s=time.perf_counter() - t0,
        plan_wall_s=plan.plan_wall_s, scope_reused=reused)
