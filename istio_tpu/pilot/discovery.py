"""Envoy v1 REST discovery service (SDS/CDS/RDS/LDS).

Reference: pilot/pkg/proxy/envoy/discovery.go — routes registered at
:360-408: /v1/registration/{service-key} (SDS),
/v1/clusters/{cluster}/{node} (CDS), /v1/routes/{name}/{cluster}/{node}
(RDS), /v1/listeners/{cluster}/{node} (LDS); whole-response cache
invalidated WHOLESALE on any registry/config event (clearCache :489 —
the deliberately conservative design the reference documents at
:124-139); per-endpoint hit/miss metrics (:784-817).
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Mapping

import prometheus_client

from istio_tpu.pilot.envoy_config import (build_egress_clusters,
                                          build_inbound_clusters,
                                          build_inbound_listeners,
                                          build_ingress_listeners,
                                          build_jwks_clusters,
                                          build_outbound_clusters,
                                          build_outbound_listeners)
from istio_tpu.pilot.routes import build_ingress_route_config
from istio_tpu.pilot.model import (NODE_INGRESS, NODE_SIDECAR,
                                   IstioConfigStore, MemoryConfigStore,
                                   Node)
from istio_tpu.pilot.registry import ServiceDiscovery
from istio_tpu.pilot.routes import build_route_config

log = logging.getLogger("istio_tpu.pilot.discovery")

REGISTRY = prometheus_client.CollectorRegistry()
CALLS = prometheus_client.Counter(
    "pilot_discovery_calls", "discovery endpoint calls",
    ["endpoint", "cache"], registry=REGISTRY)


class DiscoveryService:
    """Serves envoy v1 discovery with a response cache."""

    def __init__(self, registry: ServiceDiscovery,
                 config_store: MemoryConfigStore,
                 mesh: Mapping[str, Any] | None = None):
        self.registry = registry
        self.config = IstioConfigStore(config_store)
        self.mesh = dict(mesh or {})
        self._cache: dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._server: ThreadingHTTPServer | None = None
        if hasattr(config_store, "register_handler"):
            config_store.register_handler(lambda *_: self.clear_cache())
        if hasattr(registry, "append_service_handler"):
            registry.append_service_handler(lambda *_: self.clear_cache())

    # -- cache (discovery.go:124-139,:489) --

    def clear_cache(self) -> None:
        with self._lock:
            self._cache.clear()
        log.debug("discovery cache cleared")

    def _cached(self, key: str, endpoint: str, build) -> bytes:
        with self._lock:
            data = self._cache.get(key)
        if data is not None:
            CALLS.labels(endpoint=endpoint, cache="hit").inc()
            return data
        CALLS.labels(endpoint=endpoint, cache="miss").inc()
        data = json.dumps(build(), indent=2, sort_keys=True).encode()
        with self._lock:
            self._cache[key] = data
        return data

    @property
    def cache_size(self) -> int:
        with self._lock:
            return len(self._cache)

    # -- endpoints --

    def list_endpoints(self, service_key: str) -> bytes:
        """SDS /v1/registration/{service-key} (discovery.go:572)."""
        def build():
            hostname, _, rest = service_key.partition("|")
            port_name, _, label_str = rest.partition("|")
            labels = dict(kv.split("=", 1)
                          for kv in label_str.split(",") if "=" in kv)
            instances = self.registry.instances(
                hostname, (port_name,) if port_name else (), labels)
            return {"hosts": [
                {"ip_address": i.endpoint.address,
                 "port": i.endpoint.port,
                 "tags": {"az": i.availability_zone} if
                 i.availability_zone else {}}
                for i in instances]}
        return self._cached(f"sds/{service_key}", "sds", build)

    def list_clusters(self, cluster: str, node: str) -> bytes:
        def build():
            services = self.registry.services()
            clusters = build_outbound_clusters(services, self.config)
            clusters += build_egress_clusters(self.config)
            clusters += build_jwks_clusters(self.config)
            if Node.parse(node).type == NODE_SIDECAR:
                clusters += build_inbound_clusters(
                    self._node_instances(node))
            return {"clusters": clusters}
        return self._cached(f"cds/{cluster}/{node}", "cds", build)

    def list_routes(self, name: str, cluster: str, node: str) -> bytes:
        def build():
            if Node.parse(node).type == NODE_INGRESS:
                return build_ingress_route_config(self.config,
                                                  self.registry)
            return build_route_config(self.registry.services(),
                                      int(name), self.config)
        return self._cached(f"rds/{name}/{node}", "rds", build)

    def list_listeners(self, cluster: str, node: str) -> bytes:
        def build():
            services = self.registry.services()
            role = Node.parse(node)
            if role.type == NODE_INGRESS:
                listeners = build_ingress_listeners(
                    self.config, self.registry, self.mesh,
                    tls_context=self.mesh.get("ingress_tls"))
            else:
                listeners = build_outbound_listeners(services, self.config,
                                                     self.mesh)
                if role.type == NODE_SIDECAR:
                    listeners += build_inbound_listeners(
                        self._node_instances(node), self.mesh)
            return {"listeners": listeners}
        return self._cached(f"lds/{cluster}/{node}", "lds", build)

    def availability_zone(self, cluster: str, node: str) -> bytes:
        """/v1/az/{cluster}/{node} (discovery.go:601): the AZ of the
        node's instances (all share the node IP, hence the AZ).
        Plain-text body (the only non-JSON discovery response)."""
        CALLS.labels(endpoint="az", cache="miss").inc()
        instances = self._node_instances(node)
        if not instances:
            raise KeyError(f"az: no instances for node {node}")
        return str(instances[0].availability_zone or "").encode()

    def _node_instances(self, node: str):
        return self.registry.host_instances(
            {Node.parse(node).ip_address})

    # -- HTTP server --

    def start(self, address: str = "127.0.0.1", port: int = 0) -> int:
        ds = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet
                log.debug("discovery: " + fmt, *args)

            def do_GET(self):
                try:
                    body = ds._route(self.path)
                except KeyError:
                    self.send_error(404)
                    return
                except Exception:
                    log.exception("discovery handler failed")
                    self.send_error(500)
                    return
                self.send_response(200)
                ctype = "text/plain" if self.path.startswith("/v1/az/") \
                    else "application/json"
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((address, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="pilot-discovery")
        self._thread.start()
        self.port = self._server.server_address[1]
        log.info("pilot discovery on port %d", self.port)
        return self.port

    def _route(self, path: str) -> bytes:
        parts = [p for p in path.split("/") if p]
        if len(parts) >= 3 and parts[0] == "v1":
            if parts[1] == "registration":
                return self.list_endpoints("/".join(parts[2:]))
            if parts[1] == "clusters" and len(parts) == 4:
                return self.list_clusters(parts[2], parts[3])
            if parts[1] == "routes" and len(parts) == 5:
                return self.list_routes(parts[2], parts[3], parts[4])
            if parts[1] == "listeners" and len(parts) == 4:
                return self.list_listeners(parts[2], parts[3])
            if parts[1] == "az" and len(parts) == 4:
                return self.availability_zone(parts[2], parts[3])
        raise KeyError(path)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
