"""Envoy v1 REST discovery service (SDS/CDS/RDS/LDS) — served from
versioned snapshots with a scoped cache, delta pushes and batched
route generation.

Reference: pilot/pkg/proxy/envoy/discovery.go — routes registered at
:360-408: /v1/registration/{service-key} (SDS),
/v1/clusters/{cluster}/{node} (CDS), /v1/routes/{name}/{cluster}/{node}
(RDS), /v1/listeners/{cluster}/{node} (LDS); per-endpoint hit/miss
metrics (:784-817). The reference invalidates its whole response cache
on any registry/config event (clearCache :489 — the deliberately
conservative design documented at :124-139); this implementation
replaces that with the serving doctrine the Mixer side proved:

  * the registry/config world is published as immutable,
    generation-stamped `DiscoverySnapshot`s (pilot/snapshot.py) — the
    serving path never reads live mutable state;
  * responses are cached per (endpoint, node group, generation):
    identical sidecars SHARE one generated config (RDS groups collapse
    to (port, source-identity) — and to just (port,) when no route
    rule on the port is source-constrained; CDS groups collapse to the
    node's inbound port signature);
  * a config swap invalidates ONLY the node groups whose scoped
    content actually changed: the publish diffs per-namespace content
    digests (PR 10 machinery) and sweeps entries whose recorded
    namespace deps intersect the changed set — everything else is
    CARRIED to the new generation untouched. CDS/LDS responses embed
    mesh-wide cluster/listener sets and honestly carry mesh-wide deps
    (the reference's wholesale clear is the correct answer for them);
    SDS is namespace-scoped and RDS is port/namespace-scoped, which is
    where a 10k-sidecar fleet stops repaying full generation per
    churn;
  * delta push: sidecars long-poll /v1/watch/{cluster}/{node}?version=
    and park on their namespace's SHARD (the sharding planner's
    namespace→shard map bounds fan-out state and keeps scope keys
    stable across generations); a publish wakes only the shards whose
    namespaces changed — the rest of the fleet never re-pulls;
  * route generation for ALL pending node groups batches the
    source-admission half of the match blocks through ONE compiled
    device step (route_nfa.RouteScopeProgram — the same ruleset
    tensors the route NFA and policy engine ride), replacing the
    per-node host filter scan;
  * the serving front is the threaded stdlib server with an explicit
    quiesce ordering (PR 7 doctrine: admission → generation → flush →
    join): draining answers new pulls with a typed UNAVAILABLE
    rejection and releases parked watchers before the listener joins.

Stage decomposition (`pilot_discovery_stage_seconds`) and cache/push
counters live in runtime/monitor.py; `/debug/discovery` (here and on
the introspect server) is the operator view.
"""
from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Mapping
from urllib.parse import parse_qsl

import prometheus_client

from istio_tpu.pilot.envoy_config import (build_egress_clusters,
                                          build_inbound_clusters,
                                          build_inbound_listeners,
                                          build_ingress_listeners,
                                          build_jwks_clusters,
                                          build_outbound_clusters,
                                          build_outbound_listeners)
from istio_tpu.pilot.model import (NODE_INGRESS, NODE_SIDECAR, Node)
from istio_tpu.pilot.routes import (build_egress_virtual_hosts,
                                    build_ingress_route_config,
                                    build_route_config,
                                    build_virtual_host_from_rules)
from istio_tpu.pilot.snapshot import (DiscoverySnapshot, MESH_SCOPE,
                                      build_snapshot,
                                      changed_http_ports,
                                      changed_scopes, instance_order,
                                      scope_of_hostname)
from istio_tpu.runtime import monitor

log = logging.getLogger("istio_tpu.pilot.discovery")

REGISTRY = prometheus_client.CollectorRegistry()
CALLS = prometheus_client.Counter(
    "pilot_discovery_calls", "discovery endpoint calls",
    ["endpoint", "cache"], registry=REGISTRY)
# pre-touch the full series shape (promtext doctrine): a scrape taken
# before the first poll already shows every endpoint/cache series, so
# hit-rate dashboards never see a series pop into existence mid-storm
for _ep in ("sds", "cds", "rds", "lds", "az"):
    for _c in ("hit", "miss"):
        CALLS.labels(endpoint=_ep, cache=_c)

DEFAULT_WATCH_TIMEOUT_S = 25.0
MAX_WATCH_TIMEOUT_S = 60.0


class SnapshotCache:
    """Response cache keyed (endpoint, node group) with generation
    stamps and per-entry namespace deps.

    An entry is a hit only for the generation it is stamped with; a
    publish sweep (`invalidate`) drops entries whose deps intersect
    the changed namespace set (deps None = mesh-wide = always drops)
    and re-stamps the survivors to the new generation — the scoped-
    invalidation contract. Entries stamped with a generation OLDER
    than the sweep's `prev_version` were built against a snapshot the
    diff does not cover and are dropped unconditionally (they can
    never be proven current)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # key -> (data bytes, version, deps frozenset | None)
        self._entries: dict[tuple, tuple[bytes, int, Any]] = {}
        self.hits = 0
        self.misses = 0
        self.carried = 0
        self.invalidated = 0

    def lookup(self, key: tuple, version: int) -> bytes | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[1] == version:
                self.hits += 1
                return entry[0]
            self.misses += 1
            return None

    def peek(self, key: tuple, version: int) -> bytes | None:
        """lookup without hit/miss accounting (the post-batched-fill
        fetch — the call was already counted a miss)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[1] == version:
                return entry[0]
            return None

    def store(self, key: tuple, data: bytes, version: int,
              deps: Any) -> None:
        with self._lock:
            self._entries[key] = (data, version, deps)

    def invalidate(self, changed: set, prev_version: int,
                   new_version: int,
                   changed_ports: set = frozenset()) -> list[tuple]:
        """Publish sweep: returns the dropped keys. `changed_ports`
        (snapshot.changed_http_ports) additionally drops RDS groups
        whose port's service membership moved — the deps set records
        the namespaces ON the port at build time, which cannot see a
        cross-namespace service joining it."""
        dropped: list[tuple] = []
        carried = 0
        with self._lock:
            for key, (data, v, deps) in list(self._entries.items()):
                stale = v != prev_version
                affected = deps is None or bool(deps & changed)
                port_hit = (key[0] == "rds" and len(key) == 3
                            and key[1] in changed_ports)
                if stale or port_hit or (changed and affected):
                    del self._entries[key]
                    dropped.append(key)
                else:
                    self._entries[key] = (data, new_version, deps)
                    carried += 1
            self.invalidated += len(dropped)
            self.carried += carried
        monitor.note_discovery_cache("invalidated", len(dropped))
        monitor.note_discovery_cache("carried", carried)
        return dropped

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.invalidated += n
        monitor.note_discovery_cache("invalidated", n)
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            by_endpoint: dict[str, int] = {}
            for key in self._entries:
                by_endpoint[key[0]] = by_endpoint.get(key[0], 0) + 1
            calls = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "by_endpoint": by_endpoint,
                "hits": self.hits,
                "misses": self.misses,
                "carried": self.carried,
                "invalidated": self.invalidated,
                "hit_rate": round(self.hits / calls, 4) if calls
                else None,
            }


def _dumps(obj: Any) -> bytes:
    return json.dumps(obj, indent=2, sort_keys=True).encode()


class DiscoveryService:
    """Serves envoy v1 discovery from versioned snapshots."""

    def __init__(self, registry, config_store,
                 mesh: Mapping[str, Any] | None = None,
                 scope_shards: int = 8, watch_cap: int = 1024):
        self.registry = registry
        self.config_store = config_store
        self.mesh = dict(mesh or {})
        self._watch_cap = max(int(watch_cap), 0)
        self._cache = SnapshotCache()
        self._publish_lock = threading.Lock()
        self._gen_lock = threading.Lock()   # pending-group set
        self._watch = threading.Condition()
        self._scope_shards = max(int(scope_shards), 1)
        self._snapshot = build_snapshot(registry, config_store,
                                        version=1, prev=None,
                                        n_shards=self._scope_shards)
        self._shard_version = [1] * self._scope_shards
        self._shard_bump_wall = [0.0] * self._scope_shards
        self._pending_rds: set[tuple] = set()
        self._hold = 0
        self._dirty = False
        self._draining = False
        self._n_waiting = 0
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        monitor.set_discovery_generation(1)
        if hasattr(config_store, "register_handler"):
            config_store.register_handler(self._on_event)
        if hasattr(registry, "append_service_handler"):
            registry.append_service_handler(self._on_event)

    # -- snapshot publishing ------------------------------------------

    @property
    def snapshot(self) -> DiscoverySnapshot:
        return self._snapshot

    @property
    def generation(self) -> int:
        return self._snapshot.version

    def _on_event(self, *_args) -> None:
        if self._draining or self._hold:
            # quiesce/hold: generation is off, but the world moved —
            # remember it so start()/hold-exit republishes (a restart
            # must never serve the pre-drain snapshot forever)
            self._dirty = True
            return
        self.publish()

    @contextlib.contextmanager
    def hold_publishes(self):
        """Defer event-driven publishes (apply a churn batch, publish
        once) — the debounce seam the bench/smoke churn storms use."""
        self._hold += 1
        try:
            yield
        finally:
            self._hold -= 1
            if not self._hold and self._dirty and not self._draining:
                # while draining, _dirty STAYS set — start()'s
                # catch-up publish is what replays it after a restart
                self._dirty = False
                self.publish()

    def publish(self) -> dict:
        """Freeze the live world into the next generation, diff it
        against the current one, sweep only the affected cache
        entries, and wake only the watch shards whose namespaces
        changed. Returns the publish audit record."""
        with self._publish_lock:
            # discovery-push chaos seam: an armed delay stalls the
            # pipeline inside the publish lock (watchers stay parked on
            # the old generation until the delayed push completes) and
            # registers with the injection ledger. Lazy import keeps
            # pilot importable without the runtime package.
            from istio_tpu.runtime.resilience import CHAOS
            CHAOS.discovery_publish()
            prev = self._snapshot
            t0 = time.perf_counter()
            snap = build_snapshot(self.registry, self.config_store,
                                  version=prev.version + 1, prev=prev,
                                  n_shards=self._scope_shards)
            monitor.observe_discovery_stage(
                "snapshot_build",
                max(snap.build_wall_s - snap.plan_wall_s, 0.0))
            monitor.observe_discovery_stage("scope_plan",
                                            snap.plan_wall_s)
            t1 = time.perf_counter()
            changed = changed_scopes(prev, snap)
            ports_moved = changed_http_ports(prev, snap)
            dropped = self._cache.invalidate(changed, prev.version,
                                             snap.version,
                                             ports_moved)
            with self._gen_lock:
                self._pending_rds |= {k for k in dropped
                                      if k[0] == "rds"
                                      and k[1] != "ingress"}
            self._snapshot = snap
            shards_hit: set[int] = set()
            if changed:
                if MESH_SCOPE in changed:
                    shards_hit = set(range(self._scope_shards))
                else:
                    for ns in changed:
                        # BOTH plans: a fully-deleted namespace is
                        # gone from the new plan (shard_of falls back
                        # to the crc32 hash), but its watchers parked
                        # on the PREVIOUS plan's shard — bump that
                        # one too or they never learn their services
                        # vanished
                        shards_hit.add(snap.plan.shard_of(ns))
                        shards_hit.add(prev.plan.shard_of(ns))
            wall = time.perf_counter()
            with self._watch:
                for k in shards_hit:
                    self._shard_version[k] = snap.version
                    self._shard_bump_wall[k] = wall
                self._watch.notify_all()
            monitor.observe_discovery_stage(
                "invalidate", time.perf_counter() - t1)
            monitor.set_discovery_generation(snap.version)
            audit = {"version": snap.version,
                     "changed_scopes": sorted(changed),
                     "changed_ports": sorted(ports_moved),
                     "invalidated": len(dropped),
                     "shards_notified": sorted(shards_hit),
                     "build_wall_ms":
                         round((time.perf_counter() - t0) * 1e3, 3),
                     "scope_program_reused": snap.scope_reused}
            log.debug("discovery publish: %s", audit)
            self._last_publish = audit
            return audit

    # -- cache (scoped invalidation replaces discovery.go:489) --------

    def clear_cache(self) -> None:
        """Wholesale drop (the reference's clearCache, kept as the
        manual/admin escape hatch — registry/config events use the
        scoped publish sweep instead)."""
        self._cache.clear()
        log.debug("discovery cache cleared")

    @property
    def cache_size(self) -> int:
        return len(self._cache)

    def _serve_cached(self, key: tuple, snap: DiscoverySnapshot,
                      build) -> bytes:
        """Cache lookup → response bytes; on miss, generate against
        `snap` (RDS misses batch every pending group through one
        device step first). Hot section: one dict lookup + counters on
        the hit path."""
        t0 = time.perf_counter()
        data = self._cache.lookup(key, snap.version)
        if data is not None:
            CALLS.labels(endpoint=key[0], cache="hit").inc()
            monitor.note_discovery_cache("hit")
            monitor.observe_discovery_stage(
                "serve", time.perf_counter() - t0)
            return data
        CALLS.labels(endpoint=key[0], cache="miss").inc()
        monitor.note_discovery_cache("miss")
        if key[0] == "rds" and key[1] != "ingress":
            self._generate_rds_batch(snap, key)
            data = self._cache.peek(key, snap.version)
        if data is None:
            t1 = time.perf_counter()
            obj, deps = build(snap)
            data = _dumps(obj)
            self._cache.store(key, data, snap.version, deps)
            monitor.observe_discovery_stage(
                "generate", time.perf_counter() - t1)
        monitor.observe_discovery_stage(
            "serve", time.perf_counter() - t0)
        return data

    def _generate_rds_batch(self, snap: DiscoverySnapshot,
                            want_key: tuple) -> None:
        """Fill `want_key` plus every RDS group the last publish
        invalidated, in ONE batched generation: one source-admission
        device step shared across all pending node groups, then
        per-group JSON assembly. Hot section: the device pull lives in
        RouteScopeProgram.admit_rows behind its pragma."""
        with self._gen_lock:
            pending = {k for k in self._pending_rds
                       if k[0] == "rds" and k[1] != "ingress"}
            pending.add(want_key)
            groups = sorted(pending, key=repr)
            t0 = time.perf_counter()
            rows = snap.scope.admit_rows(
                [src for (_e, _port, src) in groups])
            monitor.observe_discovery_stage(
                "route_eval", time.perf_counter() - t0)
            t1 = time.perf_counter()
            for key, row in zip(groups, rows):
                _e, port_num, source = key
                obj, deps = self._assemble_rds(snap, port_num, row)
                self._cache.store(key, _dumps(obj), snap.version, deps)
            monitor.observe_discovery_stage(
                "generate", time.perf_counter() - t1)
            self._pending_rds -= pending

    def _assemble_rds(self, snap: DiscoverySnapshot, port_num: int,
                      admit_row) -> tuple[dict, frozenset]:
        """RDS payload for one node group from the admission row —
        byte-identical to routes.build_route_config over the same
        world (assembly is single-sourced through
        build_virtual_host_from_rules; the admission row reproduces
        the _match_source filter)."""
        vhosts = []
        deps = {MESH_SCOPE}          # egress vhosts ride every RDS
        # port_services is hostname-sorted, exactly the order the
        # whole-mesh scan visits services — O(services on port), not
        # O(mesh), per group
        for host in snap.port_services.get(port_num, ()):
            service = snap.registry.get_service(host)
            if service is None:
                continue
            for port in service.ports:
                if port.port == port_num and port.is_http:
                    rules = snap.rules_for(host)
                    kept = [r for i, r in enumerate(rules)
                            if snap.scope.admits(admit_row, host, i)]
                    vhosts.append(build_virtual_host_from_rules(
                        service, port, kept))
                    deps.add(scope_of_hostname(host))
        vhosts.extend(build_egress_virtual_hosts(snap.config, port_num))
        vhosts.sort(key=lambda v: v["name"])
        return ({"virtual_hosts": vhosts, "validate_clusters": False},
                frozenset(deps))

    # -- endpoints ----------------------------------------------------

    def list_endpoints(self, service_key: str) -> bytes:
        """SDS /v1/registration/{service-key} (discovery.go:572) —
        namespace-scoped cache entry."""
        snap = self._snapshot

        def build(s):
            hostname, _, rest = service_key.partition("|")
            port_name, _, label_str = rest.partition("|")
            labels = dict(kv.split("=", 1)
                          for kv in label_str.split(",") if "=" in kv)
            instances = s.registry.instances(
                hostname, (port_name,) if port_name else (), labels)
            return {"hosts": [
                {"ip_address": i.endpoint.address,
                 "port": i.endpoint.port,
                 "tags": {"az": i.availability_zone} if
                 i.availability_zone else {}}
                for i in instances]}, \
                frozenset({scope_of_hostname(
                    service_key.partition("|")[0])})

        return self._serve_cached(("sds", service_key), snap, build)

    def _cds_group(self, snap: DiscoverySnapshot, node: str) -> tuple:
        role = Node.parse(node)
        if role.type != NODE_SIDECAR:
            return ("cds", role.type)
        ports = tuple(sorted({i.endpoint.port
                              for i in snap.node_instances(node)}))
        return ("cds", role.type, ports)

    def list_clusters(self, cluster: str, node: str) -> bytes:
        snap = self._snapshot

        def build(s):
            services = s.registry.services()
            clusters = build_outbound_clusters(services, s.config)
            clusters += build_egress_clusters(s.config)
            clusters += build_jwks_clusters(s.config)
            if Node.parse(node).type == NODE_SIDECAR:
                clusters += build_inbound_clusters(
                    s.node_instances(node))
            return {"clusters": clusters}, None   # mesh-scoped

        return self._serve_cached(self._cds_group(snap, node), snap,
                                  build)

    def _rds_group(self, snap: DiscoverySnapshot, name: str,
                   node: str) -> tuple:
        if Node.parse(node).type == NODE_INGRESS:
            return ("rds", "ingress")
        port_num = int(name)
        source = snap.node_source(node) \
            if snap.port_has_source_rules(port_num) else None
        return ("rds", port_num, source)

    def list_routes(self, name: str, cluster: str, node: str) -> bytes:
        snap = self._snapshot
        key = self._rds_group(snap, name, node)
        if key[1] == "ingress":
            def build(s):
                return build_ingress_route_config(s.config,
                                                  s.registry), None
            return self._serve_cached(key, snap, build)

        def build(s):
            row = s.scope.admit_rows([key[2]])[0]
            return self._assemble_rds(s, key[1], row)

        return self._serve_cached(key, snap, build)

    def _lds_group(self, snap: DiscoverySnapshot, node: str) -> tuple:
        role = Node.parse(node)
        if role.type == NODE_INGRESS:
            return ("lds", "ingress")
        sig = tuple(sorted(
            (i.endpoint.address, i.endpoint.port,
             i.endpoint.service_port.protocol)
            for i in snap.node_instances(node)))
        return ("lds", role.type, sig)

    def list_listeners(self, cluster: str, node: str) -> bytes:
        snap = self._snapshot

        def build(s):
            services = s.registry.services()
            role = Node.parse(node)
            if role.type == NODE_INGRESS:
                listeners = build_ingress_listeners(
                    s.config, s.registry, self.mesh,
                    tls_context=self.mesh.get("ingress_tls"))
            else:
                listeners = build_outbound_listeners(services, s.config,
                                                     self.mesh)
                if role.type == NODE_SIDECAR:
                    listeners += build_inbound_listeners(
                        s.node_instances(node), self.mesh)
            return {"listeners": listeners}, None   # mesh-scoped

        return self._serve_cached(self._lds_group(snap, node), snap,
                                  build)

    def availability_zone(self, cluster: str, node: str) -> bytes:
        """/v1/az/{cluster}/{node} (discovery.go:601): the AZ of the
        node's instances (all share the node IP, hence the AZ).
        Plain-text body (the only non-JSON discovery response)."""
        CALLS.labels(endpoint="az", cache="miss").inc()
        instances = self._snapshot.node_instances(node)
        if not instances:
            raise KeyError(f"az: no instances for node {node}")
        return str(instances[0].availability_zone or "").encode()

    # -- delta push (long-poll version watch) -------------------------

    def watch(self, node: str, have_version: int = 0,
              timeout_s: float | None = None) -> dict:
        """Park until the node's scope shard publishes a generation
        newer than `have_version` (or timeout / drain). The scope
        shard comes from the snapshot's namespace→shard map, so a
        publish wakes only the shards whose namespaces changed —
        delta push instead of full-fleet re-pulls.

        Capacity: the shard map bounds the VERSION bookkeeping, but on
        the threaded stdlib front each parked watcher still holds one
        OS thread — `watch_cap` (constructor, default 1024) bounds
        that honestly: over-capacity watchers return IMMEDIATELY with
        `over_capacity: true` (degrading those clients to plain
        polling) instead of letting a 10k-sidecar fleet pin 10k
        threads."""
        timeout = DEFAULT_WATCH_TIMEOUT_S if timeout_s is None \
            else min(max(float(timeout_s), 0.0), MAX_WATCH_TIMEOUT_S)
        snap = self._snapshot
        shard = snap.shard_of_node(node)
        entered = time.perf_counter()
        deadline = entered + timeout
        with self._watch:
            if self._n_waiting >= self._watch_cap:
                cur = self._shard_version[shard]
                return {"version": self._snapshot.version,
                        "shard": shard, "shard_version": cur,
                        "changed": cur > have_version,
                        "over_capacity": True,
                        "draining": self._draining}
            self._n_waiting += 1
            try:
                while (not self._draining
                       and self._shard_version[shard] <= have_version):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._watch.wait(remaining)
            finally:
                self._n_waiting -= 1
            cur = self._shard_version[shard]
            bump_wall = self._shard_bump_wall[shard]
        changed = cur > have_version
        if changed and bump_wall >= entered:
            # this waiter was parked when the publish landed — the
            # wake delay IS the push fan-out latency
            monitor.observe_discovery_push(
                time.perf_counter() - bump_wall)
        return {"version": self._snapshot.version,
                "shard": shard, "shard_version": cur,
                "changed": changed, "draining": self._draining}

    # -- operator view ------------------------------------------------

    def debug_view(self) -> dict:
        """/debug/discovery payload: generation + cache occupancy/hit
        accounting, node-group counts, the scope plan's balance and
        stability, shard watch versions, push fan-out percentiles and
        the stage decomposition."""
        snap = self._snapshot
        with self._watch:
            shard_versions = list(self._shard_version)
            waiting = self._n_waiting
        lat = monitor.discovery_latency_snapshot()
        return {
            "generation": snap.version,
            "n_services": snap.n_services,
            "n_route_rules": snap.n_rules,
            "scope_shards": self._scope_shards,
            "scope_program": {
                "constrained_rules": snap.scope.n_constrained,
                "reused": snap.scope_reused,
                "digest": snap.scope.digest[:16],
            },
            "source_ports": sorted(snap.source_ports),
            "cache": self._cache.stats(),
            "pending_rds_groups": len(self._pending_rds),
            "plan": snap.plan.to_json(),
            "shard_versions": shard_versions,
            "watchers_waiting": waiting,
            "watch_cap": self._watch_cap,
            "last_publish": getattr(self, "_last_publish", None),
            "push": lat["push"],
            "stages": lat["stages"],
            "draining": self._draining,
        }

    # -- parity reference ---------------------------------------------

    def reference_bytes(self, path: str) -> bytes:
        """The UNSCOPED SINGLE-NODE generation path: rebuild the
        response for `path` directly from the LIVE registry/config
        store with the legacy per-node builders — no snapshot, no
        cache, no grouping, no batched admission. The tier-1 parity
        gate (scripts/discovery_smoke.py, tests) asserts served bytes
        are byte-identical to this."""
        from istio_tpu.pilot.model import IstioConfigStore
        parts = [p for p in path.split("/") if p]
        cfg = IstioConfigStore(self.config_store)
        if parts[1] == "registration":
            service_key = "/".join(parts[2:])
            hostname, _, rest = service_key.partition("|")
            port_name, _, label_str = rest.partition("|")
            labels = dict(kv.split("=", 1)
                          for kv in label_str.split(",") if "=" in kv)
            instances = self.registry.instances(
                hostname, (port_name,) if port_name else (), labels)
            return _dumps({"hosts": [
                {"ip_address": i.endpoint.address,
                 "port": i.endpoint.port,
                 "tags": {"az": i.availability_zone} if
                 i.availability_zone else {}}
                for i in instances]})
        node = parts[-1]
        role = Node.parse(node)
        # canonical colocated-instance order (snapshot.instance_order):
        # the live registry returns insertion order, which is process-
        # history state neither side of the parity gate may depend on
        live_instances = sorted(
            self.registry.host_instances({role.ip_address}),
            key=instance_order)
        if parts[1] == "clusters":
            services = self.registry.services()
            clusters = build_outbound_clusters(services, cfg)
            clusters += build_egress_clusters(cfg)
            clusters += build_jwks_clusters(cfg)
            if role.type == NODE_SIDECAR:
                clusters += build_inbound_clusters(live_instances)
            return _dumps({"clusters": clusters})
        if parts[1] == "routes":
            if role.type == NODE_INGRESS:
                return _dumps(build_ingress_route_config(
                    cfg, self.registry))
            hosts = sorted({i.service.hostname
                            for i in live_instances})
            source = hosts[0] if hosts else None
            return _dumps(build_route_config(
                self.registry.services(), int(parts[2]), cfg,
                source=source))
        if parts[1] == "listeners":
            services = self.registry.services()
            if role.type == NODE_INGRESS:
                listeners = build_ingress_listeners(
                    cfg, self.registry, self.mesh,
                    tls_context=self.mesh.get("ingress_tls"))
            else:
                listeners = build_outbound_listeners(services, cfg,
                                                     self.mesh)
                if role.type == NODE_SIDECAR:
                    listeners += build_inbound_listeners(
                        live_instances, self.mesh)
            return _dumps({"listeners": listeners})
        raise KeyError(path)

    # -- HTTP front ---------------------------------------------------

    def begin_drain(self) -> None:
        """Quiesce step 1+2 (admission → generation): new pulls answer
        typed UNAVAILABLE, config events stop publishing, and every
        parked watcher is released with its current version."""
        self._draining = True
        with self._watch:
            self._watch.notify_all()

    def start(self, address: str = "127.0.0.1", port: int = 0) -> int:
        ds = self
        self._draining = False
        if self._dirty:
            # events landed while drained: catch the snapshot up
            # before serving again
            self._dirty = False
            self.publish()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet
                log.debug("discovery: " + fmt, *args)

            def do_GET(self):
                from istio_tpu.runtime.resilience import \
                    UnavailableError
                try:
                    body, ctype = ds._route(self.path)
                except UnavailableError as exc:
                    body = json.dumps(
                        {"error": str(exc), "code": "UNAVAILABLE",
                         "grpc_code": exc.grpc_code}).encode()
                    self.send_response(503)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                except KeyError:
                    self.send_error(404)
                    return
                except Exception:
                    log.exception("discovery handler failed")
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((address, port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="pilot-discovery")
        self._thread.start()
        self.port = self._server.server_address[1]
        log.info("pilot discovery on port %d", self.port)
        return self.port

    def _route(self, path: str) -> tuple[bytes, str]:
        raw, _, query_str = path.partition("?")
        query = dict(parse_qsl(query_str))
        parts = [p for p in raw.split("/") if p]
        if parts == ["debug", "discovery"]:
            return (json.dumps(self.debug_view(), indent=1,
                               default=str).encode(),
                    "application/json")
        if self._draining:
            from istio_tpu.runtime.resilience import UnavailableError
            raise UnavailableError("discovery draining")
        if len(parts) >= 3 and parts[0] == "v1":
            if parts[1] == "registration":
                return (self.list_endpoints("/".join(parts[2:])),
                        "application/json")
            if parts[1] == "clusters" and len(parts) == 4:
                return (self.list_clusters(parts[2], parts[3]),
                        "application/json")
            if parts[1] == "routes" and len(parts) == 5:
                return (self.list_routes(parts[2], parts[3], parts[4]),
                        "application/json")
            if parts[1] == "listeners" and len(parts) == 4:
                return (self.list_listeners(parts[2], parts[3]),
                        "application/json")
            if parts[1] == "az" and len(parts) == 4:
                return (self.availability_zone(parts[2], parts[3]),
                        "text/plain")
            if parts[1] == "watch" and len(parts) == 4:
                try:
                    have = int(query.get("version", 0) or 0)
                except ValueError:
                    have = 0
                try:
                    timeout = float(query["timeout"]) \
                        if "timeout" in query else None
                except ValueError:
                    timeout = None
                return (json.dumps(self.watch(parts[3], have,
                                              timeout)).encode(),
                        "application/json")
        raise KeyError(path)

    def stop(self) -> None:
        """Ordered quiesce (PR 7 doctrine): admission off + watchers
        released (begin_drain) → generation off (events no-op while
        draining) → flush (the listener stops accepting and in-flight
        handlers finish) → join."""
        self.begin_drain()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
