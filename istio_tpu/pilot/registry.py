"""Service registries — the ServiceDiscovery contract + backends.

Reference: pilot/pkg/model/service.go:220 ServiceDiscovery iface,
pilot/pkg/serviceregistry/{kube,consul,eureka,cloudfoundry,aggregate}.
This image has no k8s/consul/eureka endpoints, so the concrete
backends are: MemoryRegistry (programmatic; the mock/discovery.go test
backbone and the file-driven topology source) and AggregateRegistry
(fans out queries + change handlers exactly like aggregate/
controller.go). Platform adapters implement the same four queries and
plug into the aggregate — the contract, caching and event flow are the
load-bearing parts reproduced here.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, Mapping, Sequence

from istio_tpu.pilot.model import (NetworkEndpoint, Port, Service,
                                   ServiceInstance)

Handler = Callable[[Service, str], None]


class ServiceDiscovery:
    """service.go:220: Services/GetService/Instances/HostInstances."""

    def services(self) -> list[Service]:
        raise NotImplementedError

    def get_service(self, hostname: str) -> Service | None:
        raise NotImplementedError

    def instances(self, hostname: str, ports: Sequence[str] = (),
                  labels: Mapping[str, str] | None = None
                  ) -> list[ServiceInstance]:
        raise NotImplementedError

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        """Instances co-located with a proxy's addresses."""
        raise NotImplementedError

    def get_istio_service_accounts(self, hostname: str,
                                   ports: Sequence[str]) -> list[str]:
        return []


class MemoryRegistry(ServiceDiscovery):
    """Programmatic registry (reference mock/discovery.go role)."""

    def __init__(self) -> None:
        self._services: dict[str, Service] = {}
        self._instances: dict[str, list[ServiceInstance]] = {}
        self._lock = threading.Lock()
        self._svc_handlers: list[Handler] = []

    # -- mutation --

    def add_service(self, service: Service,
                    endpoints: Iterable[tuple] = ()) -> None:
        """Register a service; endpoints = (address, labels) pairs or
        (address, labels, availability_zone) triples, one instance per
        (endpoint, service port)."""
        with self._lock:
            self._services[service.hostname] = service
            insts = []
            for ep in endpoints:
                addr, labels = ep[0], ep[1]
                az = ep[2] if len(ep) > 2 else ""
                for port in service.ports:
                    insts.append(ServiceInstance(
                        endpoint=NetworkEndpoint(address=addr,
                                                 port=port.port,
                                                 service_port=port),
                        service=service, labels=dict(labels),
                        availability_zone=az,
                        service_account=service.service_account))
            self._instances[service.hostname] = insts
        for fn in list(self._svc_handlers):
            fn(service, "add")

    def remove_service(self, hostname: str) -> None:
        with self._lock:
            svc = self._services.pop(hostname, None)
            self._instances.pop(hostname, None)
        if svc is not None:
            for fn in list(self._svc_handlers):
                fn(svc, "delete")

    # -- ServiceDiscovery --

    def services(self) -> list[Service]:
        with self._lock:
            return sorted(self._services.values(),
                          key=lambda s: s.hostname)

    def get_service(self, hostname: str) -> Service | None:
        with self._lock:
            return self._services.get(hostname)

    def instances(self, hostname, ports=(), labels=None):
        with self._lock:
            out = []
            for inst in self._instances.get(hostname, []):
                if ports and inst.endpoint.service_port.name not in ports:
                    continue
                if labels and any(inst.labels.get(k) != v
                                  for k, v in labels.items()):
                    continue
                out.append(inst)
            return out

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        with self._lock:
            return [i for insts in self._instances.values()
                    for i in insts if i.endpoint.address in addrs]

    # -- ConfigStoreCache-style handlers (kube controller.go role) --

    def append_service_handler(self, fn: Handler) -> None:
        self._svc_handlers.append(fn)


class AggregateRegistry(ServiceDiscovery):
    """serviceregistry/aggregate/controller.go: merge registries."""

    def __init__(self, registries: Sequence[ServiceDiscovery] = ()):
        self.registries = list(registries)

    def add_registry(self, registry: ServiceDiscovery) -> None:
        self.registries.append(registry)

    def services(self) -> list[Service]:
        seen: dict[str, Service] = {}
        for r in self.registries:
            for s in r.services():
                seen.setdefault(s.hostname, s)
        return sorted(seen.values(), key=lambda s: s.hostname)

    def get_service(self, hostname: str) -> Service | None:
        for r in self.registries:
            s = r.get_service(hostname)
            if s is not None:
                return s
        return None

    def instances(self, hostname, ports=(), labels=None):
        out = []
        for r in self.registries:
            out.extend(r.instances(hostname, ports, labels))
        return out

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        out = []
        for r in self.registries:
            out.extend(r.host_instances(addrs))
        return out

    def append_service_handler(self, fn: Handler) -> None:
        for r in self.registries:
            if hasattr(r, "append_service_handler"):
                r.append_service_handler(fn)
