"""Envoy v1 bootstrap/listener/cluster generation.

Reference: pilot/pkg/proxy/envoy/config.go — BuildConfig (:81,
bootstrap with RDS/admin/tracing/mixer cluster), buildListeners (:136),
sidecar in/outbound (:199,:496,:707); policy.go applyClusterPolicy
(:39: circuit breakers :179, outlier detection :152, LB :128);
mixer.go FilterMixerConfig (:82); resources.go JSON shapes.
"""
from __future__ import annotations

from typing import Any, Mapping, Sequence

from istio_tpu.pilot.model import (IstioConfigStore, Port, Service,
                                   ServiceInstance)
from istio_tpu.pilot.registry import ServiceDiscovery
from istio_tpu.pilot.routes import (build_route_config, cluster_name,
                                    inbound_cluster_name, default_route,
                                    build_fault_filter)

DEFAULT_ADMIN_PORT = 15000
DEFAULT_DISCOVERY_REFRESH_MS = 1000


# ---------------------------------------------------------------------------
# clusters
# ---------------------------------------------------------------------------

def build_outbound_clusters(services: Sequence[Service],
                            config_store: IstioConfigStore
                            ) -> list[dict[str, Any]]:
    clusters: dict[str, dict[str, Any]] = {}
    for service in services:
        # rule scan is port-independent — hoisted out of the port loop
        label_sets: list[Mapping[str, str] | None] = [None]
        for rule in config_store.route_rules(service.hostname):
            for block in rule.spec.get("route", ()):
                labels = block.get("labels") or block.get("tags")
                if labels:
                    label_sets.append(labels)
            if rule.spec.get("mirror", {}).get("labels"):
                label_sets.append(rule.spec["mirror"]["labels"])
        policy = config_store.destination_policy(service.hostname)
        for port in service.ports:
            for labels in label_sets:
                name = cluster_name(service.hostname, port, labels)
                if name in clusters:
                    continue
                cluster: dict[str, Any] = {
                    "name": name,
                    "type": "sds",
                    "service_name": service.key(port) + (
                        "|" + ",".join(f"{k}={v}" for k, v in
                                       sorted(labels.items()))
                        if labels else ""),
                    "lb_type": "round_robin",
                    "connect_timeout_ms": 1000,
                }
                if port.protocol in ("HTTP2", "GRPC"):
                    cluster["features"] = "http2"
                _apply_cluster_policy(cluster, policy)
                clusters[name] = cluster
    return [clusters[k] for k in sorted(clusters)]


def _apply_cluster_policy(cluster: dict[str, Any],
                          policy: "Any | None") -> None:
    """policy.go:39 applyClusterPolicy."""
    if policy is None:
        return
    lb = policy.spec.get("loadBalancing", {})
    if lb.get("name"):
        cluster["lb_type"] = {"ROUND_ROBIN": "round_robin",
                              "LEAST_CONN": "least_request",
                              "RANDOM": "random"}.get(lb["name"],
                                                      "round_robin")
    cb = policy.spec.get("circuitBreaker", {}).get("simpleCb", {})
    if cb:
        thresholds: dict[str, Any] = {}
        if "maxConnections" in cb:
            thresholds["max_connections"] = int(cb["maxConnections"])
        if "httpMaxPendingRequests" in cb:
            thresholds["max_pending_requests"] = \
                int(cb["httpMaxPendingRequests"])
        if "httpMaxRequests" in cb:
            thresholds["max_requests"] = int(cb["httpMaxRequests"])
        if "httpMaxRetries" in cb:
            thresholds["max_retries"] = int(cb["httpMaxRetries"])
        cluster["circuit_breakers"] = {"default": thresholds}
        outlier: dict[str, Any] = {}
        if "httpConsecutiveErrors" in cb:
            outlier["consecutive_5xx"] = int(cb["httpConsecutiveErrors"])
        if "httpDetectionInterval" in cb:
            iv = cb["httpDetectionInterval"]
            outlier["interval_ms"] = int(float(str(iv).rstrip("s")) * 1000)
        if "sleepWindow" in cb:
            sw = cb["sleepWindow"]
            outlier["base_ejection_time_ms"] = \
                int(float(str(sw).rstrip("s")) * 1000)
        if outlier:
            cluster["outlier_detection"] = outlier


def build_inbound_clusters(instances: Sequence[ServiceInstance]
                           ) -> list[dict[str, Any]]:
    clusters = {}
    for inst in instances:
        name = inbound_cluster_name(inst.endpoint.port)
        clusters[name] = {
            "name": name, "type": "static", "lb_type": "round_robin",
            "connect_timeout_ms": 1000,
            "hosts": [{"url": f"tcp://127.0.0.1:{inst.endpoint.port}"}]}
    return [clusters[k] for k in sorted(clusters)]


# ---------------------------------------------------------------------------
# listeners
# ---------------------------------------------------------------------------

def _http_filters(mesh: Mapping[str, Any],
                  faults: Sequence[dict] = ()) -> list[dict]:
    filters = list(faults)
    if mesh.get("mixer_address"):
        # mixer.go:82 FilterMixerConfig
        filters.append({"type": "decoder", "name": "mixer", "config": {
            "mixer_attributes": {
                "destination.uid": mesh.get("node_uid", ""),
            },
            "forward_attributes": {
                "source.uid": mesh.get("node_uid", ""),
            },
            "quota_name": "RequestCount",
        }})
    filters.append({"type": "decoder", "name": "router", "config": {}})
    return filters


def _port_fault_filters(port_num: int, services: Sequence[Service],
                        config_store: IstioConfigStore) -> list[dict]:
    """Fault filters for route-rules with httpFault on services exposed
    on this port, scoped by the rule's match headers (fault.go:28-139
    buildFaultFilters — faults live in the filter chain, not routes)."""
    from istio_tpu.pilot.routes import build_route_match
    faults = []
    for service in services:
        if not any(p.port == port_num and p.is_http
                   for p in service.ports):
            continue
        for rule in config_store.route_rules(service.hostname):
            fault_spec = rule.spec.get("httpFault")
            if not fault_spec:
                continue
            match = build_route_match(rule.spec.get("match"))
            headers = list(match.get("headers", ()))
            filt = build_fault_filter(fault_spec, headers)
            if filt is not None:
                filt["config"]["upstream_cluster"] = cluster_name(
                    service.hostname,
                    next(p for p in service.ports
                         if p.port == port_num and p.is_http))
                faults.append(filt)
    return faults


def build_outbound_listeners(services: Sequence[Service],
                             config_store: IstioConfigStore,
                             mesh: Mapping[str, Any]) -> list[dict]:
    """One HTTP listener per outbound port using RDS; TCP services get
    tcp_proxy with explicit routes (config.go:496)."""
    listeners: dict[int, dict[str, Any]] = {}
    kinds: dict[int, str] = {}    # port → http|tcp (conflict tracking)
    for service in services:
        for port in service.ports:
            kind = "http" if port.is_http else "tcp"
            if port.port in kinds and kinds[port.port] != kind:
                # protocol conflict on a shared port: first writer wins,
                # like the reference's listener-conflict logging
                import logging
                logging.getLogger("istio_tpu.pilot").warning(
                    "listener conflict on port %d: %s vs %s (%s dropped)",
                    port.port, kinds[port.port], kind, service.hostname)
                continue
            kinds[port.port] = kind
            if port.is_http:
                if port.port in listeners:
                    continue
                listeners[port.port] = {
                    "address": f"tcp://0.0.0.0:{port.port}",
                    "name": f"http_0.0.0.0_{port.port}",
                    "filters": [{
                        "type": "read", "name": "http_connection_manager",
                        "config": {
                            "codec_type": "auto",
                            "stat_prefix": "http",
                            "rds": {
                                "cluster": "rds",
                                "route_config_name": str(port.port),
                                "refresh_delay_ms":
                                    DEFAULT_DISCOVERY_REFRESH_MS},
                            "filters": _http_filters(
                                mesh, _port_fault_filters(
                                    port.port, services, config_store)),
                        }}],
                }
            else:
                key = port.port
                tcp_route = {"cluster": cluster_name(service.hostname,
                                                     port)}
                if service.address and service.address != "0.0.0.0":
                    tcp_route["destination_ip_list"] = \
                        [f"{service.address}/32"]
                entry = listeners.setdefault(key, {
                    "address": f"tcp://0.0.0.0:{port.port}",
                    "name": f"tcp_0.0.0.0_{port.port}",
                    "filters": [{"type": "read", "name": "tcp_proxy",
                                 "config": {"stat_prefix": "tcp",
                                            "route_config":
                                                {"routes": []}}}]})
                entry["filters"][0]["config"]["route_config"]["routes"] \
                    .append(tcp_route)
    return [listeners[k] for k in sorted(listeners)]


def build_inbound_listeners(instances: Sequence[ServiceInstance],
                            mesh: Mapping[str, Any]) -> list[dict]:
    """Per-endpoint-port inbound listeners (config.go:707)."""
    listeners = {}
    for inst in instances:
        port = inst.endpoint.port
        if port in listeners:
            continue
        sp = inst.endpoint.service_port
        if sp.is_http:
            vhost = {"name": "inbound", "domains": ["*"], "routes": [
                {"prefix": "/", "cluster": inbound_cluster_name(port),
                 "timeout_ms": 0}]}
            listeners[port] = {
                "address": f"tcp://{inst.endpoint.address}:{port}",
                "name": f"http_{inst.endpoint.address}_{port}",
                "filters": [{
                    "type": "read", "name": "http_connection_manager",
                    "config": {"codec_type": "auto",
                               "stat_prefix": "http",
                               "route_config": {"virtual_hosts": [vhost]},
                               "filters": _http_filters(mesh)}}],
            }
        else:
            listeners[port] = {
                "address": f"tcp://{inst.endpoint.address}:{port}",
                "name": f"tcp_{inst.endpoint.address}_{port}",
                "filters": [{"type": "read", "name": "tcp_proxy",
                             "config": {"stat_prefix": "tcp",
                                        "route_config": {"routes": [
                                            {"cluster":
                                             inbound_cluster_name(port)}]}}}]}
    return [listeners[k] for k in sorted(listeners)]


# ---------------------------------------------------------------------------
# bootstrap (config.go:81 BuildConfig)
# ---------------------------------------------------------------------------

def build_bootstrap(mesh: Mapping[str, Any]) -> dict[str, Any]:
    discovery = mesh.get("discovery_address", "127.0.0.1:8080")
    config: dict[str, Any] = {
        "admin": {"access_log_path": "/dev/stdout",
                  "address": f"tcp://127.0.0.1:"
                             f"{mesh.get('admin_port', DEFAULT_ADMIN_PORT)}"},
        "listeners": [],
        "lds": {"cluster": "lds", "refresh_delay_ms":
                DEFAULT_DISCOVERY_REFRESH_MS},
        "cluster_manager": {
            "clusters": [
                {"name": "rds", "type": "strict_dns",
                 "lb_type": "round_robin", "connect_timeout_ms": 1000,
                 "hosts": [{"url": f"tcp://{discovery}"}]},
                {"name": "lds", "type": "strict_dns",
                 "lb_type": "round_robin", "connect_timeout_ms": 1000,
                 "hosts": [{"url": f"tcp://{discovery}"}]},
            ],
            "sds": {"cluster": {"name": "sds", "type": "strict_dns",
                                "lb_type": "round_robin",
                                "connect_timeout_ms": 1000,
                                "hosts": [{"url": f"tcp://{discovery}"}]},
                    "refresh_delay_ms": DEFAULT_DISCOVERY_REFRESH_MS},
        },
    }
    if mesh.get("mixer_address"):
        config["cluster_manager"]["clusters"].append(
            {"name": "mixer_server", "type": "strict_dns",
             "lb_type": "round_robin", "connect_timeout_ms": 1000,
             "features": "http2",
             "hosts": [{"url": f"tcp://{mesh['mixer_address']}"}]})
    if mesh.get("zipkin_address"):
        # route.go:534 buildZipkinTracing
        config["tracing"] = {"http": {"driver": {
            "type": "zipkin",
            "config": {"collector_cluster": "zipkin",
                       "collector_endpoint": "/api/v1/spans"}}}}
        config["cluster_manager"]["clusters"].append(
            {"name": "zipkin", "type": "strict_dns",
             "lb_type": "round_robin", "connect_timeout_ms": 1000,
             "hosts": [{"url": f"tcp://{mesh['zipkin_address']}"}]})
    return config
