"""Envoy v1 bootstrap/listener/cluster generation.

Reference: pilot/pkg/proxy/envoy/config.go — BuildConfig (:81,
bootstrap with RDS/admin/tracing/mixer cluster), buildListeners (:136),
sidecar in/outbound (:199,:496,:707); policy.go applyClusterPolicy
(:39: circuit breakers :179, outlier detection :152, LB :128);
mixer.go FilterMixerConfig (:82); resources.go JSON shapes.
"""
from __future__ import annotations

from typing import Any, Mapping, Sequence

from istio_tpu.pilot.model import (IstioConfigStore, Port, Service,
                                   ServiceInstance)
from istio_tpu.pilot.registry import ServiceDiscovery
from istio_tpu.pilot.routes import (_egress_rule_ports,
                                    build_ingress_route_config,
                                    build_route_config, cluster_name,
                                    egress_cluster_name,
                                    inbound_cluster_name, default_route,
                                    build_fault_filter)

DEFAULT_ADMIN_PORT = 15000
DEFAULT_DISCOVERY_REFRESH_MS = 1000


# ---------------------------------------------------------------------------
# clusters
# ---------------------------------------------------------------------------

def build_outbound_clusters(services: Sequence[Service],
                            config_store: IstioConfigStore
                            ) -> list[dict[str, Any]]:
    clusters: dict[str, dict[str, Any]] = {}
    for service in services:
        # rule scan is port-independent — hoisted out of the port loop
        label_sets: list[Mapping[str, str] | None] = [None]
        for rule in config_store.route_rules(service.hostname):
            for block in rule.spec.get("route", ()):
                labels = block.get("labels") or block.get("tags")
                if labels:
                    label_sets.append(labels)
            if rule.spec.get("mirror", {}).get("labels"):
                label_sets.append(rule.spec["mirror"]["labels"])
        policy = config_store.destination_policy(service.hostname)
        for port in service.ports:
            for labels in label_sets:
                name = cluster_name(service.hostname, port, labels)
                if name in clusters:
                    continue
                cluster: dict[str, Any] = {
                    "name": name,
                    "type": "sds",
                    "service_name": service.key(port) + (
                        "|" + ",".join(f"{k}={v}" for k, v in
                                       sorted(labels.items()))
                        if labels else ""),
                    "lb_type": "round_robin",
                    "connect_timeout_ms": 1000,
                }
                if port.protocol in ("HTTP2", "GRPC"):
                    cluster["features"] = "http2"
                _apply_cluster_policy(cluster, policy)
                clusters[name] = cluster
    return [clusters[k] for k in sorted(clusters)]


def _apply_cluster_policy(cluster: dict[str, Any],
                          policy: "Any | None") -> None:
    """policy.go:39 applyClusterPolicy."""
    if policy is None:
        return
    lb = policy.spec.get("loadBalancing", {})
    if lb.get("name"):
        cluster["lb_type"] = {"ROUND_ROBIN": "round_robin",
                              "LEAST_CONN": "least_request",
                              "RANDOM": "random"}.get(lb["name"],
                                                      "round_robin")
    cb = policy.spec.get("circuitBreaker", {}).get("simpleCb", {})
    if cb:
        thresholds: dict[str, Any] = {}
        if "maxConnections" in cb:
            thresholds["max_connections"] = int(cb["maxConnections"])
        if "httpMaxPendingRequests" in cb:
            thresholds["max_pending_requests"] = \
                int(cb["httpMaxPendingRequests"])
        if "httpMaxRequests" in cb:
            thresholds["max_requests"] = int(cb["httpMaxRequests"])
        if "httpMaxRetries" in cb:
            thresholds["max_retries"] = int(cb["httpMaxRetries"])
        cluster["circuit_breakers"] = {"default": thresholds}
        outlier: dict[str, Any] = {}
        if "httpConsecutiveErrors" in cb:
            outlier["consecutive_5xx"] = int(cb["httpConsecutiveErrors"])
        if "httpDetectionInterval" in cb:
            iv = cb["httpDetectionInterval"]
            outlier["interval_ms"] = int(float(str(iv).rstrip("s")) * 1000)
        if "sleepWindow" in cb:
            sw = cb["sleepWindow"]
            outlier["base_ejection_time_ms"] = \
                int(float(str(sw).rstrip("s")) * 1000)
        if outlier:
            cluster["outlier_detection"] = outlier


def build_egress_clusters(config_store: IstioConfigStore
                          ) -> list[dict[str, Any]]:
    """config.go:849-1026: one cluster per (egress rule, port). Exact
    hosts resolve via strict_dns with TLS upstream for https ports;
    wildcard hosts use original-destination (the sidecar already knows
    the resolved address)."""
    clusters: dict[str, dict[str, Any]] = {}
    for rule in config_store.egress_rules():
        host = str(rule.spec.get("destination", {}).get("service", ""))
        tls = bool(rule.spec.get("useEgressProxy", False))
        for pnum, proto in _egress_rule_ports(rule):
            name = egress_cluster_name(host, pnum)
            if name in clusters:
                continue
            if host.startswith("*"):
                cluster: dict[str, Any] = {
                    "name": name, "type": "original_dst",
                    "lb_type": "original_dst_lb",
                    "connect_timeout_ms": 1000}
            else:
                cluster = {"name": name, "type": "strict_dns",
                           "lb_type": "round_robin",
                           "connect_timeout_ms": 1000,
                           "hosts": [{"url": f"tcp://{host}:{pnum}"}]}
            if proto in ("https",) or tls:
                cluster["ssl_context"] = {}
            if proto in ("http2", "grpc"):
                cluster["features"] = "http2"
            clusters[name] = cluster
    return [clusters[k] for k in sorted(clusters)]


def build_jwks_clusters(config_store: IstioConfigStore
                        ) -> list[dict[str, Any]]:
    """mixer.go:301-331 buildJwksURIClustersForProxyConfig: each JWT
    issuer's jwks_uri needs an upstream cluster so the auth filter can
    fetch signing keys."""
    from urllib.parse import urlparse
    clusters: dict[str, dict[str, Any]] = {}
    for config in config_store.store.list(
            "end-user-authentication-policy-spec"):
        for jwt in config.spec.get("jwts", ()):
            uri = str(jwt.get("jwksUri", jwt.get("jwks_uri", "")) or "")
            if not uri:
                continue
            parsed = urlparse(uri)
            if not parsed.hostname:
                continue
            secure = parsed.scheme == "https"
            port = parsed.port or (443 if secure else 80)
            name = f"jwks.{parsed.hostname}|{port}"
            cluster: dict[str, Any] = {
                "name": name, "type": "strict_dns",
                "lb_type": "round_robin", "connect_timeout_ms": 1000,
                "hosts": [{"url": f"tcp://{parsed.hostname}:{port}"}]}
            if secure:
                cluster["ssl_context"] = {}
            clusters[name] = cluster
    return [clusters[k] for k in sorted(clusters)]


def build_inbound_clusters(instances: Sequence[ServiceInstance]
                           ) -> list[dict[str, Any]]:
    clusters = {}
    for inst in instances:
        name = inbound_cluster_name(inst.endpoint.port)
        clusters[name] = {
            "name": name, "type": "static", "lb_type": "round_robin",
            "connect_timeout_ms": 1000,
            "hosts": [{"url": f"tcp://127.0.0.1:{inst.endpoint.port}"}]}
    return [clusters[k] for k in sorted(clusters)]


# ---------------------------------------------------------------------------
# listeners
# ---------------------------------------------------------------------------

def _http_filters(mesh: Mapping[str, Any],
                  faults: Sequence[dict] = ()) -> list[dict]:
    filters = list(faults)
    if mesh.get("mixer_address"):
        # mixer.go:82 FilterMixerConfig
        filters.append({"type": "decoder", "name": "mixer", "config": {
            "mixer_attributes": {
                "destination.uid": mesh.get("node_uid", ""),
            },
            "forward_attributes": {
                "source.uid": mesh.get("node_uid", ""),
            },
            "quota_name": "RequestCount",
        }})
    filters.append({"type": "decoder", "name": "router", "config": {}})
    return filters


def _port_fault_filters(port_num: int, services: Sequence[Service],
                        config_store: IstioConfigStore) -> list[dict]:
    """Fault filters for route-rules with httpFault on services exposed
    on this port, scoped by the rule's match headers (fault.go:28-139
    buildFaultFilters — faults live in the filter chain, not routes)."""
    from istio_tpu.pilot.routes import build_route_match
    faults = []
    for service in services:
        if not any(p.port == port_num and p.is_http
                   for p in service.ports):
            continue
        for rule in config_store.route_rules(service.hostname):
            fault_spec = rule.spec.get("httpFault")
            if not fault_spec:
                continue
            match = build_route_match(rule.spec.get("match"))
            headers = list(match.get("headers", ()))
            filt = build_fault_filter(fault_spec, headers)
            if filt is not None:
                filt["config"]["upstream_cluster"] = cluster_name(
                    service.hostname,
                    next(p for p in service.ports
                         if p.port == port_num and p.is_http))
                faults.append(filt)
    return faults


def _listener_kind(port: Port) -> str:
    if port.is_http:
        return "http"
    if port.protocol == "REDIS":
        return "redis"   # redis_proxy replaces tcp_proxy: exclusive
    return "tcp"         # MONGO = tcp + passive sniffer


def build_outbound_listeners(services: Sequence[Service],
                             config_store: IstioConfigStore,
                             mesh: Mapping[str, Any]) -> list[dict]:
    """One HTTP listener per outbound port using RDS; TCP services get
    tcp_proxy with explicit routes (config.go:496); egress rules add
    listeners for their ports even when no in-mesh service shares them
    (config.go:849-1026 — otherwise egress traffic is blackholed)."""
    import logging
    plog = logging.getLogger("istio_tpu.pilot")
    listeners: dict[int, dict[str, Any]] = {}
    kinds: dict[int, str] = {}    # port → http|tcp|redis conflict map

    def claim(port_num: int, kind: str, who: str) -> bool:
        prev = kinds.get(port_num)
        if prev is None:
            kinds[port_num] = kind
            return True
        # redis owns its port exclusively; http vs tcp also conflict —
        # first writer wins, like the reference's conflict logging
        if prev != kind or prev == "redis":
            plog.warning("listener conflict on port %d: %s vs %s "
                         "(%s dropped)", port_num, prev, kind, who)
            return False
        return True

    def http_listener(port_num: int) -> dict[str, Any]:
        return {
            "address": f"tcp://0.0.0.0:{port_num}",
            "name": f"http_0.0.0.0_{port_num}",
            "bind_to_port": True,
            "filters": [{
                "type": "read", "name": "http_connection_manager",
                "config": {
                    "codec_type": "auto",
                    "stat_prefix": "http",
                    "rds": {"cluster": "rds",
                            "route_config_name": str(port_num),
                            "refresh_delay_ms":
                                DEFAULT_DISCOVERY_REFRESH_MS},
                    "filters": _http_filters(
                        mesh, _port_fault_filters(port_num, services,
                                                  config_store)),
                }}],
        }

    def append_tcp_route(entry: dict[str, Any], route: dict) -> None:
        tcp = next(f for f in entry["filters"]
                   if f["name"] == "tcp_proxy")
        tcp["config"]["route_config"]["routes"].append(route)

    for service in services:
        for port in service.ports:
            kind = _listener_kind(port)
            if not claim(port.port, kind, service.hostname):
                continue
            if kind == "http":
                listeners.setdefault(port.port, http_listener(port.port))
            elif kind == "redis":
                listeners[port.port] = {
                    "address": f"tcp://0.0.0.0:{port.port}",
                    "name": f"redis_0.0.0.0_{port.port}",
                    "bind_to_port": True,
                    "filters": [{
                        "type": "read", "name": "redis_proxy",
                        "config": {"cluster_name":
                                   cluster_name(service.hostname, port),
                                   "stat_prefix": "redis",
                                   "conn_pool": {"op_timeout_ms":
                                                 30_000}}}]}
            else:
                tcp_route = {"cluster": cluster_name(service.hostname,
                                                     port)}
                if service.address and service.address != "0.0.0.0":
                    tcp_route["destination_ip_list"] = \
                        [f"{service.address}/32"]
                entry = listeners.setdefault(port.port, {
                    "address": f"tcp://0.0.0.0:{port.port}",
                    "name": f"tcp_0.0.0.0_{port.port}",
                    "bind_to_port": True,
                    "filters": [{"type": "read", "name": "tcp_proxy",
                                 "config": {"stat_prefix": "tcp",
                                            "route_config":
                                                {"routes": []}}}]})
                append_tcp_route(entry, tcp_route)
                if port.protocol == "MONGO" and not any(
                        f["name"] == "mongo_proxy"
                        for f in entry["filters"]):
                    # passive sniffer ahead of tcp_proxy
                    # (resources.go:516-613)
                    entry["filters"].insert(0, {
                        "type": "both", "name": "mongo_proxy",
                        "config": {"stat_prefix": "mongo"}})

    # egress ports: http rides RDS (the route table carries the egress
    # virtual hosts); https/tcp egress forwards raw bytes to the
    # external cluster
    for rule in config_store.egress_rules():
        host = str(rule.spec.get("destination", {}).get("service", ""))
        for pnum, proto in _egress_rule_ports(rule):
            if proto in ("http", "http2", "grpc"):
                if claim(pnum, "http", f"egress {host}"):
                    listeners.setdefault(pnum, http_listener(pnum))
            else:
                if not claim(pnum, "tcp", f"egress {host}"):
                    continue
                entry = listeners.setdefault(pnum, {
                    "address": f"tcp://0.0.0.0:{pnum}",
                    "name": f"tcp_0.0.0.0_{pnum}",
                    "bind_to_port": True,
                    "filters": [{"type": "read", "name": "tcp_proxy",
                                 "config": {"stat_prefix": "tcp",
                                            "route_config":
                                                {"routes": []}}}]})
                append_tcp_route(entry,
                                 {"cluster": egress_cluster_name(host,
                                                                 pnum)})
    return [listeners[k] for k in sorted(listeners)]


def build_inbound_listeners(instances: Sequence[ServiceInstance],
                            mesh: Mapping[str, Any]) -> list[dict]:
    """Per-endpoint-port inbound listeners (config.go:707)."""
    listeners = {}
    for inst in instances:
        port = inst.endpoint.port
        if port in listeners:
            continue
        sp = inst.endpoint.service_port
        if sp.is_http:
            vhost = {"name": "inbound", "domains": ["*"], "routes": [
                {"prefix": "/", "cluster": inbound_cluster_name(port),
                 "timeout_ms": 0}]}
            listeners[port] = {
                "address": f"tcp://{inst.endpoint.address}:{port}",
                "name": f"http_{inst.endpoint.address}_{port}",
                "bind_to_port": True,
                "filters": [{
                    "type": "read", "name": "http_connection_manager",
                    "config": {"codec_type": "auto",
                               "stat_prefix": "http",
                               "route_config": {"virtual_hosts": [vhost]},
                               "filters": _http_filters(mesh)}}],
            }
        else:
            listeners[port] = {
                "address": f"tcp://{inst.endpoint.address}:{port}",
                "name": f"tcp_{inst.endpoint.address}_{port}",
                "bind_to_port": True,
                "filters": [{"type": "read", "name": "tcp_proxy",
                             "config": {"stat_prefix": "tcp",
                                        "route_config": {"routes": [
                                            {"cluster":
                                             inbound_cluster_name(port)}]}}}]}
    return [listeners[k] for k in sorted(listeners)]


def build_ingress_listeners(config_store: IstioConfigStore, registry,
                            mesh: Mapping[str, Any],
                            tls_context: Mapping[str, Any] | None = None
                            ) -> list[dict]:
    """Ingress proxy listeners on 80/443 (ingress.go buildIngress
    Listeners): the route table comes from ingress-rule configs."""
    route_config = build_ingress_route_config(config_store, registry)
    out = []
    for port, secure in ((80, False), (443, True)):
        if secure and tls_context is None:
            continue
        listener = {
            "address": f"tcp://0.0.0.0:{port}",
            "name": f"ingress_{port}",
            "bind_to_port": True,
            "filters": [{
                "type": "read", "name": "http_connection_manager",
                "config": {"codec_type": "auto",
                           "stat_prefix": "ingress",
                           "route_config": route_config,
                           "filters": _http_filters(mesh)}}],
        }
        if secure:
            ctx = dict(tls_context)
            # always-serialized in resources.go SSLContext — terminating
            # TLS at ingress does not demand client certs by default
            ctx.setdefault("require_client_certificate", False)
            listener["ssl_context"] = ctx
        out.append(listener)
    return out


# ---------------------------------------------------------------------------
# bootstrap (config.go:81 BuildConfig)
# ---------------------------------------------------------------------------

def build_bootstrap(mesh: Mapping[str, Any]) -> dict[str, Any]:
    discovery = mesh.get("discovery_address", "127.0.0.1:8080")
    config: dict[str, Any] = {
        "admin": {"access_log_path": "/dev/stdout",
                  "address": f"tcp://127.0.0.1:"
                             f"{mesh.get('admin_port', DEFAULT_ADMIN_PORT)}"},
        "listeners": [],
        "lds": {"cluster": "lds", "refresh_delay_ms":
                DEFAULT_DISCOVERY_REFRESH_MS},
        "cluster_manager": {
            "clusters": [
                {"name": "rds", "type": "strict_dns",
                 "lb_type": "round_robin", "connect_timeout_ms": 1000,
                 "hosts": [{"url": f"tcp://{discovery}"}]},
                {"name": "lds", "type": "strict_dns",
                 "lb_type": "round_robin", "connect_timeout_ms": 1000,
                 "hosts": [{"url": f"tcp://{discovery}"}]},
            ],
            "sds": {"cluster": {"name": "sds", "type": "strict_dns",
                                "lb_type": "round_robin",
                                "connect_timeout_ms": 1000,
                                "hosts": [{"url": f"tcp://{discovery}"}]},
                    "refresh_delay_ms": DEFAULT_DISCOVERY_REFRESH_MS},
        },
    }
    if mesh.get("mixer_address"):
        config["cluster_manager"]["clusters"].append(
            {"name": "mixer_server", "type": "strict_dns",
             "lb_type": "round_robin", "connect_timeout_ms": 1000,
             "features": "http2",
             "hosts": [{"url": f"tcp://{mesh['mixer_address']}"}]})
    if mesh.get("zipkin_address"):
        # route.go:534 buildZipkinTracing
        config["tracing"] = {"http": {"driver": {
            "type": "zipkin",
            "config": {"collector_cluster": "zipkin",
                       "collector_endpoint": "/api/v1/spans"}}}}
        config["cluster_manager"]["clusters"].append(
            {"name": "zipkin", "type": "strict_dns",
             "lb_type": "round_robin", "connect_timeout_ms": 1000,
             "hosts": [{"url": f"tcp://{mesh['zipkin_address']}"}]})
    return config
