"""Shared route automaton: route-rule matches → the policy ruleset
tensors (BASELINE.json: "Pilot's route compiler emits the same NFA for
VirtualService/RouteRule header+URI match so L7 routing and policy
share one compiled automaton").

Every (service, route-rule) pair lowers its match block to ONE
predicate in the SAME expression language the policy engine compiles
(exact → EQ, prefix → startsWith, regex → matches, header presence →
`|` fallback probe), then the whole mesh's route table becomes a
RuleSetProgram. Batched route selection = one device step:

    matched [B, R]  →  choice[b] = highest-precedence matched rule
                       (argmax over precedence-ordered weights)

`select()` returns per-request route indices; index n_rules means "no
rule matched → default route". The host-side `select_host()` applies
identical semantics sequentially and is the conformance oracle.
"""
from __future__ import annotations

import dataclasses
import functools
import re
from typing import Any, Mapping, Sequence

import numpy as np

from istio_tpu.attribute.bag import Bag, bag_from_mapping
from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import Tensorizer
from istio_tpu.compiler.ruleset import Rule, compile_ruleset
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.pilot.model import Config, Service

V = ValueType

# vocabulary of the route-match automaton
ROUTE_MANIFEST: dict[str, ValueType] = {
    "destination.service": V.STRING,
    "request.path": V.STRING,
    "request.method": V.STRING,
    "request.scheme": V.STRING,
    "request.host": V.STRING,
    "request.headers": V.STRING_MAP,
    "source.service": V.STRING,
}
ROUTE_FINDER = AttributeDescriptorFinder(ROUTE_MANIFEST)

_ABSENT = "\x00absent\x00"


def _quote(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _header_ref(name: str) -> str:
    """Pseudo-headers map to first-class attributes (header.go:27
    translates :path/:method the same way)."""
    specials = {"uri": "request.path", ":path": "request.path",
                ":method": "request.method", "method": "request.method",
                ":authority": "request.host", "authority": "request.host",
                "scheme": "request.scheme", ":scheme": "request.scheme"}
    if name in specials:
        return specials[name]
    return f"request.headers[{_quote(name)}]"


def match_to_predicate(hostname: str, match: Mapping[str, Any] | None,
                       source: str | None = None) -> str:
    """Route-rule match block → one boolean expression."""
    parts = [f"destination.service == {_quote(hostname)}"]
    if source:
        parts.append(f"source.service == {_quote(source)}")
    headers = {}
    if match:
        headers = match.get("request", {}).get("headers", {}) \
            if "request" in match else match.get("headers", {}) or {}
    for name, cond in sorted(headers.items()):
        ref = _header_ref(name)
        is_map = ref.startswith("request.headers[")
        probe = f"({ref} | {_quote(_ABSENT)})" if is_map else ref
        if not cond or cond == {"presence": True}:
            parts.append(f"{probe} != {_quote(_ABSENT)}")
        elif "exact" in cond:
            parts.append(f"{probe} == {_quote(cond['exact'])}")
        elif "prefix" in cond:
            parts.append(f"{probe}.startsWith({_quote(cond['prefix'])})")
        elif "regex" in cond:
            # Envoy route regexes are FULL match; `matches` is an
            # unanchored search (Go regexp.MatchString parity), so
            # _anchor forces full-match semantics — wrapping `^(pat)$`
            # for unanchored/alternation patterns, passing
            # already-anchored pipe-free patterns through bare so they
            # keep lowering to the device DFA (which rejects nested
            # inner anchors). NOTE: the RECEIVER of .matches() is the
            # PATTERN (see testing/corpus.py).
            parts.append(f"{_quote(_anchor(cond['regex']))}"
                         f".matches({probe})")
    return " && ".join(parts)


def _anchor(pattern: str) -> str:
    """Force full-match semantics. A pattern that is already anchored
    on both ends AND safe to use bare (no top-level alternation that
    the anchors wouldn't distribute over) stays as-is — wrapping it
    would nest anchors inside the group, which the device DFA compiler
    rejects (regex_dfa: no inner anchors) and needlessly sends the
    rule to the host oracle."""
    if (pattern.startswith("^") and pattern.endswith("$")
            and not pattern.endswith("\\$") and "|" not in pattern):
        return pattern
    return f"^({pattern})$"


def _winner(matched, weight, default):
    """Shared selection tail (THE precedence rule — keep single-sourced
    across the dense/compact device kernels): highest weight among
    matched rows wins; nothing matched → default."""
    import jax.numpy as jnp
    scores = matched * weight[None, :]
    best = jnp.argmax(scores, axis=1)
    hit = jnp.max(scores, axis=1) > 0
    return jnp.where(hit, best, default)


@dataclasses.dataclass
class RouteEntry:
    rule: Config
    service: Service
    predicate: str
    precedence: int


class RouteTable:
    """The whole mesh's route rules as one device program."""

    def __init__(self, services: Sequence[Service],
                 rules_by_host: Mapping[str, Sequence[Config]],
                 max_str_len: int = 256):
        self.entries: list[RouteEntry] = []
        host_of = {s.hostname: s for s in services}
        for hostname in sorted(rules_by_host):
            service = host_of.get(hostname)
            if service is None:
                continue
            for rule in rules_by_host[hostname]:
                src = rule.spec.get("match", {}).get("source")
                pred = match_to_predicate(hostname,
                                          rule.spec.get("match"), src)
                self.entries.append(RouteEntry(
                    rule=rule, service=service, predicate=pred,
                    precedence=int(rule.spec.get("precedence", 0))))
        rules = [Rule(name=f"route{i}", match=e.predicate)
                 for i, e in enumerate(self.entries)]
        self.program = compile_ruleset(rules, ROUTE_FINDER,
                                       max_str_len=max_str_len)
        self.tensorizer = Tensorizer(self.program.layout,
                                     self.program.interner)
        # selection weights: precedence first, then config order
        # (route_rules sorting, route.go) — higher weight wins
        n = len(self.entries)
        order = sorted(range(n),
                       key=lambda i: (-self.entries[i].precedence, i))
        self._weight = np.zeros(max(n, 1), np.int64)
        for rank, idx in enumerate(order):
            self._weight[idx] = n - rank          # best rank → largest
        self.default_index = n

    # -- device path --

    def select(self, requests: Sequence[Mapping[str, Any] | Bag]
               ) -> np.ndarray:
        """One device step: per-request winning route index
        (default_index when nothing matches)."""
        bags = [r if isinstance(r, Bag) else bag_from_mapping(dict(r))
                for r in requests]
        if not self.entries:
            return np.full(len(bags), self.default_index, np.int64)
        batch = self.tensorizer.tensorize(bags)
        if not self.program.host_fallback:
            # argmax on device: pulling the [B, R] matched plane costs
            # R/64 times the bytes of the [B] winner indices (megabytes
            # per batch at 10k routes behind a high-RTT transport)
            return np.asarray(self._select_on_device(
                self.program.params, batch), dtype=np.int64)
        matched, _, _ = self.program(batch)
        matched = np.array(matched)
        for ridx in self.program.host_fallback:
            for b, bag in enumerate(bags):
                matched[b, ridx] = self.program.host_eval(ridx, bag)[0]
        scores = matched * self._weight[None, :]
        best = scores.argmax(axis=1)
        hit = scores.max(axis=1) > 0
        return np.where(hit, best, self.default_index)

    @functools.cached_property
    def native(self):
        """C++ wire→tensor decoder for the route layout (None when the
        native toolchain is unavailable)."""
        try:
            from istio_tpu.native.tensorizer import NativeTensorizer
            return NativeTensorizer(self.program.layout,
                                    self.program.interner)
        except Exception as exc:
            # select_wire silently serving the python fallback forever
            # would read as an unexplained throughput collapse
            import logging
            logging.getLogger("istio_tpu.pilot.route_nfa").warning(
                "native tensorizer unavailable, route wire path "
                "serving with the python decoder: %s", exc)
            return None

    def select_wire(self, wires: Sequence[bytes], block: bool = True):
        """Winning route per wire-encoded CompressedAttributes record —
        the sidecar-facing fast path: C++ decode + ONE device program
        (match + precedence argmax), no per-request python.

        block=False returns the un-synchronized device array so callers
        can pipeline batches (XLA queues the steps; one sync drains
        them all — the throughput shape behind a high-RTT transport).
        Falls back to the python path when the native shim is absent or
        host-fallback rules exist (those need per-row oracle evals)."""
        if not self.entries:
            return np.full(len(wires), self.default_index, np.int64)
        if self.native is None or self.program.host_fallback:
            from istio_tpu.api.wire import LazyWireBag
            return self.select([LazyWireBag(w) for w in wires])
        batch = self.native.tensorize_wire(wires)
        # COMPACT byte-plane transfer: str_bytes is [B, nbyte, L] but
        # real subjects (paths, hosts) are ~20 bytes — shipping the
        # dense plane is ~10× the payload and the host↔device link is
        # the route tier's bottleneck (profiled ~7 MB/s behind the
        # axon tunnel). Ship the ragged bytes + offsets and expand
        # with one device gather instead.
        sb = np.asarray(batch.str_bytes)
        lens = np.asarray(batch.str_lens)
        L = sb.shape[2]
        mask = np.arange(L)[None, None, :] < lens[:, :, None]
        flat = sb[mask]
        total = flat.shape[0]
        cap = max(1024, 1 << int(total).bit_length())  # stable shapes
        if cap > sb.size:     # pathological: dense is smaller
            out = self._select_on_device(self.program.params, batch)
            return np.asarray(out).astype(np.int64) if block else out
        flat_p = np.zeros(cap, np.uint8)
        flat_p[:total] = flat
        # presence bitpacked, starts recomputed on device from lens,
        # lens as int16 — every byte shipped is wall-clock here
        pres_p = np.packbits(np.asarray(batch.present), axis=1,
                             bitorder="little")
        out = self._select_on_device_compact(
            self.program.params, batch.ids, pres_p,
            batch.map_present, flat_p, lens.astype(np.int16))
        return np.asarray(out).astype(np.int64) if block else out

    @functools.cached_property
    def _select_on_device(self):
        import jax
        import jax.numpy as jnp
        weight = jnp.asarray(self._weight)
        default = self.default_index
        raw = self.program.fn          # fn(params, batch)

        def run(params, batch):
            matched, _, _ = raw(params, batch)
            return _winner(matched, weight, default)

        return jax.jit(run)

    @functools.cached_property
    def _select_on_device_compact(self):
        """select with the byte plane shipped RAGGED (flat bytes +
        per-slot offsets) and re-densified by one device gather — the
        H2D payload shrinks ~10× vs the dense [B, nbyte, L] plane (the
        transfer, not the step, bounds route throughput behind a
        high-RTT/low-bandwidth device link)."""
        import jax
        import jax.numpy as jnp

        from istio_tpu.compiler.layout import AttributeBatch

        weight = jnp.asarray(self._weight)
        default = self.default_index
        raw = self.program.fn
        L = self.program.layout.max_str_len
        n_cols = self.program.layout.n_columns

        def run(params, ids, pres_packed, map_present, flat, lens16):
            lens = lens16.astype(jnp.int32)
            b, nbyte = lens.shape
            flat_lens = lens.reshape(-1)
            starts = (jnp.cumsum(flat_lens) - flat_lens).reshape(
                b, nbyte)
            idx = starts[:, :, None] + jnp.arange(L)[None, None, :]
            sb = flat[jnp.clip(idx, 0, flat.shape[0] - 1)]
            sb = jnp.where(
                jnp.arange(L)[None, None, :] < lens[:, :, None], sb, 0)
            bits = ((pres_packed[:, :, None] >>
                     jnp.arange(8, dtype=jnp.uint8)) & 1) > 0
            present = bits.reshape(b, -1)[:, :n_cols]
            batch = AttributeBatch(
                ids=ids, present=present, map_present=map_present,
                str_bytes=sb, str_lens=lens,
                hash_ids=jnp.zeros_like(ids))   # routes never hash
            matched, _, _ = raw(params, batch)
            return _winner(matched, weight, default)

        return jax.jit(run)

    # -- host oracle --

    def select_host(self, request: Mapping[str, Any]) -> int:
        best, best_w = self.default_index, 0
        for i, entry in enumerate(self.entries):
            if self._matches_host(entry, request) and \
                    self._weight[i] > best_w:
                best, best_w = i, int(self._weight[i])
        return best

    @staticmethod
    def _matches_host(entry: RouteEntry,
                      request: Mapping[str, Any]) -> bool:
        if request.get("destination.service") != entry.service.hostname:
            return False
        spec = entry.rule.spec
        src = spec.get("match", {}).get("source")
        if src and request.get("source.service") != src:
            return False
        headers = {}
        if spec.get("match"):
            m = spec["match"]
            headers = m.get("request", {}).get("headers", {}) \
                if "request" in m else m.get("headers", {}) or {}
        for name, cond in headers.items():
            ref = _header_ref(name)
            if ref.startswith("request.headers["):
                value = (request.get("request.headers") or {}).get(name)
            else:
                value = request.get(ref)
            if not cond or cond == {"presence": True}:
                if value is None:
                    return False
            elif "exact" in cond:
                if value != cond["exact"]:
                    return False
            elif "prefix" in cond:
                if value is None or not str(value).startswith(
                        cond["prefix"]):
                    return False
            elif "regex" in cond:
                # mirror the device predicate EXACTLY: unanchored
                # search of the ^(pat)$ wrapper (same engine semantics
                # incl. the $-before-trailing-newline subtlety)
                if value is None or re.search(_anchor(cond["regex"]),
                                              str(value)) is None:
                    return False
        return True

    def route_for(self, index: int) -> RouteEntry | None:
        if 0 <= index < len(self.entries):
            return self.entries[index]
        return None


class RouteScopeProgram:
    """Source-admission half of the mesh's route-rule match blocks as
    ONE compiled program — the per-node part of config generation.

    Per-sidecar RDS generation filters each destination's route rules
    by the polling node's source identity (`match.source`,
    route.go buildVirtualHost / model._match_source). The reference
    re-evaluates that filter per node per rule on the host; here every
    source-constrained (host, rule) pair lowers its constraint to one
    `source.service == "..."` predicate in the SAME expression
    language / ruleset tensors the route NFA and policy engine compile
    (BASELINE's shared-automaton doctrine), so admission for ALL
    pending node groups is one batched device step:

        admits [B, C]  →  row b: does node-group b's source satisfy
                          constrained pair c?

    Unconstrained rules admit every source by construction and never
    enter the program; a node with no source identity admits
    everything (the `_match_source` None-source semantics) and skips
    the device plane entirely. Header/URI match halves are NOT
    evaluated here — they become envoy match JSON in the generated
    config (the data plane evaluates them per request; RouteTable
    evaluates them per request on-device for the policy tie-in).

    `digest` content-addresses the constraint set (host, rule index,
    source) so snapshots carry the compiled program across
    generations whenever no source constraint moved (PR 10 doctrine).
    Compilation is lazy — building a snapshot whose digest matches the
    previous generation never compiles.
    """

    def __init__(self, rules_by_host: Mapping[str, Sequence[Any]]):
        from istio_tpu.compiler.cache import stable_digest

        self._constrained: list[tuple[str, int]] = []
        self._sources: list[str] = []
        for host in sorted(rules_by_host):
            for i, rule in enumerate(rules_by_host[host]):
                src = (rule.spec.get("match") or {}).get("source")
                if src:
                    self._constrained.append((host, i))
                    self._sources.append(str(src))
        self._slot = {pair: j for j, pair in
                      enumerate(self._constrained)}
        self.n_constrained = len(self._constrained)
        self.digest = stable_digest(
            [(h, i, s) for (h, i), s in zip(self._constrained,
                                            self._sources)])

    @functools.cached_property
    def _program(self):
        """Lazy compile: (program, tensorizer) over the constraint
        predicates; None when nothing in the mesh is
        source-constrained."""
        if not self._constrained:
            return None
        rules = [Rule(name=f"scope{j}",
                      match=f"source.service == {_quote(src)}")
                 for j, src in enumerate(self._sources)]
        program = compile_ruleset(rules, ROUTE_FINDER, max_str_len=256)
        return program, Tensorizer(program.layout, program.interner)

    def admit_rows(self, sources: Sequence[str | None]) -> list:
        """One device step for a batch of node-group source
        identities → per-row admission maps. Row value None means
        'admit everything' (no identity, or no constrained rules).
        The batch pads to a power of two so churn storms reuse a few
        compiled shapes instead of one per pending-set size."""
        if self._program is None or not sources:
            return [None] * len(sources)
        program, tensorizer = self._program
        n = len(sources)
        cap = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
        padded = [s or "" for s in sources] + [""] * (cap - n)
        bags = [bag_from_mapping({"source.service": s}) for s in padded]
        batch = tensorizer.tensorize(bags)
        matched, _, _ = program(batch)
        m = np.asarray(matched) > 0    # hotpath: sync-ok — THE designated admission-plane pull (one per batched generation)
        for ridx in program.host_fallback:   # defensive: EQ never falls back
            for b in range(n):
                m[b, ridx] = program.host_eval(ridx, bags[b])[0]
        rows = []
        for b, s in enumerate(sources):
            if s is None:
                rows.append(None)
            else:
                rows.append({pair: bool(m[b, j]) for j, pair in
                             enumerate(self._constrained)})
        return rows

    def admits(self, row, host: str, rule_index: int) -> bool:
        """Does the admission row (one admit_rows element) include
        `rules_by_host[host][rule_index]`? Unconstrained rules and
        identity-less rows always admit."""
        if row is None:
            return True
        pair = (host, rule_index)
        if pair not in self._slot:
            return True
        return row[pair]
