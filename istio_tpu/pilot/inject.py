"""Sidecar injection (reference: pilot/pkg/kube/inject/inject.go):
`inject_required` policy (:146 — opt-in/opt-out annotations over a
default policy, host-network pods excluded), `injection_data` (:205 —
render init + proxy containers from mesh params), and file mode
`into_resource_file` (:243 — YAML in, YAML out; what
`istioctl kube-inject` calls).
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Any, Mapping

import yaml

ANNOTATION_POLICY = "sidecar.istio.io/inject"
ISTIO_SIDECAR_NAME = "istio-proxy"
ISTIO_INIT_NAME = "istio-init"


@dataclasses.dataclass
class InjectParams:
    """inject.go:119 Params."""
    init_image: str = "istio_tpu/proxy_init:latest"
    proxy_image: str = "istio_tpu/proxy:latest"
    discovery_address: str = "istio-pilot:8080"
    mixer_address: str = "istio-mixer:9091"
    include_ip_ranges: str = "*"
    verbosity: int = 2
    sidecar_proxy_uid: int = 1337
    policy: str = "enabled"        # enabled = inject unless opted out


def inject_required(params: InjectParams,
                    pod_spec: Mapping[str, Any],
                    metadata: Mapping[str, Any]) -> bool:
    """inject.go:146 injectRequired."""
    if pod_spec.get("hostNetwork"):
        return False
    annotations = (metadata.get("annotations") or {})
    value = str(annotations.get(ANNOTATION_POLICY, "")).lower()
    if value in ("true", "yes", "y", "on", "enabled"):
        return True
    if value in ("false", "no", "n", "off", "disabled"):
        return False
    return params.policy == "enabled"


def injection_data(params: InjectParams,
                   metadata: Mapping[str, Any],
                   pod_spec: Mapping[str, Any] | None = None
                   ) -> dict[str, Any]:
    """inject.go:205: the containers/volumes patch. The cert secret is
    keyed by the POD SPEC's serviceAccountName (mesh.go:136 uses
    Spec.ServiceAccountName), matching SecretController.secret_name."""
    sa = (pod_spec or {}).get("serviceAccountName") or \
        (pod_spec or {}).get("serviceAccount") or "default"
    ns = metadata.get("namespace", "default")
    proxy_args = [
        "proxy", "sidecar",
        "--discoveryAddress", params.discovery_address,
        "--mixerAddress", params.mixer_address,
        "-v", str(params.verbosity),
    ]
    return {
        "initContainers": [{
            "name": ISTIO_INIT_NAME,
            "image": params.init_image,
            "args": ["-p", "15001", "-u", str(params.sidecar_proxy_uid),
                     "-i", params.include_ip_ranges],
            "securityContext": {"capabilities": {"add": ["NET_ADMIN"]}},
        }],
        "containers": [{
            "name": ISTIO_SIDECAR_NAME,
            "image": params.proxy_image,
            "args": proxy_args,
            "env": [
                {"name": "POD_NAME", "valueFrom": {"fieldRef": {
                    "fieldPath": "metadata.name"}}},
                {"name": "POD_NAMESPACE", "valueFrom": {"fieldRef": {
                    "fieldPath": "metadata.namespace"}}},
                {"name": "INSTANCE_IP", "valueFrom": {"fieldRef": {
                    "fieldPath": "status.podIP"}}},
            ],
            "securityContext": {
                "runAsUser": params.sidecar_proxy_uid},
            "volumeMounts": [{"name": "istio-certs",
                              "mountPath": "/etc/certs",
                              "readOnly": True}],
        }],
        "volumes": [{"name": "istio-certs", "secret": {
            "secretName": f"istio.{sa}.{ns}"}}],
    }


def inject_pod(params: InjectParams, pod: Mapping[str, Any]
               ) -> dict[str, Any]:
    """Mutate one pod-shaped dict (webhook.go patch application)."""
    out = copy.deepcopy(dict(pod))
    metadata = out.setdefault("metadata", {})
    spec = out.setdefault("spec", {})
    if not inject_required(params, spec, metadata):
        return out
    if any(c.get("name") == ISTIO_SIDECAR_NAME
           for c in spec.get("containers", ())):
        return out   # already injected
    data = injection_data(params, metadata, spec)
    spec.setdefault("initContainers", []).extend(data["initContainers"])
    spec.setdefault("containers", []).extend(data["containers"])
    spec.setdefault("volumes", []).extend(data["volumes"])
    annotations = metadata.setdefault("annotations", {})
    annotations["sidecar.istio.io/status"] = "injected"
    return out


def _pod_template(resource: Mapping[str, Any]) -> Any:
    kind = resource.get("kind", "")
    if kind == "Pod":
        return resource
    if kind in ("Deployment", "ReplicaSet", "StatefulSet", "DaemonSet",
                "Job", "ReplicationController"):
        return resource.get("spec", {}).get("template")
    return None


def into_resource_file(params: InjectParams, in_yaml: str) -> str:
    """inject.go:243 IntoResourceFile: inject every pod template in a
    multi-doc YAML stream."""
    docs = []
    for doc in yaml.safe_load_all(in_yaml):
        if isinstance(doc, Mapping):
            doc = copy.deepcopy(dict(doc))
            tmpl = _pod_template(doc)
            if tmpl is not None:
                injected = inject_pod(params, tmpl)
                if doc.get("kind") == "Pod":
                    doc = injected
                else:
                    doc["spec"]["template"] = injected
        docs.append(doc)
    return yaml.safe_dump_all(docs, sort_keys=False)
