"""Mesh-wide configuration: defaults, YAML overlay, validation, watch.

Reference: pilot/pkg/model/context.go DefaultMeshConfig (:163) /
DefaultProxyConfig (:143), ApplyMeshConfigDefaults (:183), the
bootstrap initMesh chain (pilot/pkg/bootstrap/server.go:245 — file
overrides defaults, CLI flags override both), and
ValidateMeshConfig / ValidateProxyConfig
(pilot/pkg/model/validation.go). Config here is a plain dict with
snake_case keys (the shape envoy_config.py / discovery.py consume);
the value semantics and defaults mirror the reference's protos.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Mapping

INGRESS_MODES = ("OFF", "DEFAULT", "STRICT")
AUTH_POLICIES = ("NONE", "MUTUAL_TLS")


def default_proxy_config() -> dict[str, Any]:
    """model.DefaultProxyConfig (context.go:143)."""
    return {
        "config_path": "/etc/istio/proxy",
        "binary_path": "/usr/local/bin/envoy",
        "service_cluster": "istio-proxy",
        "availability_zone": "",
        "drain_duration_s": 2.0,
        "parent_shutdown_duration_s": 3.0,
        "discovery_address": "istio-pilot:15003",
        "discovery_refresh_delay_s": 1.0,
        "zipkin_address": "",
        "connect_timeout_s": 1.0,
        "statsd_udp_address": "",
        "proxy_admin_port": 15000,
        "control_plane_auth_policy": "NONE",
        "custom_config_file": "",
    }


def default_mesh_config() -> dict[str, Any]:
    """model.DefaultMeshConfig (context.go:163)."""
    return {
        "egress_proxy_address": "",
        "mixer_address": "",
        "disable_policy_checks": False,
        "proxy_listen_port": 15001,
        "connect_timeout_s": 1.0,
        "ingress_class": "istio",
        "ingress_controller_mode": "STRICT",
        "ingress_service": "istio-ingress",
        "auth_policy": "NONE",
        "rds_refresh_delay_s": 1.0,
        "enable_tracing": True,
        "access_log_file": "/dev/stdout",
        "zipkin_address": "",
        "default_config": default_proxy_config(),
    }


class MeshConfigError(ValueError):
    pass


def validate_mesh_config(mesh: Mapping[str, Any]) -> list[str]:
    """ValidateMeshConfig's rejection set (validation.go): ports in
    range, positive durations, known enum values."""
    errs: list[str] = []

    def port(key: str) -> None:
        v = mesh.get(key)
        if not isinstance(v, int) or not 0 < v <= 65535:
            errs.append(f"{key}: invalid port {v!r}")

    def duration(cfg: Mapping[str, Any], key: str, lo: float = 0.0) -> None:
        v = cfg.get(key)
        if not isinstance(v, (int, float)) or v <= lo:
            errs.append(f"{key}: invalid duration {v!r}")

    port("proxy_listen_port")
    duration(mesh, "connect_timeout_s")
    duration(mesh, "rds_refresh_delay_s")
    if mesh.get("ingress_controller_mode") not in INGRESS_MODES:
        errs.append(f"ingress_controller_mode: "
                    f"{mesh.get('ingress_controller_mode')!r} not in "
                    f"{INGRESS_MODES}")
    if mesh.get("auth_policy") not in AUTH_POLICIES:
        errs.append(f"auth_policy: {mesh.get('auth_policy')!r} not in "
                    f"{AUTH_POLICIES}")
    proxy = mesh.get("default_config")
    if not isinstance(proxy, Mapping):
        errs.append("default_config: required")
    else:
        if not isinstance(proxy.get("proxy_admin_port"), int) or \
                not 0 < proxy["proxy_admin_port"] <= 65535:
            errs.append(f"default_config.proxy_admin_port: invalid port "
                        f"{proxy.get('proxy_admin_port')!r}")
        duration(proxy, "drain_duration_s")
        duration(proxy, "parent_shutdown_duration_s")
        duration(proxy, "discovery_refresh_delay_s")
        duration(proxy, "connect_timeout_s")
        if proxy.get("control_plane_auth_policy") not in AUTH_POLICIES:
            errs.append("default_config.control_plane_auth_policy: "
                        f"{proxy.get('control_plane_auth_policy')!r}")
        for key in ("config_path", "binary_path", "service_cluster"):
            if not proxy.get(key):
                errs.append(f"default_config.{key}: required")
    return errs


def apply_mesh_config_defaults(text: str) -> dict[str, Any]:
    """ApplyMeshConfigDefaults (context.go:183): defaults overlaid with
    the YAML document; unknown keys rejected (jsonpb strict-decode
    posture); the merged result is validated."""
    import yaml

    try:
        overlay = yaml.safe_load(text) or {}
    except yaml.YAMLError as exc:
        raise MeshConfigError(f"invalid mesh config YAML: {exc}") from exc
    if not isinstance(overlay, Mapping):
        raise MeshConfigError("mesh config must be a YAML mapping")
    mesh = default_mesh_config()
    for key, value in overlay.items():
        if key == "default_config":
            if not isinstance(value, Mapping):
                raise MeshConfigError("default_config must be a mapping")
            proxy = mesh["default_config"]
            for pk, pv in value.items():
                if pk not in proxy:
                    raise MeshConfigError(
                        f"unknown proxy config field {pk!r}")
                proxy[pk] = pv
        elif key not in mesh:
            raise MeshConfigError(f"unknown mesh config field {key!r}")
        else:
            mesh[key] = value
    errs = validate_mesh_config(mesh)
    if errs:
        raise MeshConfigError("; ".join(errs))
    return mesh


def read_mesh_config(path: str) -> dict[str, Any]:
    """cmd.ReadMeshConfig: file → defaults-applied validated config."""
    with open(path, encoding="utf-8") as f:
        return apply_mesh_config_defaults(f.read())


def init_mesh(config_file: str = "",
              overrides: Mapping[str, Any] | None = None,
              on_warn: Callable[[str], None] | None = None
              ) -> dict[str, Any]:
    """The bootstrap initMesh chain (server.go:245): file if given and
    readable (falling back to defaults with a warning, like the
    reference), then explicit per-flag overrides."""
    mesh: dict[str, Any] | None = None
    if config_file:
        try:
            mesh = read_mesh_config(config_file)
        except (OSError, MeshConfigError) as exc:
            if on_warn is not None:
                on_warn(f"failed to read mesh configuration, using "
                        f"default: {exc}")
    if mesh is None:
        mesh = default_mesh_config()
    for key, value in (overrides or {}).items():
        if value in ("", None):
            continue
        if key not in mesh:
            raise MeshConfigError(f"unknown mesh override {key!r}")
        mesh[key] = value
    return mesh


class MeshWatcher:
    """Polling mesh-config reload: on a content change the callback
    receives the new validated config (bad edits are reported and the
    old config stays live — a mesh must not go down on a typo)."""

    def __init__(self, path: str,
                 on_change: Callable[[dict[str, Any]], None],
                 poll_s: float = 1.0,
                 on_error: Callable[[str], None] | None = None):
        self.path = path
        self.on_change = on_change
        self.on_error = on_error
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._last: bytes | None = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mesh-watcher")

    def start(self) -> None:
        try:
            with open(self.path, "rb") as f:
                self._last = f.read()
        except OSError:
            self._last = None
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                with open(self.path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            if data == self._last:
                continue
            self._last = data
            try:
                self.on_change(apply_mesh_config_defaults(
                    data.decode("utf-8")))
            except (MeshConfigError, UnicodeDecodeError) as exc:
                if self.on_error is not None:
                    self.on_error(str(exc))

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
