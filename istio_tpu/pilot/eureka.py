"""Eureka service registry.

Reference: pilot/pkg/serviceregistry/eureka/{client,conversion,
controller,servicediscovery}.go — a ServiceDiscovery backend over the
Eureka v2 REST API (`GET /eureka/v2/apps`), with a polling controller
that fires change handlers when the application set changes
(controller.go) and conversion rules (conversion.go):

  - only instances with ``status == "UP"`` count,
  - an instance exposes 0..2 ports (port, securePort), each gated by
    ``@enabled`` (conversion.go:106-117),
  - the protocol comes from instance metadata key ``istio.protocol``,
  - all remaining metadata keys become labels (``istio.``-prefixed
    keys are filtered out of labels),
  - services are keyed by instance hostname; conflicting protocol
    definitions on one port are logged and first-wins.

Hermetic backend: :class:`FakeEurekaServer` serves the same JSON
wire shape (client.go:26-45) in-process.
"""
from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Mapping, Sequence

from istio_tpu.pilot.model import (NetworkEndpoint, Port, Service,
                                   ServiceInstance)
from istio_tpu.pilot.registry import ServiceDiscovery

import logging

log = logging.getLogger("istio_tpu.pilot.eureka")

STATUS_UP = "UP"
APPS_PATH = "/eureka/v2/apps"
PROTOCOL_METADATA = "istio.protocol"   # conversion.go protocolMetadata


def convert_labels(metadata: Mapping[str, str]) -> dict[str, str]:
    """conversion.go convertLabels: drop istio.* keys."""
    return {k: v for k, v in metadata.items()
            if not k.startswith("istio.")}


def convert_protocol(metadata: Mapping[str, str]) -> str:
    from istio_tpu.kube.registry import protocol_from_port_name
    name = metadata.get(PROTOCOL_METADATA, "")
    return protocol_from_port_name(name) if name else "TCP"


def convert_ports(inst: Mapping[str, Any]) -> list[Port]:
    """conversion.go:106-117 — 0..2 enabled ports per instance."""
    protocol = convert_protocol(inst.get("metadata") or {})
    out = []
    for key in ("port", "securePort"):
        p = inst.get(key) or {}
        if not _enabled(p):
            continue
        num = int(p.get("$", 0))
        out.append(Port(name=f"{key.lower()}-{num}", port=num,
                        protocol=protocol))
    return out


def _enabled(p: Mapping[str, Any]) -> bool:
    v = p.get("@enabled", False)
    return v if isinstance(v, bool) else str(v).lower() == "true"


def convert_services(apps: Sequence[Mapping[str, Any]],
                     hostnames: set[str] | None = None
                     ) -> dict[str, Service]:
    """conversion.go:28-74 — group UP instances by hostname."""
    ports_by_host: dict[str, dict[int, Port]] = {}
    for app in apps:
        for inst in app.get("instance", []):
            host = inst.get("hostName", "")
            if hostnames and host not in hostnames:
                continue
            if inst.get("status") != STATUS_UP:
                continue
            ports = convert_ports(inst)
            if not ports:
                continue
            acc = ports_by_host.setdefault(host, {})
            for port in ports:
                prev = acc.get(port.port)
                if prev is not None:
                    if prev.protocol != port.protocol:
                        log.warning("eureka %s:%d conflicting protocols "
                                 "(%s, %s)", host, port.port,
                                 prev.protocol, port.protocol)
                    continue
                acc[port.port] = port
    return {h: Service(hostname=h, address="",
                       ports=tuple(ports[p] for p in sorted(ports)))
            for h, ports in ports_by_host.items()}


def convert_instances(services: Mapping[str, Service],
                      apps: Sequence[Mapping[str, Any]]
                      ) -> list[ServiceInstance]:
    """conversion.go:76-104."""
    out = []
    for app in apps:
        for inst in app.get("instance", []):
            svc = services.get(inst.get("hostName", ""))
            if svc is None or inst.get("status") != STATUS_UP:
                continue
            for port in convert_ports(inst):
                out.append(ServiceInstance(
                    endpoint=NetworkEndpoint(
                        address=inst.get("ipAddr", ""),
                        port=port.port, service_port=port),
                    service=svc,
                    labels=convert_labels(inst.get("metadata") or {})))
    return out


class EurekaClient:
    """client.go — `Applications()` via GET /eureka/v2/apps."""

    def __init__(self, url: str, timeout_s: float = 10.0):
        self.url = url if "://" in url else f"http://{url}"
        self.timeout_s = timeout_s

    def applications(self) -> list[dict]:
        req = urllib.request.Request(
            self.url + APPS_PATH, headers={"Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            data = json.loads(resp.read().decode("utf-8"))
        apps = (data.get("applications") or {}).get("application") or []
        # Eureka serializes a single app as an object, many as a list.
        if isinstance(apps, dict):
            apps = [apps]
        return apps


class EurekaRegistry(ServiceDiscovery):
    """servicediscovery.go + controller.go polling handler loop."""

    def __init__(self, url: str, poll_s: float = 2.0,
                 client: EurekaClient | None = None):
        self.client = client or EurekaClient(url)
        self.poll_s = poll_s
        self._svc_handlers: list[Callable[[Service, str], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._snapshot: dict[str, Service] = {}

    # -- ServiceDiscovery --

    def services(self) -> list[Service]:
        svcs = convert_services(self._apps())
        return sorted(svcs.values(), key=lambda s: s.hostname)

    def get_service(self, hostname: str) -> Service | None:
        return convert_services(self._apps(), {hostname}).get(hostname)

    def instances(self, hostname, ports=(), labels=None):
        apps = self._apps()
        services = convert_services(apps, {hostname})
        want = set(ports)
        out = []
        for inst in convert_instances(services, apps):
            if want and inst.endpoint.service_port.name not in want:
                continue
            if labels and any(inst.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            out.append(inst)
        return out

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        apps = self._apps()
        services = convert_services(apps)
        return [i for i in convert_instances(services, apps)
                if i.endpoint.address in addrs]

    def _apps(self) -> list[dict]:
        try:
            return self.client.applications()
        except Exception as exc:
            log.warning("eureka fetch failed: %s", exc)
            return []

    # -- controller.go --

    def append_service_handler(self, fn: Callable[[Service, str], None]
                               ) -> None:
        self._svc_handlers.append(fn)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._snapshot = convert_services(self._apps())
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="eureka-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = convert_services(self._apps())
            before, self._snapshot = self._snapshot, now
            for host, svc in now.items():
                if host not in before:
                    self._fire(svc, "add")
                elif before[host] != svc:
                    self._fire(svc, "update")
            for host, svc in before.items():
                if host not in now:
                    self._fire(svc, "delete")

    def _fire(self, svc: Service, event: str) -> None:
        for fn in list(self._svc_handlers):
            try:
                fn(svc, event)
            except Exception:
                log.exception("eureka service handler failed")


# ---------------------------------------------------------------------------
# in-process fake
# ---------------------------------------------------------------------------

class FakeEurekaServer:
    """Serves GET /eureka/v2/apps with the real wire JSON shape."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._apps: dict[str, list[dict]] = {}
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.split("?")[0] != APPS_PATH:
                    self.send_error(404)
                    return
                raw = json.dumps(fake._payload()).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fake-eureka")
        self._thread.start()

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def register(self, app: str, *, hostname: str, ip: str,
                 port: int | None = None, secure_port: int | None = None,
                 status: str = STATUS_UP,
                 metadata: Mapping[str, str] | None = None) -> None:
        inst = {"hostName": hostname, "ipAddr": ip, "status": status,
                "port": {"$": port or 0,
                         "@enabled": "true" if port else "false"},
                "securePort": {"$": secure_port or 0,
                               "@enabled": "true" if secure_port
                               else "false"},
                "metadata": dict(metadata or {})}
        with self._lock:
            self._apps.setdefault(app.upper(), []).append(inst)

    def deregister(self, app: str) -> None:
        with self._lock:
            self._apps.pop(app.upper(), None)

    def _payload(self) -> dict:
        with self._lock:
            apps = [{"name": name, "instance": [dict(i) for i in insts]}
                    for name, insts in sorted(self._apps.items())]
        return {"applications": {"application": apps}}
