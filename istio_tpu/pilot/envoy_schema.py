"""Strict Envoy v1 JSON schema validator.

The reference drives a REAL Envoy binary against its generated config
(mixer/test/client/env/envoy.go); this image ships no Envoy, so the
contract is enforced structurally instead: every emitted v1 JSON
document is validated against the exact field/type/enum shapes of
`pilot/pkg/proxy/envoy/resources.go:163-831` — unknown fields, wrong
types, missing always-serialized fields, and out-of-vocabulary enum
values all fail. The golden tests (tests/test_envoy_golden.py) run
every golden through this validator, so a malformed listener/cluster
shape can never silently ship to a proxy.

Schema encoding: {field: (TYPE, required)} where TYPE is `str`/`int`/
`bool`, ("enum", {...}), ("list", TYPE), ("obj", "SchemaName"),
("map", TYPE) or "any". Ints accept bools=False (JSON booleans are not
Envoy ints). `int_or_float` covers Go int64 fields that JSON may carry
as floats with integral values.
"""
from __future__ import annotations

from typing import Any, Mapping

__all__ = ["EnvoySchemaError", "validate", "validate_listeners",
           "validate_clusters", "validate_route_config",
           "validate_bootstrap"]


class EnvoySchemaError(ValueError):
    pass


S = str
I = int
B = bool
F = "int_or_float"


def _enum(*vals: str):
    return ("enum", frozenset(vals))


# resources.go constants
CLUSTER_TYPES = _enum("static", "strict_dns", "logical_dns",
                      "original_dst", "sds")
LB_TYPES = _enum("round_robin", "least_request", "ring_hash", "random",
                 "original_dst_lb")
CODEC_TYPES = _enum("auto", "http1", "http2")

SCHEMAS: dict[str, dict[str, tuple]] = {
    # resources.go:162-173 Config (bootstrap root)
    "Config": {
        "runtime": (("obj", "RootRuntime"), False),
        "listeners": (("list", ("obj", "Listener")), True),
        "lds": (("obj", "LDSCluster"), False),
        "admin": (("obj", "Admin"), True),
        "cluster_manager": (("obj", "ClusterManager"), True),
        "statsd_udp_ip_address": (S, False),
        "tracing": (("obj", "Tracing"), False),
    },
    "RootRuntime": {
        "symlink_root": (S, True),
        "subdirectory": (S, True),
        "override_subdirectory": (S, False),
    },
    "Tracing": {"http": (("obj", "HTTPTracer"), True)},
    "HTTPTracer": {"driver": (("obj", "HTTPTraceDriver"), True)},
    "HTTPTraceDriver": {
        "type": (_enum("zipkin"), True),
        "config": (("obj", "HTTPTraceDriverConfig"), True),
    },
    "HTTPTraceDriverConfig": {
        "collector_cluster": (S, True),
        "collector_endpoint": (S, True),
    },
    "Admin": {
        "access_log_path": (S, True),
        "address": (S, True),
    },
    "ClusterManager": {
        "clusters": (("list", ("obj", "Cluster")), True),
        "sds": (("obj", "DiscoveryCluster"), False),
        "cds": (("obj", "DiscoveryCluster"), False),
    },
    "DiscoveryCluster": {
        "cluster": (("obj", "Cluster"), True),
        "refresh_delay_ms": (F, True),
    },
    "LDSCluster": {
        "cluster": (S, True),
        "refresh_delay_ms": (F, True),
    },
    # resources.go:625-639 Listener
    "Listener": {
        "address": (S, True),
        "name": (S, False),
        "filters": (("list", ("obj", "NetworkFilter")), True),
        "ssl_context": (("obj", "SSLContext"), False),
        "bind_to_port": (B, True),
        "use_original_dst": (B, False),
    },
    "SSLContext": {
        "cert_chain_file": (S, True),
        "private_key_file": (S, True),
        "ca_cert_file": (S, False),
        "require_client_certificate": (B, True),
        "alpn_protocols": (S, False),
    },
    "SSLContextExternal": {"ca_cert_file": (S, False)},
    "UpstreamSSLContext": {
        "cert_chain_file": (S, True),
        "private_key_file": (S, True),
        "ca_cert_file": (S, False),
        "verify_subject_alt_name": (("list", S), True),
    },
    # resources.go:613-617 NetworkFilter — config schema by name
    "NetworkFilter": {
        "type": (_enum("read", "write", "both", ""), True),
        "name": (S, True),
        "config": ("any", True),   # refined in _validate_network_filter
    },
    # resources.go:496-506 HTTPFilterConfig
    "HTTPFilterConfig": {
        "codec_type": (CODEC_TYPES, True),
        "stat_prefix": (S, True),
        "generate_request_id": (B, False),
        "use_remote_address": (B, False),
        "tracing": (("obj", "HTTPFilterTraceConfig"), False),
        "route_config": (("obj", "HTTPRouteConfig"), False),
        "rds": (("obj", "RDS"), False),
        "filters": (("list", ("obj", "HTTPFilter")), True),
        "access_log": (("list", ("obj", "AccessLog")), False),
    },
    "HTTPFilterTraceConfig": {"operation_name":
                              (_enum("egress", "ingress"), True)},
    "RDS": {
        "cluster": (S, True),
        "route_config_name": (S, True),
        "refresh_delay_ms": (F, True),
    },
    "AccessLog": {
        "path": (S, True),
        "format": (S, False),
        "filter": (S, False),
    },
    "HTTPFilter": {
        "type": (_enum("decoder", "encoder", "both", ""), True),
        "name": (S, True),
        "config": ("any", True),
    },
    # resources.go:401-403 HTTPRouteConfig
    "HTTPRouteConfig": {
        "virtual_hosts": (("list", ("obj", "VirtualHost")), True),
        "validate_clusters": (B, False),
    },
    "VirtualHost": {
        "name": (S, True),
        "domains": (("list", S), True),
        "routes": (("list", ("obj", "HTTPRoute")), True),
    },
    # resources.go:264-295 HTTPRoute
    "HTTPRoute": {
        "runtime": (("obj", "Runtime"), False),
        "path": (S, False),
        "prefix": (S, False),
        "regex": (S, False),
        "prefix_rewrite": (S, False),
        "host_rewrite": (S, False),
        "path_redirect": (S, False),
        "host_redirect": (S, False),
        "cluster": (S, False),
        "weighted_clusters": (("obj", "WeightedCluster"), False),
        "headers": (("list", ("obj", "Header")), False),
        "timeout_ms": (F, False),
        "retry_policy": (("obj", "RetryPolicy"), False),
        "opaque_config": (("map", S), False),
        "auto_host_rewrite": (B, False),
        "use_websocket": (B, False),
        "shadow": (("obj", "ShadowCluster"), False),
        "request_headers_to_add": (("list", ("obj", "AppendedHeader")),
                                   False),
        "cors": (("obj", "CORSPolicy"), False),
        "decorator": (("obj", "Decorator"), False),
    },
    "Runtime": {"key": (S, True), "default": (I, True)},
    "Decorator": {"operation": (S, True)},
    "Header": {
        "name": (S, True),
        "value": (S, True),
        "regex": (B, False),
    },
    "AppendedHeader": {"key": (S, True), "value": (S, True)},
    "RetryPolicy": {
        "retry_on": (S, True),
        "num_retries": (I, False),
        "per_try_timeout_ms": (F, False),
    },
    "ShadowCluster": {"cluster": (S, True)},
    "WeightedCluster": {
        "clusters": (("list", ("obj", "WeightedClusterEntry")), True),
        "runtime_key_prefix": (S, False),
    },
    "WeightedClusterEntry": {"name": (S, True), "weight": (I, True)},
    "CORSPolicy": {
        "enabled": (B, False),
        "allow_credentials": (B, False),
        "allow_methods": (S, False),
        "allow_headers": (S, False),
        "expose_headers": (S, False),
        "max_age": (S, False),
        "allow_origin": (("list", S), False),
    },
    # resources.go:695-712 Cluster
    "Cluster": {
        "name": (S, True),
        "service_name": (S, False),
        "connect_timeout_ms": (F, True),
        "type": (CLUSTER_TYPES, True),
        "lb_type": (LB_TYPES, True),
        "max_requests_per_connection": (I, False),
        "hosts": (("list", ("obj", "Host")), False),
        "ssl_context": ("any", False),
        "features": (_enum("http2"), False),
        "circuit_breakers": (("obj", "CircuitBreaker"), False),
        "outlier_detection": (("obj", "OutlierDetection"), False),
    },
    "Host": {"url": (S, True)},
    "CircuitBreaker": {"default": (("obj", "DefaultCBPriority"), True)},
    "DefaultCBPriority": {
        "max_connections": (I, False),
        "max_pending_requests": (I, False),
        "max_requests": (I, False),
        "max_retries": (I, False),
    },
    "OutlierDetection": {
        "consecutive_5xx": (I, False),
        "interval_ms": (F, False),
        "base_ejection_time_ms": (F, False),
        "max_ejection_percent": (I, False),
    },
    # resources.go:573-601 TCP/Mongo/Redis filter configs
    "TCPProxyFilterConfig": {
        "stat_prefix": (S, True),
        "route_config": (("obj", "TCPRouteConfig"), True),
    },
    "TCPRouteConfig": {"routes": (("list", ("obj", "TCPRoute")), True)},
    "TCPRoute": {
        "cluster": (S, True),
        "destination_ip_list": (("list", S), False),
        "destination_ports": (S, False),
        "source_ip_list": (("list", S), False),
        "source_ports": (S, False),
    },
    "MongoProxyFilterConfig": {
        "stat_prefix": (S, True),
        "access_log": (S, False),
    },
    "RedisProxyFilterConfig": {
        "cluster_name": (S, True),
        "conn_pool": (("obj", "RedisConnPool"), True),
        "stat_prefix": (S, True),
    },
    "RedisConnPool": {"op_timeout_ms": (F, True)},
    "FaultFilterConfig": {
        "abort": (("obj", "AbortFilter"), False),
        "delay": (("obj", "DelayFilter"), False),
        "headers": (("list", ("obj", "Header")), False),
        "upstream_cluster": (S, False),
    },
    "AbortFilter": {
        "abort_percent": (I, False),
        "http_status": (I, False),
    },
    "DelayFilter": {
        "type": (_enum("fixed"), False),
        "fixed_delay_percent": (I, False),
        "fixed_duration_ms": (F, False),
    },
    "RouterFilterConfig": {"dynamic_stats": (B, False)},
}

# network-filter name → config schema (resources.go:86-98 + filters)
NETWORK_FILTER_CONFIGS = {
    "http_connection_manager": "HTTPFilterConfig",
    "tcp_proxy": "TCPProxyFilterConfig",
    "mongo_proxy": "MongoProxyFilterConfig",
    "redis_proxy": "RedisProxyFilterConfig",
}

# HTTP-filter name → config schema; mixer/auth configs are opaque
# (their shapes belong to other protos)
HTTP_FILTER_CONFIGS = {
    "router": "RouterFilterConfig",
    "fault": "FaultFilterConfig",
    "cors": None,       # empty config
    "mixer": None,
    "jwt-auth": None,
}


def _type_name(t: Any) -> str:
    if t is S:
        return "string"
    if t is I:
        return "int"
    if t is B:
        return "bool"
    if t == F:
        return "int"
    if isinstance(t, tuple):
        return t[0]
    return str(t)


def _check(value: Any, t: Any, path: str) -> None:
    if t == "any":
        return
    if t is S:
        if not isinstance(value, str):
            raise EnvoySchemaError(f"{path}: expected string, got "
                                   f"{type(value).__name__}")
        return
    if t is B:
        if not isinstance(value, bool):
            raise EnvoySchemaError(f"{path}: expected bool")
        return
    if t is I:
        if isinstance(value, bool) or not isinstance(value, int):
            raise EnvoySchemaError(f"{path}: expected int")
        return
    if t == F:
        if isinstance(value, bool) or not isinstance(value, (int, float)) \
                or (isinstance(value, float)
                    and not value.is_integer()):
            raise EnvoySchemaError(f"{path}: expected integral number")
        return
    kind = t[0]
    if kind == "enum":
        if value not in t[1]:
            raise EnvoySchemaError(
                f"{path}: {value!r} not in {sorted(t[1])}")
        return
    if kind == "list":
        if not isinstance(value, list):
            raise EnvoySchemaError(f"{path}: expected list")
        for i, item in enumerate(value):
            _check(item, t[1], f"{path}[{i}]")
        return
    if kind == "map":
        if not isinstance(value, Mapping):
            raise EnvoySchemaError(f"{path}: expected object")
        for k, v in value.items():
            _check(v, t[1], f"{path}.{k}")
        return
    if kind == "obj":
        validate(value, t[1], path)
        return
    raise AssertionError(f"bad schema type {t!r}")


def validate(obj: Any, schema: str, path: str = "$") -> None:
    """Validate `obj` against SCHEMAS[schema]; raises EnvoySchemaError
    naming the offending path. Unknown fields are ERRORS (a real Envoy
    v1 loader rejects unknown keys in --v2-config-only=false mode and
    silently ignoring them hides generator typos)."""
    spec = SCHEMAS[schema]
    if not isinstance(obj, Mapping):
        raise EnvoySchemaError(f"{path}: expected {schema} object, got "
                               f"{type(obj).__name__}")
    unknown = set(obj) - set(spec)
    if unknown:
        raise EnvoySchemaError(
            f"{path}: unknown {schema} field(s) {sorted(unknown)}")
    for field, (ftype, required) in spec.items():
        if field not in obj:
            if required:
                raise EnvoySchemaError(
                    f"{path}: missing required {schema}.{field}")
            continue
        _check(obj[field], ftype, f"{path}.{field}")
    if schema == "NetworkFilter":
        _validate_network_filter(obj, path)
    if schema == "HTTPFilter":
        _validate_http_filter(obj, path)
    if schema == "HTTPRoute":
        _validate_http_route(obj, path)


def _validate_network_filter(obj: Mapping, path: str) -> None:
    name = obj.get("name", "")
    sub = NETWORK_FILTER_CONFIGS.get(name)
    if sub is None:
        raise EnvoySchemaError(
            f"{path}: unknown network filter {name!r} "
            f"(known: {sorted(NETWORK_FILTER_CONFIGS)})")
    validate(obj.get("config", {}), sub, f"{path}.config")


def _validate_http_filter(obj: Mapping, path: str) -> None:
    name = obj.get("name", "")
    if name not in HTTP_FILTER_CONFIGS:
        raise EnvoySchemaError(
            f"{path}: unknown HTTP filter {name!r} "
            f"(known: {sorted(HTTP_FILTER_CONFIGS)})")
    sub = HTTP_FILTER_CONFIGS[name]
    if sub is not None:
        validate(obj.get("config", {}), sub, f"{path}.config")


def _validate_http_route(obj: Mapping, path: str) -> None:
    """Route invariants route.go relies on: a route is a redirect OR
    forwards to exactly one of cluster/weighted_clusters."""
    redirect = bool(obj.get("host_redirect") or obj.get("path_redirect"))
    has_cluster = "cluster" in obj
    has_weighted = "weighted_clusters" in obj
    if redirect and (has_cluster or has_weighted):
        raise EnvoySchemaError(
            f"{path}: redirect routes must not name clusters")
    if not redirect and has_cluster == has_weighted:
        raise EnvoySchemaError(
            f"{path}: exactly one of cluster/weighted_clusters "
            "is required")
    matchers = [m for m in ("path", "prefix", "regex") if m in obj]
    if len(matchers) > 1:
        raise EnvoySchemaError(
            f"{path}: at most one of path/prefix/regex ({matchers})")


# -- entry points the goldens/tests use ------------------------------

def validate_listeners(listeners: list) -> None:
    for i, l in enumerate(listeners):
        validate(l, "Listener", f"$.listeners[{i}]")


def validate_clusters(clusters: list) -> None:
    for i, c in enumerate(clusters):
        validate(c, "Cluster", f"$.clusters[{i}]")


def validate_route_config(rc: Mapping) -> None:
    validate(rc, "HTTPRouteConfig", "$.route_config")


def validate_bootstrap(cfg: Mapping) -> None:
    validate(cfg, "Config", "$")
